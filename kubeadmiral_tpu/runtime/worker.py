"""Reconcile workers.

The reference's universal engine is ReconcileWorker: a queue feeding N
goroutines that call ``reconcile(key) -> Result`` with per-key backoff
(reference: pkg/controllers/util/worker/worker.go:37-174).  Two variants
here:

* :class:`Worker` — the direct analogue for per-object controllers
  (sync, federate, status, ...), stepped explicitly (``step()``) or in a
  thread loop (``run()``).
* :class:`BatchWorker` — the tick-native variant: drains *all* due keys
  and hands them to one callback, which is how the scheduler amortizes a
  whole pending set into one XLA dispatch.

Results mirror worker.Result: success resets backoff; ``backoff=True``
requeues with exponential delay; ``requeue_after`` schedules a fixed
revisit.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterable, Optional

from kubeadmiral_tpu.runtime import tenancy, trace
from kubeadmiral_tpu.runtime.queue import Backoff, DirtyQueue
from kubeadmiral_tpu.runtime.metrics import Metrics, null_metrics

log = logging.getLogger("kubeadmiral.worker")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def admit_depth() -> int:
    """KT_ADMIT_DEPTH: queue depth past which new enqueues are admitted
    with a coalescing delay instead of immediately (0 disables).  Under
    an event flood the queue keeps deduping by key while ticks drain
    BIGGER, LESS FREQUENT batches — freshness gauges degrade gracefully
    instead of per-event latency p99 ballooning on tick thrash."""
    return _env_int("KT_ADMIT_DEPTH", 10000)


def admit_delay_s() -> float:
    """KT_ADMIT_DELAY_MS: the coalescing delay applied to enqueues past
    the admission depth."""
    return _env_int("KT_ADMIT_DELAY_MS", 50) / 1e3


def admit_batch() -> int:
    """KT_ADMIT_BATCH: max keys one drain hands a tick (0 = unlimited).
    Bounds a single tick's latency when a flood has already queued
    more work than one tick should absorb."""
    return _env_int("KT_ADMIT_BATCH", 0)


@dataclass
class Result:
    success: bool = True
    requeue_after: Optional[float] = None
    backoff: bool = False

    @staticmethod
    def ok() -> "Result":
        return Result()

    @staticmethod
    def retry() -> "Result":
        return Result(success=False, backoff=True)

    @staticmethod
    def after(seconds: float) -> "Result":
        return Result(success=True, requeue_after=seconds)


class _WorkerBase:
    def __init__(self, name: str, metrics: Optional[Metrics] = None, clock=None):
        self.name = name
        self.queue = DirtyQueue() if clock is None else DirtyQueue(clock)
        self.backoff = Backoff()
        self.metrics = metrics or null_metrics()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # Admission knobs resolved once per worker: the enqueue path
        # runs per watch event, where even an env read is measurable.
        self._admit_depth = admit_depth()
        self._admit_delay = admit_delay_s()
        self._admit_batch = admit_batch()
        # Shard routing consulted per enqueue — the informer/worker
        # boundary is where a replica decides whether a key is its own.
        # Resolved once like the admission knobs; with KT_SHARD_COUNT=1
        # (the default) owns() is a single attribute compare.
        from kubeadmiral_tpu.federation import shardmap

        self._shard = shardmap.get_default()
        # Threads currently inside a reconcile (ident -> depth).  An
        # in-process store delivers watch events synchronously on the
        # writing thread, so an event arriving on one of these threads
        # mid-reconcile is an echo of this controller's OWN write —
        # handlers consult is_own_thread() to skip the self-requeue.
        self._active: dict[int, int] = {}

    def is_own_thread(self) -> bool:
        return threading.get_ident() in self._active

    def _enter(self) -> int:
        ident = threading.get_ident()
        self._active[ident] = self._active.get(ident, 0) + 1
        return ident

    def _exit(self, ident: int) -> None:
        depth = self._active.get(ident, 1) - 1
        if depth <= 0:
            self._active.pop(ident, None)
        else:
            self._active[ident] = depth

    def enqueue(self, key: str, delay: float = 0.0) -> None:
        if not self._shard.owns(key):
            return
        # Queue-depth-driven admission: past KT_ADMIT_DEPTH pending
        # keys, new work coalesces behind a short delay (dedupe by key
        # makes repeated events free) so a flood turns into bigger
        # amortized ticks instead of tick thrash.
        if delay <= 0.0 and self._admit_depth > 0:
            # Unlocked dict-len read: an approximate depth is fine for a
            # soft threshold, and the add below takes the lock anyway.
            if len(self.queue._pending) > self._admit_depth:
                delay = self._admit_delay
                if delay > 0.0:
                    self.metrics.counter(
                        "worker_admission_total", controller=self.name
                    )
                    # Per-tenant deferral attribution — the data the
                    # weighted fair-admission item will arbitrate on
                    # (no-op unless a ledger is installed).
                    if tenancy.active():
                        tenancy.note_admission(tenancy.tenant_of_key(key))
        self.queue.add(key, delay)

    def _drain(self) -> list[str]:
        """drain_due plus the queue telemetry every controller shares:
        depth/age gauges and per-key wait histograms, labeled by
        controller name."""
        keys = self.queue.drain_due(limit=self._admit_batch)
        self.metrics.gauge("worker_queue_depth", len(self.queue), controller=self.name)
        self.metrics.gauge(
            "worker_queue_oldest_age_seconds",
            self.queue.oldest_age(),
            controller=self.name,
        )
        if keys:
            waits = self.queue.last_drain_waits
            # Bound per-tick histogram work: a 100k-key batch drain
            # observes a sample plus the max, not every key.
            for w in waits[:64]:
                self.metrics.histogram(
                    "worker_queue_wait_seconds", w, controller=self.name
                )
            if len(waits) > 64:
                self.metrics.histogram(
                    "worker_queue_wait_seconds", max(waits), controller=self.name
                )
        return keys

    def enqueue_all(self, keys: Iterable[str], delay: float = 0.0) -> None:
        for k in keys:
            if self._shard.owns(k):
                self.queue.add(k, delay)

    def enqueue_many(self, keys: Iterable[str]) -> None:
        """Batch-event intake: one admission decision for the whole
        flush (the depth probe and deferral bookkeeping run once, not
        per key), then per-key adds — the coalesced-delivery analogue
        of :meth:`enqueue`."""
        keys = [k for k in keys if self._shard.owns(k)]
        if not keys:
            return
        delay = 0.0
        if self._admit_depth > 0 and len(self.queue._pending) > self._admit_depth:
            delay = self._admit_delay
            if delay > 0.0:
                self.metrics.counter(
                    "worker_admission_total", controller=self.name
                )
                if tenancy.active():
                    # One ledger call per tenant per flush, not per key:
                    # note_admission takes the ledger lock, and a 100k-
                    # key flush was paying it 100k times (PR 18 profile).
                    counts: dict[str, int] = {}
                    for k in keys:
                        t = tenancy.tenant_of_key(k)
                        counts[t] = counts.get(t, 0) + 1
                    for t, n in counts.items():
                        tenancy.note_admission(t, n)
        for k in keys:
            self.queue.add(k, delay)

    def stop(self) -> None:
        self._stop.set()
        with self.queue._wakeup:
            self.queue._wakeup.notify_all()
        for t in self._threads:
            t.join(timeout=5)

    def run(self, workers: int = 1) -> None:
        for i in range(workers):
            t = threading.Thread(
                target=self._loop, name=f"{self.name}-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def _loop(self) -> None:
        while not self._stop.is_set():
            if not self.step():
                self.queue.wait(timeout=0.5)

    def step(self) -> bool:  # pragma: no cover - overridden
        raise NotImplementedError


class Worker(_WorkerBase):
    """One key per reconcile call."""

    def __init__(self, name, reconcile: Callable[[str], Result], **kw):
        super().__init__(name, **kw)
        self._reconcile = reconcile

    def step(self) -> bool:
        keys = self._drain()
        if not keys:
            return False
        for key in keys:
            self._dispatch(key)
        return True

    def _dispatch(self, key: str) -> None:
        ident = self._enter()
        start = time.perf_counter()
        try:
            # Sampled: a per-key span at e2e scale is millions of ring
            # appends that evict each other — keep 1-in-N (trace.py).
            with trace.hot_span("worker.reconcile", controller=self.name, key=key):
                with self.metrics.timer(f"{self.name}.latency"):
                    result = self._reconcile(key)
        except Exception:
            # The panic-equivalent: the reconcile escaped instead of
            # returning Result.retry().
            self.metrics.counter(f"{self.name}.panic")
            self.metrics.counter("worker_exceptions_total", controller=self.name)
            log.exception(
                "reconcile panic: controller=%s key=%s", self.name, key
            )
            result = Result.retry()
        finally:
            self._exit(ident)
        self.metrics.counter(f"{self.name}.throughput")
        self.metrics.counter("worker_reconciles_total", controller=self.name)
        self.metrics.histogram(
            "worker_process_seconds",
            time.perf_counter() - start,
            controller=self.name,
        )
        self._requeue(key, result)

    def _requeue(self, key: str, result: Result) -> None:
        if result.success:
            self.backoff.reset(key)
            if result.requeue_after is not None:
                self.metrics.counter("worker_requeues_total", controller=self.name)
                self.queue.add(key, result.requeue_after)
        elif result.backoff:
            self.metrics.counter("worker_retries_total", controller=self.name)
            self.queue.add(key, self.backoff.next_delay(key))


class BatchWorker(_WorkerBase):
    """All due keys -> one callback (the batching tick)."""

    def __init__(
        self,
        name,
        reconcile_batch: Callable[[list[str]], dict[str, Result]],
        **kw,
    ):
        super().__init__(name, **kw)
        self._reconcile_batch = reconcile_batch

    def step(self) -> bool:
        keys = self._drain()
        if not keys:
            return False
        ident = self._enter()
        start = time.perf_counter()
        try:
            with trace.span("worker.tick", controller=self.name, keys=len(keys)):
                with self.metrics.timer(f"{self.name}.tick_latency"):
                    results = self._reconcile_batch(keys)
        except Exception:
            self.metrics.counter(f"{self.name}.panic")
            self.metrics.counter("worker_exceptions_total", controller=self.name)
            log.exception(
                "batch-tick panic: controller=%s keys=%d", self.name, len(keys)
            )
            results = {k: Result.retry() for k in keys}
        finally:
            self._exit(ident)
        self.metrics.counter(f"{self.name}.throughput", len(keys))
        self.metrics.counter(
            "worker_reconciles_total", len(keys), controller=self.name
        )
        self.metrics.histogram(
            "worker_tick_seconds", time.perf_counter() - start, controller=self.name
        )
        retried = requeued = 0
        for key in keys:
            result = results.get(key, Result.ok())
            if result.success:
                self.backoff.reset(key)
                if result.requeue_after is not None:
                    requeued += 1
                    self.queue.add(key, result.requeue_after)
            elif result.backoff:
                retried += 1
                self.queue.add(key, self.backoff.next_delay(key))
        if retried:
            self.metrics.counter(
                "worker_retries_total", retried, controller=self.name
            )
        if requeued:
            self.metrics.counter(
                "worker_requeues_total", requeued, controller=self.name
            )
        return True
