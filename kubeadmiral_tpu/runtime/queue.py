"""Keyed work queues with delay + backoff.

Merges the reference's two queue layers (reference:
pkg/controllers/util/delayingdeliver/delaying_deliverer.go — a min-heap
timer queue with latest-wins per key — and pkg/controllers/util/worker/
worker.go — per-key exponential backoff, 5s initial / 1m max) into one
structure tuned for the tick architecture: controllers *drain everything
due at once* so the scheduler can batch the whole pending set into a
single device dispatch, instead of popping one key per goroutine.
"""

from __future__ import annotations

import heapq
import threading
import time

from kubeadmiral_tpu.runtime import lockcheck
from dataclasses import dataclass, field


@dataclass(order=True)
class _Entry:
    due: float
    seq: int
    key: str = field(compare=False)


class Backoff:
    """Per-key exponential backoff (worker.go:86-91, 146-155)."""

    def __init__(self, initial: float = 5.0, maximum: float = 60.0):
        self.initial = initial
        self.maximum = maximum
        self._delays: dict[str, float] = {}

    def next_delay(self, key: str) -> float:
        delay = self._delays.get(key, self.initial)
        self._delays[key] = min(delay * 2, self.maximum)
        return delay

    def reset(self, key: str) -> None:
        self._delays.pop(key, None)


@lockcheck.shared_field_guard
class DirtyQueue:
    """Thread-safe delayed queue; at most one pending entry per key
    (latest-wins, like DelayingDeliverer's key map)."""

    # Every field below is touched by producer add()s and the worker's
    # drain loop concurrently; _wakeup is a Condition OVER _lock, so
    # `with self._wakeup:` satisfies the same lock (ktlint
    # lock-discipline + runtime/lockcheck.py).
    _shared_fields_ = {
        "_heap": "_lock|_wakeup",
        "_pending": "_lock|_wakeup",
        "_enqueued_at": "_lock|_wakeup",
        "_seq": "_lock|_wakeup",
        "last_drain_waits": "_lock|_wakeup",
    }

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = lockcheck.make_lock("dirtyqueue")
        self._heap: list[_Entry] = []
        self._pending: dict[str, _Entry] = {}
        # key -> first-enqueue time while pending: the true queue wait
        # (a dedupe re-add does not reset the clock).  Telemetry only —
        # drain_due publishes the drained keys' waits in
        # ``last_drain_waits`` and ``oldest_age()`` gauges what's left.
        self._enqueued_at: dict[str, float] = {}
        self.last_drain_waits: list[float] = []
        self._seq = 0
        self._wakeup = threading.Condition(self._lock)

    def add(self, key: str, delay: float = 0.0) -> None:
        due = self._clock() + delay
        with self._wakeup:
            cur = self._pending.get(key)
            if cur is not None:
                if cur.due <= due:
                    return  # an earlier delivery is already scheduled
                cur.key = _TOMBSTONE  # lazy-delete the later one
            else:
                self._enqueued_at[key] = self._clock()
            self._seq += 1
            entry = _Entry(due, self._seq, key)
            self._pending[key] = entry
            heapq.heappush(self._heap, entry)
            self._wakeup.notify()

    def drain_due(self, limit: int = 0) -> list[str]:
        """Pop every key whose delivery time has arrived (at most
        ``limit`` keys when limit > 0 — the admission drain cap that
        bounds one tick's batch under an event flood)."""
        now = self._clock()
        out: list[str] = []
        waits: list[float] = []
        with self._lock:
            while self._heap and self._heap[0].due <= now:
                if limit and len(out) >= limit:
                    break
                entry = heapq.heappop(self._heap)
                if entry.key is _TOMBSTONE:
                    continue
                del self._pending[entry.key]
                enq = self._enqueued_at.pop(entry.key, None)
                if enq is not None:
                    waits.append(max(0.0, now - enq))
                out.append(entry.key)
            if out:
                self.last_drain_waits = waits
        return out

    def next_due_in(self) -> float | None:
        """Seconds until the earliest pending key is due (0 when one is
        due now, None when empty) — lets pollers distinguish a key
        coalescing behind a short admission delay from a long-fuse
        requeue."""
        with self._lock:
            while self._heap and self._heap[0].key is _TOMBSTONE:
                heapq.heappop(self._heap)
            if not self._heap:
                return None
            return max(0.0, self._heap[0].due - self._clock())

    def oldest_age(self) -> float:
        """Age of the longest-pending key (0 when empty) — the queue-lag
        gauge a stuck controller shows first."""
        with self._lock:
            if not self._enqueued_at:
                return 0.0
            return max(0.0, self._clock() - min(self._enqueued_at.values()))

    def wait(self, timeout: float | None = None) -> None:
        """Block until something may be due (new entry or head deadline)."""
        with self._wakeup:
            head = self._heap[0].due if self._heap else None
            now = self._clock()
            if head is not None and head <= now:
                return
            delay = None if head is None else head - now
            if timeout is not None:
                delay = timeout if delay is None else min(delay, timeout)
            self._wakeup.wait(delay)

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)


_TOMBSTONE: str = "\x00tombstone\x00"
