"""Event recording with defederation.

Controllers record Kubernetes Events against the objects they act on;
events recorded on a *federated* object are additionally re-targeted to
its source object so users watching `kubectl describe deployment` see
federation activity (reference: pkg/controllers/util/eventsink/
eventsink.go DefederatingRecorderMux — a mux of recorders where one
transform maps a federated object to its controller owner reference).

Events are objects in the host store's ``v1/events`` resource with the
usual involvedObject/reason/message/type/count shape; repeated identical
events bump ``count`` instead of piling up new objects.
"""

from __future__ import annotations

import time
from typing import Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.testing.fakekube import (
    AlreadyExists,
    Conflict,
    FakeKube,
    NotFound,
)

EVENTS = "v1/events"

# Bounded optimistic-concurrency retries for the count-bump path: two
# recorders bumping the same event race on resourceVersion; each retry
# re-reads and re-applies the increment, so no bump is silently lost
# (the real recorder serializes through a broadcaster and never races
# itself; this mux is called from many controller threads directly).
_BUMP_RETRIES = 8

# Set by the federate controller on every federated object it creates.
FEDERATED_OBJECT_ANNOTATION = C.FEDERATED_OBJECT


def _defederate_reference(obj: dict) -> Optional[dict]:
    """Federated object -> source-object reference (eventsink.go:68-98:
    the reference walks controller ownerReferences; source and federated
    objects share name/namespace here, so the de-federated kind is the
    template's)."""
    ann = obj.get("metadata", {}).get("annotations", {})
    if FEDERATED_OBJECT_ANNOTATION not in ann:
        return None
    template = obj.get("spec", {}).get("template", {})
    if not template.get("kind"):
        return None
    return {
        "apiVersion": template.get("apiVersion", ""),
        "kind": template["kind"],
        "namespace": obj["metadata"].get("namespace", ""),
        "name": obj["metadata"]["name"],
    }


class EventRecorder:
    """Records events into the host store (record.EventRecorder shape)."""

    def __init__(self, host: FakeKube, component: str, clock=time.time):
        self.host = host
        self.component = component
        self.clock = clock

    def _reference(self, obj: dict) -> dict:
        return {
            "apiVersion": obj.get("apiVersion", ""),
            "kind": obj.get("kind", ""),
            "namespace": obj.get("metadata", {}).get("namespace", ""),
            "name": obj.get("metadata", {}).get("name", ""),
        }

    def _record(self, ref: dict, event_type: str, reason: str, message: str) -> None:
        ns = ref.get("namespace", "")
        name = f"{ref['kind']}.{ref['name']}.{reason}".lower()
        key = f"{ns}/{name}" if ns else name
        # Bounded retry loop: a Conflict means another recorder updated
        # the same event between our read and write — re-read and
        # re-apply instead of dropping the bump (concurrent recorders
        # used to under-count; the regression test hammers this path
        # from many threads).
        for _ in range(_BUMP_RETRIES):
            existing = self.host.try_get(EVENTS, key)
            if existing is not None and existing.get("message") == message:
                existing["count"] = existing.get("count", 1) + 1
                existing["lastTimestamp"] = self.clock()
                try:
                    self.host.update(EVENTS, existing)
                    return
                except Conflict:
                    continue
                except NotFound:
                    continue  # deleted under us: recreate on re-read
            event = {
                "apiVersion": "v1",
                "kind": "Event",
                "metadata": {"name": name},
                "involvedObject": ref,
                "type": event_type,
                "reason": reason,
                "message": message,
                "source": {"component": self.component},
                "count": 1,
                "firstTimestamp": self.clock(),
                "lastTimestamp": self.clock(),
            }
            if ns:
                event["metadata"]["namespace"] = ns
            try:
                if existing is None:
                    self.host.create(EVENTS, event)
                else:
                    event["metadata"] = existing["metadata"]
                    self.host.update(EVENTS, event)
                return
            except (Conflict, NotFound, AlreadyExists):
                continue  # raced: re-read and retry
            except Exception:
                return  # event loss is tolerated, as with the real broadcaster

    def event(self, obj: dict, event_type: str, reason: str, message: str) -> None:
        self._record(self._reference(obj), event_type, reason, message)


class DefederatingRecorderMux(EventRecorder):
    """Records on the given object AND, for federated objects, on the
    de-federated source reference (eventsink.go NewDefederatingRecorderMux)."""

    def event(self, obj: dict, event_type: str, reason: str, message: str) -> None:
        super().event(obj, event_type, reason, message)
        source_ref = _defederate_reference(obj)
        if source_ref is not None:
            self._record(source_ref, event_type, reason, message)
