"""Durable engine snapshots: crash-safe control-plane state.

KubeAdmiral's failover contract is that a replacement leader resumes
where the old one stopped.  The durable half of our scheduler state —
placements, PropagatedVersions, trigger hashes — already lives in the
apiserver (tests/test_restart_resume.py proves a restart performs zero
writes).  What the apiserver does NOT hold is the engine's device-side
working set: the per-chunk prev planes (placements / scores /
feasibility / reasons), the adaptive pack-K hints, the member breaker
states and the flight recorder — everything that lets a tick ride the
noop / drift-gate / sub-batch fast paths instead of a cold full solve.
This module persists exactly that:

* :class:`SnapshotStore` — one file per snapshot, written write-temp +
  fsync + rename (atomic on POSIX), CRC-guarded, monotonic tick id in
  the name and header.  Load walks newest-first; a torn, truncated or
  CRC-failing file is **quarantined** (renamed ``*.quarantined``) and
  never loaded — the loader falls back to the next older snapshot, or
  to cold.  A version/guard mismatch quarantines too: a snapshot is
  never trusted blindly.

* :class:`SnapshotManager` — wires a :class:`SchedulerEngine` to a
  store: after each converged tick (every ``KT_SNAPSHOT_EVERY``-th
  state-changing tick) it captures the engine's host-side images plus
  breaker registry + flight recorder state and persists them; on boot,
  :meth:`restore` stages the newest valid snapshot into the engine
  (consumed at the first ``schedule()`` call) and restores breakers +
  recorder immediately.

Restore semantics (enforced inside the engine, see
``SchedulerEngine._consume_restore``): a snapshot whose per-kind
resourceVersion watermarks match the relist AND whose cluster tensors
are bit-identical resumes through the O(B) signature walk onto the
no-op replay path (zero dispatches); a stale-but-recent snapshot keeps
the restored planes as ``prev`` state and the first tick re-solves only
changed rows (sub-batch) / drifted columns (drift gate); any structural
mismatch — topology, geometry, engine config — falls back to cold for
the affected chunks.  Every outcome lands in
``engine_snapshot_total{result}``.

Multi-device round trip (ISSUE 12): capture gathers the engine's
SHARDED prev planes host-side (``np.asarray`` on a GSPMD array collects
the shards), and ``stage_restore`` re-device_puts them under the mesh's
row shardings — a sharded engine restores bit-identically with the
zero-dispatch no-op replay preserved (tier-1:
tests/test_multidevice.py).  The device topology is part of the
engine's snapshot config fingerprint, so a 4-device snapshot staged
into a 2-device engine is REJECTED (cold boot), never reinterpreted.

Knobs: ``KT_SNAPSHOT_DIR`` (no default — snapshots are opt-in),
``KT_SNAPSHOT_EVERY`` (persist every N-th state-changing tick, default
1), ``KT_SNAPSHOT_KEEP`` (retained generations, default 2).  See
docs/operations.md § Restart & failover runbook.
"""

from __future__ import annotations

import logging
import os
import pickle
import struct
import threading
import time
import zlib
from typing import Callable, Optional

log = logging.getLogger("kubeadmiral.snapshot")

MAGIC = b"KTSNAP01"
SNAPSHOT_VERSION = 1
_HEADER_FMT = "<Q"  # header-json byte length


def snapshot_dir() -> Optional[str]:
    return os.environ.get("KT_SNAPSHOT_DIR") or None


def shard_snapshot_store(
    base_dir: str, shard, keep: Optional[int] = None, metrics=None
) -> "SnapshotStore":
    """A :class:`SnapshotStore` scoped to one shard of the sharded
    control plane: ``<base_dir>/shard-<i>/``.  Each replica persists
    only its own keys' working set, so a standby taking over shard i
    restores shard i's artifact without ever seeing (or trusting)
    another shard's planes.  The shard identity also rides in the
    payload (see SnapshotManager ``shard=``) and is validated at
    restore — directory layout is convenience, the payload guard is
    the contract."""
    return SnapshotStore(
        os.path.join(base_dir, f"shard-{shard.shard_index}"),
        keep=keep,
        metrics=metrics,
    )


class SnapshotStore:
    """Atomic, CRC-guarded snapshot files in one directory."""

    def __init__(self, directory: str, keep: Optional[int] = None, metrics=None):
        self.dir = directory
        self.keep = (
            max(1, int(os.environ.get("KT_SNAPSHOT_KEEP", "2")))
            if keep is None
            else max(1, keep)
        )
        self.metrics = metrics
        self._lock = threading.Lock()
        self.last_write_s = 0.0
        self.last_bytes = 0

    def _count(self, result: str) -> None:
        if self.metrics is not None:
            self.metrics.counter("engine_snapshot_total", result=result)

    @staticmethod
    def _name(tick: int) -> str:
        return f"snap-{tick:012d}.ktsnap"

    # -- write ------------------------------------------------------------
    def save(self, tick: int, payload: dict) -> str:
        """Persist one snapshot: MAGIC + header(tick, crc, length) +
        pickled payload, written to a temp file, fsynced, renamed.  A
        reader can never observe a half-written snapshot under POSIX
        rename atomicity; a crash before the rename leaves only a temp
        file the loader ignores."""
        t0 = time.perf_counter()
        blob = pickle.dumps(payload, protocol=4)
        header = {
            "version": SNAPSHOT_VERSION,
            "tick": int(tick),
            "crc": zlib.crc32(blob),
            "payload_len": len(blob),
            "wall": time.time(),
        }
        hjson = pickle.dumps(header, protocol=4)
        with self._lock:
            os.makedirs(self.dir, exist_ok=True)
            final = os.path.join(self.dir, self._name(tick))
            tmp = os.path.join(self.dir, f".snap-{tick}.tmp.{os.getpid()}")
            with open(tmp, "wb") as fh:
                fh.write(MAGIC)
                fh.write(struct.pack(_HEADER_FMT, len(hjson)))
                fh.write(hjson)
                if os.environ.get("KT_SNAPSHOT_KILL") == "mid-write":
                    # Kill-matrix hook (tests/test_restart.py): die with
                    # the payload half-written and the rename not yet
                    # performed — the torn-write case the loader must
                    # survive.
                    fh.write(blob[: len(blob) // 2])
                    fh.flush()
                    os.fsync(fh.fileno())
                    os.kill(os.getpid(), 9)
                fh.write(blob)
                fh.flush()
                os.fsync(fh.fileno())
            if os.environ.get("KT_SNAPSHOT_KILL") == "pre-rename":
                os.kill(os.getpid(), 9)
            os.replace(tmp, final)
            self._fsync_dir()
            self._prune_locked()
        self.last_write_s = time.perf_counter() - t0
        self.last_bytes = len(blob)
        if self.metrics is not None:
            self.metrics.histogram(
                "engine_snapshot_write_seconds", self.last_write_s
            )
            self.metrics.store("engine_snapshot_bytes", self.last_bytes)
        self._count("written")
        log.debug(
            "snapshot written: tick=%d bytes=%d write_ms=%.1f",
            tick, self.last_bytes, self.last_write_s * 1e3,
        )
        return final

    def _fsync_dir(self) -> None:
        try:
            fd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass  # non-POSIX-durable dir: the rename still happened

    def _prune_locked(self) -> None:
        snaps = sorted(self._list())
        for _, path in snaps[: -self.keep]:
            try:
                os.unlink(path)
            except OSError:
                pass
        # Stale temp files from crashed writers.
        try:
            for de in os.scandir(self.dir):
                if de.name.startswith(".snap-") and ".tmp." in de.name:
                    try:
                        os.unlink(de.path)
                    except OSError:
                        pass
        except OSError:
            pass

    # -- read -------------------------------------------------------------
    def _list(self) -> list[tuple[int, str]]:
        out = []
        try:
            for de in os.scandir(self.dir):
                name = de.name
                if name.startswith("snap-") and name.endswith(".ktsnap"):
                    try:
                        out.append((int(name[5:-7]), de.path))
                    except ValueError:
                        continue
        except OSError:
            pass
        return out

    def quarantine(self, path: str, why: str) -> None:
        """A snapshot that failed validation is renamed aside — kept for
        forensics, never loadable again — and counted.  The operator
        runbook (docs/operations.md) explains what to do with one."""
        qpath = path + ".quarantined"
        try:
            os.replace(path, qpath)
        except OSError:
            qpath = "(unlinkable)"
        log.warning("snapshot quarantined: %s -> %s (%s)", path, qpath, why)
        self._count("quarantined")

    def load_latest(self) -> Optional[tuple[dict, dict]]:
        """(header, payload) of the newest VALID snapshot, quarantining
        any corrupt/mismatched file found on the way; None = cold."""
        for tick, path in sorted(self._list(), reverse=True):
            try:
                with open(path, "rb") as fh:
                    magic = fh.read(len(MAGIC))
                    if magic != MAGIC:
                        raise ValueError("bad magic")
                    (hlen,) = struct.unpack(
                        _HEADER_FMT, fh.read(struct.calcsize(_HEADER_FMT))
                    )
                    if hlen > 1 << 20:
                        raise ValueError("implausible header length")
                    header = pickle.loads(fh.read(hlen))
                    if header.get("version") != SNAPSHOT_VERSION:
                        raise ValueError(
                            f"version {header.get('version')} != "
                            f"{SNAPSHOT_VERSION}"
                        )
                    blob = fh.read(header["payload_len"])
                    if len(blob) != header["payload_len"]:
                        raise ValueError("truncated payload")
                    if zlib.crc32(blob) != header["crc"]:
                        raise ValueError("payload CRC mismatch")
                    payload = pickle.loads(blob)
            except Exception as e:
                self.quarantine(path, repr(e))
                continue
            return header, payload
        return None


class SnapshotManager:
    """Engine <-> store glue: periodic capture after converged ticks,
    staged restore on boot.  ``breakers`` (a BreakerRegistry) and
    ``flightrec`` (a FlightRecorder) ride along when provided; the
    ``watermark_fn`` callable supplies the per-kind resourceVersion
    watermarks recorded with each snapshot (and compared at restore)."""

    def __init__(
        self,
        engine,
        store: SnapshotStore,
        every: Optional[int] = None,
        breakers=None,
        flightrec="engine",
        watermark_fn: Optional[Callable[[], dict]] = None,
        shard=None,
    ):
        self.engine = engine
        self.store = store
        # Sharded control plane: when a ShardMap is supplied, every
        # snapshot is keyed by (shard_count, shard_index, epoch) and
        # restore REFUSES a mismatched artifact (cold boot instead) —
        # a resize bumps the epoch, so planes captured under the old
        # key→shard routing are never replayed into the new one.
        self.shard = shard
        self.every = (
            max(1, int(os.environ.get("KT_SNAPSHOT_EVERY", "1")))
            if every is None
            else max(1, every)
        )
        self.breakers = breakers
        self.flightrec = (
            getattr(engine, "flightrec", None) if flightrec == "engine" else flightrec
        )
        self.watermark_fn = watermark_fn
        self._last_snap_tick = 0
        self._ticks_since = 0
        self.last_result: Optional[str] = None
        # Engine hook: called at the end of every schedule() while the
        # schedule lock is still held, so the captured planes are the
        # converged tick's, not a racing successor's.
        engine.post_tick = self.maybe_snapshot

    # -- capture ----------------------------------------------------------
    def maybe_snapshot(self, engine) -> None:
        changed = engine.last_changed is None or bool(engine.last_changed)
        if not changed and self._last_snap_tick:
            return  # a no-op tick over already-persisted state
        self._ticks_since += 1
        if self._ticks_since < self.every and self._last_snap_tick:
            return
        self.snapshot()

    def snapshot(self) -> Optional[str]:
        state = self.engine.snapshot_state()
        if state is None:
            self.store._count("skipped")
            self.last_result = "skipped"
            return None
        payload = {
            "version": SNAPSHOT_VERSION,
            "engine": state,
            "shard": (
                {
                    "shard_count": self.shard.shard_count,
                    "shard_index": self.shard.shard_index,
                    "epoch": self.shard.epoch,
                }
                if self.shard is not None
                else None
            ),
            "watermarks": self.watermark_fn() if self.watermark_fn else None,
            "breakers": (
                self.breakers.export_state() if self.breakers is not None else None
            ),
            "flightrec": (
                self.flightrec.export_state()
                if self.flightrec is not None and self.flightrec.enabled
                else None
            ),
        }
        path = self.store.save(self.engine.tick_seq, payload)
        self._last_snap_tick = self.engine.tick_seq
        self._ticks_since = 0
        self.last_result = "written"
        return path

    # -- restore ----------------------------------------------------------
    def restore(self, watermarks: Optional[dict] = None) -> str:
        """Stage the newest valid snapshot into the engine (consumed at
        its next tick) and restore breakers + flight recorder now.
        Returns "staged" | "cold" (nothing valid on disk)."""
        loaded = self.store.load_latest()
        if loaded is None:
            self.last_result = "cold"
            return "cold"
        header, payload = loaded
        if self.shard is not None:
            want = {
                "shard_count": self.shard.shard_count,
                "shard_index": self.shard.shard_index,
                "epoch": self.shard.epoch,
            }
            got = payload.get("shard")
            if got != want:
                # Wrong shard identity or a pre-resize epoch: the
                # artifact's planes were captured under a different
                # key→shard routing.  Never stage it — cold boot.
                self.store._count("shard_mismatch")
                self.last_result = "cold"
                log.warning(
                    "snapshot shard mismatch: artifact=%s replica=%s "
                    "(cold boot)", got, want,
                )
                return "cold"
        if watermarks is None and self.watermark_fn is not None:
            watermarks = self.watermark_fn()
        snap_marks = payload.get("watermarks")
        fresh_marks = (
            watermarks is not None
            and snap_marks is not None
            and watermarks == snap_marks
        )
        self.engine.stage_restore(
            payload.get("engine"), assume_fresh=fresh_marks
        )
        if self.breakers is not None and payload.get("breakers"):
            self.breakers.restore_state(payload["breakers"])
        if self.flightrec is not None and payload.get("flightrec"):
            try:
                self.flightrec.restore_state(payload["flightrec"])
            except Exception:
                log.warning("flight-recorder restore failed", exc_info=True)
        self.last_result = "staged"
        log.info(
            "snapshot staged for restore: tick=%d watermarks=%s",
            header.get("tick", 0),
            "match" if fresh_marks else "stale-or-unknown",
        )
        return "staged"
