"""Scheduling decision flight recorder.

A bounded ring of the last N ticks' decision records: for every object
row the engine actually fetched off the device, the recorder keeps the
chosen clusters + replica split, the top-k scores among the selected
clusters, the per-reason rejection counts + feasible count (and, when
the dense fetch format shipped it, the full per-cluster reason bitmask
row, ops.reasons vocabulary) and the tick/program fingerprint — enough
to answer "why is object X on clusters {A, B} and not C?" without
re-running the solver.

Populated OFF the hot path: the engine records from the host-side
arrays its fetch stage already pulled (scheduler/engine.py packs the
reason plane into the same delta gathers / full-plane fetches it runs
anyway), so device dispatch latency is unaffected.  Ticks that ride the
noop/skip fast paths record nothing — the previous records remain
current, because the tick provably reproduced the previous outputs.
Consequently a record describes the decision AS OF the tick that last
fetched that row (each record carries its tick id and age).

Served by the health/profiling HTTP servers:

* ``GET /debug/decisions``  — ring summary (recent ticks, volumes).
* ``GET /debug/explain?key=<ns/name>`` — per-cluster verdicts for one
  object ("filtered: resources_fit", "feasible, cut by max_clusters",
  "selected, replicas=3", ...).
* ``GET /debug/drift`` — placement drift listing, fed by providers
  registered here (federation/monitor.py's drift detector).

Sizing: packed-format records cost ~300 bytes flat; dense-format
records add ~2 bytes per (object, cluster) pair (the int16 reason
row).  The ring keeps at most ``max_ticks`` tick entries and evicts
oldest-first past ``max_bytes``, but always retains the most recent
tick so a cold full-batch schedule stays explainable.  Knobs:
``KT_FLIGHTREC`` (0 disables), ``KT_FLIGHTREC_TICKS``,
``KT_FLIGHTREC_BYTES``, ``KT_FLIGHTREC_TOPK``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Mapping, Optional, Sequence

import numpy as np

from kubeadmiral_tpu.ops import reasons as RSN
from kubeadmiral_tpu.runtime import lockcheck


class DecisionRecord:
    """One object's scheduling decision, as of ``tick``.

    The format-independent core (identical whichever fetch format the
    engine ran): ``placements``, ``reason_counts`` (clusters rejected
    per reason bit, ops.reasons.REASON_BITS order), ``feasible_n``, and
    the top-k scores among the SELECTED clusters.  ``reasons`` — the
    full per-cluster mask row — is carried only when the fetch shipped
    it (KT_FETCH_FORMAT=dense, or a packed-overflow row's dense
    refetch never includes it); packed-mode records hold None there and
    /debug/explain falls back to the summary counts."""

    __slots__ = (
        "key", "tick", "when", "program", "placements", "reasons",
        "reason_counts", "feasible_n", "topk_idx", "topk_scores", "names",
    )

    def __init__(self, key, tick, when, program, placements, reasons,
                 reason_counts, feasible_n, topk_idx, topk_scores, names):
        self.key = key
        self.tick = tick
        self.when = when
        self.program = program
        self.placements = placements      # Mapping[str, Optional[int]]
        self.reasons = reasons            # np.int16[C] or None (packed)
        self.reason_counts = reason_counts  # np.int64[NUM_REASON_BITS]
        self.feasible_n = feasible_n      # int
        self.topk_idx = topk_idx          # np.int32[k] selected cluster idx
        self.topk_scores = topk_scores    # np.int64[k] matching scores
        self.names = names                # tuple[str, ...] (shared per tick)

    @property
    def nbytes(self) -> int:
        dense = self.reasons.nbytes if self.reasons is not None else 0
        return int(dense + self.reason_counts.nbytes + self.topk_idx.nbytes
                   + self.topk_scores.nbytes) + 200


class _TickEntry:
    __slots__ = ("tick", "when", "objects", "clusters", "records",
                 "nbytes", "programs")

    def __init__(self, tick, when, objects, clusters):
        self.tick = tick
        self.when = when
        self.objects = objects
        self.clusters = clusters
        self.records: dict[str, DecisionRecord] = {}
        self.nbytes = 0
        self.programs: set[str] = set()


@lockcheck.shared_field_guard
class FlightRecorder:
    # Ring/index state fed by the engine's fetch stage and read by
    # /debug/explain server threads (ktlint lock-discipline +
    # runtime/lockcheck.py).
    _shared_fields_ = {
        "_ring": "_lock",
        "_index": "_lock",
        "_tick_seq": "_lock",
        "_bytes": "_lock",
        "_current": "_lock",
    }

    def __init__(
        self,
        max_ticks: Optional[int] = None,
        max_bytes: Optional[int] = None,
        topk: Optional[int] = None,
        enabled: Optional[bool] = None,
        clock=time.time,
    ):
        env = os.environ
        self.max_ticks = int(env.get("KT_FLIGHTREC_TICKS", "8")) if max_ticks is None else max_ticks
        self.max_bytes = int(env.get("KT_FLIGHTREC_BYTES", str(256 << 20))) if max_bytes is None else max_bytes
        self.topk = int(env.get("KT_FLIGHTREC_TOPK", "8")) if topk is None else topk
        self.enabled = (env.get("KT_FLIGHTREC", "1") != "0") if enabled is None else enabled
        self.clock = clock
        self._lock = lockcheck.make_lock("flightrec")
        self._ring: deque[_TickEntry] = deque()
        self._index: dict[str, DecisionRecord] = {}
        self._tick_seq = 0
        self._bytes = 0
        self._current: Optional[_TickEntry] = None
        # Cluster-name tuple interning: one tuple shared by every record
        # of a topology, not one list per record.
        self._names_cache: Optional[tuple[str, ...]] = None

    # -- recording (engine-facing) ---------------------------------------
    def begin_tick(self, objects: int, clusters: int) -> int:
        with self._lock:
            self._tick_seq += 1
            self._current = _TickEntry(
                self._tick_seq, self.clock(), objects, clusters
            )
            return self._tick_seq

    def end_tick(self) -> None:
        with self._lock:
            self._current = None
            self._evict_locked()

    def record_rows(
        self,
        keys: Sequence[str],
        placements: Sequence[Mapping[str, Optional[int]]],
        reasons: Optional[np.ndarray],  # int[n, >=C] or None (packed fetch)
        scores: Optional[np.ndarray],   # int[n, >=C] or None
        names: Sequence[str],
        program: str = "",
        reason_counts: Optional[np.ndarray] = None,  # int[n, NUM_REASON_BITS]
        feasible_n: Optional[np.ndarray] = None,     # int[n]
        topk_idx: Optional[np.ndarray] = None,       # int[n, <=topk]
        topk_scores: Optional[np.ndarray] = None,
    ) -> None:
        """Record a batch of fetched rows for the current tick.  Padded
        cluster columns are masked out (sliced to ``len(names)``);
        callers pass only real (non-padded) object rows.

        The dense fetch format passes ``reasons`` (and optionally
        ``scores``) and the compact fields are derived here; the packed
        format passes ``reason_counts``/``feasible_n``/``topk_*``
        straight off the wire — both produce the SAME record core, so
        packed-vs-dense A/B records are identical apart from the dense
        path's extra per-cluster mask row."""
        if not self.enabled or not keys:
            return
        c = len(names)
        n = len(keys)
        k = min(self.topk, c)
        name_idx = {nm: j for j, nm in enumerate(names)}
        if reasons is not None:
            reasons = np.asarray(reasons)[:, :c].astype(np.int16)
            if reason_counts is None:
                r32 = reasons.astype(np.int64)
                reason_counts = np.stack(
                    [((r32 & bit) != 0).sum(axis=1) for bit in RSN.REASON_BITS],
                    axis=1,
                )
            if feasible_n is None:
                feasible_n = ((reasons & RSN.FILTER_REASON_MASK) == 0).sum(axis=1)
        if reason_counts is None:
            reason_counts = np.zeros((n, RSN.NUM_REASON_BITS), np.int64)
        reason_counts = np.asarray(reason_counts, dtype=np.int64)
        if feasible_n is None:
            feasible_n = np.zeros(n, np.int64)
        feasible_n = np.asarray(feasible_n)
        if topk_idx is None and scores is not None:
            # Top-k among the SELECTED clusters ("why these won"): rank
            # by score desc, index asc — the select stage's tie order.
            scores = np.asarray(scores)[:, :c]
            topk_idx, topk_scores = [], []
            for i in range(n):
                sel = sorted(
                    (j for nm in placements[i] if (j := name_idx.get(nm)) is not None)
                )
                ranked = sorted(sel, key=lambda j: (-int(scores[i, j]), j))[:k]
                topk_idx.append(np.asarray(ranked, np.int32))
                topk_scores.append(
                    np.asarray([int(scores[i, j]) for j in ranked], np.int64)
                )
        if topk_idx is None:
            empty_i = np.zeros(0, np.int32)
            empty_s = np.zeros(0, np.int64)
            topk_idx = [empty_i] * n
            topk_scores = [empty_s] * n
        with self._lock:
            entry = self._current
            if entry is None:  # recording outside a tick: tolerate
                self._tick_seq += 1
                entry = self._current = _TickEntry(
                    self._tick_seq, self.clock(), n, c
                )
            if self._names_cache is None or tuple(self._names_cache) != tuple(names):
                self._names_cache = tuple(names)
            names_t = self._names_cache
            if not entry.records and entry not in self._ring:
                self._ring.append(entry)
            if program:
                entry.programs.add(program)
            when = entry.when
            for i, key in enumerate(keys):
                rec = DecisionRecord(
                    key=key,
                    tick=entry.tick,
                    when=when,
                    program=program,
                    placements=placements[i],
                    reasons=reasons[i] if reasons is not None else None,
                    reason_counts=reason_counts[i],
                    feasible_n=int(feasible_n[i]),
                    topk_idx=np.asarray(topk_idx[i], np.int32),
                    topk_scores=np.asarray(topk_scores[i], np.int64),
                    names=names_t,
                )
                old = entry.records.get(key)
                if old is not None:
                    entry.nbytes -= old.nbytes
                    self._bytes -= old.nbytes
                entry.records[key] = rec
                entry.nbytes += rec.nbytes
                self._bytes += rec.nbytes
                self._index[key] = rec
            self._evict_locked()

    def _evict_locked(self) -> None:
        while len(self._ring) > 1 and (
            len(self._ring) > self.max_ticks or self._bytes > self.max_bytes
        ):
            evicted = self._ring.popleft()
            self._bytes -= evicted.nbytes
            for key, rec in evicted.records.items():
                if self._index.get(key) is rec:
                    del self._index[key]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._index.clear()
            self._bytes = 0
            self._current = None
            self._names_cache = None

    # -- durable state (runtime/snapshot.py) -------------------------------
    def export_state(self) -> dict:
        """Restart-durable image of the ring: every tick entry's records
        as plain fields (numpy arrays pickle verbatim).  Restoring this
        into a successor makes /debug/explain and the kill-matrix's
        reason-count comparison identical to an uninterrupted process —
        rows the successor resumes via the no-op replay never re-record,
        so without this their decisions would be unexplainable."""
        with self._lock:
            ticks = []
            for e in self._ring:
                ticks.append({
                    "tick": e.tick, "when": e.when, "objects": e.objects,
                    "clusters": e.clusters, "programs": sorted(e.programs),
                    "records": [
                        {
                            "key": r.key, "program": r.program,
                            "placements": dict(r.placements),
                            "reasons": r.reasons,
                            "reason_counts": r.reason_counts,
                            "feasible_n": r.feasible_n,
                            "topk_idx": r.topk_idx,
                            "topk_scores": r.topk_scores,
                            "names": tuple(r.names),
                        }
                        for r in e.records.values()
                    ],
                })
            return {"tick_seq": self._tick_seq, "ticks": ticks}

    def restore_state(self, payload: dict) -> None:
        """Rebuild the ring from an exported image.  Tick ids continue
        from the snapshot's sequence so restored and freshly recorded
        ticks stay ordered."""
        with self._lock:
            self._ring.clear()
            self._index.clear()
            self._bytes = 0
            self._current = None
            self._tick_seq = max(self._tick_seq, int(payload.get("tick_seq", 0)))
            for t in payload.get("ticks", ()):
                entry = _TickEntry(
                    t["tick"], t["when"], t["objects"], t["clusters"]
                )
                entry.programs = set(t.get("programs", ()))
                for rd in t.get("records", ()):
                    rec = DecisionRecord(
                        key=rd["key"], tick=entry.tick, when=entry.when,
                        program=rd.get("program", ""),
                        placements=rd["placements"],
                        reasons=rd.get("reasons"),
                        reason_counts=np.asarray(rd["reason_counts"], np.int64),
                        feasible_n=int(rd["feasible_n"]),
                        topk_idx=np.asarray(rd["topk_idx"], np.int32),
                        topk_scores=np.asarray(rd["topk_scores"], np.int64),
                        names=tuple(rd.get("names", ())),
                    )
                    entry.records[rec.key] = rec
                    entry.nbytes += rec.nbytes
                    self._bytes += rec.nbytes
                    self._index[rec.key] = rec
                if entry.records:
                    self._ring.append(entry)
            self._evict_locked()

    # -- introspection (HTTP-facing) -------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "ticks_seen": self._tick_seq,
                "ring_ticks": len(self._ring),
                "records": len(self._index),
                "bytes": self._bytes,
                "max_ticks": self.max_ticks,
                "max_bytes": self.max_bytes,
                "topk": self.topk,
            }

    def decisions(self) -> dict:
        """Ring summary for GET /debug/decisions."""
        now = self.clock()
        with self._lock:
            ticks = [
                {
                    "tick": e.tick,
                    "age_seconds": round(now - e.when, 3),
                    "objects": e.objects,
                    "clusters": e.clusters,
                    "recorded_rows": len(e.records),
                    "bytes": e.nbytes,
                    "programs": sorted(e.programs),
                }
                for e in self._ring
            ]
        out = self.stats()
        out["ticks"] = ticks
        return out

    def lookup(self, key: str) -> Optional[DecisionRecord]:
        with self._lock:
            return self._index.get(key)

    def explain(self, key: str) -> Optional[dict]:
        """Human-readable per-cluster verdicts for GET /debug/explain.

        With a dense record (full per-cluster masks) every cluster gets
        a verdict, as before.  A packed record covers the selected
        clusters individually and aggregates the rejections under
        ``rejected`` (reason slug -> cluster count) — the designed
        fidelity trade of KT_FETCH_FORMAT=packed; run dense for
        per-pair verdicts."""
        rec = self.lookup(key)
        if rec is None:
            return None
        top_by_idx = {
            int(j): (rank, int(s))
            for rank, (j, s) in enumerate(zip(rec.topk_idx, rec.topk_scores), 1)
            if s > np.iinfo(np.int64).min
        }
        feasible_n = int(rec.feasible_n)
        clusters = {}
        if rec.reasons is not None:
            for j, name in enumerate(rec.names):
                mask = int(rec.reasons[j])
                verdict = _verdict(
                    mask, rec.placements.get(name, _MISSING),
                    top_by_idx.get(j), feasible_n,
                )
                clusters[name] = verdict
        else:
            nidx = {nm: j for j, nm in enumerate(rec.names)}
            for name, reps in rec.placements.items():
                j = nidx.get(name)
                clusters[name] = _verdict(
                    0, reps, top_by_idx.get(j) if j is not None else None,
                    feasible_n,
                )
        rejected = {
            RSN.REASON_NAMES[bit]: int(count)
            for bit, count in zip(RSN.REASON_BITS, rec.reason_counts)
            if count
        }
        return {
            "key": key,
            "tick": rec.tick,
            "age_seconds": round(self.clock() - rec.when, 3),
            "program": rec.program,
            "placements": {
                cl: (None if reps is None else int(reps))
                for cl, reps in rec.placements.items()
            },
            "feasible_clusters": feasible_n,
            "clusters": clusters,
            "rejected": rejected,
        }


_MISSING = object()


def _verdict(mask, replicas, top_rank, feasible_n) -> dict:
    """One (object, cluster) verdict: the reason slugs plus a sentence."""
    slugs = RSN.describe(mask)
    if mask == 0 and replicas is not _MISSING:
        text = (
            "selected (no replica count)"
            if replicas is None
            else f"selected, replicas={int(replicas)}"
        )
    elif mask == 0:
        # Selected by the recorded tick but absent from the decoded
        # placement map — only possible for padded rows, which callers
        # never record; keep a faithful fallback.
        text = "selected"
    elif mask & RSN.REASON_STICKY:
        text = "cut by sticky_cluster (object is stickily placed)"
    elif mask & RSN.FILTER_REASON_MASK:
        text = "filtered: " + ", ".join(
            RSN.describe(mask & RSN.FILTER_REASON_MASK)
        )
    elif mask & RSN.REASON_MAX_CLUSTERS:
        if top_rank is not None:
            rank, score = top_rank
            text = (
                f"feasible, scored {score}, rank {rank}/{feasible_n}, "
                f"cut by maxClusters"
            )
        else:
            text = (
                f"feasible but below the recorded top-k of {feasible_n} "
                f"feasible clusters, cut by maxClusters"
            )
    elif mask & RSN.REASON_ZERO_REPLICAS:
        text = "selected by top-K but the replica planner assigned 0"
    else:
        text = "rejected: " + ", ".join(slugs)
    out = {"reasons": slugs, "verdict": text}
    if top_rank is not None:
        rank, score = top_rank
        out["score"] = score
        out["rank"] = rank
    return out


def summarize_reasons(rec: DecisionRecord, limit: int = 4) -> str:
    """Aggregate one record's rejection-reason counts into a short
    operator string ("resources_fit x3, taint_toleration x1") — the
    ScheduleFailed event message vocabulary.  Fed by reason_counts, so
    packed- and dense-format records summarize identically."""
    counts = {
        RSN.REASON_NAMES[bit]: int(n)
        for bit, n in zip(RSN.REASON_BITS, rec.reason_counts)
        if n
    }
    parts = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))[:limit]
    return ", ".join(f"{slug} x{n}" for slug, n in parts)


# -- process-wide default (the engine and HTTP servers meet here) --------
_default = FlightRecorder()


def get_default() -> FlightRecorder:
    return _default


# -- drift providers ------------------------------------------------------
# federation/monitor.py's drift detector registers a snapshot callable;
# GET /debug/drift renders every registered provider.  Kept here (not in
# profiling.py) so runtime/ has no federation/ import and any controller
# can contribute a drift view.
_drift_lock = threading.Lock()
_drift_providers: dict[str, Callable[[], dict]] = {}


def register_drift_provider(name: str, fn: Callable[[], dict]) -> None:
    with _drift_lock:
        _drift_providers[name] = fn


def unregister_drift_provider(name: str) -> None:
    with _drift_lock:
        _drift_providers.pop(name, None)


def drift_report() -> dict:
    with _drift_lock:
        providers = dict(_drift_providers)
    out: dict = {"providers": sorted(providers)}
    drifted: list[dict] = []
    for name, fn in sorted(providers.items()):
        try:
            snap = fn()
        except Exception as e:  # a broken provider must not 500 the route
            snap = {"error": repr(e)}
        out[name] = snap
        drifted.extend(snap.get("drifted", ()) if isinstance(snap, dict) else ())
    out["drifted"] = drifted
    out["drifted_total"] = len(drifted)
    return out
