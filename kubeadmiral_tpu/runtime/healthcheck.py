"""Mutable health-check registry + HTTP endpoints.

Mirrors the reference's controller-manager health surface (reference:
pkg/controllermanager/healthcheck/handler.go, served from
cmd/controller-manager/app/controllermanager.go:55-121): a mutable set of
named liveness/readiness checks — controllers register an
``IsControllerReady``-style predicate as they start — exposed at
``/livez`` and ``/readyz`` (200 when every check passes, 500 with the
failing names otherwise; ``?verbose`` lists each check).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

Check = Callable[[], bool]


class HealthCheckRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._liveness: dict[str, Check] = {}
        self._readiness: dict[str, Check] = {}

    def add_liveness(self, name: str, check: Check) -> None:
        with self._lock:
            self._liveness[name] = check

    def add_readiness(self, name: str, check: Check) -> None:
        with self._lock:
            self._readiness[name] = check

    def remove(self, name: str) -> None:
        with self._lock:
            self._liveness.pop(name, None)
            self._readiness.pop(name, None)

    def _run(self, checks: dict[str, Check]) -> dict[str, bool]:
        with self._lock:
            snapshot = dict(checks)
        results = {}
        for name, check in snapshot.items():
            try:
                results[name] = bool(check())
            except Exception:
                results[name] = False
        return results

    def livez(self) -> dict[str, bool]:
        return self._run(self._liveness)

    def readyz(self) -> dict[str, bool]:
        # Readiness implies liveness, as the reference wires both into
        # the same mutable handler.
        return {**self._run(self._liveness), **self._run(self._readiness)}


class HealthServer:
    """Serves the registry at /livez + /readyz (controllermanager.go's
    health HTTP server, default port 11257).  When given a ``metrics``
    registry / ``tracer`` it additionally serves ``/metrics`` (Prometheus
    text format) and ``/debug/trace`` (Chrome trace JSON) alongside the
    pprof-analogue ``/debug/*`` routes, the decision-audit routes
    ``/debug/decisions`` / ``/debug/explain`` / ``/debug/drift``
    (runtime/flightrec.py), the member-health route
    ``/debug/members`` (transport/breaker.py), the end-to-end SLO
    route ``/debug/slo`` (runtime/slo.py), the telemetry timeline
    ``/debug/timeline`` (runtime/timeline.py), the tenant attribution
    route ``/debug/tenants`` (runtime/tenancy.py) and the bare
    ``/debug`` index — one port for the whole operability surface."""

    def __init__(
        self,
        registry: HealthCheckRegistry,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics=None,
        tracer=None,
        flightrec=None,
        drift=None,
        members=None,
        slo=None,
        timeline=None,
        tenants=None,
    ):
        self.registry = registry
        self.metrics = metrics
        self.tracer = tracer
        self.flightrec = flightrec
        self.drift = drift
        self.members = members
        self.slo = slo
        self.timeline = timeline
        self.tenants = tenants
        self._host = host
        self._port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        assert self._server is not None, "server not started"
        return self._server.server_address[1]

    def start(self) -> int:
        registry = self.registry
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path, _, raw_query = self.path.partition("?")
                if (
                    path == "/debug"
                    or path.startswith("/debug/")
                    or path == "/metrics"
                ):
                    # Shared operability routes (profiling.py): metrics
                    # exposition, trace export, profile/stacks/threads.
                    from kubeadmiral_tpu.runtime import profiling

                    if not profiling.respond_debug(
                        self, path, raw_query,
                        metrics=outer.metrics, tracer=outer.tracer,
                        flightrec=outer.flightrec, drift=outer.drift,
                        members=outer.members, slo=outer.slo,
                        timeline=outer.timeline, tenants=outer.tenants,
                    ):
                        self.send_error(404)
                    return
                if path == "/livez":
                    results = registry.livez()
                elif path == "/readyz":
                    results = registry.readyz()
                else:
                    self.send_error(404)
                    return
                healthy = all(results.values())
                body = json.dumps(
                    {"healthy": healthy, "checks": results}
                ).encode()
                self.send_response(200 if healthy else 500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="health-server", daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
