"""Informer: cached LIST+WATCH over one resource of one apiserver.

The thin analogue of the reference's shared informers and
FederatedInformer (reference: pkg/controllers/util/federatedinformer.go):
a local object cache kept in sync by watch events, with handler fan-out
and a federated variant that multiplexes per-cluster stores
(FederatedReadOnlyStore semantics: GetFromAllClusters / ClustersSynced).
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional

from kubeadmiral_tpu.runtime import slo, trace
from kubeadmiral_tpu.testing.fakekube import ADDED, DELETED, FakeKube, obj_key

Handler = Callable[[str, dict], None]


class Informer:
    """Cache entries are SHARED dicts (the same snapshot the store hands
    every watcher): handlers and ``get()``/``list()`` consumers must not
    mutate them — copy anything you modify or retain, exactly as with
    real informer caches."""

    def __init__(self, kube: FakeKube, resource: str):
        self.kube = kube
        self.resource = resource
        self._lock = threading.RLock()
        self._cache: dict[str, dict] = {}
        self._handlers: list[Handler] = []
        kube.watch(resource, self._on_event, replay=True)

    def close(self) -> None:
        """Detach from the apiserver; no further events are delivered."""
        self.kube.unwatch(self.resource, self._on_event)
        with self._lock:
            self._handlers.clear()
            self._cache.clear()

    def _on_event(self, event: str, obj: dict) -> None:
        key = obj_key(obj)
        with self._lock:
            if event == DELETED:
                self._cache.pop(key, None)
            else:
                self._cache[key] = obj
            handlers = list(self._handlers)
        # SLO provenance fallback ingress: stores whose own watch
        # fan-out already mints tokens (FakeKube, HttpKube) mark
        # themselves _slo_ingress; anything else gets its birth
        # timestamp here, once per event, before handler fan-out.
        if not getattr(self.kube, "_slo_ingress", False):
            slo.ingest(self.kube, self.resource, event, obj)
        # The root span of the reconcile path: handler work (enqueues,
        # trigger checks) nests under the event that caused it.  Sampled
        # (KT_TRACE_SAMPLE_N): a 300k-event storm must not pay a span
        # record per event.
        with trace.hot_span(
            "informer.event", resource=self.resource, event=event, key=key
        ):
            for h in handlers:
                h(event, obj)

    def add_handler(self, handler: Handler, replay: bool = True) -> None:
        with self._lock:
            self._handlers.append(handler)
            snapshot = list(self._cache.values()) if replay else ()
        for obj in snapshot:
            handler(ADDED, obj)

    def get(self, key: str) -> Optional[dict]:
        with self._lock:
            return self._cache.get(key)

    def list(self) -> list[dict]:
        with self._lock:
            return list(self._cache.values())

    def keys(self) -> list[str]:
        with self._lock:
            return list(self._cache)


class MemberStore:
    """Per-cluster member-object caches fed by replayed member watches —
    the FederatedReadOnlyStore the status controllers read instead of
    issuing one member GET per (object x cluster) per reconcile
    (reference: pkg/controllers/util/federatedinformer.go:121-132; the
    status controller builds clusterStatus from cached informers,
    status/controller.go:291-450).

    Entries are store views (in-process fleets) or fresh watch-frame
    parses (HTTP): consumers must treat them as read-only and copy
    anything they retain and mutate.
    """

    def __init__(self, fleet, resource: str, on_event=None):
        self.fleet = fleet
        self.resource = resource
        self._lock = threading.Lock()
        self._objs: dict[str, dict[str, dict]] = {}  # cluster -> key -> obj
        # Set BEFORE the watch attaches: replayed initial-LIST events
        # arrive synchronously from inside watch_members.
        self._on_event = on_event
        self._attach = fleet.watch_members(
            resource, self._handle, named=True, replay=True
        )

    def _handle(self, cluster: str, event: str, obj: dict) -> None:
        key = obj_key(obj)
        with self._lock:
            if event == DELETED:
                held = self._objs.get(cluster)
                if held is not None:
                    held.pop(key, None)
            else:
                self._objs.setdefault(cluster, {})[key] = obj
        cb = self._on_event
        if cb is not None:
            cb(cluster, event, obj)

    def reattach(self) -> None:
        """Attach watches for clusters that joined after construction."""
        self._attach()

    def evict(self, cluster: str) -> None:
        """Drop a removed cluster's watch and cached objects (the
        FederatedInformer remove-cluster lifecycle): without this, the
        store would keep serving a deleted cluster's last-known objects
        as live.  Sticky: reattach() skips the cluster until
        readmit(cluster) lifts the eviction (a re-created cluster's
        lifecycle event does that)."""
        detach = getattr(self._attach, "detach", None)
        if detach is not None:
            detach(cluster)
        with self._lock:
            self._objs.pop(cluster, None)

    def readmit(self, cluster: str) -> None:
        """Lift an eviction after the cluster's object re-appeared."""
        readmit = getattr(self._attach, "readmit", None)
        if readmit is not None:
            readmit(cluster)

    @property
    def pending(self) -> set:
        """Clusters whose watch attach failed transiently (HTTP fleets:
        join secret not yet readable) — the retry channel."""
        return set(getattr(self._attach, "pending", None) or ())

    def attached(self, cluster: str) -> bool:
        att = getattr(self._attach, "attached", None)
        if att is not None:
            return cluster in att
        try:  # fleets predating the attached-set contract
            self.fleet.member(cluster)
            return True
        except Exception:
            return False

    def get(self, cluster: str, key: str) -> Optional[dict]:
        with self._lock:
            held = self._objs.get(cluster)
            return None if held is None else held.get(key)


class FederatedInformer:
    """Per-ready-cluster informers for one target resource."""

    def __init__(self, resource: str):
        self.resource = resource
        self._lock = threading.RLock()
        self._informers: dict[str, Informer] = {}
        self._handlers: list[Callable[[str, str, dict], None]] = []  # (cluster, event, obj)

    def add_cluster(self, name: str, kube: FakeKube) -> None:
        with self._lock:
            if name in self._informers:
                return
            informer = Informer(kube, self.resource)
            self._informers[name] = informer
            informer.add_handler(
                lambda event, obj, _n=name: self._fanout(_n, event, obj),
                replay=True,
            )

    def remove_cluster(self, name: str) -> None:
        with self._lock:
            informer = self._informers.pop(name, None)
        if informer is not None:
            informer.close()

    def _fanout(self, cluster: str, event: str, obj: dict) -> None:
        with self._lock:
            handlers = list(self._handlers)
        for h in handlers:
            h(cluster, event, obj)

    def add_handler(self, handler: Callable[[str, str, dict], None]) -> None:
        with self._lock:
            self._handlers.append(handler)

    def clusters(self) -> list[str]:
        with self._lock:
            return list(self._informers)

    def get(self, cluster: str, key: str) -> Optional[dict]:
        with self._lock:
            informer = self._informers.get(cluster)
        return informer.get(key) if informer else None

    def get_from_all(self, key: str) -> dict[str, dict]:
        out = {}
        with self._lock:
            items = list(self._informers.items())
        for name, informer in items:
            obj = informer.get(key)
            if obj is not None:
                out[name] = obj
        return out
