"""The knob catalog: every ``KT_*`` environment knob this control plane
reads.

Sibling of :mod:`kubeadmiral_tpu.runtime.metric_catalog`, with the same
contract: ONE source of truth, three consumers.

* ``tools/ktlint`` (rule ``knob-catalog``, run by ``make lint``) walks
  every source tree for ``os.environ``/``getenv``/env-helper reads of
  literal ``KT_*`` names and FAILS on names not listed here — a new
  knob must be cataloged (and thereby documented) before it ships.  The
  same rule cross-checks the docs: every ``KT_*`` token mentioned under
  ``docs/`` must be cataloged, and every catalog entry must be both
  read somewhere in code and documented in its anchor file — zero
  orphans in either direction (the pre-ktlint state was 61 knobs read
  vs 63 named in docs, with no check either way).
* ``docs/operations.md`` / ``docs/observability.md`` render the
  operator-facing knob tables; ``anchor`` names the file that owns a
  knob's row.
* Tests assert the catalog's shape so the vocabulary cannot drift
  silently (tests/test_ktlint.py).

Naming rules: public knobs match ``^KT_[A-Z0-9_]+$``.  Process-internal
sentinels (subprocess handshakes like ``_KT_DRYRUN_SUBPROCESS``) carry
a leading underscore and are exempt from the catalog by convention.
"""

from __future__ import annotations

from typing import NamedTuple


class KnobSpec(NamedTuple):
    type: str     # bool | int | float | str | path
    default: str  # rendered default ("" = unset)
    anchor: str   # docs file owning the operator-facing row
    help: str


_OPS = "operations.md"
_OBS = "observability.md"

KNOBS: dict[str, KnobSpec] = {
    # -- engine geometry & fast paths (scheduler/engine.py) --------------
    "KT_CELL_BUDGET": KnobSpec(
        "int", "4096*5120", _OPS,
        "Megachunk sizing: cells (rows x padded clusters) per chunk dispatch."),
    "KT_MEGACHUNK_ROWS": KnobSpec(
        "int", "4096", _OPS,
        "Independent cap on rows per chunk at any cluster width."),
    "KT_DONATE": KnobSpec(
        "bool", "1", _OPS,
        "Donate the previous tick's output planes into each tick dispatch."),
    "KT_PIPELINE_DEPTH": KnobSpec(
        "int", "16", _OPS,
        "In-flight chunk window before the batched device->host drain."),
    "KT_FETCH_FORMAT": KnobSpec(
        "str", "packed", _OPS,
        "Result-fetch wire format: packed [B,K] slots or dense [B,C] planes."),
    "KT_PACK_K": KnobSpec(
        "int", "16", _OPS,
        "Minimum packed-slot bucket K (adapts per chunk from observed counts)."),
    "KT_PACK_OVERFLOW_PCT": KnobSpec(
        "float", "0.01", _OPS,
        "Adaptive-K target overflow fraction."),
    "KT_PACK_WIDEN": KnobSpec(
        "float", "1.25", _OPS,
        "Adaptive-K widen-once cap."),
    "KT_NARROW": KnobSpec(
        "bool", "1", _OPS,
        "Narrow [B,M] candidate solve with per-row exactness certificate."),
    "KT_NARROW_M": KnobSpec(
        "int", "128", _OPS,
        "Floor for the narrow candidate width M."),
    "KT_REPLAN": KnobSpec(
        "bool", "1", _OPS,
        "Fit-flip survivors ride the selection-known replan / score-only kernels."),
    "KT_DRIFT_RESOLVE": KnobSpec(
        "bool", "1", _OPS,
        "Sort-free survivor resolve from stored planes on drift ticks."),
    "KT_SURVIVOR_UNIFIED": KnobSpec(
        "bool", "1", _OPS,
        "One unified survivor kernel per gated chunk (vs three streams)."),
    "KT_SURVIVOR_ROWSHARD": KnobSpec(
        "bool", "1", _OPS,
        "Rows-first sharding for gathered survivor sub-problems under a mesh."),
    "KT_SCORE_F16": KnobSpec(
        "bool", "0", _OPS,
        "f16 compression of the resident prev SCORE plane (exactness-guarded)."),
    "KT_PHASE1_I32": KnobSpec(
        "bool", "1", _OPS,
        "i32 phase-1 arithmetic where the range analysis allows."),
    "KT_DELTA_FEAT": KnobSpec(
        "bool", "1", _OPS,
        "Row-wise featurize patches + streaming dirty-row hints."),
    "KT_PALLAS": KnobSpec(
        "bool", "0", _OPS,
        "Fused Pallas phase-1 front for the narrow slab programs."),
    "KT_HBM_BUDGET_GB": KnobSpec(
        "float", "16", _OPS,
        "Per-device HBM budget the c6 memory census compares against."),
    "KT_COMPILE_CACHE_DIR": KnobSpec(
        "path", "~/.cache/kubeadmiral_tpu/xla-cache", _OPS,
        "Persistent XLA compilation-cache location (empty/0 disables)."),
    "KT_DRYRUN_LARGE": KnobSpec(
        "str", "2048x512,1024x5120", _OPS,
        "Large sharding-validation shapes in __graft_entry__.dryrun_multichip."),
    # -- AOT store & restart (scheduler/aot.py, runtime/snapshot.py) -----
    "KT_AOT": KnobSpec(
        "bool", "1", _OPS,
        "AOT program store: warm boots preload jax.export artifacts."),
    "KT_AOT_DIR": KnobSpec(
        "path", "<compile-cache>/aot", _OPS,
        "AOT manifest root override (bench/restart isolation)."),
    "KT_SNAPSHOT_DIR": KnobSpec(
        "path", "", _OPS,
        "Durable engine-snapshot directory (unset disables snapshots)."),
    "KT_SNAPSHOT_KEEP": KnobSpec(
        "int", "2", _OPS,
        "Snapshot generations retained."),
    "KT_SNAPSHOT_EVERY": KnobSpec(
        "int", "1", _OPS,
        "Persist every Nth converged state-changing tick."),
    "KT_SNAPSHOT_KILL": KnobSpec(
        "str", "", _OPS,
        "Fault injection for the SIGKILL matrix: die mid-write/pre-rename."),
    "KT_SHUTDOWN_DEADLINE_S": KnobSpec(
        "float", "10", _OPS,
        "SIGTERM drain deadline before hard exit."),
    # -- streaming front-end (scheduler/streaming.py) --------------------
    "KT_SLAB_ROWS": KnobSpec(
        "int", "1024", _OPS,
        "Row-slab size watermark (per-device under a mesh)."),
    "KT_SLAB_AGE_MS": KnobSpec(
        "float", "50", _OPS,
        "Row-slab age watermark."),
    "KT_SLAB_GROW": KnobSpec(
        "int", "<engine chunk>", _OPS,
        "Placeholder-pool grow block."),
    # -- logging & concurrency harness (runtime/) ------------------------
    "KT_LOG_LEVEL": KnobSpec(
        "str", "WARNING", _OPS,
        "Level for the kubeadmiral.* logger tree."),
    "KT_LOG_JSON": KnobSpec(
        "bool", "0", _OPS,
        "JSON-lines log emission."),
    "KT_LOCKCHECK": KnobSpec(
        "bool", "0", _OPS,
        "Instrumented locks + declared-shared-field write guard "
        "(runtime/lockcheck.py; tests enable it suite-wide)."),
    # -- observability (runtime/devprof.py, flightrec.py, slo.py) --------
    "KT_DEVPROF": KnobSpec(
        "bool", "1", _OPS,
        "Dispatch ledger: per-program device-time attribution."),
    "KT_DEVPROF_TICKS": KnobSpec(
        "int", "8", _OPS,
        "Tick waterfalls kept in the ledger ring."),
    "KT_PROFILE_DIR": KnobSpec(
        "path", "/tmp/kt-jax-profile", _OPS,
        "Root directory for on-demand jax.profiler artifacts."),
    "KT_PROFILE_TICKS": KnobSpec(
        "int", "0", _OPS,
        "Bench-side jax.profiler capture around N scheduling ticks."),
    "KT_FLIGHTREC": KnobSpec(
        "bool", "1", _OBS,
        "Decision flight recorder master switch."),
    "KT_FLIGHTREC_TICKS": KnobSpec(
        "int", "8", _OBS,
        "Flight-recorder tick ring size."),
    "KT_FLIGHTREC_BYTES": KnobSpec(
        "int", "256<<20", _OBS,
        "Flight-recorder byte budget."),
    "KT_FLIGHTREC_TOPK": KnobSpec(
        "int", "8", _OBS,
        "Per-decision top-K score introspection width."),
    "KT_TRACE_SAMPLE_N": KnobSpec(
        "int", "64", _OBS,
        "Hot-path span sampling: trace 1 in N per-event/per-key spans "
        "(1 = trace everything, 0 = trace none); ticks and "
        "once-per-batch spans stay unconditional."),
    "KT_SLO": KnobSpec(
        "bool", "1", _OPS,
        "Provenance-token SLO path master switch."),
    "KT_SLO_E2E_P99_S": KnobSpec(
        "float", "5.0", _OPS,
        "event_to_written_p99 objective threshold."),
    "KT_SLO_WRITE_P99_S": KnobSpec(
        "float", "2.0", _OPS,
        "member_write_p99 objective threshold."),
    "KT_SLO_FRESHNESS_S": KnobSpec(
        "float", "30", _OPS,
        "freshness objective threshold (oldest pending event age)."),
    "KT_SLO_WINDOWS_S": KnobSpec(
        "str", "60,300", _OPS,
        "Burn-rate windows (seconds, comma-separated)."),
    "KT_SLO_EXEMPLARS": KnobSpec(
        "int", "32", _OPS,
        "Slowest-N exemplar ring at /debug/slo."),
    "KT_SLO_PENDING_CAP": KnobSpec(
        "int", "200000", _OPS,
        "Bound on in-flight provenance tokens."),
    "KT_SLO_MAX_AGE_S": KnobSpec(
        "float", "0", _OPS,
        "Age-out for pending tokens (0 = never)."),
    # -- member transport & dispatch (transport/, federation/dispatch.py) -
    "KT_BREAKER_FAILURES": KnobSpec(
        "int", "3", _OPS,
        "Consecutive failures that open a member's breaker."),
    "KT_BREAKER_STALL_S": KnobSpec(
        "float", "1.0", _OPS,
        "Single-round-trip stall threshold (opens immediately)."),
    "KT_BREAKER_LATENCY_S": KnobSpec(
        "float", "5.0", _OPS,
        "Latency-EWMA open threshold."),
    "KT_BREAKER_OPEN_S": KnobSpec(
        "float", "5.0", _OPS,
        "Cool-down before half-open."),
    "KT_DISPATCH_DEADLINE_S": KnobSpec(
        "float", "30", _OPS,
        "Per-tick member-write deadline budget."),
    "KT_DISPATCH_POOL": KnobSpec(
        "int", "8", _OPS,
        "Bounded in-flight pool of the per-op fan-out."),
    "KT_RETRY_MAX": KnobSpec(
        "int", "3", _OPS,
        "Retries per op beyond the first attempt."),
    "KT_RETRY_BASE_S": KnobSpec(
        "float", "0.05", _OPS,
        "Retry backoff base."),
    "KT_RETRY_CAP_S": KnobSpec(
        "float", "2.0", _OPS,
        "Retry backoff cap."),
    "KT_FARM_SUBPROCESS": KnobSpec(
        "str", "", _OPS,
        "kwok-lite farm: run members as subprocesses."),
    "KT_WRITE_COALESCE": KnobSpec(
        "bool", "1", _OPS,
        "Coalesce staged member writes into bulk /batch requests "
        "(0 = one request per (object, member) op — the A/B baseline)."),
    "KT_MEMBER_BATCH": KnobSpec(
        "int", "128", _OPS,
        "Max operations per bulk member request (write coalescing and "
        "bulk point reads)."),
    "KT_MEMBER_INFLIGHT": KnobSpec(
        "int", "4", _OPS,
        "Bulk requests concurrently in flight per member during one "
        "flush (the pipelined write window)."),
    "KT_BULK_READS": KnobSpec(
        "bool", "1", _OPS,
        "Batch point reads on network fleets: fed objects and candidate "
        "member objects prefetched through /batch instead of per-object "
        "GETs."),
    "KT_ADMIT_DEPTH": KnobSpec(
        "int", "10000", _OPS,
        "Queue depth past which new enqueues are admitted with a "
        "coalescing delay (0 disables admission backpressure)."),
    "KT_ADMIT_DELAY_MS": KnobSpec(
        "int", "50", _OPS,
        "Coalescing delay applied to enqueues past KT_ADMIT_DEPTH."),
    "KT_ADMIT_BATCH": KnobSpec(
        "int", "0", _OPS,
        "Max keys one worker drain hands a tick (0 = unlimited)."),
    "KT_STORE_COALESCE": KnobSpec(
        "bool", "1", _OPS,
        "In-process store: columnar batch commits + one coalesced watch "
        "notification per committed flush (0 = per-op lock/apply/notify "
        "— the A/B baseline the coalesced event stream must match "
        "bit-identically)."),
    "KT_SHARD_COUNT": KnobSpec(
        "int", "1", _OPS,
        "Engine-replica shard count consulted at the informer/worker "
        "boundary (1 = this process owns every key; routing is "
        "identity)."),
    "KT_SHARD_INDEX": KnobSpec(
        "int", "0", _OPS,
        "This replica's shard in [0, KT_SHARD_COUNT)."),
    # -- bench / CI drivers (bench.py, bench_e2e.py, tools/) -------------
    "KT_BENCH_GATE_TOL": KnobSpec(
        "float", "0.10", _OPS,
        "bench-gate regression tolerance (fraction)."),
    "KT_CHURN_FLOOR": KnobSpec(
        "float", "<3x r03>", _OPS,
        "bench-gate churn objects-revalidated/s floor override."),
    "KT_CHURN_P99_CEIL_MS": KnobSpec(
        "float", "3000", _OPS,
        "bench-gate churn event->placement p99 ceiling."),
    "KT_CENSUS_OBJECTS": KnobSpec(
        "int", "1000000", _OPS,
        "c6 memory-census world: objects."),
    "KT_CENSUS_CLUSTERS": KnobSpec(
        "int", "10000", _OPS,
        "c6 memory-census world: clusters."),
    "KT_CENSUS_DEVICES": KnobSpec(
        "int", "4", _OPS,
        "c6 memory-census world: devices on the objects axis."),
    "KT_CENSUS_VALIDATE_OBJECTS": KnobSpec(
        "int", "8192", _OPS,
        "Census model-validation slice: objects."),
    "KT_CENSUS_VALIDATE_CLUSTERS": KnobSpec(
        "int", "256", _OPS,
        "Census model-validation slice: clusters."),
    "KT_RESTART_WARM": KnobSpec(
        "bool", "0", _OPS,
        "Restart bench: this process is the warm successor."),
    "KT_RESTART_BENCH_DIR": KnobSpec(
        "path", "", _OPS,
        "Restart bench: shared workdir (snapshots + AOT manifest)."),
    "KT_RESTART_TIMEOUT_S": KnobSpec(
        "int", "3600", _OPS,
        "Restart bench: per-phase subprocess timeout."),
    "KT_RESTART_MULTIDEV": KnobSpec(
        "int", "4", _OPS,
        "Restart bench: N-device warm-boot phase (0 skips)."),
    "KT_RESTART_DIR": KnobSpec(
        "path", "", _OPS,
        "SIGKILL matrix: victim/successor workdir."),
    "KT_RESTART_OBJECTS": KnobSpec(
        "int", "192", _OPS,
        "SIGKILL matrix world: objects."),
    "KT_RESTART_CLUSTERS": KnobSpec(
        "int", "10", _OPS,
        "SIGKILL matrix world: clusters."),
    "KT_RESTART_PREWARM": KnobSpec(
        "bool", "0", _OPS,
        "SIGKILL matrix: run the prewarm ladder in the victim."),
    "KT_RESTART_KILL_PHASE": KnobSpec(
        "str", "", _OPS,
        "SIGKILL matrix: phase the victim dies in."),
    "KT_RESTART_ARTIFACT": KnobSpec(
        "path", "successor.json", _OPS,
        "SIGKILL matrix: successor's convergence artifact path."),
    # -- telemetry timeline (runtime/timeline.py, ISSUE 16) ---------------
    "KT_TIMELINE": KnobSpec(
        "bool", "1", _OBS,
        "Telemetry timeline sampler (0 removes the thread entirely)."),
    "KT_TIMELINE_INTERVAL_S": KnobSpec(
        "float", "1.0", _OBS,
        "Sampler period of the timeline thread."),
    "KT_TIMELINE_BYTES": KnobSpec(
        "int", "2097152", _OBS,
        "Ring budget; overflow downsamples raw→10s→60s tiers."),
    # -- tenant attribution (runtime/tenancy.py, ISSUE 16) ----------------
    "KT_TENANT_LABEL": KnobSpec(
        "str", "", _OBS,
        "Metadata label overriding the namespace-derived tenant."),
    "KT_TENANT_MAX": KnobSpec(
        "int", "64", _OBS,
        "Tenant-label cardinality cap (overflow → \"~other\")."),
    # -- all-stressors soak (bench.py --scenario soak, ISSUE 16) ----------
    "KT_SOAK_ROUNDS": KnobSpec(
        "int", "10", _OPS,
        "Soak: total schedule rounds."),
    "KT_SOAK_ARRIVALS": KnobSpec(
        "int", "6", _OPS,
        "Soak: object arrivals per round."),
    "KT_SOAK_KILL_ROUND": KnobSpec(
        "int", "5", _OPS,
        "Soak: round after which the victim is SIGKILLed."),
    "KT_SOAK_SHARDS": KnobSpec(
        "int", "1", _OPS,
        "Soak: shard the control plane across N replica processes "
        "(victim+successor own shard 0; peers own 1..N-1; the oracle "
        "stays unsharded and the union of shards must match it "
        "bit-identically)."),
    # -- sharded control plane replicas (testing/shardreplica.py,
    #    ISSUE 20) --------------------------------------------------------
    "KT_REPLICA_HOST_URL": KnobSpec(
        "str", "", _OPS,
        "Shard replica subprocess: host apiserver URL to attach to."),
    "KT_REPLICA_HOST_TOKEN": KnobSpec(
        "str", "", _OPS,
        "Shard replica subprocess: bearer token for the host apiserver."),
    "KT_REPLICA_FTC": KnobSpec(
        "str", "deployments.apps", _OPS,
        "Shard replica subprocess: FTC source resource to run the "
        "controller stack for."),
    # -- fleet observatory (runtime/telespill.py, runtime/fleetscrape.py,
    #    ISSUE 17) --------------------------------------------------------
    "KT_SPILL": KnobSpec(
        "bool", "1", _OPS,
        "Crash-durable telemetry spill master switch (0 = no files, no "
        "spiller thread; the overhead A/B arm)."),
    "KT_TELEMETRY_DIR": KnobSpec(
        "path", "", _OPS,
        "Spill directory; unset disables spilling (like "
        "KT_SNAPSHOT_DIR for snapshots)."),
    "KT_SPILL_BYTES": KnobSpec(
        "int", "8388608", _OPS,
        "Per-instance spill byte bound; oldest segments pruned past it."),
    "KT_SPILL_INTERVAL_S": KnobSpec(
        "float", "1.0", _OPS,
        "Background spill period (<=0 = explicit spill_now only)."),
    "KT_FLEET_SCRAPE_S": KnobSpec(
        "float", "0.0", _OPS,
        "Fleet-scraper background refresh period (0 = scrape on "
        "/debug/fleet demand)."),
}


def is_cataloged(name: str) -> bool:
    return name in KNOBS
