"""Lightweight span tracer for the reconcile path.

Answers "where did the last tick's 44ms go?" the way a distributed
tracer would, without the dependency: a context-manager span API with a
thread-local stack (so child spans record their parent), a bounded ring
of completed spans, and Chrome trace-event JSON export served at
``GET /debug/trace`` (load it in chrome://tracing or ui.perfetto.dev).

Spans are threaded through the full reconcile path — informer event
delivery (runtime/informer.py), worker dequeue/reconcile
(runtime/worker.py), the engine's featurize/dispatch/fetch stages
(scheduler/engine.py), and member dispatch (federation/dispatch.py).
Overhead per span is two ``perf_counter`` calls and a deque append, so
it stays on in production.

Most callers use the module-level default tracer (``trace.span(...)``);
tests and embedders may construct their own :class:`Tracer`.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Optional

DEFAULT_RING = 16384

# One epoch per process: span timestamps are microseconds since this
# moment, comparable across threads and tracers.
_EPOCH = time.perf_counter()


def epoch() -> float:
    """The process trace epoch (a perf_counter reading): other
    timestamp sources merging into the Chrome trace — the dispatch
    ledger's device lanes (runtime/devprof.py) — subtract this so one
    trace load shows host spans and device records on one timeline."""
    return _EPOCH


class Span:
    __slots__ = (
        "name", "span_id", "parent_id", "start", "end", "args", "tid",
        "thread_name",
    )

    def __init__(self, name: str, span_id: int, parent_id: Optional[int], args: dict):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = time.perf_counter() - _EPOCH
        self.end: Optional[float] = None
        self.args = args
        self.tid = threading.get_ident()
        self.thread_name = threading.current_thread().name

    def set(self, **args) -> None:
        """Attach attributes to an open span (e.g. a result count known
        only at the end of the work)."""
        self.args.update(args)


class Tracer:
    def __init__(self, ring: int = DEFAULT_RING):
        # Bounded deque; append/clear/iteration-snapshot are each atomic
        # under the GIL, so the hot record path takes NO lock — a storm
        # of writer threads must not serialize on the tracer.
        self._ring: deque[Span] = deque(maxlen=ring)
        self._ids = itertools.count(1)
        self._local = threading.local()

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @contextmanager
    def span(self, name: str, **args):
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        sp = Span(name, next(self._ids), parent, args)
        stack.append(sp)
        try:
            yield sp
        finally:
            sp.end = time.perf_counter() - _EPOCH
            stack.pop()
            self._ring.append(sp)

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def clear(self) -> None:
        self._ring.clear()

    def spans(self) -> list[Span]:
        return list(self._ring)

    def chrome_trace(self) -> dict:
        """The completed ring as Chrome trace-event JSON: one complete
        ("X") event per span (ts/dur in microseconds), span/parent ids in
        args so nesting survives tools that ignore timing, plus
        thread-name metadata events."""
        pid = os.getpid()
        events = []
        threads: dict[int, str] = {}
        for sp in self.spans():
            threads.setdefault(sp.tid, sp.thread_name)
            args = {"span_id": sp.span_id}
            if sp.parent_id is not None:
                args["parent_id"] = sp.parent_id
            args.update(sp.args)
            events.append(
                {
                    "name": sp.name,
                    "ph": "X",
                    "ts": round(sp.start * 1e6, 3),
                    "dur": round(((sp.end or sp.start) - sp.start) * 1e6, 3),
                    "pid": pid,
                    "tid": sp.tid,
                    "args": args,
                }
            )
        for tid, tname in threads.items():
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def chrome_trace_json(self) -> str:
        return json.dumps(self.chrome_trace())


_default = Tracer()


def get_default() -> Tracer:
    return _default


def span(name: str, **args):
    """Open a span on the process-default tracer."""
    return _default.span(name, **args)
