"""Lightweight span tracer for the reconcile path.

Answers "where did the last tick's 44ms go?" the way a distributed
tracer would, without the dependency: a context-manager span API with a
thread-local stack (so child spans record their parent), a bounded ring
of completed spans, and Chrome trace-event JSON export served at
``GET /debug/trace`` (load it in chrome://tracing or ui.perfetto.dev).

Spans are threaded through the full reconcile path — informer event
delivery (runtime/informer.py), worker dequeue/reconcile
(runtime/worker.py), the engine's featurize/dispatch/fetch stages
(scheduler/engine.py), and member dispatch (federation/dispatch.py).
Overhead per span is two ``perf_counter`` calls and a deque append, so
it stays on in production.

Cross-process correlation (the fleet observatory,
docs/observability.md § Fleet observatory):

* Every root span mints a 128-bit **trace id**; children inherit it, so
  one scheduling decision's whole span tree shares one trace id.
* Span ids are globally unique (a per-tracer random 32-bit prefix over
  a local counter), so two processes' rings can merge without id
  collisions.
* :func:`current_traceparent` renders the innermost open span as a
  W3C-traceparent header value (``00-<trace id>-<span id>-01``); the
  HTTP client injects it on every request, and
  :meth:`Tracer.server_span` on the apiserver side adopts the inbound
  trace id + parent so the server-side span is a true child of the
  caller's span — across process boundaries.
* :meth:`Tracer.span_from` parents a span explicitly (the pipelined
  dispatch chunk threads: work submitted to a pool carries the
  submitting span along instead of starting an orphan trace).
* The Chrome export carries ``otherData.wall_epoch`` — the wall-clock
  instant of this process's perf_counter epoch — so
  ``tools/trace_assemble.py`` can align per-process traces on one
  shared timeline (perf_counter epochs alone are incomparable across
  processes).

Most callers use the module-level default tracer (``trace.span(...)``);
tests and embedders may construct their own :class:`Tracer`.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Optional

DEFAULT_RING = 16384

# One epoch per process: span timestamps are microseconds since this
# moment, comparable across threads and tracers.
_EPOCH = time.perf_counter()


def epoch() -> float:
    """The process trace epoch (a perf_counter reading): other
    timestamp sources merging into the Chrome trace — the dispatch
    ledger's device lanes (runtime/devprof.py) — subtract this so one
    trace load shows host spans and device records on one timeline."""
    return _EPOCH


def wall_epoch() -> float:
    """The wall-clock time (``time.time()``) of :func:`epoch` — the
    per-process anchor that makes two processes' trace timestamps
    comparable: ``wall = wall_epoch() + span.start``.  Recomputed from
    the current clocks on each call (drift between the two clocks over
    a process lifetime is far below the microsecond resolution of the
    export)."""
    return time.time() - (time.perf_counter() - _EPOCH)


def _mint_trace_id() -> str:
    """A fresh 128-bit trace id, lowercase hex (W3C trace-context)."""
    return os.urandom(16).hex()


def format_traceparent(trace_id: str, span_id: int) -> str:
    """``00-<32 hex trace id>-<16 hex span id>-01`` (W3C traceparent)."""
    return f"00-{trace_id}-{span_id & ((1 << 64) - 1):016x}-01"


def parse_traceparent(header: Optional[str]) -> Optional[tuple[str, int]]:
    """(trace_id, parent span id) from a traceparent header, or None
    for anything malformed — a bad header degrades to an unparented
    server span, never an error on the request path."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4 or parts[0] != "00":
        return None
    trace_id, span_hex = parts[1], parts[2]
    if len(trace_id) != 32 or len(span_hex) != 16:
        return None
    try:
        int(trace_id, 16)
        span_id = int(span_hex, 16)
    except ValueError:
        return None
    if span_id == 0 or int(trace_id, 16) == 0:
        return None
    return trace_id.lower(), span_id


class Span:
    __slots__ = (
        "name", "span_id", "parent_id", "trace_id", "start", "end",
        "args", "tid", "thread_name",
    )

    def __init__(
        self, name: str, span_id: int, parent_id: Optional[int],
        trace_id: str, args: dict,
    ):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.trace_id = trace_id
        self.start = time.perf_counter() - _EPOCH
        self.end: Optional[float] = None
        self.args = args
        self.tid = threading.get_ident()
        self.thread_name = threading.current_thread().name

    def set(self, **args) -> None:
        """Attach attributes to an open span (e.g. a result count known
        only at the end of the work)."""
        self.args.update(args)

    def traceparent(self) -> str:
        """This span as a traceparent header value."""
        return format_traceparent(self.trace_id, self.span_id)


class Tracer:
    def __init__(self, ring: int = DEFAULT_RING):
        # Bounded deque; append/clear/iteration-snapshot are each atomic
        # under the GIL, so the hot record path takes NO lock — a storm
        # of writer threads must not serialize on the tracer.
        self._ring: deque[Span] = deque(maxlen=ring)
        self._ids = itertools.count(1)
        # Span ids must be unique across every tracer in every process
        # whose rings may merge into one trace: a random 32-bit prefix
        # over the local counter keeps ids collision-free without
        # coordination (and keeps the hot path a counter increment).
        self._id_base = int.from_bytes(os.urandom(4), "big") << 32
        self._local = threading.local()

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _next_id(self) -> int:
        return self._id_base | next(self._ids)

    def _span_gen(self, name, trace_id, parent_id, args):
        stack = self._stack()
        sp = Span(name, self._next_id(), parent_id, trace_id, args)
        stack.append(sp)
        try:
            yield sp
        finally:
            sp.end = time.perf_counter() - _EPOCH
            stack.pop()
            self._ring.append(sp)

    @contextmanager
    def span(self, name: str, **args):
        stack = self._stack()
        if stack:
            trace_id, parent_id = stack[-1].trace_id, stack[-1].span_id
        else:
            trace_id, parent_id = _mint_trace_id(), None
        yield from self._span_gen(name, trace_id, parent_id, args)

    @contextmanager
    def span_from(self, name: str, parent: Optional[Span], **args):
        """A span explicitly parented on ``parent`` — for work handed to
        another thread (pool-submitted dispatch chunks), where the
        submitting thread's stack is invisible to the worker.  A None
        parent falls back to :meth:`span` semantics."""
        if parent is None:
            with self.span(name, **args) as sp:
                yield sp
            return
        yield from self._span_gen(
            name, parent.trace_id, parent.span_id, args
        )

    @contextmanager
    def server_span(self, name: str, traceparent: Optional[str], **args):
        """The server half of cross-process propagation: a span adopting
        the inbound header's trace id with the caller's span as parent,
        so a member-apiserver write shows up as a child of the manager's
        dispatch span in the assembled trace.  No/invalid header opens
        an ordinary (locally rooted) span."""
        ctx = parse_traceparent(traceparent)
        if ctx is None:
            with self.span(name, **args) as sp:
                yield sp
            return
        trace_id, parent_id = ctx
        args["remote_parent"] = True
        yield from self._span_gen(name, trace_id, parent_id, args)

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def current_traceparent(self) -> Optional[str]:
        """The innermost open span as a traceparent header value, or
        None when this thread has no open span."""
        sp = self.current()
        return sp.traceparent() if sp is not None else None

    def clear(self) -> None:
        self._ring.clear()

    def spans(self) -> list[Span]:
        return list(self._ring)

    def chrome_trace(self) -> dict:
        """The completed ring as Chrome trace-event JSON: one complete
        ("X") event per span (ts/dur in microseconds), span/parent/trace
        ids in args so nesting survives tools that ignore timing, plus
        thread-name metadata events and the per-process wall-clock
        anchor (``otherData.wall_epoch``) trace_assemble aligns lanes
        with."""
        pid = os.getpid()
        events = []
        threads: dict[int, str] = {}
        for sp in self.spans():
            threads.setdefault(sp.tid, sp.thread_name)
            args = {"span_id": sp.span_id, "trace_id": sp.trace_id}
            if sp.parent_id is not None:
                args["parent_id"] = sp.parent_id
            args.update(sp.args)
            events.append(
                {
                    "name": sp.name,
                    "ph": "X",
                    "ts": round(sp.start * 1e6, 3),
                    "dur": round(((sp.end or sp.start) - sp.start) * 1e6, 3),
                    "pid": pid,
                    "tid": sp.tid,
                    "args": args,
                }
            )
        for tid, tname in threads.items():
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"wall_epoch": wall_epoch(), "pid": pid},
        }

    def chrome_trace_json(self) -> str:
        return json.dumps(self.chrome_trace())


_default = Tracer()


def get_default() -> Tracer:
    return _default


def span(name: str, **args):
    """Open a span on the process-default tracer."""
    return _default.span(name, **args)


# -- sampled hot-path spans ---------------------------------------------------
#
# Per-event handler spans (informer.event, worker.reconcile) cost two
# perf_counter calls, a dict build and a deque append PER EVENT — at the
# 10000x500 e2e scale that is millions of spans whose ring evicts all
# but the last 16k anyway.  hot_span() keeps 1-in-KT_TRACE_SAMPLE_N of
# them (default 64; 1 = trace everything, 0 = trace nothing), with a
# fast no-allocation pass-through for the skipped ones.  Ticks and
# coarser once-per-batch spans stay unconditional — sampling is only
# for per-event/per-key fan-out sites.

def _sample_every() -> int:
    raw = os.environ.get("KT_TRACE_SAMPLE_N", "")
    try:
        return int(raw) if raw else 64
    except ValueError:
        return 64


_sample_n = _sample_every()
_sample_counter = itertools.count()


class _NullSpan:
    """The skipped-sample stand-in: accepts set() and traceparent()."""

    __slots__ = ()

    def set(self, **args) -> None:
        pass

    def traceparent(self) -> Optional[str]:  # pragma: no cover - trivial
        return None


_NULL_SPAN = _NullSpan()


@contextmanager
def _null_span():
    yield _NULL_SPAN


def reset_sampling() -> int:
    """Re-read KT_TRACE_SAMPLE_N (tests, embedders); returns the rate."""
    global _sample_n
    _sample_n = _sample_every()
    return _sample_n


def hot_span(name: str, **args):
    """A sampled span for per-event hot paths: records 1 in
    KT_TRACE_SAMPLE_N calls on the default tracer, a cheap counter
    bump + no-op context otherwise.  The sampled-in spans keep full
    parent/trace-id semantics; sampled-out calls leave the thread's
    span stack untouched (children of a skipped span root normally)."""
    n = _sample_n
    if n == 1:
        return _default.span(name, **args)
    if n <= 0 or next(_sample_counter) % n:
        return _null_span()
    args["sampled_1_in"] = n
    return _default.span(name, **args)


def current_traceparent() -> Optional[str]:
    """The calling thread's innermost open span on the default tracer,
    as a traceparent header value (None with no open span)."""
    return _default.current_traceparent()
