"""The c6 memory census: does the resident working set fit on the mesh?

At c6 scale (1M objects x 10k clusters) the engine's resident planes are
~10^10 cells: six [B, C] output planes per chunk (selected i8, replicas
i32, counted i8, scores i32-or-f16, feasible i8, reasons i32), the
cached per-object input tensors, the precomputed tie-break plane and the
[B] companion vectors.  Whether that fits a device — and at how many
devices, and with which compression engaged — must be a NUMBER before
the first on-chip c6 run, not a discovery during it.  This module owns
that number:

* :func:`project` — the analytic inventory: walks the engine's real
  geometry policy (``SchedulerEngine._tick_geometry`` via a throwaway
  engine, so chunk split / padding / ladder rules can never drift from
  the model) and books every resident plane family at its device dtype,
  per device: rows-sharded [B, ...] planes divide across the objects
  mesh axis, replicated [B] vectors book whole on every device.

* :func:`validate` — the honesty check: schedules a small live world,
  walks the ACTUAL device buffers
  (``SchedulerEngine.resident_state_bytes``) and compares them against
  the model at the same shape.  A model that can't predict 8k x 256 has
  no business predicting 1M x 10k; ``bench.py --scenario census`` fails
  its artifact when the error exceeds the tolerance.

* :func:`decide` — the compress-or-shard decision against the HBM
  budget knob (``KT_HBM_BUDGET_GB``, default 16 GiB/device): fits as-is
  -> ``fits``; fits with the f16 score plane (``KT_SCORE_F16``, exact
  by construction behind the per-row exactness guard — see
  scheduler/engine.py) -> ``compress``; otherwise the minimum
  objects-axis device count that fits (compression engaged) ->
  ``shard``.

``bench.py --scenario census`` emits the artifact
(``BENCH_CENSUS_r<n>.json``) and ``tools/bench_gate.py`` surfaces it —
a census over budget at the configured device count FAILS the gate.
"""

from __future__ import annotations

import os
from typing import Optional

# Device dtype widths of the resident planes (scheduler/engine.py store
# sites; the reasons plane is i32 on device — its 10 reason bits would
# fit i16, which is the next compression lever after scores and is
# called out by `decide` when it would matter).
_PREV_PLANE_BYTES = {
    "selected": 1, "replicas": 4, "counted": 1,
    "scores_i32": 4, "scores_f16": 2,
    "feasible": 1, "reasons": 4,
}
# Compact-format per-object residency per row: the id vectors
# (gvk/tol/sel/pref/place i32 + placement_has i8 + filter/score enables
# i8[5]+i8[5] + request i64[R] + max_clusters/total/... i32) plus the
# sparse entry block and key bytes.  These are shape-dependent; the
# constants below are the per-row fixed part measured at the bench
# worlds (validate() catches drift between this table and the real
# featurizer — see test_multidevice.py's census block).
_PER_ROW_FIXED = 96
_SPARSE_ENTRY_BYTES = 6 * 4  # idx/min/max/weight/capacity/cur i32 slots
_KEY_BYTE = 1


def hbm_budget_bytes() -> int:
    """KT_HBM_BUDGET_GB (GiB per device, default 16 — a v4/v5 class
    chip's usable HBM after XLA scratch)."""
    return int(
        float(os.environ.get("KT_HBM_BUDGET_GB", "16")) * (1 << 30)
    )


def _geometry(n_objects: int, n_clusters: int, device_count: int):
    """The engine's REAL geometry at this shape/topology: a program-free
    throwaway engine with a stub mesh of the requested objects-axis size
    runs the actual ``SchedulerEngine._tick_geometry`` — the census can
    model topologies larger than the local device set, and the model
    can never drift from the policy it predicts."""
    from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

    eng = SchedulerEngine.__new__(SchedulerEngine)
    # The minimal attribute set _tick_geometry reads.
    if device_count <= 1:
        eng.mesh = None
    else:
        class _StubGrid:
            shape = (device_count, 1)

        class _StubMesh:
            devices = _StubGrid()

        eng.mesh = _StubMesh()
    eng.min_bucket = 64
    eng.min_cluster_bucket = 8
    eng.chunk_size = 4096
    eng.canonical_c = 256
    eng.cell_budget = int(os.environ.get("KT_CELL_BUDGET", str(4096 * 5120)))
    eng.megachunk_rows = int(os.environ.get("KT_MEGACHUNK_ROWS", "4096"))
    c_bucket, eff_chunk, ladder = SchedulerEngine._tick_geometry(
        eng, n_clusters
    )
    n_chunks = -(-n_objects // eff_chunk)
    # Padding follows SchedulerEngine._bucket_rows exactly: multi-chunk
    # batches pad EVERY chunk (incl. the tail) to eff_chunk; a
    # single-chunk batch pads to its ladder rung / pow2 bucket.
    if n_chunks > 1:
        b_pad_total = n_chunks * eff_chunk
    else:
        tail = n_objects
        b_pad_total = SchedulerEngine._bucket_rows(
            eng, tail, ladder, eff_chunk, False
        )
    return {
        "c_bucket": c_bucket,
        "eff_chunk": eff_chunk,
        "n_chunks": n_chunks,
        "padded_rows": b_pad_total,
    }


def project(
    n_objects: int,
    n_clusters: int,
    device_count: int = 1,
    score_f16: Optional[bool] = None,
    sparse_entries: int = 8,
    key_len: int = 64,
    with_scores_plane: bool = True,
) -> dict:
    """Analytic resident-plane inventory at (B, C) on an N-device
    objects mesh, in bytes.  ``sparse_entries`` / ``key_len`` size the
    compact per-object block (bench worlds measure ~8 sparse slots and
    <=64 key bytes)."""
    if score_f16 is None:
        score_f16 = os.environ.get("KT_SCORE_F16", "0") in ("1", "true", "yes")
    geo = _geometry(n_objects, n_clusters, device_count)
    rows = geo["padded_rows"]
    cells = rows * geo["c_bucket"]
    sco = "scores_f16" if score_f16 else "scores_i32"
    prev = {
        name: cells * width
        for name, width in _PREV_PLANE_BYTES.items()
        if name not in ("scores_i32", "scores_f16")
    }
    prev["scores"] = cells * _PREV_PLANE_BYTES[sco]
    per_object = rows * (
        _PER_ROW_FIXED
        + sparse_entries * _SPARSE_ENTRY_BYTES
        + key_len * _KEY_BYTE
    )
    tiebreak = cells * 4  # i32[B, C], compact drift path
    vectors = rows * 4 + (rows * 1 if score_f16 else 0)  # nfeas + exactness
    total = sum(prev.values()) + per_object + tiebreak + vectors
    # Rows-sharded planes divide across the mesh; [B] vectors replicate.
    per_device = (total - vectors) // device_count + vectors
    return {
        "n_objects": n_objects,
        "n_clusters": n_clusters,
        "device_count": device_count,
        "score_dtype": "f16" if score_f16 else "i32",
        "geometry": {
            k: geo[k] for k in ("c_bucket", "eff_chunk", "n_chunks",
                                "padded_rows")
        },
        "by_family": {
            "prev_planes": sum(prev.values()),
            "per_object": per_object,
            "tiebreak": tiebreak,
            "vectors": vectors,
        },
        "prev_plane_split": prev,
        "total": total,
        "per_device": per_device,
    }


def validate(n_objects: int = 8192, n_clusters: int = 256) -> dict:
    """Model-vs-live cross check: schedule a real world at a small
    shape, walk the actual device buffers and compare against
    :func:`project` at the same shape/topology.  Returns both numbers
    and the relative error of the families the model claims to predict
    (prev planes — the c6-dominant family; per-object/tiebreak are
    workload-shaped and compared loosely)."""
    import numpy as np

    from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

    rng = np.random.default_rng(20260805)
    units, clusters = _census_world(rng, n_objects, n_clusters)
    eng = SchedulerEngine()
    eng.schedule(units, clusters)
    live = eng.resident_state_bytes()
    model = project(
        n_objects, n_clusters,
        device_count=live["device_count"],
        score_f16=eng.score_f16,
    )
    lp, mp = live["by_family"]["prev_planes"], model["by_family"]["prev_planes"]
    err = abs(lp - mp) / max(1, lp)
    return {
        "shape": f"{n_objects}x{n_clusters}",
        "live": live,
        "model_prev_planes": mp,
        "live_prev_planes": lp,
        "prev_planes_err_pct": round(err * 100.0, 2),
        "ok": err <= 0.15,
    }


def _census_world(rng, b: int, c: int):
    """A small live world for validate(): the bench build_world shape
    without importing bench.py (which owns process-level env policy)."""
    from kubeadmiral_tpu.models.types import (
        ClusterState, MODE_DIVIDE, SchedulingUnit, parse_resources,
    )

    gvk = "apps/v1/Deployment"
    clusters = [
        ClusterState(
            name=f"member-{j:05d}",
            labels={"region": ("us", "eu", "ap")[j % 3], "tier": str(j % 4)},
            allocatable=parse_resources(
                {"cpu": str(16 + j % 32), "memory": f"{64 + j % 128}Gi"}
            ),
            available=parse_resources(
                {"cpu": str(8 + j % 16), "memory": f"{32 + j % 64}Gi"}
            ),
            api_resources=frozenset({gvk}),
        )
        for j in range(c)
    ]
    units = [
        SchedulingUnit(
            gvk=gvk,
            namespace=f"ns-{i % 97}",
            name=f"workload-{i:06d}",
            scheduling_mode=MODE_DIVIDE if i % 4 else "Duplicate",
            desired_replicas=(i % 50) + 1 if i % 4 else None,
            resource_request=parse_resources(
                {"cpu": f"{(i % 4) * 250}m", "memory": f"{(i % 8) * 256}Mi"}
            ),
            max_clusters=(i % 20) + 1 if i % 5 == 0 else None,
        )
        for i in range(b)
    ]
    return units, clusters


def decide(
    n_objects: int,
    n_clusters: int,
    device_count: int,
    budget_bytes: Optional[int] = None,
) -> dict:
    """The compress-or-shard decision at (B, C, N) against the budget:

    * ``fits``      — i32 scores fit per device as-is;
    * ``compress``  — over budget at i32, under with the f16 score plane
                      (engage KT_SCORE_F16);
    * ``shard``     — over budget even compressed: the verdict carries
                      the minimum objects-axis device count that fits
                      (compression engaged), i.e. how much further the
                      mesh must scale out.
    """
    if budget_bytes is None:
        budget_bytes = hbm_budget_bytes()
    plain = project(n_objects, n_clusters, device_count, score_f16=False)
    packed = project(n_objects, n_clusters, device_count, score_f16=True)
    if plain["per_device"] <= budget_bytes:
        verdict, engaged = "fits", plain
    elif packed["per_device"] <= budget_bytes:
        verdict, engaged = "compress", packed
    else:
        verdict, engaged = "shard", packed
    min_devices = device_count
    if verdict == "shard":
        n = device_count
        while n < 4096:
            n *= 2
            if project(n_objects, n_clusters, n, score_f16=True)[
                "per_device"
            ] <= budget_bytes:
                break
        min_devices = n
    over = engaged["per_device"] > budget_bytes
    return {
        "verdict": verdict,
        "budget_bytes": budget_bytes,
        "per_device_i32": plain["per_device"],
        "per_device_f16": packed["per_device"],
        "per_device": engaged["per_device"],
        "over_budget": bool(over),
        "min_devices": min_devices,
        "projection": engaged,
        # The next lever if even sharding is unpalatable: the reasons
        # plane's 10 reason bits fit i16 (the flight recorder already
        # stores i16 host-side) — halves another i32 plane.
        "reasons_i16_would_save": engaged["geometry"]["padded_rows"]
        * engaged["geometry"]["c_bucket"] * 2 // device_count,
    }
