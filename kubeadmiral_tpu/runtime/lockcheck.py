"""Instrumented lock wrapper + shared-field write guard (KT_LOCKCHECK).

Python has no TSan; the thread-stress suite (tests/test_stress_threads)
fuzzes for divergence but can only see races that LOSE.  This module is
the deterministic half: when ``KT_LOCKCHECK`` is on (tests/conftest.py
enables it suite-wide; default off in production), every lock built via
:func:`make_lock` records, per thread, the set of locks currently held,
and the module maintains a global acquisition-order graph:

* **Lock-order inversions.**  Acquiring B while holding A records the
  edge A→B; a later acquisition of A while holding B — the classic
  deadlock shape, which only hangs when two threads hit the window
  together — is reported immediately, with both stacks, even when the
  storm got lucky.  Same-name edges (two instances of the same lock
  class) are ignored: order within a class is not expressible by name.

* **Declared-shared field writes.**  Classes annotate their
  cross-thread state in a ``_shared_fields_`` registry
  (``{"field": "lockattr"}`` — alternates joined with ``|``), the same
  registry the static pass (``tools/ktlint`` rule ``lock-discipline``)
  checks mutation sites against.  :func:`shared_field_guard` wraps the
  class's ``__setattr__`` so a REBIND of a declared field off-lock is
  recorded at runtime too (the PR-3 race class: a worker thread
  persisting empty placements through an unlocked reassignment).
  Container mutations (``.append``/``[k] = v``) don't pass through
  ``__setattr__`` — those are the static rule's half of the contract.

Violations are collected, not raised: a storm must run to completion so
every inversion is reported at once.  Tests call :func:`reset` before
the storm and assert :func:`violations` is empty after.  Overhead when
disabled is zero (plain ``threading.Lock``/``RLock`` objects are
returned and classes are left untouched).

See docs/static_analysis.md (runtime harness) and docs/operations.md
(KT_LOCKCHECK row).
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Callable, Optional

__all__ = [
    "enabled",
    "make_lock",
    "make_rlock",
    "CheckedLock",
    "shared_field_guard",
    "assumes_held",
    "violations",
    "reset",
]


def enabled() -> bool:
    """KT_LOCKCHECK: instrumented locks + shared-field write guard
    (default off; tests/conftest.py turns it on for the suite)."""
    return os.environ.get("KT_LOCKCHECK", "0") in ("1", "true", "yes")


# -- violation collection -------------------------------------------------

_violations: list[str] = []
_violations_lock = threading.Lock()


def _record(kind: str, message: str) -> None:
    stack = "".join(traceback.format_stack(limit=8)[:-2])
    with _violations_lock:
        _violations.append(f"[{kind}] {message}\n{stack}")


def violations() -> list[str]:
    """Every violation recorded since the last :func:`reset`."""
    with _violations_lock:
        return list(_violations)


def reset() -> None:
    """Clear recorded violations AND the acquisition-order graph."""
    with _violations_lock:
        _violations.clear()
    with _graph_lock:
        _edges.clear()


# -- lock-order graph -----------------------------------------------------

# (held_name, acquired_name) -> one representative stack (first seen).
_edges: dict[tuple[str, str], str] = {}
_graph_lock = threading.Lock()

_tls = threading.local()


def _held_stack() -> list["CheckedLock"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = []
        _tls.held = stack
    return stack


class CheckedLock:
    """A ``threading.Lock``/``RLock`` proxy that tracks per-thread
    acquisition order and detects inversions at acquire time."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self._reentrant = reentrant

    # Condition() consults _is_owned when the wrapped lock provides it;
    # our per-thread held stack answers exactly that question.
    def _is_owned(self) -> bool:
        return self.held_by_current()

    def held_by_current(self) -> bool:
        return any(entry is self for entry in _held_stack())

    def _note_acquired(self) -> None:
        held = _held_stack()
        for prior in held:
            if prior is self or prior.name == self.name:
                continue  # re-entry / same-class nesting: not orderable by name
            edge = (prior.name, self.name)
            inverse = (self.name, prior.name)
            with _graph_lock:
                other = _edges.get(inverse)
                if edge not in _edges:
                    _edges[edge] = "".join(
                        traceback.format_stack(limit=6)[:-3]
                    )
            if other is not None:
                _record(
                    "lock-order-inversion",
                    f"acquired {self.name!r} while holding {prior.name!r}, "
                    f"but the opposite order was previously recorded at:\n"
                    f"{other}",
                )
        held.append(self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._note_acquired()
        return ok

    def release(self) -> None:
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked() if not self._reentrant else self.held_by_current()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def make_lock(name: str):
    """A lock for a declared-shared structure: plain ``threading.Lock``
    in production, :class:`CheckedLock` under KT_LOCKCHECK."""
    if enabled():
        return CheckedLock(name)
    return threading.Lock()


def make_rlock(name: str):
    if enabled():
        return CheckedLock(name, reentrant=True)
    return threading.RLock()


# -- declared-shared field guard ------------------------------------------


def _lock_held(obj, lock_spec: str) -> bool:
    for lock_name in lock_spec.split("|"):
        lock = getattr(obj, lock_name, None)
        if isinstance(lock, threading.Condition):
            lock = lock._lock
        if lock is None:
            continue
        if isinstance(lock, CheckedLock):
            if lock.held_by_current():
                return True
        else:
            # Uninstrumented lock (constructed before enablement or a
            # plain Lock): ownership is unknowable — don't guess.
            return True
    return False


def shared_field_guard(cls):
    """Class decorator: under KT_LOCKCHECK, record any rebind of a
    ``_shared_fields_`` field made without its declared lock held.
    Writes during ``__init__`` (pre-publication) are exempt — the
    guard arms when ``__init__`` returns."""
    if not enabled():
        return cls
    fields = dict(getattr(cls, "_shared_fields_", {}) or {})
    if not fields:
        return cls

    orig_setattr = cls.__setattr__
    orig_init = cls.__init__

    def __setattr__(self, name, value):
        if name in fields and getattr(self, "_lockcheck_armed_", False):
            if not _lock_held(self, fields[name]):
                _record(
                    "shared-field-write",
                    f"{cls.__name__}.{name} rebound without holding "
                    f"{fields[name]!r}",
                )
        orig_setattr(self, name, value)

    def __init__(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        orig_setattr(self, "_lockcheck_armed_", True)

    cls.__setattr__ = __setattr__
    cls.__init__ = __init__
    return cls


def assumes_held(lock_spec: str) -> Callable:
    """Method decorator: the caller must already hold ``lock_spec``
    (``"lockattr"`` or ``"a|b"`` alternates).  The static
    lock-discipline rule treats decorated methods as lock-held context;
    under KT_LOCKCHECK the assumption is VERIFIED on every entry."""

    def deco(fn):
        if not enabled():
            fn.__assumes_held__ = lock_spec
            return fn

        def wrapper(self, *args, **kwargs):
            if not _lock_held(self, lock_spec):
                _record(
                    "assumes-held",
                    f"{type(self).__name__}.{fn.__name__} entered without "
                    f"holding {lock_spec!r}",
                )
            return fn(self, *args, **kwargs)

        wrapper.__name__ = fn.__name__
        wrapper.__doc__ = fn.__doc__
        wrapper.__assumes_held__ = lock_spec
        wrapper.__wrapped__ = fn
        return wrapper

    return deco
