"""The controller-manager runtime: registry + dynamic FTC lifecycle.

The reference's controller-manager (reference:
cmd/controller-manager/app/controllermanager.go:45-178,
pkg/controllermanager/ftcmanager.go:63-249) runs two kinds of
controllers:

* always-on controllers (cluster, follower) started once at boot, behind
  the ``--controllers`` enable/disable list;
* per-FederatedTypeConfig sub-controllers (scheduler, federate, sync,
  status, statusaggregator, policyrc, nsautoprop, override,
  automigration) started and stopped dynamically as FTC objects appear,
  change and disappear — the FederatedTypeConfigManager.

Here both live in one :class:`ControllerManager`: it watches the FTC
resource on the host, (re)builds each type's controller set from the
parsed FTC (a spec change restarts the set), registers per-controller
readiness into the health registry, and exposes ``step_all`` for
deterministic drivers plus ``run`` for threaded operation.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubeadmiral_tpu.federation.automigration import AutoMigrationController
from kubeadmiral_tpu.federation.clusterctl import FederatedClusterController
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.follower import FollowerController
from kubeadmiral_tpu.federation.monitor import MonitorController
from kubeadmiral_tpu.federation.nsautoprop import NamespaceAutoPropagationController
from kubeadmiral_tpu.federation.overridectl import OverrideController
from kubeadmiral_tpu.federation.policyrc import PolicyRCController
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.federation.statusctl import StatusAggregator, StatusController
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.models.ftc import (
    FEDERATED_TYPE_CONFIGS,
    FederatedTypeConfig,
    parse_ftc,
)
from kubeadmiral_tpu.runtime.healthcheck import HealthCheckRegistry
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine
from kubeadmiral_tpu.testing.fakekube import ClusterFleet

# Always-on controller names (controllermanager.go knownControllers; the
# monitor controller is off by default there too).
CLUSTER_CONTROLLER = "cluster"
FOLLOWER_CONTROLLER = "follower"
MONITOR_CONTROLLER = "monitor"
DEFAULT_CONTROLLERS = (CLUSTER_CONTROLLER, FOLLOWER_CONTROLLER)

# Per-FTC sub-controller names (ftcmanager.go knownFTCSubControllers +
# the legacy federatedtypeconfig controller's set).
SCHEDULER = "scheduler"
FEDERATE = "federate"
AUTOMIGRATION = "automigration"
SYNC = "sync"
STATUS = "status"
STATUS_AGGREGATOR = "statusaggregator"
POLICYRC = "policyrc"
NSAUTOPROP = "nsautoprop"
OVERRIDE = "override"


@dataclass
class _FTCRuntime:
    ftc: FederatedTypeConfig
    controllers: dict[str, object] = field(default_factory=dict)


class ControllerManager:
    """One leader's controller set over one host + member fleet."""

    def __init__(
        self,
        fleet: ClusterFleet,
        enabled: Optional[list[str]] = None,
        metrics: Optional[Metrics] = None,
        health: Optional[HealthCheckRegistry] = None,
        engine: Optional[SchedulerEngine] = None,
        cluster_controller_kwargs: Optional[dict] = None,
        max_pod_listers: int = 4,
        enable_pod_pruning: bool = True,
    ):
        self.fleet = fleet
        self.host = fleet.host
        self.metrics = metrics or Metrics()
        self.health = health or HealthCheckRegistry()
        # The ambient shard identity, captured ONCE like every worker
        # does (shardmap.scoped() around manager construction shards the
        # whole controller set).  Drives per-shard snapshot artifacts
        # and the /debug/shards report; with the default 1-shard map
        # everything below behaves exactly as before.
        from kubeadmiral_tpu.federation import shardmap as _shardmap

        self.shard = _shardmap.get_default()
        # ONE pod informer shared by every per-FTC automigration
        # controller: pruned per-cluster pod caches with a bounded
        # cold-LIST semaphore (reference: federatedclient/podinformer.go,
        # --max-pod-listers / --enable-pod-pruning).
        from kubeadmiral_tpu.runtime.podinformer import PodInformer

        self.pod_informer = PodInformer(
            fleet,
            max_pod_listers=max_pod_listers,
            enable_pruning=enable_pod_pruning,
        )
        # One shared XLA engine: FTCs share compile caches and the
        # cluster view (ftcmanager starts schedulers per FTC; the batch
        # engine makes sharing the natural default).  It reports into the
        # manager's metrics registry so one /metrics scrape covers
        # controllers and the device hot path alike.
        self.engine = engine or SchedulerEngine(metrics=self.metrics)
        # The end-to-end SLO recorder (runtime/slo.py) reports into the
        # same registry, so slo_* families and member_write_seconds ride
        # the one /metrics scrape (last manager wins for the process
        # default, like the dispatch ledger's attach).
        from kubeadmiral_tpu.runtime import slo as SLO

        SLO.get_default().attach(self.metrics)
        # Durable engine snapshots (runtime/snapshot.py): opt-in via
        # KT_SNAPSHOT_DIR.  The manager owns the glue — the engine hook
        # that persists after converged ticks, the per-kind
        # resourceVersion watermarks recorded with each snapshot, and
        # the breaker registry + flight recorder riding along.
        self.snapshots = None
        from kubeadmiral_tpu.runtime.snapshot import snapshot_dir

        snap_dir = snapshot_dir()
        if snap_dir:
            from kubeadmiral_tpu.runtime.snapshot import (
                SnapshotManager,
                SnapshotStore,
                shard_snapshot_store,
            )
            from kubeadmiral_tpu.transport import breaker as B

            # Sharded: each replica persists its own keys' working set
            # into <dir>/shard-<i>/ with the shard identity + ShardMap
            # epoch in the payload (restore refuses a mismatch).
            if self.shard.shard_count > 1:
                store = shard_snapshot_store(
                    snap_dir, self.shard, metrics=self.metrics
                )
            else:
                store = SnapshotStore(snap_dir, metrics=self.metrics)
            self.snapshots = SnapshotManager(
                self.engine,
                store,
                breakers=B.for_fleet(fleet, metrics=self.metrics),
                watermark_fn=self._snapshot_watermarks,
                shard=self.shard if self.shard.shard_count > 1 else None,
            )
        self._enabled = self._resolve_enabled(enabled)
        self._lock = threading.RLock()
        self._ftcs: dict[str, _FTCRuntime] = {}
        # Set by run(): controllers started after that point get their
        # worker threads immediately.
        self._threaded_workers: Optional[int] = None

        self.always_on: dict[str, object] = {}
        if CLUSTER_CONTROLLER in self._enabled:
            self.always_on[CLUSTER_CONTROLLER] = FederatedClusterController(
                fleet, metrics=self.metrics, **(cluster_controller_kwargs or {})
            )
        self._follower: Optional[FollowerController] = None
        self.health.add_readiness("controller-manager", lambda: True)

        # The FTC watch is the FederatedTypeConfigManager reconcile loop.
        self.host.watch(FEDERATED_TYPE_CONFIGS, self._on_ftc_event, replay=True)

        # /debug/shards provider (last manager wins for the process
        # default, like the SLO attach above) + the epoch gauge every
        # scrape carries, so shard-skew triage can correlate per-shard
        # metrics with the routing generation they were produced under.
        from kubeadmiral_tpu.runtime import profiling as _profiling

        _profiling.set_shards_provider(self.shard_report)
        self.metrics.gauge(
            "shard_epoch", self.shard.epoch, shard=str(self.shard.shard_index)
        )

    def shard_report(self) -> dict:
        """The /debug/shards document: this replica's ShardMap identity
        and epoch, every shard lease's holder + freshness, per-resource
        owned-key counts (the skew view), and snapshot freshness."""
        from kubeadmiral_tpu.runtime.leaderelection import shard_lease_status

        report = self.shard.describe()
        try:
            report["leases"] = shard_lease_status(
                self.host, self.shard.shard_count
            )
        except Exception:
            report["leases"] = None  # transport without lease reads
        owned: dict[str, int] = {}
        try:
            with self._lock:
                resources = sorted(
                    rt.ftc.federated.resource for rt in self._ftcs.values()
                )
            for r in resources:
                owned[r] = sum(1 for k in self.host.keys(r) if self.shard.owns(k))
        except Exception:
            pass
        report["owned_keys"] = owned
        report["snapshot"] = (
            {
                "dir": self.snapshots.store.dir,
                "last_result": self.snapshots.last_result,
            }
            if self.snapshots is not None
            else None
        )
        return report

    @staticmethod
    def _resolve_enabled(enabled: Optional[list[str]]) -> set[str]:
        """--controllers semantics (app/util.go:55-78): names enable,
        "-name" disables, "*" means all defaults."""
        if not enabled:
            return set(DEFAULT_CONTROLLERS)
        result = set()
        star = "*" in enabled
        if star:
            result |= set(DEFAULT_CONTROLLERS)
        for name in enabled:
            if name == "*":
                continue
            if name.startswith("-"):
                result.discard(name[1:])
            else:
                result.add(name)
        return result

    # -- FTC lifecycle (ftcmanager.go:139-245) ---------------------------
    def _on_ftc_event(self, event: str, obj: dict) -> None:
        name = obj["metadata"]["name"]
        if event == "DELETED" or obj["metadata"].get("deletionTimestamp"):
            self._stop_ftc(name)
            return
        try:
            ftc = parse_ftc(obj)
        except Exception:
            self.metrics.counter("ftc-manager.parse_errors")
            return
        with self._lock:
            existing = self._ftcs.get(name)
            if existing is not None and existing.ftc == ftc:
                return  # no spec change
            if existing is not None:
                self._stop_ftc(name)
            self._start_ftc(ftc)

    def _start_ftc(self, ftc: FederatedTypeConfig) -> None:
        runtime = _FTCRuntime(ftc=ftc)
        pipeline = {c for group in ftc.controllers for c in group}
        controllers = runtime.controllers
        controllers[FEDERATE] = FederateController(
            self.host, ftc, metrics=self.metrics
        )
        if "kubeadmiral.io/global-scheduler" in pipeline:
            controllers[SCHEDULER] = SchedulerController(
                self.host, ftc, engine=self.engine, metrics=self.metrics
            )
        if "kubeadmiral.io/overridepolicy-controller" in pipeline:
            controllers[OVERRIDE] = OverrideController(
                self.host, ftc, metrics=self.metrics
            )
        if "kubeadmiral.io/nsautoprop-controller" in pipeline:
            controllers[NSAUTOPROP] = NamespaceAutoPropagationController(
                self.host, ftc, metrics=self.metrics
            )
        controllers[SYNC] = SyncController(self.fleet, ftc, metrics=self.metrics)
        controllers[POLICYRC] = PolicyRCController(
            self.host, ftc, metrics=self.metrics
        )
        if ftc.status_collection and ftc.status is not None:
            controllers[STATUS] = StatusController(
                self.fleet, ftc, metrics=self.metrics
            )
        if ftc.status_aggregation:
            controllers[STATUS_AGGREGATOR] = StatusAggregator(
                self.fleet, ftc, metrics=self.metrics
            )
        if ftc.auto_migration:
            controllers[AUTOMIGRATION] = AutoMigrationController(
                self.fleet, ftc, metrics=self.metrics,
                pod_informer=self.pod_informer,
            )
        if MONITOR_CONTROLLER in self._enabled:
            # Off by default, like the reference's monitor controller.
            controllers[MONITOR_CONTROLLER] = MonitorController(
                self.host, ftc, metrics=self.metrics
            )
        with self._lock:
            self._ftcs[ftc.name] = runtime
        for cname, controller in controllers.items():
            self.health.add_readiness(
                f"{ftc.name}/{cname}", self._controller_ready(controller)
            )
            self._maybe_thread(controller)
        self._rebuild_follower()

    def _teardown(self, controller) -> None:
        """Fully release a dynamically stopped controller: worker
        threads, watch registrations, dispatch pools.  Controllers with
        watch-holding sub-objects expose them via ``watch_owners()``
        (the generic contract; hardcoding attribute names here would
        silently leak the next sub-indexer's watches)."""
        for worker in self._workers_of(controller):
            worker.stop()
        owners = getattr(controller, "watch_owners", None)
        for owner in owners() if owners is not None else (controller,):
            self.fleet.unwatch_owner(owner)
        pool = getattr(controller, "pool", None)
        if pool is not None:
            pool.shutdown(wait=False)

    def _maybe_thread(self, controller) -> None:
        """After run(), newly started controllers thread immediately."""
        if self._threaded_workers is None:
            return
        for worker in self._workers_of(controller):
            if not worker._threads:
                worker.run(self._threaded_workers)

    def _stop_ftc(self, name: str) -> None:
        with self._lock:
            runtime = self._ftcs.pop(name, None)
        if runtime is None:
            return
        for cname, controller in runtime.controllers.items():
            self.health.remove(f"{name}/{cname}")
            self._teardown(controller)
        self._rebuild_follower()

    def _rebuild_follower(self) -> None:
        """The follower controller spans all workload FTCs; rebuild it
        when the FTC set changes (reference starts it once with the full
        informer set; here the FTC set is dynamic)."""
        if FOLLOWER_CONTROLLER not in self._enabled:
            return
        if self._follower is not None:
            self._teardown(self._follower)
        with self._lock:
            ftcs = [rt.ftc for rt in self._ftcs.values()]
        self._follower = FollowerController(self.host, ftcs, metrics=self.metrics)
        self._maybe_thread(self._follower)

    @staticmethod
    def _controller_ready(controller) -> Callable[[], bool]:
        return lambda: True  # in-memory informers are synchronously warm

    @staticmethod
    def _workers_of(controller) -> list:
        workers = []
        for attr in ("worker", "count_worker", "pp_persist_worker", "op_persist_worker"):
            worker = getattr(controller, attr, None)
            if worker is not None and worker not in workers:
                workers.append(worker)
        return workers

    # -- driving ---------------------------------------------------------
    def _all_controllers(self) -> list:
        with self._lock:
            out = list(self.always_on.values())
            if self._follower is not None:
                out.append(self._follower)
            for runtime in self._ftcs.values():
                out.extend(runtime.controllers.values())
        return out

    def step_all(self) -> bool:
        """One reconcile step of every controller; True when any
        progressed (the deterministic driver used by tests/benches)."""
        progressed = False
        for controller in self._all_controllers():
            step_all = getattr(controller, "step_all", None)
            if step_all is not None:
                progressed |= step_all()
                continue
            worker = getattr(controller, "worker", None)
            if worker is not None:
                progressed |= worker.step()
        return progressed

    def settle(self, max_rounds: int = 200) -> None:
        for _ in range(max_rounds):
            if not self.step_all():
                return

    def run(self, workers_per_controller: int = 1) -> None:
        """Threaded operation: every controller worker gets its own
        thread(s) (the reference's N goroutines per ReconcileWorker).
        Controllers started later — new/changed FTCs — are threaded as
        they appear."""
        from kubeadmiral_tpu.runtime.gctune import tune_gc_for_service
        from kubeadmiral_tpu.runtime.logconf import setup_logging

        tune_gc_for_service()
        # One process-wide handler for the kubeadmiral.* logger tree
        # (KT_LOG_LEVEL / KT_LOG_JSON; idempotent — an embedder that
        # configured logging first wins via its own handlers).
        setup_logging()
        # Crash recovery: stage the newest valid snapshot into the
        # engine BEFORE the first reconcile tick — a warm replacement
        # resumes via the no-op replay / drift-gate paths instead of a
        # cold solve.  A missing/corrupt snapshot degrades to cold.
        if self.snapshots is not None:
            try:
                self.snapshots.restore()
            except Exception:
                logging.getLogger("kubeadmiral.manager").warning(
                    "snapshot restore skipped", exc_info=True
                )
        self._threaded_workers = workers_per_controller
        # Pre-warm the engine's XLA programs for the current topology in
        # a background thread: the first real scheduling tick should hit
        # compiled (or persistent-cache-loaded) programs instead of
        # stalling the reconcile loop on XLA (VERDICT r2 #3).
        try:
            from kubeadmiral_tpu.federation.common import FEDERATED_CLUSTERS

            # list() (not list_view) — present on FakeKube AND HttpKube,
            # so prewarm also runs over the real transport.
            clusters = self.host.list(FEDERATED_CLUSTERS)
            # Extended resources advertised by members are part of the
            # request tensor's R axis, i.e. of the program shape.
            scalars = sorted(
                {
                    r
                    for c in clusters
                    for r in (
                        c.get("status", {}).get("resources", {}).get("allocatable")
                        or {}
                    )
                    if r not in ("cpu", "memory", "ephemeral-storage", "pods")
                }
            )
            with self._lock:
                fed_resources = {
                    rt.ftc.federated.resource for rt in self._ftcs.values()
                }
            all_keys = [k for r in fed_resources for k in self.host.keys(r)]
            n_objects = len(all_keys) or self.engine.chunk_size
            # The longest object key picks the compact key-byte bucket.
            key_len = max((len(k) for k in all_keys), default=32)
            from kubeadmiral_tpu.scheduler.webhook import SCHEDULER_WEBHOOK_CONFIGS

            webhooks = bool(self.host.list(SCHEDULER_WEBHOOK_CONFIGS))
            self.engine.prewarm(
                n_objects,
                max(1, len(clusters)),
                scalar_resources=scalars,
                key_len=key_len,
                webhooks=webhooks,
            )
        except Exception:
            import logging

            logging.getLogger("kubeadmiral.manager").warning(
                "engine prewarm skipped", exc_info=True
            )
        for controller in self._all_controllers():
            self._maybe_thread(controller)

    def stop(self) -> None:
        # Clear the threading mode first so controllers started by a
        # late watch event stay inert instead of spawning threads on a
        # stopped manager.
        self._threaded_workers = None
        for controller in self._all_controllers():
            for worker in self._workers_of(controller):
                worker.stop()

    def _snapshot_watermarks(self) -> Optional[dict]:
        """Per-kind resourceVersion watermarks recorded with each
        snapshot: the max resourceVersion over every object of each
        federated kind (plus the cluster CRs).  A successor whose relist
        sees the same watermarks knows the snapshot world IS the current
        world (the engine still re-proves it row-by-row before trusting
        anything)."""
        try:
            from kubeadmiral_tpu.federation.common import FEDERATED_CLUSTERS

            with self._lock:
                resources = {
                    rt.ftc.federated.resource for rt in self._ftcs.values()
                }
            resources.add(FEDERATED_CLUSTERS)
            marks: dict[str, int] = {}
            for r in sorted(resources):
                lister = getattr(self.host, "list_view", None) or self.host.list
                top = 0
                for obj in lister(r):
                    try:
                        top = max(
                            top,
                            int(obj.get("metadata", {}).get("resourceVersion", 0)),
                        )
                    except (TypeError, ValueError):
                        continue
                marks[r] = top
            return marks
        except Exception:
            return None

    def shutdown(self, deadline_s: Optional[float] = None) -> dict:
        """Graceful termination (the SIGTERM path): stop reconcile
        workers, drain in-flight dispatch flushes under a bounded
        deadline (``KT_SHUTDOWN_DEADLINE_S``), shed + account whatever
        cannot land (member_shed_writes_total; the apiserver-durable
        state re-drives it on the next boot), and write a final engine
        snapshot so the successor resumes warm.  Leadership release
        stays with the caller that owns the elector (__main__)."""
        from kubeadmiral_tpu.federation import dispatch as D

        if deadline_s is None:
            deadline_s = float(os.environ.get("KT_SHUTDOWN_DEADLINE_S", "10"))
        t0 = time.monotonic()
        self.stop()
        shed = D.finalize_all_sinks(
            max(0.0, deadline_s - (time.monotonic() - t0))
        )
        snapshot_path = None
        if self.snapshots is not None:
            try:
                snapshot_path = self.snapshots.snapshot()
            except Exception:
                logging.getLogger("kubeadmiral.manager").warning(
                    "final snapshot failed", exc_info=True
                )
        summary = {
            "shed_writes": shed,
            "snapshot": snapshot_path,
            "elapsed_s": round(time.monotonic() - t0, 3),
        }
        logging.getLogger("kubeadmiral.manager").info(
            "graceful shutdown: shed=%d snapshot=%s elapsed=%.2fs",
            shed, snapshot_path, summary["elapsed_s"],
        )
        return summary
