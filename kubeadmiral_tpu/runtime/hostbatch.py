"""Host-side bulk write staging for batch-tick controllers.

One BatchWorker tick stages every object's host writes here and flushes
them as ``host.batch()`` round trips (transport/apiserver.py
_serve_batch) — the host-side sibling of dispatch.BatchSink's per-member
bulk writes.  Used by the sync controller (status/annotation/version
writes) and the scheduler (placement persists).
"""

from __future__ import annotations

from typing import Callable, Optional


class HostBatch:
    """Host-side write staging for one BatchWorker tick: every object's
    status/annotation update rides ONE ``host.batch()`` round trip per
    drain instead of one round trip per write.  Callbacks may stage
    follow-up ops (the syncing annotation uses the resourceVersion the
    status write returned), so ``flush`` drains until quiescent.
    Per-op conflicts fall back to the caller's synchronous retry loops."""

    def __init__(self, host):
        self.host = host
        self._ops: list[tuple[dict, Callable[[dict], None], Optional[Callable[[], None]]]] = []

    def stage(
        self,
        op: dict,
        on_result: Callable[[dict], None],
        on_panic: Optional[Callable[[], None]] = None,
    ) -> None:
        self._ops.append((op, on_result, on_panic))

    def flush(self) -> None:
        while self._ops:
            ops, self._ops = self._ops, []
            try:
                results = self.host.batch([op for op, _, _ in ops])
            except Exception as e:
                results = [
                    {"code": 500, "status": {"reason": "Transport", "message": str(e)}}
                ] * len(ops)
            if len(results) < len(ops):
                results = list(results) + [
                    {"code": 500, "status": {"reason": "Transport",
                                             "message": "batch result missing"}}
                ] * (len(ops) - len(results))
            for (_, on_result, on_panic), result in zip(ops, results):
                try:
                    on_result(result)
                except Exception:
                    # A callback (or its synchronous fallback) died: the
                    # object must RETRY, not silently pass as finished.
                    if on_panic is not None:
                        on_panic()
