"""The metric catalog: every metric name this control plane may emit.

One source of truth shared by three consumers:

* ``tools/metrics_lint.py`` (``make metrics-lint``) walks the source for
  emission calls and FAILS on names not listed here — new metrics must
  be cataloged before they ship, so the exposition never drifts from
  the documentation;
* ``docs/observability.md`` renders this as the operator-facing metric
  reference;
* ``bench.py`` embeds engine series under these names in its BENCH
  artifact, so the perf trajectory and live ``/metrics`` scrapes share
  one vocabulary.

``CATALOG`` holds the labeled, Prometheus-shaped families.  The
``LEGACY_PATTERNS`` grandfather the pre-exposition dotted names (worker
``<name>.panic`` counters, ``monitor.<ftc>.*`` gauges, per-controller
``scheduler-<ftc>.*`` counters): they still render (sanitized) in the
exposition and existing tests read them, but new emissions should use
the labeled families.
"""

from __future__ import annotations

from fnmatch import fnmatch
from typing import NamedTuple


class MetricSpec(NamedTuple):
    type: str  # counter | gauge | histogram
    unit: str
    labels: tuple[str, ...]
    help: str


CATALOG: dict[str, MetricSpec] = {
    # -- reconcile workers (runtime/worker.py) ---------------------------
    "worker_reconciles_total": MetricSpec(
        "counter", "reconciles", ("controller",),
        "Keys reconciled, per controller worker."),
    "worker_exceptions_total": MetricSpec(
        "counter", "exceptions", ("controller",),
        "Reconciles that escaped with an exception (the panic analogue)."),
    "worker_retries_total": MetricSpec(
        "counter", "retries", ("controller",),
        "Keys requeued with exponential backoff after a failed reconcile."),
    "worker_requeues_total": MetricSpec(
        "counter", "requeues", ("controller",),
        "Successful reconciles that scheduled a fixed-delay revisit."),
    "worker_process_seconds": MetricSpec(
        "histogram", "seconds", ("controller",),
        "Per-key reconcile latency (single-key workers)."),
    "worker_tick_seconds": MetricSpec(
        "histogram", "seconds", ("controller",),
        "Whole-batch tick latency (batch workers)."),
    "worker_queue_wait_seconds": MetricSpec(
        "histogram", "seconds", ("controller",),
        "Enqueue-to-drain wait of dequeued keys (sampled per drain)."),
    "worker_queue_depth": MetricSpec(
        "gauge", "keys", ("controller",),
        "Pending keys in the controller's dirty queue."),
    "worker_admission_total": MetricSpec(
        "counter", "enqueues", ("controller",),
        "Enqueues deferred by queue-depth-driven admission "
        "(KT_ADMIT_DEPTH / KT_ADMIT_DELAY_MS): past the depth "
        "threshold, new keys coalesce behind a short delay so an event "
        "flood drains as bigger amortized ticks (freshness gauges "
        "degrade gracefully) instead of thrashing per-event p99."),
    "worker_queue_oldest_age_seconds": MetricSpec(
        "gauge", "seconds", ("controller",),
        "Age of the longest-pending key; the first stuck-controller signal."),
    "member_watch_flushes_total": MetricSpec(
        "counter", "flushes", ("controller",),
        "Coalesced member-watch deliveries received by sync "
        "(KT_STORE_COALESCE): one committed store flush per count."),
    "member_watch_flush_events_total": MetricSpec(
        "counter", "events", ("controller",),
        "Member-watch events carried by coalesced deliveries; divided "
        "by member_watch_flushes_total this is the store-side "
        "coalescing factor."),
    # -- XLA scheduling engine (scheduler/engine.py, ops/pipeline.py) ----
    "engine_ticks_total": MetricSpec(
        "counter", "ticks", (),
        "schedule() calls (any fast path included)."),
    "engine_tick_objects": MetricSpec(
        "gauge", "objects", (),
        "Batch size of the last scheduling tick."),
    "engine_tick_seconds": MetricSpec(
        "histogram", "seconds", (),
        "Wall time of one whole scheduling tick."),
    "engine_tick_stage_seconds": MetricSpec(
        "histogram", "seconds", ("stage",),
        "Per-tick wall time of one stage: featurize, device, fetch, "
        "decode (+ follower when a FollowerIndex is applied), plus "
        "sub-phase splits — gate_wait and overflow_fetch overlap the "
        "fetch stage (drift-gate compute blocked on, and wide [n, C] "
        "K-overflow re-fetches), narrow_fallback is the dense re-solve "
        "+ repair of certificate-failed narrow rows."),
    "engine_chunk_cache_total": MetricSpec(
        "counter", "chunks", ("result",),
        "Incremental-featurization outcomes per chunk: hit, patch, miss."),
    "engine_fetch_total": MetricSpec(
        "counter", "chunks", ("path",),
        "Result-fetch path per chunk: noop, subbatch, skip, delta, full."),
    "engine_fetch_bytes_total": MetricSpec(
        "counter", "bytes", ("format",),
        "Device->host result-transfer volume, labeled by the engine's "
        "fetch wire format (packed = [B,K] top-k-compacted rows, dense "
        "= full [B,C] planes; KT_FETCH_FORMAT)."),
    "engine_fetch_overflow_rows_total": MetricSpec(
        "counter", "rows", (),
        "Packed-export K-overflow rows (selected set exceeded the K "
        "bucket) re-fetched through the dense row-gather fallback."),
    "engine_upload_bytes_total": MetricSpec(
        "counter", "bytes", ("plane",),
        "Host->device input-transfer volume: object = cached per-object "
        "tensors (full uploads, row scatter-repairs, sub-batch slabs), "
        "cluster = the shared once-per-tick cluster-axis planes and "
        "vocabulary tables.  A drift tick must move cluster bytes only."),
    "engine_drift_rows_total": MetricSpec(
        "counter", "rows", ("kind",),
        "Drift-gate row classification on cluster-capacity drift ticks: "
        "skip = provably identical outputs, wcheck = dynamic-weight "
        "comparison rows, wcheck_changed = weight comparisons that "
        "found a difference, unified = survivors settled by the ONE "
        "unified survivor kernel (the default path — subsumes the "
        "resolve/replan/score_only specializations, KT_SURVIVOR_"
        "UNIFIED), resolve = survivors settled by the sort-free "
        "drift-resolve program, replan = kinf fit-flip survivors "
        "settled by the selection-known replan (no select sort), "
        "score_only = finite-K fit-flip survivors settled by the "
        "stored-plane score-only narrow solve (the latter three engage "
        "only under KT_SURVIVOR_UNIFIED=0), *_fallback = rows of those "
        "paths whose certificate failed (slab re-solve), recompute = "
        "rows re-scheduled through the sub-batch slabs."),
    "engine_stale_rows_total": MetricSpec(
        "counter", "rows", ("phase",),
        "Stale device-input rows scatter-repaired, by phase: churn = "
        "repaired EAGERLY inside the tick that made them stale (the "
        "default), drift = repaired on a drift gate's critical path "
        "(the backstop — must stay 0 under eager repair; nonzero means "
        "a churn path left rows it could not reach eagerly), dispatch "
        "= repaired at a full-dispatch upload."),
    "engine_featurize_rows_total": MetricSpec(
        "counter", "rows", ("path",),
        "Rows featurized per path: full = whole-chunk rebuilds (cold "
        "boot, topology change, vocabulary overflow, webhook ticks, "
        "snapshot restore), delta = row-wise patches of cached chunks. "
        "A steady/churn tick must move delta rows only — full rows "
        "outside cold/topology transitions mean the O(changed-rows) "
        "featurization contract regressed (KT_DELTA_FEAT)."),
    "engine_gate_inflight": MetricSpec(
        "gauge", "gates", (),
        "Drift-gate programs currently in flight on the device (set at "
        "gate-drain entry, cleared when every gated chunk settles)."),
    # -- dispatch ledger (runtime/devprof.py) -----------------------------
    "engine_device_seconds": MetricSpec(
        "histogram", "seconds", ("program", "device"),
        "Measured device occupancy per dispatched program (the dispatch "
        "ledger's in-order chain model: ready_i - max(dispatch_i, "
        "ready_{i-1})), labeled by program kind (tick, tick_narrow, "
        "gate, resolve, pack, ...) and device lane (d<id> for a single "
        "committed device, mesh<N> for a GSPMD program spanning N "
        "devices).  Pure execution time — jit tracing happens host-side "
        "before the observation and never lands here."),
    "engine_queue_wait_seconds": MetricSpec(
        "histogram", "seconds", ("program", "device"),
        "Time each dispatched program sat enqueued behind earlier "
        "device work before executing — the dispatch backpressure the "
        "host-side stage timers misattribute to fetch/decode.  Same "
        "device-lane label as engine_device_seconds."),
    "engine_resident_bytes": MetricSpec(
        "gauge", "bytes", ("family",),
        "Device bytes of the engine's resident working set, by plane "
        "family (prev_planes = the six [B, C] output planes, per_object "
        "= cached input tensors, tiebreak = precomputed planner "
        "tie-break planes, vectors = [B] nfeas / score-exactness "
        "companions) — the live half of the c6 memory census "
        "(runtime/census.py; bench --scenario census)."),
    "engine_resident_bytes_per_device": MetricSpec(
        "gauge", "bytes", (),
        "Resident working-set bytes PER DEVICE (rows-sharded planes "
        "divided by the objects-axis device count, replicated vectors "
        "booked whole) — the number compared against the KT_HBM_BUDGET_GB "
        "knob by the census."),
    "engine_dispatch_inflight": MetricSpec(
        "gauge", "dispatches", (),
        "Dispatched programs whose readiness the ledger has not yet "
        "observed (the device queue depth as the ledger sees it)."),
    "engine_stream_stage_seconds": MetricSpec(
        "histogram", "seconds", ("stage",),
        "Streaming event latency decomposed by stage: queued (event "
        "enqueue -> its slab's flush start, per event), apply (event "
        "application + world snapshot, per flush), engine (the flush's "
        "engine tick, per flush).  queued+apply+engine bounds the "
        "event->placement-visible latency histogram.  Extended bucket "
        "ladder (to 120s): the queued stage legitimately reaches "
        "seconds under slab-age coalescing and must not saturate +Inf."),
    "engine_stream_events_total": MetricSpec(
        "counter", "events", ("kind",),
        "Streaming-scheduler events flushed, by kind: upsert (object "
        "add/update), delete, capacity (cluster drift snapshot)."),
    "engine_stream_flushes_total": MetricSpec(
        "counter", "flushes", ("trigger",),
        "Row-slab flushes by watermark trigger: rows (KT_SLAB_ROWS "
        "reached), age (KT_SLAB_AGE_MS exceeded), manual."),
    "engine_stream_slab_depth": MetricSpec(
        "gauge", "events", (),
        "Events currently coalescing in the pending row slab."),
    "engine_stream_slab_rows": MetricSpec(
        "gauge", "rows", (),
        "Object rows carried by the most recent slab flush."),
    "engine_stream_world_rows": MetricSpec(
        "gauge", "rows", (),
        "Total unit-list rows owned by the streaming scheduler "
        "(placeholder slots included)."),
    "engine_stream_latency_seconds": MetricSpec(
        "histogram", "seconds", (),
        "Event enqueue to placement-visible latency (per event, "
        "recorded at its slab's flush)."),
    "engine_stream_flush_seconds": MetricSpec(
        "histogram", "seconds", (),
        "Wall time of one slab flush (apply events + engine tick)."),
    "engine_narrow_rows_total": MetricSpec(
        "counter", "rows", ("path",),
        "Narrow-solve (KT_NARROW) row outcomes: narrow = rows whose "
        "per-row exactness certificate held (solved over the top-M "
        "candidate columns), fallback = uncertified rows re-solved "
        "through the full-width dense program (bit-identical by "
        "construction either way)."),
    "engine_aot_programs_total": MetricSpec(
        "counter", "programs", ("result",),
        "AOT program-store resolutions per (program, shape signature): "
        "loaded = deserialized from the jax.export manifest under "
        "KT_COMPILE_CACHE_DIR (no Python trace), traced = live trace "
        "(exported too when the prewarm ladder is running), rejected = "
        "a manifest entry existed but failed its guard (jax/platform/"
        "code-hash mismatch, CRC, deserialize or first-call error) and "
        "fell back to a live trace."),
    "engine_snapshot_total": MetricSpec(
        "counter", "snapshots", ("result",),
        "Durable engine-snapshot outcomes (KT_SNAPSHOT_DIR): written, "
        "loaded_fresh (restore rode the no-op replay — cluster tensors "
        "and row signatures bit-identical), loaded_stale (restore "
        "resumed through the drift-gate/sub-batch revalidation), "
        "rejected (config/topology/geometry mismatch -> cold), "
        "quarantined (torn/corrupt/version-mismatched file renamed "
        "aside, never loaded), skipped (nothing coherent to persist), "
        "shard_mismatch (snapshot stamped for a different shard "
        "identity/epoch than this replica's ShardMap -> cold)."),
    "engine_snapshot_bytes": MetricSpec(
        "gauge", "bytes", (),
        "Payload size of the most recent durable engine snapshot."),
    "engine_snapshot_write_seconds": MetricSpec(
        "histogram", "seconds", (),
        "Wall time of one atomic snapshot persist (serialize + fsync + "
        "rename), inside the post-tick hook."),
    "engine_persistent_cache_total": MetricSpec(
        "counter", "traces", ("result",),
        "Persistent XLA compilation-cache outcome per observed trace: "
        "miss wrote a new on-disk entry (a real compile), hit loaded "
        "the program from KT_COMPILE_CACHE_DIR."),
    "engine_compile_cache_total": MetricSpec(
        "counter", "dispatches", ("result", "shape"),
        "Program-shape cache outcome per device dispatch: a shape's "
        "first dispatch is the miss that traces a new XLA program."),
    "engine_dispatches_total": MetricSpec(
        "counter", "dispatches", ("shape",),
        "Device dispatches per (format, rows, clusters) shape bucket."),
    "engine_xla_compiles_total": MetricSpec(
        "counter", "compiles", ("program", "shape"),
        "True XLA traces observed in ops.pipeline (the jitted body ran), "
        "per program and shape."),
    "engine_vocab_overflow_total": MetricSpec(
        "counter", "overflows", ("scope",),
        "Compact-vocabulary cap overflows forcing the dense fallback: "
        "topology (vocabulary build), chunk (full featurize), patch "
        "(row re-featurize)."),
    "engine_program_shapes": MetricSpec(
        "gauge", "programs", (),
        "Distinct program shapes dispatched since engine construction."),
    # -- decision flight recorder (runtime/flightrec.py) -----------------
    "flightrec_records": MetricSpec(
        "gauge", "objects", (),
        "Per-object decision records currently held by the flight "
        "recorder's ring (the /debug/explain working set)."),
    "flightrec_bytes": MetricSpec(
        "gauge", "bytes", (),
        "Memory held by flight-recorder decision records (bounded by "
        "KT_FLIGHTREC_BYTES, oldest ticks evicted first)."),
    "flightrec_ring_ticks": MetricSpec(
        "gauge", "ticks", (),
        "Tick entries in the flight recorder's bounded ring."),
    # -- controllers (federation/) ---------------------------------------
    "scheduler_scheduled_total": MetricSpec(
        "counter", "objects", ("ftc",),
        "Objects pushed through the engine by the scheduler controller."),
    "pending_controllers_depth": MetricSpec(
        "gauge", "objects", ("ftc", "controller"),
        "Objects whose FIRST pending-controllers group names the "
        "controller — each pipeline stage's backlog."),
    "placement_drift_objects": MetricSpec(
        "gauge", "objects", ("ftc", "kind"),
        "Desired-vs-observed placement drift found by the monitor "
        "controller's detector, per kind: missing (desired placement "
        "absent from the member), orphan (member object outside the "
        "desired set), replicas (member replicas != scheduler override), "
        "decision (persisted placement != flight-recorder decision)."),
    # -- member fault tolerance (transport/breaker.py, federation/) ------
    "member_breaker_state": MetricSpec(
        "gauge", "state", ("cluster",),
        "Per-member circuit-breaker state: 0 closed (healthy), 1 "
        "half-open (cooled down, probing), 2 open (short-circuiting). "
        "Surfaced with full detail at GET /debug/members."),
    "member_dispatch_retries_total": MetricSpec(
        "counter", "retries", ("cluster",),
        "Member-write operations re-sent by the dispatch retry budget "
        "(transport failures, 5xx results, 409-after-conflict-refresh) "
        "with bounded exponential backoff + jitter under the per-tick "
        "deadline (KT_RETRY_*, KT_DISPATCH_DEADLINE_S)."),
    "member_shed_writes_total": MetricSpec(
        "counter", "writes", ("cluster",),
        "Member writes shed off the tick's critical path: breaker-open "
        "short-circuits (recorded as ClusterNotReady immediately) and "
        "flush-deadline expiries (statuses stay *_TIMED_OUT); the "
        "owning worker's backoff requeue re-drives them."),
    "member_probe_latency": MetricSpec(
        "histogram", "seconds", ("cluster",),
        "Member /healthz heartbeat probe latency (the cluster "
        "controller's reachability probe, which doubles as the "
        "breaker's half-open probe)."),
    # -- end-to-end SLO layer (runtime/slo.py) ----------------------------
    "slo_event_to_written_seconds": MetricSpec(
        "histogram", "seconds", ("stage",),
        "Event→placement-written latency decomposed by pipeline stage "
        "(the provenance-token decomposition; stages in SLO_STAGES "
        "order plus 'total').  Consecutive intervals of one clock — the "
        "stage sum equals the measured end-to-end latency by "
        "construction.  Extended buckets (to 300s) so outage-scale "
        "latencies land in finite buckets."),
    "slo_oldest_pending_event_seconds": MetricSpec(
        "gauge", "seconds", (),
        "Age of the oldest watch event whose expected member writes "
        "have not all acked — how stale the written world is versus the "
        "observed world.  Rises monotonically while a dispatch path is "
        "wedged, even when no new events flow; sampled by the monitor "
        "tick."),
    "slo_unwritten_placements": MetricSpec(
        "gauge", "placements", (),
        "Expected (object, member) placement writes not yet acked "
        "across all pending provenance tokens — the freshness gauge's "
        "volume companion."),
    "slo_burn_rate": MetricSpec(
        "gauge", "ratio", ("objective", "window"),
        "Error-budget burn rate per declared SLO objective "
        "(SLO_OBJECTIVES) and window: 1.0 = spending budget exactly as "
        "fast as allowed; an objective is RED when every window burns "
        "≥ 1.  Served with red/green detail at GET /debug/slo."),
    "slo_events_total": MetricSpec(
        "counter", "events", ("result",),
        "Provenance-token lifecycle outcomes: minted (new token), "
        "superseded (newer event replaced an in-flight token), echo "
        "(MODIFIED without a generation bump — our own write echo, no "
        "token), dropped (pending cap hit), written (finalized on full "
        "ack), settled (no-op sync round, dropped without a sample), "
        "forgotten (object deleted mid-flight), expired "
        "(KT_SLO_MAX_AGE_S aged out)."),
    "member_write_seconds": MetricSpec(
        "histogram", "seconds", ("cluster",),
        "Per-member write-batch round-trip latency (retries included) "
        "as dispatch observed it — joined with breaker state and "
        "shed/retry tallies in GET /debug/members, so a slow member is "
        "distinguishable from a slow engine."),
    "member_bulk_writes_total": MetricSpec(
        "counter", "requests", ("cluster", "result"),
        "Coalesced bulk member-write requests (dispatch.run_member_"
        "batches; KT_WRITE_COALESCE/KT_MEMBER_BATCH/KT_MEMBER_INFLIGHT) "
        "by outcome: ok (every op landed), partial (per-op failures in "
        "the results — retried per item), transport (the whole request "
        "failed at the transport after retries).  Joined with the "
        "batch-size reservoir in GET /debug/members."),
    "member_batch_ops": MetricSpec(
        "histogram", "ops", (),
        "Operations per coalesced bulk member-write request — the "
        "batch-size distribution of the write-path coalescing window "
        "(1 everywhere means KT_WRITE_COALESCE=0 or nothing to "
        "amortize)."),
    # -- per-tenant attribution (runtime/tenancy.py, ISSUE 16) ----------
    # The tenant label is namespace-derived (KT_TENANT_LABEL overrides)
    # and BOUNDED: at most KT_TENANT_MAX distinct values, later
    # arrivals collapse into "~other" — so these families can never
    # blow up the registry.  Full report at GET /debug/tenants.
    "tenant_events_total": MetricSpec(
        "counter", "events", ("tenant", "result"),
        "Finalized event→placement-written provenance tokens per "
        "tenant: good (within the event_to_written_p99 threshold) vs "
        "bad (breached it) — the per-tenant numerator/denominator of "
        "the error-budget burn."),
    "tenant_slo_burn": MetricSpec(
        "gauge", "ratio", ("tenant",),
        "Whole-run event_to_written_p99 error-budget burn per tenant "
        "(bad fraction / allowed bad fraction; 1.0 = spending exactly "
        "as fast as allowed) — WHICH tenant is burning the budget, "
        "where slo_burn_rate only says the control plane is."),
    "tenant_stage_seconds": MetricSpec(
        "histogram", "seconds", ("tenant", "stage"),
        "Per-tenant share of the provenance stage decomposition "
        "(queued/slab/engine/fetch/dispatch/write) — a tenant whose "
        "latency lives in `write` has slow members, one in `queued` is "
        "being back-pressured."),
    "tenant_write_seconds": MetricSpec(
        "histogram", "seconds", ("tenant",),
        "Member-write round-trip latency attributed to the written "
        "ops' tenant (retries included) — member_write_seconds sliced "
        "by who, not where."),
    "tenant_shed_writes_total": MetricSpec(
        "counter", "writes", ("tenant",),
        "Member writes shed by an open circuit breaker, attributed to "
        "the shed ops' tenant — whose freshness a degraded member is "
        "costing."),
    "tenant_admission_deferrals_total": MetricSpec(
        "counter", "deferrals", ("tenant",),
        "Worker-queue admission deferrals (KT_ADMISSION depth gate) "
        "per tenant of the deferred key — who is driving queue-depth "
        "backpressure."),
    "tenant_rows_flushed_total": MetricSpec(
        "counter", "rows", ("tenant",),
        "Streaming-slab rows flushed into engine ticks per tenant — "
        "the scheduling-demand side of the attribution (arrival "
        "volume, pre-placement)."),
    "tenant_scheduled_total": MetricSpec(
        "counter", "objects", ("tenant",),
        "Objects pushed through the batch scheduler per tenant "
        "(rescheduling included) — the demand denominator for weighted "
        "fair admission (ROADMAP item 4)."),
    # -- fleet observatory (ISSUE 17) -----------------------------------
    # Member-apiserver request accounting (transport/apiserver.py) plus
    # the crash-durable telemetry spill (runtime/telespill.py) and the
    # manager-side fleet scraper (runtime/fleetscrape.py) feeding
    # GET /debug/fleet.  See docs/observability.md § Fleet observatory.
    "apiserver_requests_total": MetricSpec(
        "counter", "requests", ("verb",),
        "Requests served by a member apiserver, by verb (get/list/"
        "watch/create/update/update_status/delete/batch) — scraped "
        "from every member's /metrics by the fleet scraper, so the "
        "merged pane shows who the managers are actually hammering."),
    "telespill_records_total": MetricSpec(
        "counter", "records", ("kind",),
        "Telemetry records spilled to the crash-durable segment log "
        "(KT_TELEMETRY_DIR), by kind (spans/timeline/flightrec)."),
    "telespill_bytes_written_total": MetricSpec(
        "counter", "bytes", (),
        "Framed bytes appended to spill segments (frame headers "
        "included) — the spill's disk-rate denominator against "
        "KT_SPILL_BYTES."),
    "telespill_segment_rotations_total": MetricSpec(
        "counter", "rotations", (),
        "Spill segment files opened (first open included): rotation "
        "grain is max_bytes/8, so a fast-rotating spill means the "
        "telemetry volume outruns the byte budget."),
    "telespill_segments_deleted_total": MetricSpec(
        "counter", "segments", (),
        "Oldest spill segments pruned to keep one instance under "
        "KT_SPILL_BYTES — history lost to the byte bound, visible."),
    "telespill_quarantined_total": MetricSpec(
        "counter", "segments", (),
        "Damaged spill segments renamed *.quarantined on read (bad "
        "magic, torn frame, CRC mismatch): the fully-framed prefix is "
        "salvaged, the file is never re-read."),
    "fleet_scrapes_total": MetricSpec(
        "counter", "scrapes", (),
        "Whole-roster fleet scrapes (GET /debug/fleet refreshes plus "
        "KT_FLEET_SCRAPE_S background refreshes)."),
    "fleet_scrape_errors_total": MetricSpec(
        "counter", "errors", (),
        "Per-instance scrape failures across fleet scrapes (an "
        "unreachable or non-200 member /metrics) — nonzero means the "
        "merged pane is PARTIAL, the down members are named in the "
        "payload."),
    "fleet_instances": MetricSpec(
        "gauge", "instances", (),
        "Roster size of the last fleet scrape (manager's own registry "
        "included when attached)."),
    # -- sharded control plane (federation/shardmap.py, ISSUE 20) --------
    "shard_epoch": MetricSpec(
        "gauge", "epoch", ("shard",),
        "Routing generation this replica's ShardMap snapshot was built "
        "under, labeled by shard index — shard-skew triage correlates "
        "per-shard metrics with the resize epoch they were produced "
        "under (GET /debug/shards carries the same value)."),
}

# -- end-to-end SLO catalog ------------------------------------------------
# Provenance stage vocabulary (runtime/slo.py STAGES): metrics-lint fails
# when the recorder's stages drift from this documented order.
SLO_STAGES: tuple[str, ...] = (
    "queued", "slab", "engine", "fetch", "dispatch", "write",
)


class SLOObjectiveSpec(NamedTuple):
    kind: str         # "ratio" (latency-threshold) | "gauge" (freshness)
    target: float     # required good-event fraction (ratio kinds)
    threshold_s: float  # default threshold; env overrides
    env: str          # KT_SLO_* env var overriding threshold_s
    help: str


# The declared objectives the in-process evaluator runs (runtime/slo.py
# SLOEvaluator builds exactly these; metrics-lint cross-checks both
# directions so the burn-rate label vocabulary never drifts from docs).
SLO_OBJECTIVES: dict[str, SLOObjectiveSpec] = {
    "event_to_written_p99": SLOObjectiveSpec(
        "ratio", 0.99, 5.0, "KT_SLO_E2E_P99_S",
        "99% of watch events reach an acked member placement write "
        "within the threshold (the end-to-end latency SLO items 4/5 "
        "gate on)."),
    "member_write_p99": SLOObjectiveSpec(
        "ratio", 0.99, 2.0, "KT_SLO_WRITE_P99_S",
        "99% of per-member write batches (retries included) complete "
        "within the threshold — the member-side half of the "
        "member-vs-engine triage."),
    "freshness": SLOObjectiveSpec(
        "gauge", 0.0, 30.0, "KT_SLO_FRESHNESS_S",
        "The oldest pending event stays younger than the threshold: "
        "the written world may not silently fall behind the observed "
        "world."),
}

# -- decision audit vocabulary -------------------------------------------
# Kubernetes Event reasons this control plane may record
# (runtime/eventsink.py recorders).  tools/metrics_lint.py walks
# ``.event(obj, type, reason, message)`` calls and fails on literal
# reasons not listed here — like metric names, the event vocabulary is
# documented (docs/observability.md) before it ships.
EVENT_REASONS: frozenset[str] = frozenset({
    "Scheduled",        # scheduler: placement decided (message: clusters + replicas)
    "ScheduleFailed",   # scheduler: no cluster selected (message: reason summary)
    "PropagationFailed",  # sync: member writes failed (message: clusters)
})

# Rejection-reason slugs served by /debug/explain and embedded in
# ScheduleFailed messages — must stay in lockstep with
# ops.reasons.REASON_NAMES (metrics-lint cross-checks both directions).
DECISION_REASONS: frozenset[str] = frozenset({
    "api_resources",
    "taint_toleration",
    "resources_fit",
    "placement",
    "cluster_affinity",
    "webhook_filter",
    "cluster_invalid",
    "max_clusters",
    "zero_replicas",
    "sticky_cluster",
})

# The flight-recorder record schema (runtime/flightrec.py
# DecisionRecord.__slots__): metrics-lint fails when the record grows or
# renames a field without this catalog (and docs/observability.md)
# following along.
FLIGHT_RECORDER_FIELDS: tuple[str, ...] = (
    "key", "tick", "when", "program", "placements", "reasons",
    "reason_counts", "feasible_n", "topk_idx", "topk_scores", "names",
)

# Pre-exposition dotted names, matched with fnmatch.  "*" also stands in
# for f-string interpolations in the linter's extracted names (e.g.
# f"scheduler-{ftc}.scheduled" lints as "scheduler-*.scheduled").
LEGACY_PATTERNS: tuple[str, ...] = (
    # runtime/worker.py per-worker counters/timers (worker name prefix).
    "*.panic",
    "*.throughput",
    "*.latency",
    "*.tick_latency",
    # federation controllers' per-FTC counters.
    "scheduler-*.scheduled",
    "scheduler-*.unit_errors",
    "scheduler-*.webhook_errors",
    "scheduler-*.webhook_config_errors",
    "scheduler-*.webhook_unsupported_payload",
    "scheduler-*.persist_panic",
    "scheduler-*.engine_latency",
    "sync-*.plan_panic",
    "sync-*.finish_panic",
    "sync-*.host_write_panic",
    "sync-*.plan_rollout_failed",
    "status.plan_panic",
    "statusagg.plan_panic",
    "ftc-manager.parse_errors",
    # federation/monitor.py gauges (monitor.<ftc>.<field> via a prefix
    # variable, so the linter sees "*.<field>").
    "monitor.*",
    "*.total",
    "*.propagated",
    "*.unpropagated",
    "*.out_of_sync_seconds",
    "*.sync_latency",
    "*.worker_exceptions",
    "*.worker_retries",
)


def is_cataloged(name: str) -> bool:
    """True when an emitted metric name (possibly containing "*" where
    an f-string interpolated) is covered by the catalog."""
    if name in CATALOG:
        return True
    return any(fnmatch(name, pattern) for pattern in LEGACY_PATTERNS)
