"""Long-running-service GC tuning.

A control plane serializing/parsing thousands of JSON objects per second
allocates fast enough that default gen0 collections (every ~700
allocations) fire constantly — and each collection also runs jax's
registered gc callback, stalling every worker and transport thread for
tens of milliseconds at a time (observed by stack sampling over the HTTP
transport).  Collect much less often; the values are empirical.
"""

from __future__ import annotations

import gc


def tune_gc_for_service() -> None:
    gc.set_threshold(50_000, 50, 50)
