"""Per-cluster pod informers with memory discipline.

The reference's FederatedClientFactory optionally maintains a pod
informer per member with two safeguards for 50k-pod clusters
(reference: pkg/controllers/util/federatedclient/podinformer.go:33-137,
flags --max-pod-listers / --enable-pod-pruning,
cmd/controller-manager/app/options/options.go):

* **pruning** — cached pods are stripped to exactly the fields the
  consumers read (auto-migration's unschedulable counting, the cluster
  controller's resource aggregation); everything else (env, volumes,
  probes — the bulk of a pod object) is dropped before it enters
  controller memory.
* **lister semaphore** — cold LISTs against member apiservers are
  bounded to ``max_pod_listers`` concurrent calls, so a restart with
  thousands of clusters doesn't stampede them.

After the cold LIST, per-member watches keep each cache incremental.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from kubeadmiral_tpu.testing.fakekube import DELETED, NotFound, obj_key

log = logging.getLogger("kubeadmiral.podinformer")

PODS = "v1/pods"

# spec fields the consumers read: node binding + resource requests.
_SPEC_KEYS = ("nodeName", "unschedulable", "overhead")


def prune_pod(pod: dict) -> dict:
    """podinformer.go's transform: keep scheduling-relevant fields only."""
    meta = pod.get("metadata", {})
    spec = pod.get("spec", {}) or {}
    status = pod.get("status", {}) or {}
    pruned_spec: dict = {k: spec[k] for k in _SPEC_KEYS if k in spec}
    for field in ("containers", "initContainers"):
        if field in spec:
            pruned_spec[field] = [
                {"resources": {"requests": dict(
                    (c.get("resources") or {}).get("requests") or {}
                )}}
                for c in spec[field] or []
            ]
    return {
        "metadata": {
            k: meta[k]
            for k in ("name", "namespace", "labels", "deletionTimestamp",
                      "resourceVersion")
            if k in meta
        },
        "spec": pruned_spec,
        "status": {
            k: status[k] for k in ("phase", "conditions") if k in status
        },
    }


class _WatchState:
    """One cluster's watch registration.  ``cache`` is staged privately
    during the cold LIST replay and only published into the informer's
    ``_caches`` once ``member.watch()`` returns — readers must never see
    a half-replayed snapshot (pods_for's None contract)."""

    __slots__ = ("member", "handler", "cache")

    def __init__(self, member):
        self.member = member
        self.handler: Optional[Callable] = None
        self.cache: dict[str, dict] = {}


class PodInformer:
    """Pruned per-cluster pod caches over a fleet."""

    def __init__(
        self,
        fleet,
        max_pod_listers: int = 4,
        enable_pruning: bool = True,
    ):
        self.fleet = fleet
        self.enable_pruning = enable_pruning
        self.max_pod_listers = max(1, max_pod_listers)
        self._lock = threading.Lock()
        # A cluster key EXISTS in _caches only once its cold LIST+WATCH
        # replay completed; consumers treat a missing key as "informer
        # not ready" (pods_for returns None) and fall back to a direct
        # member scan rather than trusting an empty snapshot.
        self._caches: dict[str, dict[str, dict]] = {}
        # cluster name -> _WatchState: a rejoined cluster gets a NEW
        # client/store, detected by identity, and is re-listed from
        # scratch; the old handler is unwatched so its stream stops.
        self._watched: dict[str, _WatchState] = {}

    def _transform(self, pod: dict) -> dict:
        return prune_pod(pod) if self.enable_pruning else pod

    # -- lifecycle --------------------------------------------------------
    def attach(self) -> None:
        """Start watching pods in every currently known member; call
        again on cluster lifecycle events (the FederatedInformer
        re-attach pattern).  Removed clusters are evicted; re-added
        ones (a new member object) are re-listed.  Cold LIST+WATCHes
        fan out across at most ``max_pod_listers`` threads — the
        --max-pod-listers stampede bound."""
        current = dict(getattr(self.fleet, "members", {}))
        # Resolve member clients OUTSIDE the lock: HttpFleet.member() can
        # block on a host apiserver round trip, and this lock is shared
        # with every pod-event handler across all clusters.  A member
        # that fails to resolve is simply retried on the next attach.
        members: dict[str, object] = {}
        for name in current:
            try:
                members[name] = self.fleet.member(name)
            except NotFound:
                continue
            except Exception:
                log.warning("resolving member client for %s failed", name, exc_info=True)
        to_watch: list[tuple[str, object]] = []
        to_unwatch: list[_WatchState] = []
        with self._lock:
            for name in list(self._watched):
                if name not in current:
                    to_unwatch.append(self._watched.pop(name))
                    self._caches.pop(name, None)
            for name, member in members.items():
                watched = self._watched.get(name)
                if watched is not None and watched.member is member:
                    continue  # already watching this exact client
                if watched is not None:
                    to_unwatch.append(watched)  # rejoin: stop the old stream
                # Drop (don't empty) the snapshot: a missing key means
                # "not ready", so readers fall back until the replay done.
                self._caches.pop(name, None)
                self._watched.pop(name, None)
                to_watch.append((name, member))
        for old in to_unwatch:
            try:
                old.member.unwatch(PODS, old.handler)
            except Exception:
                pass  # a dead transport can't deliver events anyway

        if not to_watch:
            return

        def start_watch(item):
            name, member = item
            state = _WatchState(member)

            def handler(event: str, pod: dict, _state=state, _cluster=name) -> None:
                with self._lock:
                    if self._watched.get(_cluster) is not _state:
                        return  # superseded by a rejoin
                    key = obj_key(pod)
                    if event == DELETED:
                        _state.cache.pop(key, None)
                    else:
                        _state.cache[key] = self._transform(pod)

            state.handler = handler
            with self._lock:
                self._watched[name] = state
            # The replay IS the cold LIST (LIST+WATCH); both transports
            # complete the replay before watch() returns.  Replay events
            # accumulate in state.cache (staged, invisible to readers)
            # and publish atomically below.  A down member must not
            # abort attach or the caller's event-dispatch context: drop
            # the registration and retry on the next attach.
            try:
                member.watch(PODS, handler, replay=True)
            except Exception:
                log.warning("pod watch for %s failed; will retry", name, exc_info=True)
                with self._lock:
                    if self._watched.get(name) is state:
                        del self._watched[name]
                try:
                    member.unwatch(PODS, handler)
                except Exception:
                    pass
                return
            with self._lock:
                if self._watched.get(name) is state:
                    self._caches[name] = state.cache  # ready (maybe podless)

        if len(to_watch) == 1:
            start_watch(to_watch[0])
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=self.max_pod_listers,
                thread_name_prefix="pod-lister",
            ) as pool:
                list(pool.map(start_watch, to_watch))

    # -- reads ------------------------------------------------------------
    def pods_for(
        self,
        cluster: str,
        namespace: Optional[str] = None,
        selector: Optional[dict[str, str]] = None,
    ) -> Optional[list[dict]]:
        """None = informer not (yet) watching this cluster — the caller
        must fall back to a direct member scan, NOT treat it as 'no
        pods' (a wrong empty answer would clear auto-migration's
        estimatedCapacity)."""
        with self._lock:
            cache = self._caches.get(cluster)
            if cache is None:
                return None
            out = []
            for pod in cache.values():
                meta = pod.get("metadata", {})
                if namespace is not None and meta.get("namespace", "") != namespace:
                    continue
                if selector:
                    labels = meta.get("labels") or {}
                    if any(labels.get(k) != v for k, v in selector.items()):
                        continue
                out.append(pod)
            return out

    def cache_size(self, cluster: str) -> int:
        with self._lock:
            return len(self._caches.get(cluster, {}))
