"""Per-cluster pod informers with memory discipline.

The reference's FederatedClientFactory optionally maintains a pod
informer per member with two safeguards for 50k-pod clusters
(reference: pkg/controllers/util/federatedclient/podinformer.go:33-137,
flags --max-pod-listers / --enable-pod-pruning,
cmd/controller-manager/app/options/options.go):

* **pruning** — cached pods are stripped to exactly the fields the
  consumers read (auto-migration's unschedulable counting, the cluster
  controller's resource aggregation); everything else (env, volumes,
  probes — the bulk of a pod object) is dropped before it enters
  controller memory.
* **lister semaphore** — cold LISTs against member apiservers are
  bounded to ``max_pod_listers`` concurrent calls, so a restart with
  thousands of clusters doesn't stampede them.

After the cold LIST, per-member watches keep each cache incremental.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from kubeadmiral_tpu.testing.fakekube import DELETED, NotFound, obj_key

PODS = "v1/pods"

# spec fields the consumers read: node binding + resource requests.
_SPEC_KEYS = ("nodeName", "unschedulable", "overhead")


def prune_pod(pod: dict) -> dict:
    """podinformer.go's transform: keep scheduling-relevant fields only."""
    meta = pod.get("metadata", {})
    spec = pod.get("spec", {}) or {}
    status = pod.get("status", {}) or {}
    pruned_spec: dict = {k: spec[k] for k in _SPEC_KEYS if k in spec}
    for field in ("containers", "initContainers"):
        if field in spec:
            pruned_spec[field] = [
                {"resources": {"requests": dict(
                    (c.get("resources") or {}).get("requests") or {}
                )}}
                for c in spec[field] or []
            ]
    return {
        "metadata": {
            k: meta[k]
            for k in ("name", "namespace", "labels", "deletionTimestamp",
                      "resourceVersion")
            if k in meta
        },
        "spec": pruned_spec,
        "status": {
            k: status[k] for k in ("phase", "conditions") if k in status
        },
    }


class PodInformer:
    """Pruned per-cluster pod caches over a fleet."""

    def __init__(
        self,
        fleet,
        max_pod_listers: int = 4,
        enable_pruning: bool = True,
    ):
        self.fleet = fleet
        self.enable_pruning = enable_pruning
        self.max_pod_listers = max(1, max_pod_listers)
        self._lock = threading.Lock()
        self._caches: dict[str, dict[str, dict]] = {}
        # cluster name -> the member client object watched: a rejoined
        # cluster gets a NEW client/store, detected by identity, and is
        # re-listed from scratch.
        self._watched: dict[str, object] = {}

    def _transform(self, pod: dict) -> dict:
        return prune_pod(pod) if self.enable_pruning else pod

    # -- lifecycle --------------------------------------------------------
    def attach(self) -> None:
        """Start watching pods in every currently known member; call
        again on cluster lifecycle events (the FederatedInformer
        re-attach pattern).  Removed clusters are evicted; re-added
        ones (a new member object) are re-listed.  Cold LIST+WATCHes
        fan out across at most ``max_pod_listers`` threads — the
        --max-pod-listers stampede bound."""
        to_watch: list[tuple[str, object]] = []
        current = dict(getattr(self.fleet, "members", {}))
        with self._lock:
            for name in list(self._watched):
                if name not in current:
                    self._watched.pop(name, None)
                    self._caches.pop(name, None)
            for name in current:
                try:
                    member = self.fleet.member(name)
                except NotFound:
                    continue
                if self._watched.get(name) is member:
                    continue  # already watching this exact client
                self._watched[name] = member
                self._caches[name] = {}  # rejoin: drop the old snapshot
                to_watch.append((name, member))
        if not to_watch:
            return

        def start_watch(item):
            name, member = item
            def handler(event: str, pod: dict, _cluster=name, _member=member) -> None:
                with self._lock:
                    if self._watched.get(_cluster) is not _member:
                        return  # superseded by a rejoin
                    cache = self._caches.setdefault(_cluster, {})
                    key = obj_key(pod)
                    if event == DELETED:
                        cache.pop(key, None)
                    else:
                        cache[key] = self._transform(pod)

            # The replay IS the cold LIST (LIST+WATCH).
            member.watch(PODS, handler, replay=True)

        if len(to_watch) == 1:
            start_watch(to_watch[0])
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=self.max_pod_listers,
                thread_name_prefix="pod-lister",
            ) as pool:
                list(pool.map(start_watch, to_watch))

    # -- reads ------------------------------------------------------------
    def pods_for(
        self,
        cluster: str,
        namespace: Optional[str] = None,
        selector: Optional[dict[str, str]] = None,
    ) -> list[dict]:
        with self._lock:
            cache = self._caches.get(cluster)
            if cache is None:
                return []
            out = []
            for pod in cache.values():
                meta = pod.get("metadata", {})
                if namespace is not None and meta.get("namespace", "") != namespace:
                    continue
                if selector:
                    labels = meta.get("labels") or {}
                    if any(labels.get(k) != v for k, v in selector.items()):
                        continue
                out.append(pod)
            return out

    def cache_size(self, cluster: str) -> int:
        with self._lock:
            return len(self._caches.get(cluster, {}))
