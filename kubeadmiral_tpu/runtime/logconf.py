"""Structured logging for the ``kubeadmiral.*`` logger tree.

Every module logs through a ``kubeadmiral.<component>`` logger
(engine, streaming, dispatch, worker, transport, manager, ...).  This
module owns the one process-wide handler configuration:

* ``KT_LOG_LEVEL`` — level for the ``kubeadmiral`` tree (DEBUG, INFO,
  WARNING, ...; default WARNING, so steady-state operation is silent).
  DEBUG turns on the per-tick engine lines (tick id, stage split) and
  per-flush streaming lines (flush id, engine tick).
* ``KT_LOG_JSON`` — ``1`` emits one JSON object per line (ts, level,
  logger, msg, tick/span correlation) instead of the text format; the
  shape log aggregators ingest directly.

Records carry a ``span`` attribute — the id of the innermost open
trace span on the emitting thread (runtime/trace.py) — so a log line
can be joined against ``/debug/trace`` output; engine/streaming lines
additionally embed their tick/flush ids in the message
(``tick=<id>``), the same ids ``/debug/waterfall`` keys on.

``setup_logging()`` is idempotent and is called by the controller
manager at start and by ``python -m kubeadmiral_tpu``; embedders that
own their logging config simply never call it (module loggers then
propagate to whatever the host app configured).  See
docs/operations.md.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Optional

ROOT_LOGGER = "kubeadmiral"

_configured = False


class SpanContextFilter(logging.Filter):
    """Attach the innermost open trace-span id (this thread) to every
    record, so text and JSON lines both carry the /debug/trace join
    key."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            from kubeadmiral_tpu.runtime import trace

            span = trace.get_default().current()
            record.span = span.span_id if span is not None else "-"
        except Exception:
            record.span = "-"
        return True


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": round(time.time(), 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
            "span": getattr(record, "span", "-"),
            "thread": record.threadName,
        }
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc)


TEXT_FORMAT = (
    "%(asctime)s %(levelname)-7s %(name)s span=%(span)s %(message)s"
)


def setup_logging(
    level: Optional[str] = None,
    json_lines: Optional[bool] = None,
    stream=None,
    force: bool = False,
) -> logging.Logger:
    """Configure the ``kubeadmiral`` logger tree from the KT_LOG_*
    knobs (arguments override them; ``force=True`` reconfigures an
    already-configured tree — tests use it).  Returns the tree root."""
    global _configured
    logger = logging.getLogger(ROOT_LOGGER)
    if _configured and not force:
        return logger
    if level is None:
        level = os.environ.get("KT_LOG_LEVEL", "WARNING")
    if json_lines is None:
        json_lines = os.environ.get("KT_LOG_JSON", "0") not in (
            "0", "false", "no", "",
        )
    resolved = getattr(logging, str(level).upper(), None)
    if not isinstance(resolved, int):
        resolved = logging.WARNING
    for h in list(logger.handlers):
        logger.removeHandler(h)
    handler = logging.StreamHandler(stream if stream is not None else sys.stderr)
    handler.addFilter(SpanContextFilter())
    handler.setFormatter(
        JsonFormatter() if json_lines else logging.Formatter(TEXT_FORMAT)
    )
    logger.addHandler(handler)
    logger.setLevel(resolved)
    # Propagation stays ON: pytest's caplog and embedder root handlers
    # capture through the root logger; the cost is a duplicate line
    # when BOTH this handler and a root handler exist, which only a
    # host app that also calls basicConfig() would see.
    _configured = True
    return logger
