"""The pending-controllers pipeline annotation.

Controllers are choreographed through an ordered list of controller
groups on each federated object (reference: pkg/controllers/util/
pendingcontrollers/pendingcontrollers.go:29-147): a controller may act
only while it appears in the *first* pending group; when done it removes
itself from that group, and if it changed the object it re-arms every
group downstream of its own position so later controllers run again.
This is the control plane's pipeline: scheduler -> override -> sync.

The federate controller stamps the initial annotation when it creates
the federated object; a missing annotation is an error, as in the
reference.
"""

from __future__ import annotations

import json
from typing import Sequence

PENDING_CONTROLLERS = "kubeadmiral.io/pending-controllers"

ControllerGroups = list[list[str]]


def normalize(groups: Sequence[Sequence[str]]) -> ControllerGroups:
    return [list(g) for g in groups if len(g) > 0]


def get_pending(obj: dict) -> ControllerGroups:
    raw = obj.get("metadata", {}).get("annotations", {}).get(PENDING_CONTROLLERS)
    if raw is None:
        raise KeyError(f"annotation {PENDING_CONTROLLERS} does not exist")
    value = json.loads(raw)
    if not isinstance(value, list):
        raise ValueError(f"invalid pending controllers: {raw!r}")
    return normalize(value)


def set_pending(obj: dict, groups: Sequence[Sequence[str]]) -> bool:
    """Returns True when the annotation value changed."""
    encoded = json.dumps(normalize(groups), separators=(",", ":"))
    ann = obj.setdefault("metadata", {}).setdefault("annotations", {})
    if ann.get(PENDING_CONTROLLERS) == encoded:
        return False
    ann[PENDING_CONTROLLERS] = encoded
    return True


def dependencies_fulfilled(obj: dict, controller: str) -> bool:
    """True when the controller is in the first pending group (or none
    are pending)."""
    groups = get_pending(obj)
    if not groups:
        return True
    return controller in groups[0]


def _downstream(all_groups: Sequence[Sequence[str]], current: str) -> ControllerGroups:
    for i, group in enumerate(all_groups):
        if current in group:
            return [list(g) for g in all_groups[i + 1 :]]
    return []


def _next_groups(
    obj: dict,
    to_remove: str,
    set_downstream: bool,
    all_groups: Sequence[Sequence[str]],
) -> list:
    """The pending groups after one controller's pass — the ONE
    definition shared by the mutating update and the probe."""
    groups = get_pending(obj)
    current = list(groups[0]) if groups else []
    rest = groups[1:] if groups else []
    if to_remove in current:
        current.remove(to_remove)
    if set_downstream:
        rest = _downstream(all_groups, to_remove)
    return [current] + list(rest)


def would_update(
    obj: dict,
    to_remove: str,
    set_downstream: bool,
    all_groups: Sequence[Sequence[str]],
) -> bool:
    """Non-mutating probe of :func:`update_pending`: True when applying
    it would change the annotation — lets view-read reconciles skip the
    copy + write entirely in the steady state."""
    encoded = json.dumps(
        normalize(_next_groups(obj, to_remove, set_downstream, all_groups)),
        separators=(",", ":"),
    )
    ann = obj.get("metadata", {}).get("annotations", {})
    return ann.get(PENDING_CONTROLLERS) != encoded


def update_pending(
    obj: dict,
    to_remove: str,
    set_downstream: bool,
    all_groups: Sequence[Sequence[str]],
) -> bool:
    """Remove ``to_remove`` from the current group; when the controller
    changed the object (``set_downstream``), re-arm everything after its
    group in ``all_groups``.  Returns True when the annotation changed."""
    return set_pending(obj, _next_groups(obj, to_remove, set_downstream, all_groups))
