"""FederatedTypeConfig: the CRD-driven type registry.

The FTC tells the control plane which source types are federated, what
the federated companion type is called, where replicas/status live in the
object, and which controller pipeline processes it (reference:
pkg/apis/core/v1alpha1/types_federatedtypeconfig.go:63-182).

Resource addressing convention: "<group>/<version>/<plural>" (core group
has an empty group segment collapsed, e.g. "v1/configmaps").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


def resource_key(group: str, version: str, plural: str) -> str:
    return f"{group}/{version}/{plural}" if group else f"{version}/{plural}"


def gvk_key(group: str, version: str, kind: str) -> str:
    return f"{group}/{version}/{kind}" if group else f"{version}/{kind}"


@dataclass(frozen=True)
class TypeRef:
    group: str
    version: str
    kind: str
    plural: str

    @property
    def resource(self) -> str:
        return resource_key(self.group, self.version, self.plural)

    @property
    def gvk(self) -> str:
        return gvk_key(self.group, self.version, self.kind)

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version


@dataclass(frozen=True)
class PathDefinition:
    """Dotted paths into the source/target object
    (types_federatedtypeconfig.go:146-182)."""

    replicas_spec: str = ""
    replicas_status: str = ""
    available_replicas_status: str = ""
    ready_replicas_status: str = ""
    label_selector: str = ""


@dataclass(frozen=True)
class FederatedTypeConfig:
    name: str
    source: TypeRef
    federated: TypeRef
    status: Optional[TypeRef] = None
    path: PathDefinition = PathDefinition()
    # Ordered controller pipeline groups (spec.controllers).
    controllers: tuple[tuple[str, ...], ...] = (
        ("kubeadmiral.io/global-scheduler",),
        ("kubeadmiral.io/overridepolicy-controller",),
    )
    status_collection: bool = False
    # Dotted paths collected from member objects into the status CR
    # (types_federatedtypeconfig.go StatusCollection.Fields).
    status_collection_fields: tuple[str, ...] = ("status",)
    status_aggregation: bool = False
    revision_history: bool = False
    rollout_plan: bool = False
    auto_migration: bool = False
    namespaced: bool = True  # target scope (drives PropagatedVersion kind)

    @property
    def controller_groups(self) -> list[list[str]]:
        return [list(g) for g in self.controllers]


def federated_ref(source: TypeRef) -> TypeRef:
    """Default federated companion naming: FederatedX in the kubeadmiral
    types group."""
    return TypeRef(
        group="types.kubeadmiral.io",
        version="v1alpha1",
        kind=f"Federated{source.kind}",
        plural=f"federated{source.plural}",
    )


def status_ref(source: TypeRef) -> TypeRef:
    """Default status-CR naming: FederatedXStatus in the kubeadmiral types
    group (types_federatedtypeconfig.go StatusType)."""
    return TypeRef(
        group="types.kubeadmiral.io",
        version="v1alpha1",
        kind=f"Federated{source.kind}Status",
        plural=f"federated{source.plural}statuses",
    )


def make_ftc(
    name: str,
    group: str,
    version: str,
    kind: str,
    plural: str,
    **kw,
) -> FederatedTypeConfig:
    src = TypeRef(group, version, kind, plural)
    if kw.get("status_collection") and "status" not in kw:
        kw["status"] = status_ref(src)
    return FederatedTypeConfig(
        name=name, source=src, federated=federated_ref(src), **kw
    )


WORKLOAD_PATH = PathDefinition(
    replicas_spec="spec.replicas",
    replicas_status="status.replicas",
    available_replicas_status="status.availableReplicas",
    ready_replicas_status="status.readyReplicas",
    label_selector="spec.selector.matchLabels",
)


WORKLOAD_PIPELINE = (
    ("kubeadmiral.io/global-scheduler",),
    ("kubeadmiral.io/overridepolicy-controller",),
    ("kubeadmiral.io/follower-controller",),
)


def default_ftcs() -> list[FederatedTypeConfig]:
    """The sample set the reference ships (config/sample/host/01-ftc.yaml),
    trimmed to the types the tests/bench exercise; more are added by
    simply registering additional FTC objects.  Workload leader types run
    the follower controller after scheduling (01-ftc.yaml:94-97)."""
    return [
        make_ftc(
            "deployments.apps",
            "apps",
            "v1",
            "Deployment",
            "deployments",
            controllers=WORKLOAD_PIPELINE,
            path=WORKLOAD_PATH,
            status_collection=True,
            status_aggregation=True,
            revision_history=True,
            auto_migration=True,
        ),
        make_ftc(
            "statefulsets.apps",
            "apps",
            "v1",
            "StatefulSet",
            "statefulsets",
            controllers=WORKLOAD_PIPELINE,
            path=WORKLOAD_PATH,
            status_collection=True,
        ),
        make_ftc(
            "daemonsets.apps", "apps", "v1", "DaemonSet", "daemonsets",
            controllers=WORKLOAD_PIPELINE,
            status_collection=True,
        ),
        make_ftc("configmaps", "", "v1", "ConfigMap", "configmaps"),
        make_ftc("secrets", "", "v1", "Secret", "secrets"),
        make_ftc("services", "", "v1", "Service", "services"),
        make_ftc("serviceaccounts", "", "v1", "ServiceAccount", "serviceaccounts"),
        # Namespaces are placed by nsautoprop, not the scheduler
        # (01-ftc.yaml:23-25; running both would fight over placements).
        make_ftc(
            "namespaces", "", "v1", "Namespace", "namespaces", namespaced=False,
            controllers=(
                ("kubeadmiral.io/nsautoprop-controller",),
                ("kubeadmiral.io/overridepolicy-controller",),
            ),
        ),
        make_ftc(
            "jobs.batch", "batch", "v1", "Job", "jobs",
            controllers=WORKLOAD_PIPELINE,
            path=PathDefinition(replicas_spec="spec.parallelism"),
            status_collection=True,
        ),
        make_ftc("cronjobs.batch", "batch", "v1", "CronJob", "cronjobs",
            controllers=WORKLOAD_PIPELINE),
        make_ftc(
            "ingresses.networking.k8s.io",
            "networking.k8s.io",
            "v1",
            "Ingress",
            "ingresses",
        ),
        make_ftc(
            "persistentvolumeclaims",
            "",
            "v1",
            "PersistentVolumeClaim",
            "persistentvolumeclaims",
        ),
    ]
