"""FederatedTypeConfig: the CRD-driven type registry.

The FTC tells the control plane which source types are federated, what
the federated companion type is called, where replicas/status live in the
object, and which controller pipeline processes it (reference:
pkg/apis/core/v1alpha1/types_federatedtypeconfig.go:63-182).

Resource addressing convention: "<group>/<version>/<plural>" (core group
has an empty group segment collapsed, e.g. "v1/configmaps").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


def resource_key(group: str, version: str, plural: str) -> str:
    return f"{group}/{version}/{plural}" if group else f"{version}/{plural}"


def gvk_key(group: str, version: str, kind: str) -> str:
    return f"{group}/{version}/{kind}" if group else f"{version}/{kind}"


@dataclass(frozen=True)
class TypeRef:
    group: str
    version: str
    kind: str
    plural: str

    @property
    def resource(self) -> str:
        return resource_key(self.group, self.version, self.plural)

    @property
    def gvk(self) -> str:
        return gvk_key(self.group, self.version, self.kind)

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version


@dataclass(frozen=True)
class PathDefinition:
    """Dotted paths into the source/target object
    (types_federatedtypeconfig.go:146-182)."""

    replicas_spec: str = ""
    replicas_status: str = ""
    available_replicas_status: str = ""
    ready_replicas_status: str = ""
    label_selector: str = ""


# Default controller pipeline when an FTC doesn't specify one.
DEFAULT_PIPELINE: tuple[tuple[str, ...], ...] = (
    ("kubeadmiral.io/global-scheduler",),
    ("kubeadmiral.io/overridepolicy-controller",),
)


@dataclass(frozen=True)
class FederatedTypeConfig:
    name: str
    source: TypeRef
    federated: TypeRef
    status: Optional[TypeRef] = None
    path: PathDefinition = PathDefinition()
    # Ordered controller pipeline groups (spec.controllers).
    controllers: tuple[tuple[str, ...], ...] = DEFAULT_PIPELINE
    status_collection: bool = False
    # Dotted paths collected from member objects into the status CR
    # (types_federatedtypeconfig.go StatusCollection.Fields).
    status_collection_fields: tuple[str, ...] = ("status",)
    status_aggregation: bool = False
    revision_history: bool = False
    rollout_plan: bool = False
    auto_migration: bool = False
    namespaced: bool = True  # target scope (drives PropagatedVersion kind)

    @property
    def controller_groups(self) -> list[list[str]]:
        return [list(g) for g in self.controllers]


FEDERATED_TYPE_CONFIGS = "core.kubeadmiral.io/v1alpha1/federatedtypeconfigs"


def _parse_type_ref(raw: dict) -> TypeRef:
    return TypeRef(
        group=raw.get("group", ""),
        version=raw.get("version", ""),
        kind=raw.get("kind", ""),
        plural=raw.get("pluralName", raw.get("plural", "")),
    )


def _type_ref_to_raw(ref: TypeRef) -> dict:
    raw = {"version": ref.version, "kind": ref.kind, "pluralName": ref.plural}
    if ref.group:
        raw["group"] = ref.group
    return raw


def parse_ftc(obj: dict) -> FederatedTypeConfig:
    """Unstructured FederatedTypeConfig -> typed registry entry
    (types_federatedtypeconfig.go:63-182).  This is what makes the type
    registry CRD-driven: the manager watches these objects and starts the
    per-type controllers from them."""
    spec = obj.get("spec", {})
    source_raw = spec.get("sourceType") or {}
    source = _parse_type_ref(source_raw)
    federated = (
        _parse_type_ref(spec["federatedType"])
        if spec.get("federatedType")
        else federated_ref(source)
    )
    status = _parse_type_ref(spec["statusType"]) if spec.get("statusType") else None
    path_raw = spec.get("pathDefinition") or {}

    # Absent controllers -> default pipeline; an explicit [] stays empty
    # ("no pipeline controllers" is expressible, e.g. sync-only types).
    if "controllers" in spec and spec["controllers"] is not None:
        controllers = tuple(
            tuple(group) for group in spec["controllers"] if group
        )
    else:
        controllers = DEFAULT_PIPELINE

    def feature(raw) -> tuple[bool, dict]:
        """Normalize a toggle that may be bool, "Enabled", null or an
        object with an ``enabled`` field."""
        if isinstance(raw, dict):
            return bool(raw.get("enabled", False)), raw
        return raw in ("Enabled", True), {}

    status_collection, sc_raw = feature(spec.get("statusCollection"))
    auto_migration, _ = feature(spec.get("autoMigration"))

    return FederatedTypeConfig(
        name=obj["metadata"]["name"],
        source=source,
        federated=federated,
        status=status,
        path=PathDefinition(
            replicas_spec=path_raw.get("replicasSpec", ""),
            replicas_status=path_raw.get("replicasStatus", ""),
            available_replicas_status=path_raw.get("availableReplicasStatus", ""),
            ready_replicas_status=path_raw.get("readyReplicasStatus", ""),
            label_selector=path_raw.get("labelSelector", ""),
        ),
        controllers=controllers,
        status_collection=status_collection,
        status_collection_fields=tuple(sc_raw.get("fields") or ("status",)),
        status_aggregation=spec.get("statusAggregation", "") in ("Enabled", True),
        revision_history=spec.get("revisionHistory", "") in ("Enabled", True),
        rollout_plan=spec.get("rolloutPlan", "") in ("Enabled", True),
        auto_migration=auto_migration,
        namespaced=source_raw.get("scope", "Namespaced") != "Cluster",
    )


def ftc_to_object(ftc: FederatedTypeConfig) -> dict:
    """Typed registry entry -> unstructured FederatedTypeConfig object."""
    spec: dict = {
        "sourceType": {
            **_type_ref_to_raw(ftc.source),
            "scope": "Namespaced" if ftc.namespaced else "Cluster",
        },
        "federatedType": _type_ref_to_raw(ftc.federated),
        "controllers": [list(g) for g in ftc.controllers],
    }
    if ftc.status is not None:
        spec["statusType"] = _type_ref_to_raw(ftc.status)
    path = {
        k: v
        for k, v in (
            ("replicasSpec", ftc.path.replicas_spec),
            ("replicasStatus", ftc.path.replicas_status),
            ("availableReplicasStatus", ftc.path.available_replicas_status),
            ("readyReplicasStatus", ftc.path.ready_replicas_status),
            ("labelSelector", ftc.path.label_selector),
        )
        if v
    }
    if path:
        spec["pathDefinition"] = path
    if ftc.status_collection:
        spec["statusCollection"] = {
            "enabled": True,
            "fields": list(ftc.status_collection_fields),
        }
    if ftc.status_aggregation:
        spec["statusAggregation"] = "Enabled"
    if ftc.revision_history:
        spec["revisionHistory"] = "Enabled"
    if ftc.rollout_plan:
        spec["rolloutPlan"] = "Enabled"
    if ftc.auto_migration:
        spec["autoMigration"] = {"enabled": True}
    return {
        "apiVersion": "core.kubeadmiral.io/v1alpha1",
        "kind": "FederatedTypeConfig",
        "metadata": {"name": ftc.name},
        "spec": spec,
    }


def federated_ref(source: TypeRef) -> TypeRef:
    """Default federated companion naming: FederatedX in the kubeadmiral
    types group."""
    return TypeRef(
        group="types.kubeadmiral.io",
        version="v1alpha1",
        kind=f"Federated{source.kind}",
        plural=f"federated{source.plural}",
    )


def status_ref(source: TypeRef) -> TypeRef:
    """Default status-CR naming: FederatedXStatus in the kubeadmiral types
    group (types_federatedtypeconfig.go StatusType)."""
    return TypeRef(
        group="types.kubeadmiral.io",
        version="v1alpha1",
        kind=f"Federated{source.kind}Status",
        plural=f"federated{source.plural}statuses",
    )


def make_ftc(
    name: str,
    group: str,
    version: str,
    kind: str,
    plural: str,
    **kw,
) -> FederatedTypeConfig:
    src = TypeRef(group, version, kind, plural)
    if kw.get("status_collection") and "status" not in kw:
        kw["status"] = status_ref(src)
    return FederatedTypeConfig(
        name=name, source=src, federated=federated_ref(src), **kw
    )


WORKLOAD_PATH = PathDefinition(
    replicas_spec="spec.replicas",
    replicas_status="status.replicas",
    available_replicas_status="status.availableReplicas",
    ready_replicas_status="status.readyReplicas",
    label_selector="spec.selector.matchLabels",
)


WORKLOAD_PIPELINE = (
    ("kubeadmiral.io/global-scheduler",),
    ("kubeadmiral.io/overridepolicy-controller",),
    ("kubeadmiral.io/follower-controller",),
)


def default_ftcs() -> list[FederatedTypeConfig]:
    """The full default set the reference ships — all 21 types of
    config/sample/host/01-ftc.yaml (namespaces, workloads, config/rbac/
    quota/storage types, CRDs); more are added by simply registering
    additional FTC objects.  Workload leader types run the follower
    controller after scheduling (01-ftc.yaml:94-97)."""
    return [
        make_ftc(
            "deployments.apps",
            "apps",
            "v1",
            "Deployment",
            "deployments",
            controllers=WORKLOAD_PIPELINE,
            path=WORKLOAD_PATH,
            status_collection=True,
            status_aggregation=True,
            revision_history=True,
            auto_migration=True,
        ),
        make_ftc(
            "statefulsets.apps",
            "apps",
            "v1",
            "StatefulSet",
            "statefulsets",
            controllers=WORKLOAD_PIPELINE,
            path=WORKLOAD_PATH,
            status_collection=True,
        ),
        make_ftc(
            "daemonsets.apps", "apps", "v1", "DaemonSet", "daemonsets",
            controllers=WORKLOAD_PIPELINE,
            status_collection=True,
        ),
        make_ftc("configmaps", "", "v1", "ConfigMap", "configmaps"),
        make_ftc("secrets", "", "v1", "Secret", "secrets"),
        make_ftc("services", "", "v1", "Service", "services"),
        make_ftc("serviceaccounts", "", "v1", "ServiceAccount", "serviceaccounts"),
        # Namespaces are placed by nsautoprop, not the scheduler
        # (01-ftc.yaml:23-25; running both would fight over placements).
        make_ftc(
            "namespaces", "", "v1", "Namespace", "namespaces", namespaced=False,
            controllers=(
                ("kubeadmiral.io/nsautoprop-controller",),
                ("kubeadmiral.io/overridepolicy-controller",),
            ),
        ),
        make_ftc(
            "jobs.batch", "batch", "v1", "Job", "jobs",
            controllers=WORKLOAD_PIPELINE,
            path=PathDefinition(replicas_spec="spec.parallelism"),
            status_collection=True,
        ),
        make_ftc("cronjobs.batch", "batch", "v1", "CronJob", "cronjobs",
            controllers=WORKLOAD_PIPELINE),
        make_ftc(
            "ingresses.networking.k8s.io",
            "networking.k8s.io",
            "v1",
            "Ingress",
            "ingresses",
        ),
        make_ftc(
            "persistentvolumeclaims",
            "",
            "v1",
            "PersistentVolumeClaim",
            "persistentvolumeclaims",
        ),
        make_ftc(
            "persistentvolumes", "", "v1", "PersistentVolume",
            "persistentvolumes", namespaced=False,
        ),
        make_ftc(
            "storageclasses.storage.k8s.io", "storage.k8s.io", "v1",
            "StorageClass", "storageclasses", namespaced=False,
        ),
        make_ftc(
            "roles.rbac.authorization.k8s.io",
            "rbac.authorization.k8s.io", "v1", "Role", "roles",
        ),
        make_ftc(
            "rolebindings.rbac.authorization.k8s.io",
            "rbac.authorization.k8s.io", "v1", "RoleBinding", "rolebindings",
        ),
        make_ftc(
            "clusterroles.rbac.authorization.k8s.io",
            "rbac.authorization.k8s.io", "v1", "ClusterRole", "clusterroles",
            namespaced=False,
        ),
        make_ftc(
            "clusterrolebindings.rbac.authorization.k8s.io",
            "rbac.authorization.k8s.io", "v1", "ClusterRoleBinding",
            "clusterrolebindings", namespaced=False,
        ),
        make_ftc("limitranges", "", "v1", "LimitRange", "limitranges"),
        make_ftc("resourcequotas", "", "v1", "ResourceQuota", "resourcequotas"),
        make_ftc(
            "customresourcedefinitions.apiextensions.k8s.io",
            "apiextensions.k8s.io", "v1", "CustomResourceDefinition",
            "customresourcedefinitions", namespaced=False,
        ),
    ]
