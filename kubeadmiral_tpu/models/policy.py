"""PropagationPolicy / ClusterPropagationPolicy parsing.

Unstructured policy dicts -> typed scheduling directives
(reference: pkg/apis/core/v1alpha1/types_propagationpolicy.go:62-189),
plus the policy->SchedulingUnit projection used by the scheduler
controller (reference: pkg/controllers/scheduler/schedulingunit.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kubeadmiral_tpu.models.types import (
    ClusterAffinity,
    MODE_DUPLICATE,
    PreferredSchedulingTerm,
    SelectorRequirement,
    SelectorTerm,
    Toleration,
)

PROPAGATION_POLICY_LABEL = "kubeadmiral.io/propagation-policy-name"
CLUSTER_PROPAGATION_POLICY_LABEL = "kubeadmiral.io/cluster-propagation-policy-name"

PROPAGATION_POLICIES = "core.kubeadmiral.io/v1alpha1/propagationpolicies"
CLUSTER_PROPAGATION_POLICIES = "core.kubeadmiral.io/v1alpha1/clusterpropagationpolicies"
OVERRIDE_POLICIES = "core.kubeadmiral.io/v1alpha1/overridepolicies"
CLUSTER_OVERRIDE_POLICIES = "core.kubeadmiral.io/v1alpha1/clusteroverridepolicies"
SCHEDULING_PROFILES = "core.kubeadmiral.io/v1alpha1/schedulingprofiles"


def parse_selector_requirement(raw: dict) -> SelectorRequirement:
    return SelectorRequirement(
        key=raw.get("key", ""),
        operator=raw.get("operator", "In"),
        values=tuple(raw.get("values", ())),
    )


def parse_selector_term(raw: dict) -> SelectorTerm:
    return SelectorTerm(
        match_expressions=tuple(
            parse_selector_requirement(r) for r in raw.get("matchExpressions", ())
        ),
        match_fields=tuple(
            parse_selector_requirement(r) for r in raw.get("matchFields", ())
        ),
    )


def parse_toleration(raw: dict) -> Toleration:
    return Toleration(
        key=raw.get("key", ""),
        operator=raw.get("operator", "Equal"),
        value=raw.get("value", ""),
        effect=raw.get("effect", ""),
    )


@dataclass
class PolicySpec:
    name: str
    namespace: str = ""
    generation: int = 1
    scheduling_profile: str = ""
    scheduling_mode: str = MODE_DUPLICATE
    sticky_cluster: bool = False
    cluster_selector: dict[str, str] = field(default_factory=dict)
    cluster_affinity: tuple[SelectorTerm, ...] = ()
    tolerations: tuple[Toleration, ...] = ()
    max_clusters: Optional[int] = None
    placements: list[dict] = field(default_factory=list)
    disable_follower_scheduling: bool = False
    auto_migration_enabled: bool = False
    keep_unschedulable_replicas: bool = False
    pod_unschedulable_seconds: Optional[float] = None
    avoid_disruption: bool = True

    @property
    def cluster_names(self) -> frozenset[str]:
        return frozenset(p["cluster"] for p in self.placements)

    def min_replicas(self) -> dict[str, int]:
        out = {}
        for p in self.placements:
            v = p.get("preferences", {}).get("minReplicas")
            if v is not None:
                out[p["cluster"]] = int(v)
        return out

    def max_replicas(self) -> dict[str, int]:
        out = {}
        for p in self.placements:
            v = p.get("preferences", {}).get("maxReplicas")
            if v is not None:
                out[p["cluster"]] = int(v)
        return out

    def weights(self) -> dict[str, int]:
        out = {}
        for p in self.placements:
            v = p.get("preferences", {}).get("weight")
            if v is not None:
                out[p["cluster"]] = int(v)
        return out

    def affinity(self) -> Optional[ClusterAffinity]:
        """The scheduler treats policy clusterAffinity terms as the
        required affinity (schedulingunit.go getAffinityFromPolicy)."""
        if not self.cluster_affinity:
            return None
        return ClusterAffinity(required=self.cluster_affinity)


def _parse_duration(raw: Optional[str]) -> Optional[float]:
    if not raw:
        return None
    units = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}
    total, num = 0.0, ""
    i = 0
    while i < len(raw):
        ch = raw[i]
        if ch.isdigit() or ch == ".":
            num += ch
            i += 1
            continue
        for u in ("ms", "s", "m", "h"):
            if raw.startswith(u, i) and (u != "m" or not raw.startswith("ms", i)):
                total += float(num) * units[u]
                num = ""
                i += len(u)
                break
        else:
            raise ValueError(f"invalid duration {raw!r}")
    if num:
        total += float(num)
    return total


def parse_policy(obj: dict) -> PolicySpec:
    meta_ = obj.get("metadata", {})
    spec = obj.get("spec", {})
    auto = spec.get("autoMigration")
    resched = spec.get("replicaRescheduling")
    return PolicySpec(
        name=meta_.get("name", ""),
        namespace=meta_.get("namespace", ""),
        generation=meta_.get("generation", 1),
        scheduling_profile=spec.get("schedulingProfile", ""),
        scheduling_mode=spec.get("schedulingMode", MODE_DUPLICATE),
        sticky_cluster=spec.get("stickyCluster", False),
        cluster_selector=dict(spec.get("clusterSelector", {})),
        cluster_affinity=tuple(
            parse_selector_term(t) for t in spec.get("clusterAffinity", ())
        ),
        tolerations=tuple(parse_toleration(t) for t in spec.get("tolerations", ())),
        max_clusters=spec.get("maxClusters"),
        placements=list(spec.get("placement", ())),
        disable_follower_scheduling=spec.get("disableFollowerScheduling", False),
        auto_migration_enabled=auto is not None,
        keep_unschedulable_replicas=bool(auto and auto.get("keepUnschedulableReplicas")),
        pod_unschedulable_seconds=_parse_duration(
            (auto or {}).get("when", {}).get("podUnschedulableFor")
        ),
        avoid_disruption=resched.get("avoidDisruption", True)
        if resched is not None
        else True,
    )


def matched_policy_key(fed_obj: dict) -> Optional[tuple[str, str]]:
    """(namespace, name) of the matched policy; namespace "" means a
    ClusterPropagationPolicy (reference: scheduler/util.go:37-50)."""
    labels = fed_obj.get("metadata", {}).get("labels", {})
    ns = fed_obj.get("metadata", {}).get("namespace", "")
    if PROPAGATION_POLICY_LABEL in labels and ns:
        return (ns, labels[PROPAGATION_POLICY_LABEL])
    if CLUSTER_PROPAGATION_POLICY_LABEL in labels:
        return ("", labels[CLUSTER_PROPAGATION_POLICY_LABEL])
    return None
