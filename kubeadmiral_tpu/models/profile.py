"""SchedulingProfile: per-policy plugin enablement.

A SchedulingProfile selects which scheduler plugins run for objects bound
to a policy that names it (reference:
pkg/apis/core/v1alpha1/types_schedulingprofile.go, application logic
pkg/controllers/scheduler/profile.go:52-82).  Semantics per extension
point (filter / score / select):

* ``disabled`` removes default plugins by name; ``"*"`` removes all.
* ``enabled`` appends plugins after the surviving defaults.

In the batch engine the resolved plugin name lists become per-object
boolean enable masks over the fused tick's plugin axes
(ops.filters.F_* / ops.scores.S_*); disabling MaxCluster at the select
point lifts the top-K limit for that object.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeadmiral_tpu.models import types as T

SCHEDULING_PROFILES = "core.kubeadmiral.io/v1alpha1/schedulingprofiles"

DEFAULT_SELECTS: tuple[str, ...] = (T.MAX_CLUSTER,)


@dataclass(frozen=True)
class PluginSet:
    """Enabled/disabled plugin names for one extension point."""

    enabled: tuple[str, ...] = ()
    disabled: tuple[str, ...] = ()


@dataclass(frozen=True)
class ProfileSpec:
    name: str
    generation: int = 1
    # None means "extension point not specified" -> defaults untouched.
    filter: PluginSet = field(default_factory=PluginSet)
    score: PluginSet = field(default_factory=PluginSet)
    select: PluginSet = field(default_factory=PluginSet)


def _parse_plugin_set(raw: dict | None) -> PluginSet:
    raw = raw or {}
    return PluginSet(
        enabled=tuple(p.get("name", "") for p in raw.get("enabled") or ()),
        disabled=tuple(p.get("name", "") for p in raw.get("disabled") or ()),
    )


def parse_profile(obj: dict) -> ProfileSpec:
    """Unstructured SchedulingProfile -> ProfileSpec."""
    spec = obj.get("spec", {})
    plugins = spec.get("plugins") or {}
    return ProfileSpec(
        name=obj["metadata"]["name"],
        generation=obj["metadata"].get("generation", 1),
        filter=_parse_plugin_set(plugins.get("filter", {})),
        score=_parse_plugin_set(plugins.get("score", {})),
        select=_parse_plugin_set(plugins.get("select", {})),
    )


def reconcile_ext_point(
    defaults: tuple[str, ...], plugin_set: PluginSet
) -> tuple[str, ...]:
    """Apply one PluginSet to the default plugin list
    (profile.go reconcileExtPoint): drop disabled defaults ("*" drops
    all), then append enabled plugins."""
    disabled = set(plugin_set.disabled)
    result: list[str] = []
    if "*" not in disabled:
        result.extend(name for name in defaults if name not in disabled)
    result.extend(plugin_set.enabled)
    return tuple(result)


def resolve_plugins(
    profile: ProfileSpec | None,
) -> tuple[tuple[str, ...], tuple[str, ...], tuple[str, ...]]:
    """Resolved (filters, scores, selects) name lists for a profile
    (None -> defaults, matching GetDefaultEnabledPlugins)."""
    if profile is None:
        return T.DEFAULT_FILTERS, T.DEFAULT_SCORES, DEFAULT_SELECTS
    return (
        reconcile_ext_point(T.DEFAULT_FILTERS, profile.filter),
        reconcile_ext_point(T.DEFAULT_SCORES, profile.score),
        reconcile_ext_point(DEFAULT_SELECTS, profile.select),
    )
