"""CRD manifests for the kubeadmiral API surface.

The authoritative definitions live here as code; ``config/crds/*.yaml``
are generated artifacts (``python -m kubeadmiral_tpu.models.crds``) kept
in-repo like the reference's ``config/crds/*.yaml`` (reference:
pkg/apis/core/v1alpha1/*.go + generated manifests).  ``install`` creates
the CRD objects on a host apiserver, and ``crd_for_ftc`` generates the
federated-object CRD for a FederatedTypeConfig the way
``--create-crds-for-ftcs`` does (reference:
pkg/controllers/federatedtypeconfig/federatedtypeconfig_controller.go:437-520).
"""

from __future__ import annotations

import os

GROUP = "core.kubeadmiral.io"
VERSION = "v1alpha1"
TYPES_GROUP = "types.kubeadmiral.io"
CRD_RESOURCE = "apiextensions.k8s.io/v1/customresourcedefinitions"

_ANY = {"x-kubernetes-preserve-unknown-fields": True}
_STR = {"type": "string"}
_INT = {"type": "integer"}
_BOOL = {"type": "boolean"}


def _obj(props: dict, required: list[str] | None = None) -> dict:
    out = {"type": "object", "properties": props}
    if required:
        out["required"] = required
    return out


def _arr(items: dict) -> dict:
    return {"type": "array", "items": items}


_SELECTOR_TERM = _obj(
    {
        "matchExpressions": _arr(
            _obj({"key": _STR, "operator": _STR, "values": _arr(_STR)})
        ),
        "matchFields": _arr(
            _obj({"key": _STR, "operator": _STR, "values": _arr(_STR)})
        ),
    }
)

_TOLERATION = _obj(
    {
        "key": _STR,
        "operator": _STR,
        "value": _STR,
        "effect": _STR,
        "tolerationSeconds": _INT,
    }
)

_POLICY_SPEC = _obj(
    {
        "schedulingMode": {"type": "string", "enum": ["Duplicate", "Divide"]},
        "stickyCluster": _BOOL,
        "clusterSelector": {"type": "object", "additionalProperties": _STR},
        "clusterAffinity": _arr(_SELECTOR_TERM),
        "tolerations": _arr(_TOLERATION),
        "maxClusters": _INT,
        "placement": _arr(
            _obj(
                {
                    "cluster": _STR,
                    "preferences": _obj(
                        {
                            "minReplicas": _INT,
                            "maxReplicas": _INT,
                            "weight": _INT,
                        }
                    ),
                },
                required=["cluster"],
            )
        ),
        "schedulingProfile": _STR,
        "disableFollowerScheduling": _BOOL,
        "autoMigration": _obj(
            {
                "when": _obj({"podUnschedulableFor": _STR}),
                "keepUnschedulableReplicas": _BOOL,
            }
        ),
        "replicaRescheduling": _obj({"avoidDisruption": _BOOL}),
    }
)

_OVERRIDE_SPEC = _obj(
    {
        "overrideRules": _arr(
            _obj(
                {
                    "targetClusters": _obj(
                        {
                            "clusters": _arr(_STR),
                            "clusterSelector": {
                                "type": "object",
                                "additionalProperties": _STR,
                            },
                            "clusterAffinity": _arr(_SELECTOR_TERM),
                        }
                    ),
                    "overriders": _obj(
                        {
                            "jsonpatch": _arr(
                                _obj(
                                    {
                                        "operator": _STR,
                                        "path": _STR,
                                        "value": _ANY,
                                    },
                                    required=["path"],
                                )
                            )
                        }
                    ),
                }
            )
        )
    }
)

_FTC_SPEC = _obj(
    {
        "sourceType": _obj(
            {"group": _STR, "version": _STR, "kind": _STR, "pluralName": _STR,
             "scope": _STR},
            required=["version", "kind", "pluralName"],
        ),
        "federatedType": _obj(
            {"group": _STR, "version": _STR, "kind": _STR, "pluralName": _STR,
             "scope": _STR},
        ),
        "statusType": _obj(
            {"group": _STR, "version": _STR, "kind": _STR, "pluralName": _STR,
             "scope": _STR},
        ),
        "controllers": _arr(_arr(_STR)),
        "pathDefinition": _obj(
            {
                "replicasSpec": _STR,
                "replicasStatus": _STR,
                "availableReplicasStatus": _STR,
                "readyReplicasStatus": _STR,
                "labelSelector": _STR,
            }
        ),
        "statusCollection": _obj({"enabled": _BOOL, "fields": _arr(_STR)}),
        "statusAggregation": _STR,
        "revisionHistory": _STR,
        "rolloutPlan": _STR,
        "autoMigration": _obj({"enabled": _BOOL}),
    }
)

_PLUGIN_SET = _obj(
    {
        "enabled": _arr(_obj({"name": _STR}, required=["name"])),
        "disabled": _arr(_obj({"name": _STR}, required=["name"])),
    }
)


def crd(
    kind: str,
    plural: str,
    scope: str,
    spec_schema: dict,
    group: str = GROUP,
    version: str = VERSION,
    status: bool = True,
) -> dict:
    schema_props: dict = {
        "apiVersion": _STR,
        "kind": _STR,
        "metadata": {"type": "object"},
        "spec": spec_schema,
    }
    if status:
        schema_props["status"] = _ANY
    versions = [
        {
            "name": version,
            "served": True,
            "storage": True,
            "schema": {"openAPIV3Schema": _obj(schema_props)},
        }
    ]
    if status:
        versions[0]["subresources"] = {"status": {}}
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{group}"},
        "spec": {
            "group": group,
            "names": {
                "kind": kind,
                "listKind": f"{kind}List",
                "plural": plural,
                "singular": kind.lower(),
            },
            "scope": scope,
            "versions": versions,
        },
    }


def core_crds() -> list[dict]:
    return [
        crd("FederatedTypeConfig", "federatedtypeconfigs", "Cluster", _FTC_SPEC),
        crd(
            "FederatedCluster",
            "federatedclusters",
            "Cluster",
            _obj(
                {
                    "apiEndpoint": _STR,
                    "secretRef": _obj({"name": _STR}),
                    "insecure": _BOOL,
                    "useServiceAccountToken": _BOOL,
                    "taints": _arr(
                        _obj({"key": _STR, "value": _STR, "effect": _STR})
                    ),
                }
            ),
        ),
        crd("PropagationPolicy", "propagationpolicies", "Namespaced", _POLICY_SPEC),
        crd(
            "ClusterPropagationPolicy",
            "clusterpropagationpolicies",
            "Cluster",
            _POLICY_SPEC,
        ),
        crd("OverridePolicy", "overridepolicies", "Namespaced", _OVERRIDE_SPEC),
        crd(
            "ClusterOverridePolicy",
            "clusteroverridepolicies",
            "Cluster",
            _OVERRIDE_SPEC,
        ),
        crd(
            "SchedulingProfile",
            "schedulingprofiles",
            "Cluster",
            _obj(
                {
                    "plugins": _obj(
                        {
                            "filter": _PLUGIN_SET,
                            "score": _PLUGIN_SET,
                            "select": _PLUGIN_SET,
                        }
                    )
                }
            ),
            status=False,
        ),
        crd(
            "SchedulerPluginWebhookConfiguration",
            "schedulerpluginwebhookconfigurations",
            "Cluster",
            _obj(
                {
                    "urlPrefix": _STR,
                    "filterPath": _STR,
                    "scorePath": _STR,
                    "selectPath": _STR,
                    "payloadVersions": _arr(_STR),
                    "httpTimeout": _STR,
                    "tlsConfig": _ANY,
                },
                required=["urlPrefix", "payloadVersions"],
            ),
            status=False,
        ),
        crd(
            "PropagatedVersion",
            "propagatedversions",
            "Namespaced",
            _ANY,
        ),
        crd(
            "ClusterPropagatedVersion",
            "clusterpropagatedversions",
            "Cluster",
            _ANY,
        ),
    ]


def crd_for_ftc(ftc) -> dict:
    """The federated-object CRD a FederatedTypeConfig implies."""
    fed = ftc.federated
    spec_schema = _obj(
        {
            "template": _ANY,
            "placements": _arr(
                _obj(
                    {
                        "controller": _STR,
                        "placement": _arr(
                            _obj({"cluster": _STR}, required=["cluster"])
                        ),
                    },
                    required=["controller"],
                )
            ),
            "overrides": _arr(
                _obj(
                    {
                        "controller": _STR,
                        "override": _arr(
                            _obj(
                                {
                                    "clusters": _arr(_STR),
                                    "patches": _arr(_ANY),
                                }
                            )
                        ),
                    }
                )
            ),
            "follows": _arr(
                _obj({"group": _STR, "kind": _STR, "name": _STR,
                      "namespace": _STR})
            ),
        }
    )
    group, version, plural = fed.resource.split("/")
    scope = "Namespaced" if ftc.namespaced else "Cluster"
    return crd(fed.kind, plural, scope, spec_schema, group=group, version=version)


def install(store, ftcs=()) -> int:
    """Create CRD objects on a host apiserver (idempotent); with ftcs,
    also the implied federated-object CRDs (--create-crds-for-ftcs)."""
    from kubeadmiral_tpu.testing.fakekube import AlreadyExists

    n = 0
    for manifest in core_crds() + [crd_for_ftc(f) for f in ftcs]:
        try:
            store.create(CRD_RESOURCE, manifest)
            n += 1
        except AlreadyExists:
            pass
    return n


MANIFEST_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "config",
    "crds",
)


def write_manifests(directory: str = MANIFEST_DIR) -> list[str]:
    import yaml

    class _Dumper(yaml.SafeDumper):
        def ignore_aliases(self, data):  # no &id anchors in manifests
            return True

    os.makedirs(directory, exist_ok=True)
    paths = []
    for manifest in core_crds():
        name = manifest["metadata"]["name"]
        path = os.path.join(directory, f"{name}.yaml")
        with open(path, "w") as f:
            yaml.dump(manifest, f, Dumper=_Dumper, sort_keys=False)
        paths.append(path)
    return paths


if __name__ == "__main__":
    for p in write_manifests():
        print(p)
