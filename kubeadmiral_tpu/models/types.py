"""Core scheduling-facing data model.

Python equivalents of the reference API types the scheduler consumes
(reference: pkg/apis/core/v1alpha1, pkg/controllers/scheduler/framework/
types.go).  Kept deliberately lean: federated objects themselves travel as
unstructured dicts through the control plane; these typed structs cover
the scheduling contract where exact matching semantics matter.

Canonical resource units (dict key -> int):
  "cpu" -> millicores (Quantity.MilliValue), everything else ->
  Quantity.Value (bytes for memory/storage), matching the reference's
  framework.Resource extraction (framework/util.go NewResource).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Optional, Sequence

from kubeadmiral_tpu.utils.quantity import cpu_to_millis, to_int_value

# Taint effects / scheduling modes / operators mirror the k8s constants.
NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"

MODE_DUPLICATE = "Duplicate"
MODE_DIVIDE = "Divide"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    """Pod/workload toleration with k8s ToleratesTaint semantics."""

    key: str = ""
    operator: str = "Equal"  # "Equal" | "Exists" ("" behaves as Equal)
    value: str = ""
    effect: str = ""  # "" tolerates every effect

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        # Empty key with Exists tolerates all taints.
        if self.operator == "Exists":
            return self.value == ""
        return self.value == taint.value  # Equal / unset operator


@dataclass(frozen=True)
class SelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: tuple[str, ...] = ()


@dataclass(frozen=True)
class SelectorTerm:
    """ANDed requirements over labels plus fields (metadata.name)."""

    match_expressions: tuple[SelectorRequirement, ...] = ()
    match_fields: tuple[SelectorRequirement, ...] = ()


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    preference: SelectorTerm


@dataclass(frozen=True)
class ClusterAffinity:
    """required=None means "matches everything" (no constraint); an empty
    tuple matches nothing (reference: cluster_affinity.go:69-93)."""

    required: Optional[tuple[SelectorTerm, ...]] = None
    preferred: tuple[PreferredSchedulingTerm, ...] = ()


def parse_resources(raw: Mapping[str, "str | int | float"]) -> dict[str, int]:
    """Quantity strings -> canonical ints (cpu in millis, rest in units)."""
    out: dict[str, int] = {}
    for name, q in raw.items():
        out[name] = cpu_to_millis(q) if name == "cpu" else to_int_value(q)
    return out


@dataclass
class ClusterState:
    """Scheduling-relevant view of a member cluster
    (reference: types_federatedcluster.go FederatedCluster + status)."""

    name: str
    labels: dict[str, str] = field(default_factory=dict)
    taints: tuple[Taint, ...] = ()
    allocatable: dict[str, int] = field(default_factory=dict)  # canonical units
    available: dict[str, int] = field(default_factory=dict)
    api_resources: frozenset[str] = frozenset()  # "group/version/Kind"


@dataclass
class AutoMigrationSpec:
    keep_unschedulable_replicas: bool = False
    estimated_capacity: dict[str, int] = field(default_factory=dict)


@dataclass(frozen=True)
class SchedulingUnit:
    """The per-object scheduling request
    (reference: framework/types.go:34-73).

    Frozen: the engine's cross-tick caches use object identity as a
    fast-path for "unchanged since last tick", so a unit must never be
    modified after construction — including its nested dicts.  Derive
    changed units with ``dataclasses.replace`` and fresh dict values
    (which is what the controllers do: each reconcile builds new units
    from the API objects)."""

    gvk: str  # "group/version/Kind"
    namespace: str
    name: str
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)

    desired_replicas: Optional[int] = None
    resource_request: dict[str, int] = field(default_factory=dict)

    current_clusters: dict[str, Optional[int]] = field(default_factory=dict)
    auto_migration: Optional[AutoMigrationSpec] = None

    scheduling_mode: str = MODE_DUPLICATE
    sticky_cluster: bool = False
    avoid_disruption: bool = True

    cluster_selector: dict[str, str] = field(default_factory=dict)
    cluster_names: frozenset[str] = frozenset()  # explicit placement list
    affinity: Optional[ClusterAffinity] = None
    tolerations: tuple[Toleration, ...] = ()
    max_clusters: Optional[int] = None
    min_replicas: dict[str, int] = field(default_factory=dict)
    max_replicas: dict[str, int] = field(default_factory=dict)
    weights: dict[str, int] = field(default_factory=dict)

    # Enabled plugin names per extension point (None = defaults).  Names
    # that aren't in-tree refer to registered webhook plugins.
    enabled_filters: Optional[tuple[str, ...]] = None
    enabled_scores: Optional[tuple[str, ...]] = None
    enabled_selects: Optional[tuple[str, ...]] = None

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}" if self.namespace else self.name


# In-tree plugin names (reference: framework/plugins/names/names.go).
APIRESOURCES = "APIResources"
TAINT_TOLERATION = "TaintToleration"
CLUSTER_RESOURCES_FIT = "ClusterResourcesFit"
PLACEMENT_FILTER = "PlacementFilter"
CLUSTER_AFFINITY = "ClusterAffinity"
CLUSTER_RESOURCES_BALANCED = "ClusterResourcesBalancedAllocation"
CLUSTER_RESOURCES_LEAST = "ClusterResourcesLeastAllocated"
CLUSTER_RESOURCES_MOST = "ClusterResourcesMostAllocated"
MAX_CLUSTER = "MaxCluster"
CLUSTER_CAPACITY_WEIGHT = "ClusterCapacityWeight"

# Default enabled plugins (reference: extensions_schedulingprofile.go:24-49).
DEFAULT_FILTERS: tuple[str, ...] = (
    APIRESOURCES,
    TAINT_TOLERATION,
    CLUSTER_RESOURCES_FIT,
    PLACEMENT_FILTER,
    CLUSTER_AFFINITY,
)
DEFAULT_SCORES: tuple[str, ...] = (
    TAINT_TOLERATION,
    CLUSTER_RESOURCES_BALANCED,
    CLUSTER_RESOURCES_LEAST,
    CLUSTER_AFFINITY,
)
