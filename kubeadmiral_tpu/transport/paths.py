"""Kubernetes-style REST path codec.

Resource keys in this framework are ``group/version/plural`` (or
``version/plural`` for the core group), matching how the reference
addresses resources by GVR.  These map onto apiserver URL paths the same
way real Kubernetes lays them out:

    v1/pods, ns=default, name=web  ->  /api/v1/namespaces/default/pods/web
    apps/v1/deployments (all ns)   ->  /apis/apps/v1/deployments
    core.kubeadmiral.io/v1alpha1/federatedclusters, name=c1
        -> /apis/core.kubeadmiral.io/v1alpha1/federatedclusters/c1
"""

from __future__ import annotations

from typing import NamedTuple, Optional


class ParsedPath(NamedTuple):
    resource: str
    namespace: Optional[str]  # None = cluster-scoped or all-namespace list
    name: Optional[str]
    subresource: Optional[str]


def resource_to_path(
    resource: str,
    namespace: Optional[str] = None,
    name: Optional[str] = None,
    subresource: Optional[str] = None,
) -> str:
    parts = resource.split("/")
    if len(parts) == 2:
        version, plural = parts
        base = f"/api/{version}"
    elif len(parts) == 3:
        group, version, plural = parts
        base = f"/apis/{group}/{version}"
    else:
        raise ValueError(f"bad resource key: {resource!r}")
    if namespace:
        base += f"/namespaces/{namespace}"
    base += f"/{plural}"
    if name:
        base += f"/{name}"
    if subresource:
        base += f"/{subresource}"
    return base


def key_to_path(
    resource: str, key: str, subresource: Optional[str] = None
) -> str:
    """Path for a store key ('ns/name' or 'name')."""
    if "/" in key:
        ns, name = key.split("/", 1)
    else:
        ns, name = None, key
    return resource_to_path(resource, ns, name, subresource)


def parse_path(path: str) -> ParsedPath:
    segs = [s for s in path.split("/") if s]
    if len(segs) >= 2 and segs[0] == "api":
        prefix = segs[1]  # version only (core group)
        rest = segs[2:]
    elif len(segs) >= 3 and segs[0] == "apis":
        prefix = f"{segs[1]}/{segs[2]}"
        rest = segs[3:]
    else:
        raise ValueError(f"unroutable path: {path!r}")
    if not rest:
        raise ValueError(f"no resource in path: {path!r}")

    namespace: Optional[str] = None
    if rest[0] == "namespaces" and len(rest) >= 3 and rest[2] != "status":
        # /…/namespaces/{ns}/{plural}[/{name}[/{sub}]]
        namespace = rest[1]
        rest = rest[2:]
    # else: operations on the namespaces resource itself
    # (/api/v1/namespaces[/{name}[/status]]) fall through with rest[0]
    # == "namespaces" as the plural.

    plural = rest[0]
    name = rest[1] if len(rest) >= 2 else None
    subresource = rest[2] if len(rest) >= 3 else None
    if len(rest) > 3:
        raise ValueError(f"path too deep: {path!r}")
    return ParsedPath(f"{prefix}/{plural}", namespace, name, subresource)
