"""Per-member circuit breakers: the member fault-tolerance core.

KubeAdmiral's lineage treats member unavailability as a first-class
state (ClusterNotReady propagation status, Offline/Ready conditions),
not an exception path.  This module gives every consumer of a member
client one shared view of that state:

* a :class:`MemberBreaker` per member cluster — CLOSED while healthy;
  consecutive failures, a single stall/timeout, or a latency EWMA past
  threshold OPEN it; after a cool-down it goes HALF_OPEN and admits one
  probe at a time; a successful probe (a real round trip — dispatch
  write, member read, or the cluster controller's healthz heartbeat)
  CLOSEs it again;
* a :class:`BreakerRegistry` per fleet (``for_fleet``) shared by the
  sync dispatch fan-out, the cluster controller's heartbeat, the status
  controller and the monitor, so a member that stalled one sync flush
  is invisible to the next tick's reads too — no thread ever parks on
  a socket the fleet already knows is dead;
* catalog-enforced telemetry (``member_breaker_state``,
  ``member_dispatch_retries_total``, ``member_shed_writes_total``,
  ``member_probe_latency``) and the ``GET /debug/members`` report
  (``members_report`` aggregates every live registry).

Knobs (read at registry construction): ``KT_BREAKER_FAILURES`` (3
consecutive failures open), ``KT_BREAKER_OPEN_S`` (5 s cool-down before
half-open), ``KT_BREAKER_LATENCY_S`` (5 s EWMA latency opens),
``KT_BREAKER_STALL_S`` (a single failure slower than this counts as a
stall and opens immediately — the "one deadline, then short-circuit"
contract).  See docs/operations.md § Degraded member runbook.
"""

from __future__ import annotations

import os
import threading

from kubeadmiral_tpu.runtime import lockcheck
import time
import weakref
from collections import deque
from typing import Callable, Optional

CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"

# Gauge encoding for member_breaker_state{cluster}.
STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

TransitionCallback = Callable[[str, str, str], None]  # (member, old, new)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


class BreakerConfig:
    """Thresholds shared by every breaker of a registry."""

    __slots__ = (
        "failure_threshold",
        "open_seconds",
        "latency_threshold_s",
        "stall_threshold_s",
        "ewma_alpha",
    )

    def __init__(
        self,
        failure_threshold: Optional[int] = None,
        open_seconds: Optional[float] = None,
        latency_threshold_s: Optional[float] = None,
        stall_threshold_s: Optional[float] = None,
        ewma_alpha: float = 0.3,
    ):
        self.failure_threshold = (
            failure_threshold
            if failure_threshold is not None
            else _env_int("KT_BREAKER_FAILURES", 3)
        )
        self.open_seconds = (
            open_seconds
            if open_seconds is not None
            else _env_float("KT_BREAKER_OPEN_S", 5.0)
        )
        self.latency_threshold_s = (
            latency_threshold_s
            if latency_threshold_s is not None
            else _env_float("KT_BREAKER_LATENCY_S", 5.0)
        )
        self.stall_threshold_s = (
            stall_threshold_s
            if stall_threshold_s is not None
            else _env_float("KT_BREAKER_STALL_S", 1.0)
        )
        self.ewma_alpha = ewma_alpha


@lockcheck.shared_field_guard
class MemberBreaker:
    """One member's circuit state.  Thread-safe; the CLOSED fast paths
    (``allow`` with a closed breaker, ``note_ok`` with no failure
    history) are lock-free attribute reads so the per-(object, cluster)
    hot loops pay nothing while the fleet is healthy."""

    # Circuit state shared by every dispatch/sync thread of the fleet
    # (ktlint lock-discipline + runtime/lockcheck.py); reads may be
    # lock-free (the documented fast paths), writes never.
    _shared_fields_ = {
        "_state": "_lock",
        "_consecutive": "_lock",
        "_opened_at": "_lock",
        "_probe_inflight": "_lock",
        "_ewma_latency": "_lock",
        "_failures_total": "_lock",
        "_opens_total": "_lock",
        "_last_error_at": "_lock",
    }

    def __init__(self, name: str, config: BreakerConfig,
                 registry: Optional["BreakerRegistry"] = None,
                 clock=time.monotonic):
        self.name = name
        self.config = config
        self._registry = registry
        self._clock = clock
        self._lock = lockcheck.make_lock("breaker")
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._ewma_latency: Optional[float] = None
        self._failures_total = 0
        self._opens_total = 0
        self._last_error_at: Optional[float] = None

    @property
    def state(self) -> str:
        return self._state

    # -- admission --------------------------------------------------------
    def allow(self, consume_probe: bool = True) -> bool:
        """May a call proceed to this member right now?

        CLOSED: always.  OPEN: no, until the cool-down elapses (then the
        breaker turns HALF_OPEN).  HALF_OPEN: one in-flight probe at a
        time when ``consume_probe`` (the write paths — the call itself
        is the probe); ``consume_probe=False`` is the cheap read-side
        check (open-and-cooling means no)."""
        if self._state is CLOSED:  # lock-free fast path
            return True
        fired = None
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.config.open_seconds:
                    return False
                fired = self._transition_locked(HALF_OPEN)
                self._probe_inflight = False
            # HALF_OPEN
            if not consume_probe:
                result = True
            elif self._probe_inflight:
                result = False
            else:
                self._probe_inflight = True
                result = True
        if fired:
            self._fire(*fired)
        return result

    # -- evidence ---------------------------------------------------------
    def note_ok(self, latency_s: Optional[float] = None) -> None:
        """Record an incidental successful round trip.  Free while the
        breaker is closed and clean; otherwise full success recording
        (a real round trip through a suspect member is a probe)."""
        if self._state is CLOSED and self._consecutive == 0:
            return
        self.record_success(latency_s)

    def record_success(self, latency_s: Optional[float] = None,
                       probe: bool = False) -> None:
        fired = None
        with self._lock:
            if latency_s is not None:
                a = self.config.ewma_alpha
                self._ewma_latency = (
                    latency_s
                    if self._ewma_latency is None
                    else a * latency_s + (1 - a) * self._ewma_latency
                )
            self._consecutive = 0
            self._probe_inflight = False
            if self._state == HALF_OPEN:
                fired = self._transition_locked(CLOSED)
            elif self._state == OPEN:
                # An out-of-band probe (the heartbeat) closes only once
                # the cool-down elapsed — before that, a lone success
                # must not defeat the open window's load shedding.
                if probe and (
                    self._clock() - self._opened_at >= self.config.open_seconds
                ):
                    fired = self._transition_locked(CLOSED)
            elif (
                self._state == CLOSED
                and self._ewma_latency is not None
                and self.config.latency_threshold_s > 0
                and self._ewma_latency > self.config.latency_threshold_s
            ):
                # Latency EWMA past threshold: the member answers, but so
                # slowly it would serialize the tick — open anyway.
                fired = self._open_locked()
        if fired:
            self._fire(*fired)

    def record_failure(self, latency_s: Optional[float] = None,
                       timeout: bool = False) -> None:
        """A failed round trip.  ``timeout=True`` (a stall: deadline or
        ``KT_BREAKER_STALL_S`` exceeded) opens immediately — one parked
        deadline is all a dead member gets."""
        if latency_s is not None and latency_s >= self.config.stall_threshold_s:
            timeout = True
        fired = None
        with self._lock:
            self._consecutive += 1
            self._failures_total += 1
            self._last_error_at = self._clock()
            self._probe_inflight = False
            if self._state == HALF_OPEN:
                fired = self._open_locked()
            elif self._state == CLOSED and (
                timeout or self._consecutive >= self.config.failure_threshold
            ):
                fired = self._open_locked()
        if fired:
            self._fire(*fired)

    # -- transitions ------------------------------------------------------
    def _open_locked(self):
        self._opened_at = self._clock()
        self._opens_total += 1
        return self._transition_locked(OPEN)

    def _transition_locked(self, new: str):
        old, self._state = self._state, new
        return (old, new) if old != new else None

    def _fire(self, old: str, new: str) -> None:
        if self._registry is not None:
            self._registry._on_breaker_transition(self.name, old, new)

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "failures_total": self._failures_total,
                "opens_total": self._opens_total,
                "ewma_latency_ms": (
                    round(self._ewma_latency * 1000.0, 3)
                    if self._ewma_latency is not None
                    else None
                ),
            }
            if self._state != CLOSED:
                out["opened_ago_s"] = round(self._clock() - self._opened_at, 3)
            if self._last_error_at is not None:
                out["last_error_ago_s"] = round(
                    self._clock() - self._last_error_at, 3
                )
        return out

    # -- durable state (runtime/snapshot.py) -------------------------------
    def export_state(self) -> dict:
        """Restart-durable image of this breaker.  Open windows export
        their REMAINING cool-down (clocks are process-local monotonic,
        so absolute open timestamps would be meaningless to a
        successor)."""
        with self._lock:
            remaining = 0.0
            if self._state != CLOSED:
                remaining = max(
                    0.0,
                    self.config.open_seconds - (self._clock() - self._opened_at),
                )
            return {
                "state": self._state,
                "remaining_s": remaining,
                "consecutive": self._consecutive,
                "failures_total": self._failures_total,
                "opens_total": self._opens_total,
                "ewma_latency_s": self._ewma_latency,
            }

    def restore_state(self, state: dict, downtime_s: float = 0.0) -> None:
        """Resume a pre-crash breaker: an OPEN member stays OPEN with the
        remaining cool-down (minus the measured downtime) instead of
        getting a free probe storm on the first post-restart tick; a
        HALF_OPEN member re-enters the open window's tail (its probe
        outcome died with the old process).  The half-open probe then
        fires when the ORIGINAL window would have elapsed, never from a
        restarted full window."""
        fired = None
        with self._lock:
            new = state.get("state", CLOSED)
            if new == HALF_OPEN:
                new = OPEN
            remaining = max(
                0.0, float(state.get("remaining_s", 0.0)) - max(0.0, downtime_s)
            )
            self._consecutive = int(state.get("consecutive", 0))
            self._failures_total = int(state.get("failures_total", 0))
            self._opens_total = int(state.get("opens_total", 0))
            ewma = state.get("ewma_latency_s")
            self._ewma_latency = float(ewma) if ewma is not None else None
            self._probe_inflight = False
            if new == OPEN:
                # Re-anchor the open window so exactly `remaining`
                # cool-down is left on this process's clock.
                self._opened_at = (
                    self._clock() - (self.config.open_seconds - remaining)
                )
                fired = self._transition_locked(OPEN)
            else:
                fired = self._transition_locked(CLOSED)
        if fired:
            self._fire(*fired)


# Live registries, for the aggregated /debug/members report.
_REGISTRIES: "weakref.WeakSet[BreakerRegistry]" = weakref.WeakSet()


@lockcheck.shared_field_guard
class BreakerRegistry:
    """One fleet's breakers + shed/retry accounting + telemetry."""

    _shared_fields_ = {
        "_breakers": "_lock",
        "_callbacks": "_lock",
        "_shed": "_lock",
        "_retries": "_lock",
        "_write_lat": "_lock",
        "_write_ops": "_lock",
        "_write_flushes": "_lock",
        "_batch_sizes": "_lock",
        "_bulk_counts": "_lock",
    }

    def __init__(self, metrics=None, config: Optional[BreakerConfig] = None,
                 clock=time.monotonic):
        self.metrics = metrics
        self.config = config or BreakerConfig()
        self._clock = clock
        self._lock = lockcheck.make_lock("breaker-registry")
        self._breakers: dict[str, MemberBreaker] = {}
        self._callbacks: list[TransitionCallback] = []
        self._shed: dict[str, int] = {}
        self._retries: dict[str, int] = {}
        # Per-member write-latency reservoir (dispatch feeds it via
        # note_write): bounded recent samples + cumulative totals, so
        # /debug/members joins write p50/p99 with breaker state and a
        # slow member is triaged without leaving the endpoint.
        self._write_lat: dict[str, "deque[float]"] = {}
        self._write_ops: dict[str, int] = {}
        self._write_flushes: dict[str, int] = {}
        # Coalesced bulk-write attribution (dispatch.run_member_batches
        # feeds note_batch): recent per-request batch sizes + cumulative
        # outcome counts, joined into GET /debug/members so an operator
        # sees whether a member's writes actually coalesce.
        self._batch_sizes: dict[str, "deque[int]"] = {}
        self._bulk_counts: dict[str, dict[str, int]] = {}
        _REGISTRIES.add(self)

    def for_member(self, name: str) -> MemberBreaker:
        breaker = self._breakers.get(name)  # lock-free hot path
        if breaker is not None:
            return breaker
        with self._lock:
            breaker = self._breakers.get(name)
            if breaker is None:
                breaker = MemberBreaker(
                    name, self.config, registry=self, clock=self._clock
                )
                self._breakers[name] = breaker
                self._emit_state(name, CLOSED)
            return breaker

    def allow(self, name: str, consume_probe: bool = True) -> bool:
        return self.for_member(name).allow(consume_probe=consume_probe)

    def on_transition(self, callback: TransitionCallback) -> None:
        with self._lock:
            self._callbacks.append(callback)

    def _on_breaker_transition(self, name: str, old: str, new: str) -> None:
        self._emit_state(name, new)
        with self._lock:
            callbacks = list(self._callbacks)
        for cb in callbacks:
            try:
                cb(name, old, new)
            except Exception:
                pass  # observers must not break state accounting

    def _emit_state(self, name: str, state: str) -> None:
        if self.metrics is not None:
            self.metrics.gauge(
                "member_breaker_state", STATE_CODE[state], cluster=name
            )

    # -- shed / retry accounting (dispatch feeds these) --------------------
    def count_shed(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._shed[name] = self._shed.get(name, 0) + n
        if self.metrics is not None:
            self.metrics.counter("member_shed_writes_total", n, cluster=name)

    def count_retry(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._retries[name] = self._retries.get(name, 0) + n
        if self.metrics is not None:
            self.metrics.counter(
                "member_dispatch_retries_total", n, cluster=name
            )

    def note_write(self, name: str, seconds: float, ops: int = 1) -> None:
        """One completed write batch against this member (dispatch's
        per-member attribution feed; retries included in ``seconds``)."""
        with self._lock:
            reservoir = self._write_lat.get(name)
            if reservoir is None:
                reservoir = self._write_lat[name] = deque(maxlen=256)
            reservoir.append(float(seconds))
            self._write_ops[name] = self._write_ops.get(name, 0) + int(ops)
            self._write_flushes[name] = self._write_flushes.get(name, 0) + 1

    def note_batch(self, name: str, ops: int, outcome: str) -> None:
        """One coalesced bulk request against this member completed
        (outcome: ok | partial | transport)."""
        with self._lock:
            reservoir = self._batch_sizes.get(name)
            if reservoir is None:
                reservoir = self._batch_sizes[name] = deque(maxlen=256)
            reservoir.append(int(ops))
            counts = self._bulk_counts.setdefault(name, {})
            counts[outcome] = counts.get(outcome, 0) + 1

    def shed_total(self) -> int:
        with self._lock:
            return sum(self._shed.values())

    # -- durable state (runtime/snapshot.py) -------------------------------
    def export_state(self) -> dict:
        """Restart-durable registry image: per-member breaker states
        plus a wall-clock stamp so restore can subtract the downtime
        from open windows."""
        with self._lock:
            breakers = list(self._breakers.values())
        return {
            "wall": time.time(),
            "members": {b.name: b.export_state() for b in breakers},
        }

    def restore_state(self, payload: dict) -> None:
        """Resume pre-crash breaker states: a member whose breaker was
        OPEN stays skipped (ClusterNotReady) on the first post-restart
        tick, and its half-open probe resumes after the REMAINING
        cool-down — a controller restart is never a probe amnesty."""
        downtime = max(0.0, time.time() - float(payload.get("wall", time.time())))
        for name, state in (payload.get("members") or {}).items():
            self.for_member(name).restore_state(state, downtime_s=downtime)

    def open_members(self) -> list[str]:
        with self._lock:
            breakers = list(self._breakers.values())
        return [b.name for b in breakers if b.state != CLOSED]

    def snapshot(self) -> dict:
        with self._lock:
            breakers = dict(self._breakers)
            shed = dict(self._shed)
            retries = dict(self._retries)
            write_lat = {n: sorted(d) for n, d in self._write_lat.items()}
            write_ops = dict(self._write_ops)
            write_flushes = dict(self._write_flushes)
            batch_sizes = {n: sorted(d) for n, d in self._batch_sizes.items()}
            bulk_counts = {n: dict(c) for n, c in self._bulk_counts.items()}
        out = {}
        for name, breaker in sorted(breakers.items()):
            entry = breaker.snapshot()
            entry["shed_writes"] = shed.get(name, 0)
            entry["dispatch_retries"] = retries.get(name, 0)
            ranked = write_lat.get(name)
            if ranked:
                entry["write_latency"] = {
                    "flushes": write_flushes.get(name, 0),
                    "ops": write_ops.get(name, 0),
                    "p50_ms": round(ranked[len(ranked) // 2] * 1e3, 3),
                    "p99_ms": round(
                        ranked[min(len(ranked) - 1, int(len(ranked) * 0.99))]
                        * 1e3, 3,
                    ),
                    "max_ms": round(ranked[-1] * 1e3, 3),
                }
            sizes = batch_sizes.get(name)
            if sizes:
                entry["batch"] = {
                    "requests": bulk_counts.get(name, {}),
                    "p50_ops": sizes[len(sizes) // 2],
                    "max_ops": sizes[-1],
                }
            out[name] = entry
        return out


def for_fleet(fleet, metrics=None,
              config: Optional[BreakerConfig] = None) -> BreakerRegistry:
    """The fleet's shared registry, created on first use: every
    controller of one control plane must see the same member state (a
    member that stalled sync's flush is short-circuited by the next
    read too)."""
    registry = getattr(fleet, "_member_breakers", None)
    if registry is None:
        registry = BreakerRegistry(metrics=metrics, config=config)
        fleet._member_breakers = registry
    return registry


def members_report() -> dict:
    """The GET /debug/members payload: every live registry's member
    snapshots (one control plane per process is the common case; tests
    run several, which merge here keyed by member name)."""
    members: dict[str, dict] = {}
    for registry in list(_REGISTRIES):
        for name, entry in registry.snapshot().items():
            members[name] = entry
    return {
        "members": members,
        "open": sorted(n for n, e in members.items() if e["state"] != CLOSED),
        "generated_at": time.time(),
    }
