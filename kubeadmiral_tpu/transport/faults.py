"""Member fault injection: the chaos seam for transport and testing.

Nothing in the control plane may *assume* a healthy member; this module
is how tests and benches make that falsifiable.  A :class:`FaultPolicy`
describes one member's misbehavior — added latency, an error rate,
dropped connections, a stalled watch stream, a hard (connect-timeout)
partition, or flapping between partitioned and healthy — optionally
scheduled over time (``start_s`` delay, ``duration_s`` auto-expiry).
A :class:`FaultInjector` holds the per-member policies and resolves
them into instantaneous :class:`FaultAction`\\ s at request time.

Two enforcement points honor the same injector:

* **server side** — :class:`transport.apiserver.KubeApiServer` (and the
  kwok-lite farm wiring it up) gates every request and watch stream, so
  HTTP clients experience real timeouts, severed sockets and silent
  watch streams;
* **client side** — :class:`FaultyKube` wraps any FakeKube-duck-typed
  client so purely in-process fleets are injectable too (partition
  becomes a bounded sleep + :class:`TransportError`, a stalled watch
  buffers events until the stall clears).

The circuit breakers (:mod:`kubeadmiral_tpu.transport.breaker`) and the
stall-proof dispatch fan-out (:mod:`kubeadmiral_tpu.federation.dispatch`)
are tested exclusively through this seam (``tests/test_faults.py``,
``make chaos``).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from kubeadmiral_tpu.transport.client import TransportError


@dataclass(frozen=True)
class FaultPolicy:
    """One member's scheduled misbehavior.  All fields compose; the
    zero policy is a no-op."""

    latency_s: float = 0.0      # added to every request
    jitter_s: float = 0.0       # uniform extra latency in [0, jitter_s)
    error_rate: float = 0.0     # fraction of requests answered HTTP 500
    drop_rate: float = 0.0      # fraction of connections severed, no response
    partition: bool = False     # hard partition: requests hang, then sever
    watch_stall: bool = False   # watch streams stop delivering (and heartbeating)
    flap_period_s: float = 0.0  # >0: partition toggles with this period
    flap_duty: float = 0.5      # fraction of each flap period spent partitioned
    start_s: float = 0.0        # schedule: engage this long after set_fault()
    duration_s: float = 0.0     # >0: auto-expire this long after engaging


@dataclass(frozen=True)
class FaultAction:
    """A policy resolved at one instant for one request."""

    latency_s: float = 0.0
    error: bool = False
    drop: bool = False
    partition: bool = False
    watch_stall: bool = False


class FaultInjector:
    """Per-member fault policies with time-based resolution.

    Thread-safe; shared by every apiserver of a farm and by client-side
    :class:`FaultyKube` proxies.  ``partition_hang_s`` caps how long a
    server handler holds a partitioned request before severing (the
    client's own timeout fires first in practice)."""

    def __init__(self, clock=time.monotonic, seed: int = 0,
                 partition_hang_s: float = 30.0):
        self._clock = clock
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._policies: dict[str, tuple[FaultPolicy, float]] = {}
        self.partition_hang_s = partition_hang_s

    # -- policy management -----------------------------------------------
    def set_fault(self, member: str, policy: Optional[FaultPolicy]) -> None:
        with self._lock:
            if policy is None:
                self._policies.pop(member, None)
            else:
                self._policies[member] = (policy, self._clock())

    def clear(self, member: str) -> None:
        self.set_fault(member, None)

    def clear_all(self) -> None:
        with self._lock:
            self._policies.clear()

    def policy(self, member: str) -> Optional[FaultPolicy]:
        with self._lock:
            entry = self._policies.get(member)
        return entry[0] if entry is not None else None

    # -- resolution -------------------------------------------------------
    def _resolve(self, member: str) -> Optional[tuple[FaultPolicy, float]]:
        """(policy, seconds-since-engaged), or None when no policy is
        active right now (not yet started, or expired)."""
        with self._lock:
            entry = self._policies.get(member)
            if entry is None:
                return None
            policy, set_at = entry
            t = self._clock() - set_at - policy.start_s
            if t < 0:
                return None  # scheduled but not engaged yet
            if policy.duration_s > 0 and t > policy.duration_s:
                del self._policies[member]  # expired
                return None
            return policy, t

    def action(self, member: str) -> Optional[FaultAction]:
        """Resolve one request's fate; None = no active fault."""
        resolved = self._resolve(member)
        if resolved is None:
            return None
        policy, t = resolved
        partitioned = policy.partition
        if policy.flap_period_s > 0:
            phase = (t % policy.flap_period_s) / policy.flap_period_s
            partitioned = phase < policy.flap_duty
        with self._lock:
            r_err = self._rng.random()
            r_drop = self._rng.random()
            r_lat = self._rng.random()
        latency = policy.latency_s + policy.jitter_s * r_lat
        return FaultAction(
            latency_s=latency,
            error=r_err < policy.error_rate,
            drop=r_drop < policy.drop_rate,
            partition=partitioned,
            watch_stall=policy.watch_stall,
        )

    def partitioned(self, member: str) -> bool:
        act = self.action(member)
        return act is not None and act.partition

    def watch_stalled(self, member: str) -> bool:
        resolved = self._resolve(member)
        if resolved is None:
            return False
        policy, _ = resolved
        return policy.watch_stall or self.partitioned(member)


class _StallGate:
    """Wraps one watch handler: while the member's watch is stalled,
    events buffer in order; they drain before the first post-stall event
    is delivered (a stalled-then-recovered stream catches up, it never
    loses events — the in-process fleets have no relist to fall back
    on)."""

    def __init__(self, handler: Callable, member: str, injector: FaultInjector):
        self._handler = handler
        self._member = member
        self._injector = injector
        self._lock = threading.Lock()
        self._buffer: list[tuple[str, dict]] = []
        # Preserve owner detection (fakekube.handler_owner) through the
        # wrapper so unwatch_owner() still finds this registration.
        owner = getattr(handler, "__self__", None)
        if owner is None:
            owner = getattr(getattr(handler, "func", None), "__self__", None)
        if owner is not None:
            self.__self__ = owner

    def __call__(self, event: str, obj: dict) -> None:
        with self._lock:
            if self._injector.watch_stalled(self._member):
                self._buffer.append((event, obj))
                return
            drained, self._buffer = self._buffer, []
        for ev, o in drained:
            self._handler(ev, o)
        self._handler(event, obj)

    def drain(self) -> None:
        """Deliver anything buffered (called opportunistically once the
        stall clears; the next live event also drains)."""
        with self._lock:
            if self._injector.watch_stalled(self._member):
                return
            drained, self._buffer = self._buffer, []
        for ev, o in drained:
            self._handler(ev, o)


class FaultyKube:
    """A fault-injecting proxy over any FakeKube-duck-typed client.

    CRUD/batch/list calls resolve the member's policy first: partition
    sleeps up to ``timeout`` (in slices, so a flap shorter than the
    timeout lets the request through late) then raises
    :class:`TransportError`; injected errors and drops raise
    immediately; latency sleeps then proceeds.  Watch registrations are
    wrapped in a :class:`_StallGate`."""

    def __init__(self, inner, name: str, injector: FaultInjector,
                 timeout: float = 1.0, clock=time.monotonic):
        self._inner = inner
        self.name = name
        self._injector = injector
        self._timeout = timeout
        self._clock = clock
        self._gates: dict[tuple[str, int], _StallGate] = {}
        self._gates_lock = threading.Lock()

    # -- the fault gate ---------------------------------------------------
    def _gate(self) -> None:
        act = self._injector.action(self.name)
        if act is None:
            return
        if act.partition:
            deadline = self._clock() + self._timeout
            while self._clock() < deadline:
                time.sleep(min(0.02, self._timeout))
                if not self._injector.partitioned(self.name):
                    return  # flap cleared mid-request: serve it late
            raise TransportError(f"{self.name}: partitioned (fault injected)")
        if act.drop:
            raise TransportError(f"{self.name}: connection dropped (fault injected)")
        if act.error:
            if act.latency_s:
                time.sleep(act.latency_s)
            raise TransportError(f"{self.name}: injected error")
        if act.latency_s:
            time.sleep(act.latency_s)

    # -- gated CRUD seam --------------------------------------------------
    def create(self, resource, obj, **kw):
        self._gate()
        return self._inner.create(resource, obj, **kw)

    def get(self, resource, key):
        self._gate()
        return self._inner.get(resource, key)

    def try_get(self, resource, key):
        self._gate()
        return self._inner.try_get(resource, key)

    def try_get_view(self, resource, key):
        self._gate()
        view = getattr(self._inner, "try_get_view", None)
        if view is not None:
            return view(resource, key)
        return self._inner.try_get(resource, key)

    def update(self, resource, obj, **kw):
        self._gate()
        return self._inner.update(resource, obj, **kw)

    def update_status(self, resource, obj, **kw):
        self._gate()
        return self._inner.update_status(resource, obj, **kw)

    def delete(self, resource, key):
        self._gate()
        return self._inner.delete(resource, key)

    def batch(self, operations):
        self._gate()
        return self._inner.batch(operations)

    def list(self, resource, *a, **kw):
        self._gate()
        return self._inner.list(resource, *a, **kw)

    def list_view(self, resource, *a, **kw):
        self._gate()
        return self._inner.list_view(resource, *a, **kw)

    def keys(self, resource):
        self._gate()
        return self._inner.keys(resource)

    def scan(self, resource, fn):
        self._gate()
        return self._inner.scan(resource, fn)

    @property
    def healthy(self) -> bool:
        try:
            self._gate()
        except TransportError:
            return False
        return bool(getattr(self._inner, "healthy", True))

    # -- watch (stall-gated) ----------------------------------------------
    def watch(self, resource, handler, replay: bool = True) -> None:
        gate = _StallGate(handler, self.name, self._injector)
        with self._gates_lock:
            self._gates[(resource, id(handler))] = gate
        self._inner.watch(resource, gate, replay=replay)

    def unwatch(self, resource, handler) -> None:
        with self._gates_lock:
            gate = self._gates.pop((resource, id(handler)), None)
        self._inner.unwatch(resource, gate if gate is not None else handler)

    def unwatch_owner(self, owner) -> None:
        self._inner.unwatch_owner(owner)

    def drain_stalled(self) -> None:
        """Flush every stall gate's buffer (tests call this after
        clearing a watch_stall so convergence doesn't wait for the next
        live event)."""
        with self._gates_lock:
            gates = list(self._gates.values())
        for gate in gates:
            gate.drain()

    # Everything else (dump/restore, current_rv, watch_all, ...) passes
    # through un-gated: those are host-side/diagnostic surfaces.
    def __getattr__(self, item):
        return getattr(self._inner, item)
