"""HTTP apiserver: Kubernetes-style REST + watch over a FakeKube store.

This is the server half of the real transport (SURVEY.md §2.2 "generated
clients / apiserver transport"): it serves a :class:`FakeKube` store —
which already implements the semantics the control plane depends on
(optimistic concurrency, finalizer-gated deletion, generation bumps,
status subresource) — over real sockets with the protocol shape of an
apiserver:

* ``GET/POST/PUT/DELETE`` on ``/api/...`` / ``/apis/...`` paths
  (:mod:`kubeadmiral_tpu.transport.paths`), JSON bodies, k8s-style
  ``Status`` error objects with ``reason`` Conflict/NotFound/AlreadyExists.
* ``GET ...?watch=true&resourceVersion=N`` — chunked-transfer watch
  stream of ``{"type": ..., "object": ...}`` lines resuming after N,
  backed by a bounded per-resource event log; a too-old N gets 410 Gone
  and the client must relist (exactly client-go's contract).
* ``PUT .../{name}/status`` — status subresource.
* ``GET /healthz`` — respects ``store.healthy`` so tests can fail probes.
* Optional bearer-token auth: an admin token plus any service-account
  token minted by the server (see ``mint_sa_tokens``), which is how the
  cluster-join handshake's credentials become real
  (reference: pkg/controllers/federatedcluster/clusterjoin.go:241-580).
"""

from __future__ import annotations

import bisect
import hashlib
import hmac
import json
import secrets as pysecrets
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from kubeadmiral_tpu.runtime import trace
from kubeadmiral_tpu.testing.fakekube import (
    ADDED,
    AlreadyExists,
    Conflict,
    FakeKube,
    NotFound,
    obj_key as fk_obj_key,
)
from kubeadmiral_tpu.transport.paths import parse_path

SERVICE_ACCOUNTS = "v1/serviceaccounts"
SECRETS = "v1/secrets"

# Watch streams send a heartbeat line when idle so dead peers are
# detected; clients ignore it (k8s uses BOOKMARK events similarly).
HEARTBEAT = b'{"type":"HEARTBEAT"}\n'


class _ResourceLog:
    """One resource's event history: parallel (seqs, lines) lists with
    front-eviction by compaction, so resume is a bisect + slice instead
    of an O(cap) scan per watcher wakeup.  ``lines`` entries are either
    rendered ``bytes`` or a pending ``(event, obj)`` tuple — see
    :meth:`_EventLog.since`."""

    __slots__ = ("seqs", "lines", "evicted")

    def __init__(self):
        self.seqs: list[int] = []
        self.lines: list = []
        self.evicted = False


class _EventLog:
    """Per-resource bounded event logs with resourceVersion resume.

    Serialization is LAZY: the write path (which runs as a store
    observer, under the store lock) appends ``(event, obj)`` tuples;
    a line is rendered to JSON bytes on first watcher read and memoized
    in place, so a resource nobody watches never pays ``json.dumps`` at
    all, N watchers of one resource pay it once, and the store lock
    never holds serialization work.  Safe because the COW store's
    committed nodes are immutable — the obj captured at append time is
    the exact state the event described."""

    def __init__(self, cap: int = 100_000):
        self.cap = cap
        self.cond = threading.Condition()
        self.logs: dict[str, _ResourceLog] = {}

    def append(self, resource: str, event: str, obj: dict, seq: int) -> None:
        with self.cond:
            log = self.logs.setdefault(resource, _ResourceLog())
            log.seqs.append(seq)
            log.lines.append((event, obj))
            if len(log.seqs) > 2 * self.cap:  # amortized O(1) eviction
                drop = len(log.seqs) - self.cap
                del log.seqs[:drop]
                del log.lines[:drop]
                log.evicted = True
            self.cond.notify_all()

    def append_many(self, items: list) -> None:
        """Append one committed store flush ``[(resource, event, obj,
        seq), ...]`` under ONE cond hold with ONE wakeup, instead of a
        lock/notify_all cycle per event (the store-side analogue of the
        write-coalescing that batches the writes themselves).  No
        per-op ``json.dumps`` here — rendering is deferred to first
        read (one serialization pass per coalesced batch, and only for
        watched resources)."""
        with self.cond:
            for resource, event, obj, seq in items:
                log = self.logs.setdefault(resource, _ResourceLog())
                log.seqs.append(seq)
                log.lines.append((event, obj))
                if len(log.seqs) > 2 * self.cap:
                    drop = len(log.seqs) - self.cap
                    del log.seqs[:drop]
                    del log.lines[:drop]
                    log.evicted = True
            self.cond.notify_all()

    def since(self, resource: str, rv: int) -> tuple[Optional[list[bytes]], int]:
        """(lines after rv, latest seq); lines is None when rv is too old
        (already evicted from the log) and the watcher must relist.
        Pending entries are rendered here, once, and memoized in place
        for every later reader."""
        with self.cond:
            log = self.logs.get(resource)
            if log is None or not log.seqs:
                return [], rv
            latest = log.seqs[-1]
            if log.evicted and rv < log.seqs[0] - 1:
                return None, latest  # history truncated: 410 Gone
            idx = bisect.bisect_right(log.seqs, rv)
            out = []
            lines = log.lines
            for i in range(idx, len(lines)):
                line = lines[i]
                if type(line) is not bytes:
                    line = (
                        json.dumps({"type": line[0], "object": line[1]}).encode()
                        + b"\n"
                    )
                    lines[i] = line
                out.append(line)
            return out, latest


class KubeApiServer:
    """One apiserver process-equivalent serving ``store`` on localhost."""

    def __init__(
        self,
        store: FakeKube,
        host: str = "127.0.0.1",
        port: int = 0,
        admin_token: Optional[str] = None,
        mint_sa_tokens: bool = False,
        event_log_cap: int = 100_000,
        sa_signing_key: Optional[str] = None,
        fault_injector=None,
        fault_name: Optional[str] = None,
        metrics=None,
    ):
        self.store = store
        self.admin_token = admin_token
        # Optional per-server registry: request counts by verb, served
        # at GET /metrics (with the rest of the /debug surface) so the
        # fleet scraper can aggregate member apiservers too.
        self.metrics = metrics
        # Fault-injection seam (transport/faults.py): when given, every
        # request and watch stream resolves this member's FaultPolicy
        # first — added latency, injected 500s, severed connections,
        # connect-timeout partitions, silent watch streams.
        self.fault_injector = fault_injector
        self.fault_name = fault_name or store.name
        self._tokens: set[str] = set()
        # Minted tokens are self-authenticating: HMAC(signing key,
        # secret key + SA name) — the analogue of the real apiserver's
        # JWT signature.  The type string, annotation and data.token of
        # a secret are all client-settable (sync can propagate workload
        # Secrets claiming anything), but a valid HMAC cannot be forged
        # without the signing key; and because trust is recomputed from
        # the value itself, a server restarted over a resumed store
        # (given the same sa_signing_key, like the real apiserver's
        # --service-account-key-file) re-grants exactly the tokens it
        # minted and nothing an attacker planted meanwhile.
        self._sa_key = (sa_signing_key or pysecrets.token_hex(16)).encode()
        # secret key -> token currently granted, so rotation/annotation
        # changes revoke the stale value instead of leaking it forever.
        self._granted: dict[str, str] = {}
        self._log = _EventLog(event_log_cap)
        self._closed = threading.Event()
        self._mint_sa_tokens = mint_sa_tokens

        for secret in store.list_view(SECRETS):
            self._regrant(secret)
        store.watch_all(self._on_store_event, batch=self._on_store_events)

        class _TrackingServer(ThreadingHTTPServer):
            """Tracks live per-connection sockets so close() can sever
            kept-alive connections — shutdown() alone only stops NEW
            accepts, and a crashed/"unreachable" member must look dead
            to clients holding pooled connections too."""

            # http.server's default backlog of 5 resets fresh
            # connections under a mutation storm (every write rides a
            # new connection by design; an overflowed accept queue +
            # syncookies RSTs the first payload).  A control plane's
            # apiserver must absorb bursts.
            request_queue_size = 128

            def __init__(self_srv, *a, **kw):
                self_srv.live_sockets = set()
                self_srv.live_lock = threading.Lock()
                super().__init__(*a, **kw)

            def process_request(self_srv, request, client_address):
                with self_srv.live_lock:
                    self_srv.live_sockets.add(request)
                super().process_request(request, client_address)

            def close_request(self_srv, request):
                with self_srv.live_lock:
                    self_srv.live_sockets.discard(request)
                super().close_request(request)

        server = _TrackingServer((host, port), _Handler)
        server.daemon_threads = True
        server.api = self  # type: ignore[attr-defined]
        self._server = server
        self.host = host
        self.port = server.server_address[1]
        self.url = f"http://{self.host}:{self.port}"
        self._thread = threading.Thread(
            target=server.serve_forever, name=f"apiserver-{store.name}",
            daemon=True,
        )
        self._thread.start()

    def _mint_value(self, secret_key: str, sa_name: str) -> str:
        """The (deterministic, unforgeable) token for one SA's secret."""
        msg = f"{secret_key}\x00{sa_name}".encode()
        return hmac.new(self._sa_key, msg, hashlib.sha256).hexdigest()

    def _trusted_token(self, secret: dict) -> Optional[str]:
        """The token this secret legitimately carries, or None.

        Mirrors the real token controller's contract: the secret is
        token-typed, its kubernetes.io/service-account.name annotation
        references a ServiceAccount that exists, and data.token
        verifies against the signing key for exactly this (secret, SA)
        pair.  A federated workload Secret propagated by sync can fake
        the type, the annotation and the value — but not the HMAC."""
        if secret.get("type") != "kubernetes.io/service-account-token":
            return None
        meta = secret.get("metadata") or {}
        sa_name = (meta.get("annotations") or {}).get(
            "kubernetes.io/service-account.name"
        )
        if not sa_name:
            return None
        namespace = meta.get("namespace", "")
        sa_key = f"{namespace}/{sa_name}" if namespace else sa_name
        if self.store.try_get(SERVICE_ACCOUNTS, sa_key) is None:
            return None
        token = (secret.get("data") or {}).get("token")
        # data.token is client-settable: a non-str / non-ASCII value must
        # read as "untrusted", not raise out of the store event feed (and
        # permanently crash server restarts over the resumed store).
        if not isinstance(token, str) or not token:
            return None
        expected = self._mint_value(fk_obj_key(secret), sa_name)
        try:
            if not hmac.compare_digest(token, expected):
                return None
        except TypeError:
            return None
        return token

    def _regrant(self, secret: dict, deleted: bool = False) -> None:
        """Recompute one secret's grant, revoking any stale value: the
        single transition point for grant state, so rotation, annotation
        changes, SA appearance/disappearance and deletion all converge
        (no path can leak a previously granted token)."""
        key = fk_obj_key(secret)
        new = None if deleted else self._trusted_token(secret)
        old = self._granted.get(key)
        if old is not None and old != new:
            self._tokens.discard(old)
        if new is None:
            self._granted.pop(key, None)
        else:
            self._granted[key] = new
            self._tokens.add(new)

    def _secrets_referencing(self, sa: dict) -> list[dict]:
        """Token-typed secrets annotated with this SA's name."""
        meta = sa.get("metadata", {})
        out = []
        for secret in self.store.list_view(SECRETS):
            if secret.get("type") != "kubernetes.io/service-account-token":
                continue
            smeta = secret.get("metadata") or {}
            if smeta.get("namespace", "") != meta.get("namespace", ""):
                continue
            if (smeta.get("annotations") or {}).get(
                "kubernetes.io/service-account.name"
            ) == meta.get("name"):
                out.append(secret)
        return out

    # -- store event feed (runs under the store lock) --------------------
    def _on_store_events(self, flush: list) -> None:
        """Coalesced feed of one committed store flush.  Event-log lines
        land FIRST, all of them, under one cond hold: the SA/secret side
        effects below write back into the store, and those nested events
        must append strictly AFTER this flush's lines or per-resource
        log seqs stop being sorted and watch-resume bisect breaks."""
        self._log.append_many(flush)
        for resource, event, obj, _ in flush:
            if resource in (SECRETS, SERVICE_ACCOUNTS):
                self._on_credential_event(resource, event, obj)

    def _on_store_event(self, resource: str, event: str, obj: dict, seq: int) -> None:
        self._log.append(resource, event, obj, seq)
        if resource in (SECRETS, SERVICE_ACCOUNTS):
            self._on_credential_event(resource, event, obj)

    def _on_credential_event(self, resource: str, event: str, obj: dict) -> None:
        if resource == SECRETS:
            self._regrant(obj, deleted=event == "DELETED")
        elif resource == SERVICE_ACCOUNTS:
            if event == ADDED and self._mint_sa_tokens:
                self._mint_token(obj)
            if event == "DELETED" and self._mint_sa_tokens:
                # Token-controller garbage collection: a deleted SA's
                # token secrets go with it (k8s's legacy token cleanup).
                # Without this, unjoin cleanup could never remove the
                # credential it is itself authenticating with — deleting
                # the SA first revokes the token and every subsequent
                # member call 401s.  Matched by type + SA annotation
                # (never by name convention: a sync-propagated workload
                # secret named "<sa>-token" must survive).
                for secret in self._secrets_referencing(obj):
                    try:
                        self.store.delete(SECRETS, fk_obj_key(secret))
                    except NotFound:
                        pass
            # Re-evaluate grants of secrets referencing this SA: its
            # appearance enables boot-trusted secrets that landed first;
            # its deletion revokes their tokens even while a secret
            # lingers (crash between SA handling and secret GC, or a
            # non-minting server — no live credential either way).
            for secret in self._secrets_referencing(obj):
                self._regrant(secret)

    def _mint_token(self, sa: dict) -> None:
        """Create a token Secret for a new ServiceAccount — the member-
        side token controller the join handshake waits on (the reference
        reads the SA's token secret, clusterjoin.go:449-529)."""
        meta = sa["metadata"]
        name = f"{meta['name']}-token"
        namespace = meta.get("namespace", "")
        key = f"{namespace}/{name}" if namespace else name
        try:
            self.store.create(
                SECRETS,
                {
                    "apiVersion": "v1",
                    "kind": "Secret",
                    "type": "kubernetes.io/service-account-token",
                    "metadata": {
                        "name": name,
                        "namespace": namespace,
                        "annotations": {
                            "kubernetes.io/service-account.name": meta["name"]
                        },
                    },
                    "data": {"token": self._mint_value(key, meta["name"])},
                },
            )
        except AlreadyExists:
            # A lingering secret from a previous SA incarnation carries
            # the same deterministic value; the caller's regrant loop
            # re-grants it now that the SA exists again.
            pass

    # -- auth ------------------------------------------------------------
    def authorized(self, header: Optional[str]) -> bool:
        if self.admin_token is None:
            return True
        if not header or not header.startswith("Bearer "):
            return False
        token = header[len("Bearer "):]
        return token == self.admin_token or token in self._tokens

    def close(self) -> None:
        self._closed.set()
        with self._log.cond:
            self._log.cond.notify_all()  # release idle watch streams
        self._server.shutdown()
        self._server.server_close()
        # Sever kept-alive connections: a closed server must be
        # unreachable, not half-alive through pooled client sockets.
        with self._server.live_lock:
            sockets = list(self._server.live_sockets)
            self._server.live_sockets.clear()
        for sock in sockets:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # Nagle + delayed ACK turns every response on a kept-alive
    # connection into a ~40 ms stall (the response spans multiple small
    # writes); an apiserver's latency budget is microseconds.
    disable_nagle_algorithm = True

    @property
    def api(self) -> KubeApiServer:
        return self.server.api  # type: ignore[attr-defined]

    def log_message(self, *args):  # silence request logging
        pass

    # -- plumbing --------------------------------------------------------
    def _send_json(self, code: int, payload: dict, extra: Optional[dict] = None) -> None:
        body = json.dumps(payload).encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            # The client gave up mid-response (e.g. its timeout fired
            # while a fault held this request): a vanished peer is a
            # closed connection, not a handler crash to traceback.
            self.close_connection = True

    def _send_status(self, code: int, reason: str, message: str) -> None:
        self._send_json(
            code,
            {
                "kind": "Status",
                "apiVersion": "v1",
                "status": "Failure",
                "reason": reason,
                "message": message,
                "code": code,
            },
        )

    def _read_body(self) -> Optional[dict]:
        length = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(length) if length else b""
        if not data:
            return {}
        try:
            return json.loads(data)
        except ValueError:
            return None

    def _route(self):
        split = urlsplit(self.path)
        if split.path == "/healthz":
            return None
        parsed = parse_path(split.path)
        query = {k: v[-1] for k, v in parse_qs(split.query).items()}
        return parsed, query

    def _object_key(self, parsed) -> str:
        return f"{parsed.namespace}/{parsed.name}" if parsed.namespace else parsed.name

    # -- fault injection (transport/faults.py seam) ----------------------
    def _fault_gate(self) -> bool:
        """Resolve this member's fault policy for one request; True when
        the request was consumed by the fault (severed or 500'd)."""
        inj = self.api.fault_injector
        if inj is None:
            return False
        act = inj.action(self.api.fault_name)
        if act is None:
            return False
        if act.latency_s:
            time.sleep(act.latency_s)
        if act.partition:
            # Connect-timeout partition: hold the request unanswered —
            # the client's own socket timeout fires first — until the
            # fault clears or the hang cap elapses, then sever.
            deadline = time.monotonic() + inj.partition_hang_s
            while time.monotonic() < deadline and not self.api._closed.is_set():
                if not inj.partitioned(self.api.fault_name):
                    return False  # flap cleared mid-request: serve it late
                time.sleep(0.05)
            self._sever()
            return True
        if act.drop:
            self._sever()
            return True
        if act.error:
            self._send_status(500, "InternalError", "injected fault")
            return True
        return False

    def _sever(self) -> None:
        """Close the connection without a response: the client sees EOF
        / connection reset, never an HTTP status."""
        self.close_connection = True
        try:
            self.connection.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def _watch_stalled(self) -> bool:
        inj = self.api.fault_injector
        if inj is None:
            return False
        return inj.watch_stalled(self.api.fault_name)

    # -- observability ---------------------------------------------------
    def _count(self, verb: str) -> None:
        m = self.api.metrics
        if m is not None:
            m.counter("apiserver_requests_total", verb=verb)

    def _server_span(self, name: str, **args):
        """A server-side span in THIS process's ring, adopting the
        caller's traceparent header when present — the member half of
        cross-process trace propagation."""
        return trace.get_default().server_span(
            name, self.headers.get("traceparent"), **args
        )

    # -- verbs -----------------------------------------------------------
    def do_GET(self):
        if self._fault_gate():
            return
        split = urlsplit(self.path)
        if split.path == "/healthz":
            if self.api.store.healthy:
                self._send_json(200, {"status": "ok"})
            else:
                self._send_status(500, "InternalError", "unhealthy")
            return
        if not self._check_auth():
            return
        # The /debug surface (and /metrics when a registry was given):
        # member apiservers expose the same observability routes as the
        # manager, which is what the fleet scraper aggregates.  Mounted
        # after auth, before parse_path (which would 404 them).
        if split.path == "/metrics" or split.path == "/debug" or (
            split.path.startswith("/debug/")
        ):
            from kubeadmiral_tpu.runtime import profiling

            if not profiling.respond_debug(
                self, split.path, split.query, metrics=self.api.metrics
            ):
                self.send_error(404)
            return
        try:
            parsed, query = self._route()
        except ValueError as e:
            self._send_status(404, "NotFound", str(e))
            return
        try:
            if parsed.name is None:
                if query.get("watch") in ("true", "1"):
                    self._count("watch")
                    self._serve_watch(parsed.resource, int(query.get("resourceVersion", 0)))
                else:
                    self._count("list")
                    self._serve_list(parsed, query)
            else:
                self._count("get")
                obj = self.api.store.get(parsed.resource, self._object_key(parsed))
                self._send_json(200, obj)
        except NotFound as e:
            self._send_status(404, "NotFound", str(e))

    def _serve_batch(self, body: dict) -> None:
        """POST /batch — one request, many operations (the bulk-write
        protocol the per-member sync fan-out amortizes its round trips
        through; extends the apiserver the way the webhook "-batch"
        endpoints extended the reference's per-pair calls).

        Body: {"operations": [{"verb": create|update|update_status|
        delete|get, "resource": ..., "object": ...|"key": ...}, ...]}.
        Response: {"results": [{"code": ..., "object"|"status": ...}]}
        — one entry per operation, order preserved; each operation
        succeeds or fails independently (per-object conflict retry stays
        with the caller)."""
        # The store's bulk verb does the work — one columnar lock pass,
        # one coalesced watch flush (KT_STORE_COALESCE) — and op objects
        # are adopted by reference, which is safe here because they are
        # this request's fresh JSON parse.  Result objects are store
        # views: serialized into the response immediately, never
        # retained or mutated.  This handler only reshapes the store's
        # plain results into the wire's Status envelopes.
        results = []
        for entry in self.api.store.batch(body.get("operations", ())):
            if "object" in entry:
                results.append(entry)
            elif entry["code"] == 200:
                results.append({"code": 200, "status": {"kind": "Status", "status": "Success"}})
            else:
                st = entry.get("status", {})
                results.append(
                    self._status_entry(
                        entry["code"],
                        st.get("reason", "BadRequest"),
                        st.get("message", ""),
                    )
                )
        self._send_json(200, {"results": results})

    def _serve_faultz(self, body: dict) -> None:
        """POST /faultz — the fault-control endpoint (transport/faults.py
        seam over the wire): lets a parent process drive fault injection
        on a member apiserver running in ANOTHER process (the kwok-lite
        subprocess farm), so `farm.set_fault` works for every member
        shape.  Routed BEFORE the fault gate — a partitioned member must
        still accept the request that clears its partition.

        Body: {"policy": {FaultPolicy fields...} | null, "member": ...?}
        — null clears; "member" defaults to this server's fault name."""
        import dataclasses

        from kubeadmiral_tpu.transport.faults import FaultInjector, FaultPolicy

        api = self.api
        if api.fault_injector is None:
            api.fault_injector = FaultInjector()
        member = body.get("member") or api.fault_name
        policy = body.get("policy")
        if policy is None:
            api.fault_injector.clear(member)
            self._send_json(200, {"status": "cleared", "member": member})
            return
        names = {f.name for f in dataclasses.fields(FaultPolicy)}
        unknown = set(policy) - names
        if unknown:
            self._send_status(
                400, "BadRequest", f"unknown FaultPolicy fields: {sorted(unknown)}"
            )
            return
        try:
            parsed = FaultPolicy(**policy)
        except (TypeError, ValueError) as e:
            self._send_status(400, "BadRequest", f"invalid FaultPolicy: {e}")
            return
        api.fault_injector.set_fault(member, parsed)
        self._send_json(200, {"status": "ok", "member": member})

    @staticmethod
    def _status_entry(code: int, reason: str, message: str) -> dict:
        return {
            "code": code,
            "status": {
                "kind": "Status",
                "apiVersion": "v1",
                "status": "Failure",
                "reason": reason,
                "message": message,
                "code": code,
            },
        }

    def do_POST(self):
        # Drain the body before any error response: leftover body bytes
        # would be parsed as the next request line on this keep-alive
        # connection, corrupting the client's pooled connection.
        obj = self._read_body()
        # Fault control is exempt from the fault gate by construction:
        # clearing a partition must not hang on the partition itself.
        if urlsplit(self.path).path == "/faultz":
            if not self._check_auth():
                return
            if obj is None:
                self._send_status(400, "BadRequest", "invalid JSON body")
                return
            self._serve_faultz(obj)
            return
        if self._fault_gate():
            return
        if not self._check_auth():
            return
        if obj is None:
            self._send_status(400, "BadRequest", "invalid JSON body")
            return
        if urlsplit(self.path).path == "/batch":
            self._count("batch")
            with self._server_span(
                "apiserver.batch", ops=len(obj.get("operations", ()))
            ):
                self._serve_batch(obj)
            return
        try:
            parsed, _ = self._route()
        except ValueError as e:
            self._send_status(404, "NotFound", str(e))
            return
        if parsed.namespace:
            obj.setdefault("metadata", {}).setdefault("namespace", parsed.namespace)
        self._count("create")
        try:
            with self._server_span("apiserver.create", resource=parsed.resource):
                created = self.api.store.create(parsed.resource, obj)
            self._send_json(201, created)
        except AlreadyExists as e:
            self._send_status(409, "AlreadyExists", str(e))

    def do_PUT(self):
        obj = self._read_body()  # drain before any error response
        if self._fault_gate():
            return
        if not self._check_auth():
            return
        if obj is None:
            self._send_status(400, "BadRequest", "invalid JSON body")
            return
        try:
            parsed, _ = self._route()
        except ValueError as e:
            self._send_status(404, "NotFound", str(e))
            return
        store = self.api.store
        try:
            if parsed.subresource == "status":
                self._count("update_status")
                with self._server_span(
                    "apiserver.update_status", resource=parsed.resource
                ):
                    updated = store.update_status(parsed.resource, obj)
            elif parsed.subresource is None:
                self._count("update")
                with self._server_span(
                    "apiserver.update", resource=parsed.resource
                ):
                    updated = store.update(parsed.resource, obj)
            else:
                self._send_status(404, "NotFound", f"subresource {parsed.subresource}")
                return
            self._send_json(200, updated)
        except Conflict as e:
            self._send_status(409, "Conflict", str(e))
        except NotFound as e:
            self._send_status(404, "NotFound", str(e))

    def do_DELETE(self):
        if self._fault_gate():
            return
        if not self._check_auth():
            return
        try:
            parsed, _ = self._route()
        except ValueError as e:
            self._send_status(404, "NotFound", str(e))
            return
        self._count("delete")
        try:
            with self._server_span("apiserver.delete", resource=parsed.resource):
                self.api.store.delete(parsed.resource, self._object_key(parsed))
            self._send_json(200, {"kind": "Status", "status": "Success"})
        except NotFound as e:
            self._send_status(404, "NotFound", str(e))

    def _check_auth(self) -> bool:
        if self.api.authorized(self.headers.get("Authorization")):
            return True
        self._send_status(401, "Unauthorized", "invalid bearer token")
        return False

    # -- list + watch ----------------------------------------------------
    def _serve_list(self, parsed, query) -> None:
        selector = None
        if "labelSelector" in query:
            selector = dict(
                part.split("=", 1)
                for part in query["labelSelector"].split(",")
                if "=" in part
            )
        items, rv = self.api.store.list_with_rv(
            parsed.resource, parsed.namespace or None, selector
        )
        self._send_json(
            200,
            {"kind": "List", "items": items, "metadata": {"resourceVersion": str(rv)}},
            extra={"X-Resource-Version": str(rv)},
        )

    def _serve_watch(self, resource: str, since_rv: int) -> None:
        log = self.api._log
        lines, cursor = log.since(resource, since_rv)
        if lines is None:
            self._send_status(410, "Expired", f"resourceVersion {since_rv} is too old")
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            while not self.api._closed.is_set():
                heartbeat = False
                # Watch-stall fault: hold delivery (lines AND heartbeats)
                # until the stall clears — the client sees a silent,
                # still-open stream, times out as a dead peer and
                # reconnects with backoff; events deliver late, never
                # lost (the log keeps them, resume rv catches up).
                while self._watch_stalled() and not self.api._closed.is_set():
                    time.sleep(0.05)
                for line in lines:
                    self._write_chunk(line)
                # cursor from since() is the latest logged seq at query
                # time, i.e. the resume point after the lines just sent.
                with log.cond:
                    while True:
                        if self.api._closed.is_set():
                            return
                        lines, cursor = log.since(resource, cursor)
                        if lines is None:
                            return  # truncated under us: client relists
                        if lines:
                            break
                        if not log.cond.wait(timeout=15.0):
                            heartbeat = True
                            break
                if heartbeat and not self._watch_stalled():
                    self._write_chunk(HEARTBEAT)
        except (BrokenPipeError, ConnectionResetError):
            return
        finally:
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass

    def _write_chunk(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()
