"""HTTP client implementing the FakeKube seam over real sockets.

:class:`HttpKube` is interface-compatible with
:class:`kubeadmiral_tpu.testing.fakekube.FakeKube` — the same CRUD +
watch + view-read surface every controller is written against — so the
whole control plane runs over a real apiserver unmodified.

Watches are LIST+WATCH: one streaming connection per watched resource
(shared by all handlers via a mux), resuming from the list's
resourceVersion, relisting on 410 Gone, reconnecting with backoff on
connection loss.  This is the client-go reflector loop
(reference: pkg/controllers/util/federatedinformer.go:151-250).

:class:`FederatedClientFactory` builds per-member clients from
FederatedCluster ``spec.apiEndpoint`` + the join secret's token
(reference: pkg/controllers/util/federatedclient/client.go:48-386), and
:class:`HttpFleet` exposes the ClusterFleet interface over it.
"""

from __future__ import annotations

import functools
import http.client
import json
import logging
import random
import socket as pysocket
import threading
import time
from typing import Callable, Optional
from urllib.parse import urlsplit

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.runtime import slo as _slo
from kubeadmiral_tpu.runtime import trace as _trace
from kubeadmiral_tpu.testing.fakekube import (
    ADDED,
    DELETED,
    AlreadyExists,
    Conflict,
    Handler,
    NotFound,
    handler_owner,
    obj_key as _obj_key,
)
from kubeadmiral_tpu.transport.paths import key_to_path, resource_to_path

# Mirrors clusterctl.FED_SYSTEM_NAMESPACE (kept literal to avoid a
# transport -> federation.clusterctl import cycle).
FED_SYSTEM_NAMESPACE = "kube-admiral-system"
SECRETS = "v1/secrets"


class TransportError(Exception):
    """Connection-level or unexpected-HTTP-status failure."""


class Gone(Exception):
    """410: watch resourceVersion expired — relist."""


def watch_backoff(
    attempt: int, base: float = 0.1, cap: float = 5.0, rng=None
) -> float:
    """Reconnect delay for the reflector loop: exponential with jitter,
    capped.  Uniform in [span/2, span] of ``min(cap, base * 2^attempt)``
    — the floor stops a partitioned fleet's watchers from retrying in
    lockstep at zero, the cap bounds recovery latency once the member
    returns, and the jitter de-phases a reconnect storm (hundreds of
    streams dropped by one member restart must not re-dial as one
    thundering herd)."""
    span = min(cap, base * (2 ** min(max(attempt, 0), 16)))
    r = (rng or random).random()
    return span * (0.5 + 0.5 * r)


class _NoDelayConnection(http.client.HTTPConnection):
    """HTTPConnection with TCP_NODELAY: requests are small multi-write
    payloads, and Nagle + the peer's delayed ACK add ~40 ms per call on
    kept-alive connections."""

    def connect(self):
        super().connect()
        try:
            self.sock.setsockopt(pysocket.IPPROTO_TCP, pysocket.TCP_NODELAY, 1)
        except OSError:
            pass


class HttpKube:
    """One apiserver client; duck-types FakeKube."""

    # Watch streams of this client mint SLO provenance tokens themselves
    # (_ResourceWatch._dispatch): informers on top must not double-mint.
    _slo_ingress = True

    # Point reads are HTTP round trips here: callers choosing between
    # per-key view reads and one LIST must take the LIST.
    local_views = False

    def __init__(
        self,
        base_url: str,
        token: Optional[str] = None,
        name: str = "",
        timeout: float = 10.0,
        watch_timeout: float = 30.0,
    ):
        split = urlsplit(base_url)
        self.name = name or split.netloc
        self._netloc = split.netloc
        self._token = token
        self._timeout = timeout
        # Watch-stream read timeout: a stream silent past this (no
        # events, no heartbeats — the server sends one every ~15 s when
        # idle) is presumed dead and reconnects.
        self._watch_timeout = watch_timeout
        self._local = threading.local()
        self._mux: dict[str, _ResourceWatch] = {}
        self._mux_lock = threading.Lock()
        self._closed = threading.Event()

    # -- HTTP plumbing ---------------------------------------------------
    def _headers(self) -> dict[str, str]:
        headers = {"Content-Type": "application/json"}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        # Cross-process trace propagation: any request issued under an
        # open span carries it as a W3C traceparent header, so the
        # server side (transport/apiserver.py) can record a true child
        # span in ITS ring — one scheduling decision's sync -> member
        # write is a single parented trace across processes.
        traceparent = _trace.current_traceparent()
        if traceparent is not None:
            headers["traceparent"] = traceparent
        return headers

    def _conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = _NoDelayConnection(self._netloc, timeout=self._timeout)
            self._local.conn = conn
        return conn

    def _request(
        self, method: str, path: str, body: Optional[dict] = None
    ) -> tuple[int, dict, dict]:
        payload = json.dumps(body).encode() if body is not None else None
        if method != "GET":
            # Mutations ride a fresh connection: a stale kept-alive socket
            # fails ambiguously (the server may already have applied the
            # request), and blindly re-sending a POST/PUT/DELETE would
            # surface spurious AlreadyExists/Conflict/NotFound to callers
            # that treat those as genuine races.  A localhost handshake
            # costs microseconds; ambiguity costs correctness.
            conn = _NoDelayConnection(self._netloc, timeout=self._timeout)
            try:
                conn.request(method, path, body=payload, headers=self._headers())
                resp = conn.getresponse()
                data = resp.read()
                headers = dict(resp.getheaders())
                return resp.status, (json.loads(data) if data else {}), headers
            except (OSError, http.client.HTTPException) as e:
                raise TransportError(f"{method} {self._netloc}{path}: {e}")
            finally:
                conn.close()
        # Idempotent GETs reuse the pooled connection, retrying once on a
        # stale keep-alive.
        last_err: Optional[Exception] = None
        for _ in range(2):
            conn = self._conn()
            try:
                conn.request(method, path, body=payload, headers=self._headers())
                resp = conn.getresponse()
                data = resp.read()
                headers = dict(resp.getheaders())
                return resp.status, (json.loads(data) if data else {}), headers
            except (OSError, http.client.HTTPException) as e:
                last_err = e
                conn.close()
                self._local.conn = None
        raise TransportError(f"{method} {self._netloc}{path}: {last_err}")

    def _raise_for(self, status: int, payload: dict, context: str):
        reason = payload.get("reason", "")
        message = payload.get("message", context)
        if status == 404:
            raise NotFound(message)
        if status == 409 and reason == "AlreadyExists":
            raise AlreadyExists(message)
        if status == 409:
            raise Conflict(message)
        if status == 410:
            raise Gone(message)
        raise TransportError(f"{context}: HTTP {status} {reason} {message}")

    # -- health ----------------------------------------------------------
    @property
    def healthy(self) -> bool:
        try:
            status, _, _ = self._request("GET", "/healthz")
            return status == 200
        except TransportError:
            return False

    # -- CRUD (the FakeKube seam) ----------------------------------------
    # ``_copy_result`` mirrors FakeKube's signature so transport-agnostic
    # callers can opt out of result copies on the in-process store; HTTP
    # results are fresh JSON parses, so there is never a copy to skip.
    def create(self, resource: str, obj: dict, _copy_result: bool = True) -> dict:
        meta = obj.get("metadata", {})
        path = resource_to_path(resource, meta.get("namespace") or None)
        status, payload, _ = self._request("POST", path, obj)
        if status != 201:
            self._raise_for(status, payload, f"create {resource}")
        return payload

    def get(self, resource: str, key: str) -> dict:
        status, payload, _ = self._request("GET", key_to_path(resource, key))
        if status != 200:
            self._raise_for(status, payload, f"get {resource} {key}")
        return payload

    def try_get(self, resource: str, key: str) -> Optional[dict]:
        try:
            return self.get(resource, key)
        except NotFound:
            return None

    # View reads have no cache to alias into over HTTP; they are the
    # same round-trip as their copying counterparts.
    try_get_view = try_get

    def update(self, resource: str, obj: dict, _copy_result: bool = True) -> dict:
        key = _obj_key(obj)
        status, payload, _ = self._request("PUT", key_to_path(resource, key), obj)
        if status != 200:
            self._raise_for(status, payload, f"update {resource} {key}")
        return payload

    def update_status(
        self, resource: str, obj: dict, _copy_result: bool = True
    ) -> dict:
        key = _obj_key(obj)
        path = key_to_path(resource, key, subresource="status")
        status, payload, _ = self._request("PUT", path, obj)
        if status != 200:
            self._raise_for(status, payload, f"update_status {resource} {key}")
        return payload

    def delete(self, resource: str, key: str) -> None:
        status, payload, _ = self._request("DELETE", key_to_path(resource, key))
        if status != 200:
            self._raise_for(status, payload, f"delete {resource} {key}")

    def batch(self, operations: list[dict]) -> list[dict]:
        """POST /batch: many operations, ONE round trip (the bulk-write
        protocol; see transport/apiserver.py _serve_batch).  Returns one
        result entry per operation ({"code", "object"|"status"}), order
        preserved; per-operation failures stay in the results (the
        caller owns conflict retry), only transport-level failures
        raise."""
        status, payload, _ = self._request("POST", "/batch", {"operations": operations})
        if status != 200:
            self._raise_for(status, payload, "batch")
        return payload.get("results", [])

    def list(
        self,
        resource: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict[str, str]] = None,
    ) -> list[dict]:
        items, _ = self._list_rv(resource, namespace, label_selector)
        return items

    list_view = list

    def _list_rv(
        self,
        resource: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict[str, str]] = None,
    ) -> tuple[list[dict], int]:
        path = resource_to_path(resource, namespace or None)
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in sorted(label_selector.items()))
            path += f"?labelSelector={sel}"
        status, payload, headers = self._request("GET", path)
        if status != 200:
            self._raise_for(status, payload, f"list {resource}")
        rv = int(headers.get("X-Resource-Version", 0))
        return payload.get("items", []), rv

    def keys(self, resource: str) -> list[str]:
        return [_obj_key(obj) for obj in self.list(resource)]

    def scan(self, resource: str, fn: Callable[[dict], None]) -> None:
        for obj in self.list(resource):
            fn(obj)

    # -- watch (reflector mux) -------------------------------------------
    def watch(self, resource: str, handler: Handler, replay: bool = True) -> None:
        with self._mux_lock:
            mux = self._mux.get(resource)
            if mux is None:
                mux = _ResourceWatch(self, resource)
                self._mux[resource] = mux
        mux.add(handler, replay)

    def unwatch(self, resource: str, handler: Handler) -> None:
        mux = self._mux.get(resource)
        if mux is not None:
            mux.remove(handler)

    def unwatch_owner(self, owner: object) -> None:
        for mux in list(self._mux.values()):
            mux.remove_owner(owner)

    def close(self) -> None:
        self._closed.set()
        for mux in list(self._mux.values()):
            mux.stop()
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()


class _ResourceWatch:
    """One streaming watch per resource, fanned out to handlers."""

    def __init__(self, kube: HttpKube, resource: str):
        self.kube = kube
        self.resource = resource
        self._lock = threading.Lock()
        self._handlers: list[Handler] = []
        self._known: dict[str, dict] = {}  # stream-thread only
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Reconnect delays actually slept (bounded) — the observable
        # backoff schedule tests assert against.
        self.reconnect_delays: list[float] = []

    def add(self, handler: Handler, replay: bool) -> None:
        # Register BEFORE the replay list: an object created between the
        # list response and registration would otherwise be dispatched
        # only to the pre-existing handlers and this one would never see
        # it.  The cost is possible duplicates (stream event + replay
        # ADDED), which level-triggered controllers dedupe by key.
        with self._lock:
            self._handlers.append(handler)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run,
                    name=f"watch-{self.kube.name}-{self.resource}",
                    daemon=True,
                )
                self._thread.start()
        if replay:
            pred = getattr(handler, "kt_predicate", None)
            for obj in self.kube.list(self.resource):
                if pred is not None and not pred(ADDED, obj):
                    continue
                handler(ADDED, obj)

    def remove(self, handler: Handler) -> None:
        with self._lock:
            if handler in self._handlers:
                self._handlers.remove(handler)

    def remove_owner(self, owner: object) -> None:
        with self._lock:
            self._handlers[:] = [
                h for h in self._handlers if handler_owner(h) is not owner
            ]

    def stop(self) -> None:
        self._stop.set()

    def _dispatch(self, event: str, obj: dict) -> None:
        # Track known keys so a relist can synthesize DELETED events for
        # objects that vanished during a watch gap (client-go's reflector
        # emits DeletedFinalStateUnknown the same way).
        key = _obj_key(obj)
        if event == DELETED:
            self._known.pop(key, None)
        else:
            meta = obj.get("metadata", {})
            self._known[key] = {
                "name": meta.get("name"),
                "namespace": meta.get("namespace", ""),
            }
        # SLO provenance: the HTTP watch stream is where an event enters
        # this control plane — mint the birth timestamp before handler
        # fan-out (once per event; untracked resources early-out).
        _slo.ingest(self.kube, self.resource, event, obj)
        with self._lock:
            handlers = list(self._handlers)
        # One metadata_change_sig memo for the whole fan-out (the
        # in-process store's _deliver_flush_locked does the same): four
        # controllers watching the fed resource hash the trigger fields
        # once per event, not once per handler.
        with C.sig_memo_scope():
            self._dispatch_handlers(handlers, event, obj, key)

    def _dispatch_handlers(
        self, handlers: list, event: str, obj: dict, key: str
    ) -> None:
        for handler in handlers:
            # Isolate handler failures from the reflector loop (client-go
            # informers do the same): one controller's bad handler must
            # not kill watch delivery for every other handler of this
            # resource, and an unhandled exception here would silently
            # end the reflector thread.
            try:
                # Shard-intake predicate (fakekube._Watch parity): a
                # replica drops non-owned keys here, before the handler
                # costs an enqueue.
                pred = getattr(handler, "kt_predicate", None)
                if pred is not None and not pred(event, obj):
                    continue
                handler(event, obj)
            except Exception:
                logging.getLogger("kubeadmiral.transport").exception(
                    "watch handler failed for %s %s on %s",
                    event,
                    key,
                    self.resource,
                )

    # -- the reflector loop ---------------------------------------------
    def _run(self) -> None:
        rv = 0
        need_list = True
        attempt = 0
        while not self._stop.is_set() and not self.kube._closed.is_set():
            try:
                if need_list:
                    items, rv = self.kube._list_rv(self.resource)
                    listed = {_obj_key(obj) for obj in items}
                    for key, meta in list(self._known.items()):
                        if key not in listed:
                            self._dispatch(
                                DELETED,
                                {"metadata": dict(meta)},
                            )
                    for obj in items:
                        self._dispatch(ADDED, obj)
                    need_list = False
                rv, got_any = self._stream(rv)
                if got_any:
                    attempt = 0  # a live stream resets the backoff ladder
                else:
                    # Closed (or read-timed-out) without delivering a
                    # single line: a member restart loop or partition.
                    # Reconnecting flat-out turns that into a storm —
                    # back off, capped and jittered.
                    attempt += 1
                    self._sleep_backoff(attempt)
            except Gone:
                need_list = True  # relist immediately: 410 is not a fault
            except (TransportError, OSError, http.client.HTTPException, ValueError):
                attempt += 1
                self._sleep_backoff(attempt)

    def _sleep_backoff(self, attempt: int) -> None:
        delay = watch_backoff(attempt - 1)
        if len(self.reconnect_delays) < 256:
            self.reconnect_delays.append(delay)
        self._stop.wait(delay)

    def _stream(self, rv: int) -> tuple[int, bool]:
        """One watch connection; returns (last seen resourceVersion,
        whether ANY line — event or heartbeat — arrived).  A silent
        stream past the watch timeout reads as dead-peer and returns for
        a (backed-off) reconnect."""
        conn = http.client.HTTPConnection(
            self.kube._netloc, timeout=self.kube._watch_timeout
        )
        got_any = False
        try:
            path = resource_to_path(self.resource) + f"?watch=true&resourceVersion={rv}"
            conn.request("GET", path, headers=self.kube._headers())
            resp = conn.getresponse()
            if resp.status == 410:
                resp.read()
                raise Gone(f"watch {self.resource} from {rv}")
            if resp.status != 200:
                resp.read()
                raise TransportError(f"watch {self.resource}: HTTP {resp.status}")
            while not self._stop.is_set() and not self.kube._closed.is_set():
                try:
                    line = resp.readline()
                except (TimeoutError, pysocket.timeout):
                    return rv, got_any  # silent stream: reconnect from rv
                if not line:
                    return rv, got_any  # stream closed; reconnect from rv
                got_any = True
                event = json.loads(line)
                if event.get("type") == "HEARTBEAT":
                    continue
                obj = event["object"]
                obj_rv = int(obj.get("metadata", {}).get("resourceVersion", 0))
                rv = max(rv, obj_rv)
                self._dispatch(event["type"], obj)
            return rv, got_any
        finally:
            conn.close()


class FederatedClientFactory:
    """Per-member clients from FederatedCluster join secrets."""

    def __init__(self, host, timeout: float = 10.0):
        self.host = host
        self.timeout = timeout
        self._cache: dict[tuple[str, str], HttpKube] = {}
        self._lock = threading.Lock()

    def client_for(self, cluster: dict) -> HttpKube:
        name = cluster["metadata"]["name"]
        spec = cluster.get("spec", {})
        endpoint = spec.get("apiEndpoint")
        if not endpoint:
            raise NotFound(f"cluster {name} has no apiEndpoint")
        secret_name = (spec.get("secretRef") or {}).get("name") or f"{name}-secret"
        secret = self.host.try_get(SECRETS, f"{FED_SYSTEM_NAMESPACE}/{secret_name}")
        if secret is None:
            raise NotFound(f"cluster {name}: join secret {secret_name} missing")
        token = (secret.get("data") or {}).get("token")
        cache_key = (endpoint, token or "")
        with self._lock:
            client = self._cache.get(cache_key)
            if client is None:
                client = HttpKube(
                    endpoint, token=token, name=name, timeout=self.timeout
                )
                self._cache[cache_key] = client
            return client

    def close(self) -> None:
        with self._lock:
            for client in self._cache.values():
                client.close()
            self._cache.clear()


class _PredicatedHandler:
    """A member-watch handler carrying a shard-intake predicate the
    reflector consults pre-delivery (fakekube.ShardIntake's transport
    twin).  ``func`` exposes the underlying bound method so
    handler_owner() still resolves the owning controller through a
    functools.partial wrapper."""

    __slots__ = ("_inner", "func", "kt_predicate")

    def __init__(self, inner: Handler, predicate: Callable):
        self._inner = inner
        self.func = getattr(inner, "func", inner)
        self.kt_predicate = predicate

    def __call__(self, event: str, obj: dict) -> None:
        self._inner(event, obj)


class HttpFleet:
    """ClusterFleet interface over HTTP: host client + join-secret-built
    member clients, member watches driven by FederatedCluster state."""

    def __init__(self, host: HttpKube, factory: Optional[FederatedClientFactory] = None):
        self.host = host
        self.factory = factory or FederatedClientFactory(host)
        self.members: dict[str, HttpKube] = {}
        # Invalidate cached member clients on cluster deletion/endpoint
        # change (see member()).
        host.watch(C.FEDERATED_CLUSTERS, self._on_cluster_change, replay=False)

    def member(self, name: str) -> HttpKube:
        # Cache hit first: resolving through the host costs TWO round
        # trips (cluster + join secret) and sits on the sync dispatcher's
        # hottest path.  The fleet's own FederatedClusters watch (below)
        # pops entries on deletion and spec changes, so a removed
        # cluster raises NotFound on the next call (ClusterFleet.member
        # parity) and endpoint/credential rotation rebuilds the client —
        # the reference's informer-backed FederatedClientFactory caches
        # the same way (federatedclient/client.go:48-386).
        client = self.members.get(name)
        if client is not None:
            return client
        cluster = self.host.try_get(C.FEDERATED_CLUSTERS, name)
        if cluster is None:
            raise NotFound(f"cluster {name}")
        client = self.factory.client_for(cluster)
        self.members[name] = client
        return client

    def _on_cluster_change(self, event: str, obj: dict) -> None:
        name = obj["metadata"]["name"]
        if event == DELETED:
            self.members.pop(name, None)
            return
        cached = self.members.get(name)
        if cached is None:
            return
        # Endpoint moved: drop the stale client (the factory re-reads
        # the join secret on the next member() miss).
        endpoint = (obj.get("spec") or {}).get("apiEndpoint")
        if endpoint and f"//{cached._netloc}" not in endpoint:
            self.members.pop(name, None)

    def unwatch_owner(self, owner: object) -> None:
        self.host.unwatch_owner(owner)
        for client in self.members.values():
            client.unwatch_owner(owner)

    def watch_members(
        self, resource: str, handler: Handler, named: bool = False,
        replay: bool = False, batch: Optional[Callable] = None,
        predicate: Optional[Callable] = None,
    ) -> Callable[[], None]:
        # ``batch`` (the in-process fleet's coalesced-delivery variant)
        # is accepted for interface parity and unused: HTTP watch
        # streams deliver per event, so consumers registered against
        # either fleet shape fall back to their per-event handler here.
        # ``predicate`` (the shard-intake filter) IS honored: the
        # per-member reflector consults kt_predicate before delivery,
        # so a replica never pays an enqueue for a key it doesn't own.
        del batch
        attached: set[str] = set()
        detached: set[str] = set()
        wrapped: dict[str, tuple[HttpKube, Handler]] = {}

        def attach() -> None:
            pending: set[str] = set()
            for cluster in self.host.list(C.FEDERATED_CLUSTERS):
                name = cluster["metadata"]["name"]
                if name in attached or name in detached:
                    continue
                try:
                    client = self.factory.client_for(cluster)
                except NotFound:
                    # Not joined yet (join secret unreadable); surfaced
                    # via attach.pending so watchers keep retrying even
                    # after the cluster's lifecycle state stabilizes.
                    pending.add(name)
                    continue
                attached.add(name)
                self.members[name] = client
                h = functools.partial(handler, name) if named else handler
                if predicate is not None:
                    h = _PredicatedHandler(h, predicate)
                wrapped[name] = (client, h)
                client.watch(resource, h, replay=replay)
            attach.pending = pending

        def detach(name: str) -> None:
            """Tear down one cluster's watch (the FederatedInformer
            remove-cluster lifecycle) — the stream would otherwise keep
            feeding stale objects after the cluster left the federation.
            Sticky until readmit(name), mirroring ClusterFleet."""
            attached.discard(name)
            detached.add(name)
            entry = wrapped.pop(name, None)
            if entry is not None:
                client, h = entry
                client.unwatch(resource, h)

        def readmit(name: str) -> None:
            """Lift a detach (the cluster's object re-appeared)."""
            detached.discard(name)

        attach.pending = set()
        attach.attached = attached
        attach.detach = detach
        attach.readmit = readmit
        attach()
        return attach

    def close(self) -> None:
        self.factory.close()
        self.host.close()
