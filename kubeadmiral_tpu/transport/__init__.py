"""Real HTTP transport for the control plane.

The controllers speak to apiservers through a small duck-typed seam
(create/get/update/update_status/delete/list/watch + view reads) defined
by :mod:`kubeadmiral_tpu.testing.fakekube`.  This package provides the
real-network implementation of that seam:

* :mod:`kubeadmiral_tpu.transport.apiserver` — an HTTP apiserver serving
  a store over Kubernetes-style REST paths with chunked watch streams,
  optimistic concurrency, status subresources and bearer-token auth.
* :mod:`kubeadmiral_tpu.transport.client` — the HTTP client implementing
  the same interface as FakeKube, per-member clients built from
  FederatedCluster join secrets (the FederatedClientFactory analogue;
  reference: pkg/controllers/util/federatedclient/client.go:48-386),
  and an HttpFleet the controller manager can run over unmodified.
"""
