"""Out-of-process scheduler plugins over HTTP (webhook plugins).

A SchedulerPluginWebhookConfiguration registers an external service that
participates in scheduling at the filter/score/select extension points
(reference: pkg/apis/core/v1alpha1/types_schedulerpluginwebhookconfiguration.go,
payload schema pkg/apis/schedulerwebhook/v1alpha1/types.go:29-102, HTTP
adapter pkg/controllers/scheduler/extensions/webhook/v1alpha1/plugin.go).

Request/response wire format (one POST per call, JSON both ways):

* filter: {schedulingUnit, cluster} -> {selected, error}
* score:  {schedulingUnit, cluster} -> {score, error}
* select: {schedulingUnit, clusterScores: [{cluster, score}]}
          -> {selectedClusterNames, error}

In the batch engine, filter/score results are evaluated host-side (they
are network calls) and enter the fused XLA tick as an extra mask / score
plane; select plugins narrow the tick's output afterwards.

``HTTPClient`` is injectable so tests run against a fake transport, as
the reference's plugin tests do (plugin.go:42-44).
"""

from __future__ import annotations

import base64
import http.client
import json
import socket
import ssl
import tempfile
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Optional, Protocol
from urllib.parse import urlsplit

from kubeadmiral_tpu.models import types as T

SCHEDULER_WEBHOOK_CONFIGS = (
    "core.kubeadmiral.io/v1alpha1/schedulerpluginwebhookconfigurations"
)

PAYLOAD_VERSION = "v1alpha1"
SUPPORTED_PAYLOAD_VERSIONS = frozenset({PAYLOAD_VERSION})

DEFAULT_TIMEOUT_SECONDS = 5.0


class WebhookError(Exception):
    pass


class WebhookStatusError(Exception):
    """Non-200 HTTP status from the webhook server.  Deliberately NOT a
    WebhookError: a 404 on a "-batch" endpoint means "reference-protocol
    server, fall back to per-pair calls", not a protocol failure."""

    def __init__(self, code: int):
        super().__init__(f"unexpected status code: {code}")
        self.code = code


@dataclass(frozen=True)
class WebhookTLSConfig:
    """spec.tlsConfig (reference:
    types_schedulerpluginwebhookconfiguration.go:68-90, consumed by
    scheduler/webhook.go:117-119): CA bundle + optional client cert for
    mTLS, insecure skip-verify for testing, SNI/verify name override.
    PEM fields arrive base64-encoded ([]byte JSON encoding)."""

    insecure: bool = False
    server_name: str = ""
    ca_data: str = ""    # PEM
    cert_data: str = ""  # PEM (client certificate)
    key_data: str = ""   # PEM (client key)


def parse_tls_config(raw: Optional[dict]) -> Optional[WebhookTLSConfig]:
    if not raw:
        return None

    def pem(field: str) -> str:
        value = raw.get(field, "")
        if not value:
            return ""
        if "-----BEGIN" in value:
            return value  # already PEM (convenience for tests/manifests)
        try:
            return base64.b64decode(value).decode()
        except Exception as e:
            # Silent "" would downgrade to system CAs / no client cert
            # and every call would fail as a generic transport error;
            # fail loudly at parse time instead (the config watcher
            # counts the parse error and skips the plugin).
            raise ValueError(f"tlsConfig.{field} is not valid base64 PEM: {e}")

    return WebhookTLSConfig(
        insecure=bool(raw.get("insecure", False)),
        server_name=raw.get("serverName", ""),
        ca_data=pem("caData"),
        cert_data=pem("certData"),
        key_data=pem("keyData"),
    )


class HTTPClient(Protocol):
    def post(
        self,
        url: str,
        body: bytes,
        timeout: float,
        tls: Optional[WebhookTLSConfig] = None,
    ) -> bytes: ...


class UrllibClient:
    """Default transport: stdlib http.client with the reference's
    headers and per-webhook TLS (CA bundle / client cert / insecure /
    SNI override — webhook.go:117-119 builds the equivalent
    http.Transport from the config's TLSClientConfig)."""

    def __init__(self):
        self._ctx_cache: dict[WebhookTLSConfig, ssl.SSLContext] = {}

    def _context(self, tls: Optional[WebhookTLSConfig]) -> ssl.SSLContext:
        key = tls or WebhookTLSConfig()
        ctx = self._ctx_cache.get(key)
        if ctx is not None:
            return ctx
        ctx = ssl.create_default_context(
            cadata=key.ca_data if key.ca_data else None
        )
        if key.insecure:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if key.cert_data and key.key_data:
            # load_cert_chain only takes paths; stage the PEM through a
            # private temp file.
            with tempfile.NamedTemporaryFile("w", suffix=".pem") as f:
                f.write(key.cert_data)
                f.write("\n")
                f.write(key.key_data)
                f.flush()
                ctx.load_cert_chain(f.name)
        self._ctx_cache[key] = ctx
        return ctx

    def post(
        self,
        url: str,
        body: bytes,
        timeout: float,
        tls: Optional[WebhookTLSConfig] = None,
    ) -> bytes:
        split = urlsplit(url)
        headers = {
            "Content-Type": "application/json",
            "Accept": "application/json",
            "User-Agent": "kubeadmiral-tpu-scheduler",
        }
        if split.scheme == "https":
            ctx = self._context(tls)
            server_name = (tls.server_name if tls else "") or split.hostname
            conn = http.client.HTTPSConnection(
                split.hostname, split.port, timeout=timeout, context=ctx
            )
            # SNI / verification-name override (TLSConfig.ServerName):
            # wrap the socket ourselves so the name presented to the
            # server (and checked against its cert) is the configured
            # one, not the dial host.
            def connect(_conn=conn, _ctx=ctx, _name=server_name):
                sock = socket.create_connection(
                    (_conn.host, _conn.port), _conn.timeout
                )
                _conn.sock = _ctx.wrap_socket(sock, server_hostname=_name)

            conn.connect = connect
        else:
            conn = http.client.HTTPConnection(
                split.hostname, split.port, timeout=timeout
            )
        try:
            path = split.path or "/"
            if split.query:
                path += "?" + split.query
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise WebhookStatusError(resp.status)
            return data
        finally:
            conn.close()


# -- payload conversion (adapter.go ConvertSchedulingUnit) ---------------

def scheduling_unit_payload(su: T.SchedulingUnit) -> dict:
    parts = su.gvk.split("/")  # "group/version/Kind" ("" group collapsed)
    if len(parts) == 3:
        group, version, kind = parts
    else:
        group, (version, kind) = "", parts
    payload: dict = {
        "apiVersion": f"{group}/{version}" if group else version,
        "kind": kind,
        "resource": kind.lower() + "s",
        "name": su.name,
        "schedulingMode": su.scheduling_mode,
        "currentClusters": sorted(su.current_clusters),
    }
    if su.namespace:
        payload["namespace"] = su.namespace
    if su.labels:
        payload["labels"] = dict(su.labels)
    if su.annotations:
        payload["annotations"] = dict(su.annotations)
    if su.desired_replicas is not None:
        payload["desiredReplicas"] = int(su.desired_replicas)
    if su.resource_request:
        payload["resourceRequest"] = {
            name: str(q) for name, q in sorted(su.resource_request.items())
        }
    distribution = {
        c: int(r) for c, r in su.current_clusters.items() if r is not None
    }
    if distribution:
        payload["currentReplicaDistribution"] = distribution
    if su.cluster_selector:
        payload["clusterSelector"] = dict(su.cluster_selector)
    if su.tolerations:
        payload["tolerations"] = [
            {
                k: v
                for k, v in (
                    ("key", t.key),
                    ("operator", t.operator),
                    ("value", t.value),
                    ("effect", t.effect),
                )
                if v
            }
            for t in su.tolerations
        ]
    if su.max_clusters is not None:
        payload["maxClusters"] = int(su.max_clusters)
    return payload


def cluster_payload(cluster: T.ClusterState) -> dict:
    """ClusterState -> FederatedCluster-shaped JSON."""
    return {
        "metadata": {"name": cluster.name, "labels": dict(cluster.labels)},
        "spec": {
            "taints": [
                {"key": t.key, "value": t.value, "effect": t.effect}
                for t in cluster.taints
            ]
        },
        "status": {
            "resources": {
                "allocatable": {
                    name: str(q) for name, q in sorted(cluster.allocatable.items())
                },
                "available": {
                    name: str(q) for name, q in sorted(cluster.available.items())
                },
            },
            "apiResourceTypes": sorted(cluster.api_resources),
        },
    }


@dataclass(frozen=True)
class WebhookConfig:
    """Parsed SchedulerPluginWebhookConfiguration."""

    name: str
    url_prefix: str
    filter_path: str = ""
    score_path: str = ""
    select_path: str = ""
    payload_versions: tuple[str, ...] = (PAYLOAD_VERSION,)
    timeout: float = DEFAULT_TIMEOUT_SECONDS
    generation: int = 1
    tls: Optional[WebhookTLSConfig] = None


_DURATION_UNITS = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9}


def parse_duration(raw) -> Optional[float]:
    """metav1.Duration-style string ("5s", "500ms", "1m30s") or bare
    number -> seconds; None when absent or unparseable."""
    if raw is None:
        return None
    if isinstance(raw, (int, float)):
        return float(raw)
    total, number = 0.0, ""
    i, s = 0, str(raw).strip()
    try:
        while i < len(s):
            ch = s[i]
            if ch.isdigit() or ch in ".+-":
                number += ch
                i += 1
                continue
            unit = ch
            if s[i : i + 2] in _DURATION_UNITS:
                unit = s[i : i + 2]
            if unit not in _DURATION_UNITS:
                return None
            total += float(number) * _DURATION_UNITS[unit]
            number = ""
            i += len(unit)
        if number:  # bare trailing number
            total += float(number)
        return total
    except ValueError:
        return None


def parse_webhook_config(obj: dict) -> WebhookConfig:
    spec = obj.get("spec", {})
    timeout = parse_duration(spec.get("httpTimeout"))
    return WebhookConfig(
        name=obj["metadata"]["name"],
        url_prefix=spec.get("urlPrefix", ""),
        filter_path=spec.get("filterPath", ""),
        score_path=spec.get("scorePath", ""),
        select_path=spec.get("selectPath", ""),
        payload_versions=tuple(spec.get("payloadVersions", (PAYLOAD_VERSION,))),
        timeout=timeout if timeout else DEFAULT_TIMEOUT_SECONDS,
        generation=obj["metadata"].get("generation", 1),
        tls=parse_tls_config(spec.get("tlsConfig")),
    )


class WebhookPlugin:
    """One registered webhook, callable at its configured extension
    points (plugin.go:46-251)."""

    def __init__(self, config: WebhookConfig, client: Optional[HTTPClient] = None):
        self.config = config
        self.name = config.name
        self.client = client or UrllibClient()
        # Extension points whose "-batch" sibling endpoint turned out to
        # be unavailable (reference-protocol server): fall back to
        # per-pair calls for this plugin instance's lifetime (instances
        # are rebuilt on config generation changes).
        self._batch_unsupported: set[str] = set()

    @property
    def has_filter(self) -> bool:
        return bool(self.config.filter_path)

    @property
    def has_score(self) -> bool:
        return bool(self.config.score_path)

    @property
    def has_select(self) -> bool:
        return bool(self.config.select_path)

    def _call(self, path: str, body: dict) -> dict:
        url = self.config.url_prefix.rstrip("/") + "/" + path.lstrip("/")
        # tls is passed only when configured, so injected fake clients
        # with the bare (url, body, timeout) signature keep working.
        kwargs = {"tls": self.config.tls} if self.config.tls is not None else {}
        raw = self.client.post(
            url, json.dumps(body).encode(), timeout=self.config.timeout, **kwargs
        )
        response = json.loads(raw)
        if response.get("error"):
            raise WebhookError(response["error"])
        return response

    def filter(self, su: T.SchedulingUnit, cluster: T.ClusterState) -> bool:
        response = self._call(
            self.config.filter_path,
            {
                "schedulingUnit": scheduling_unit_payload(su),
                "cluster": cluster_payload(cluster),
            },
        )
        return bool(response.get("selected"))

    def score(self, su: T.SchedulingUnit, cluster: T.ClusterState) -> int:
        response = self._call(
            self.config.score_path,
            {
                "schedulingUnit": scheduling_unit_payload(su),
                "cluster": cluster_payload(cluster),
            },
        )
        return int(response.get("score", 0))

    # -- batched protocol -------------------------------------------------
    # One POST per plugin per tick carrying the whole (units x clusters)
    # problem — the batch-native extension of the reference's per-pair
    # protocol (which makes O(B x C) HTTP calls per tick,
    # plugin.go:77-251).  Servers opt in by serving "<path>-batch";
    # anything else transparently degrades to per-pair calls.

    def _batch_call(self, kind: str, path: str, units, clusters) -> Optional[dict]:
        if kind in self._batch_unsupported:
            return None
        body = {
            "schedulingUnits": [scheduling_unit_payload(su) for su in units],
            "clusters": [cluster_payload(c) for c in clusters],
        }
        try:
            return self._call(path.rstrip("/") + "-batch", body)
        except WebhookError:
            raise  # the server answered with a protocol error
        except (urllib.error.HTTPError, WebhookStatusError) as e:
            if e.code in (404, 405, 501):
                # The endpoint genuinely doesn't exist (reference-
                # protocol server): remember permanently.
                self._batch_unsupported.add(kind)
            return None  # transient HTTP failure: per-pair this tick
        except Exception:
            # Transient transport error (timeout, reset) or a fake test
            # client that doesn't know the URL: fall back to per-pair
            # calls for THIS tick only and probe again next tick.
            return None

    @staticmethod
    def _validated_rows(rows, n_units: int, n_clusters: int, context: str) -> list:
        """A malformed grid (wrong row count / ragged rows) is a protocol
        error, not a crash: callers contain WebhookError per plugin."""
        if len(rows) != n_units or any(len(row) != n_clusters for row in rows):
            raise WebhookError(
                f"{context}: bad batch response shape "
                f"(want {n_units}x{n_clusters})"
            )
        return rows

    def filter_batch(
        self, units: list[T.SchedulingUnit], clusters: list[T.ClusterState]
    ) -> Optional[list[list[bool]]]:
        """[len(units)][len(clusters)] feasibility, or None when the
        server doesn't speak the batch protocol."""
        response = self._batch_call("filter", self.config.filter_path, units, clusters)
        if response is None:
            return None
        rows = self._validated_rows(
            response.get("selected", []), len(units), len(clusters),
            f"{self.name} filter-batch",
        )
        return [[bool(x) for x in row] for row in rows]

    def score_batch(
        self, units: list[T.SchedulingUnit], clusters: list[T.ClusterState]
    ) -> Optional[list[list[int]]]:
        response = self._batch_call("score", self.config.score_path, units, clusters)
        if response is None:
            return None
        rows = self._validated_rows(
            response.get("scores", []), len(units), len(clusters),
            f"{self.name} score-batch",
        )
        return [[int(x) for x in row] for row in rows]

    def select(
        self, su: T.SchedulingUnit, cluster_scores: list[tuple[T.ClusterState, int]]
    ) -> list[str]:
        response = self._call(
            self.config.select_path,
            {
                "schedulingUnit": scheduling_unit_payload(su),
                "clusterScores": [
                    {"cluster": cluster_payload(c), "score": int(s)}
                    for c, s in cluster_scores
                ],
            },
        )
        return list(response.get("selectedClusterNames", ()))
