"""Featurizer: API objects -> packed tensors for the fused tick.

The string-matching world (taints/tolerations, label selectors, affinity
terms, API resource lists) is resolved host-side into boolean/integer
tensors; the trick that keeps this off the critical path is **dedup +
gather**: objects share a handful of distinct toleration sets, selector
specs and policies, and clusters share a handful of taint/label sets, so
each distinct pair is matched once into a small matrix and then gathered
into [B, C] with numpy advanced indexing.  Only the planner tie-break
hash is inherently per-(object, cluster); its rows are cached by object
key since they change only when the cluster set changes.

This replaces the reference's per-object, per-cluster, per-plugin Go
call chain (reference: pkg/controllers/scheduler/framework/runtime/
framework.go:114-181) with O(unique pairs) host work + one device gather.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from kubeadmiral_tpu.models import types as T
from kubeadmiral_tpu.ops import filters as OF
from kubeadmiral_tpu.ops import scores as OS
from kubeadmiral_tpu.ops.pipeline import NIL_REPLICAS, TickInputs
from kubeadmiral_tpu.ops.planner import INT32_INF, validate_ranges
from kubeadmiral_tpu.utils import labels as L
from kubeadmiral_tpu.utils.hashing import (
    fnv32,
    fnv32_extend,
    uint32_to_sortable_int32,
)

_FILTER_INDEX = {
    T.APIRESOURCES: OF.F_API_RESOURCES,
    T.TAINT_TOLERATION: OF.F_TAINT_TOLERATION,
    T.CLUSTER_RESOURCES_FIT: OF.F_RESOURCES_FIT,
    T.PLACEMENT_FILTER: OF.F_PLACEMENT,
    T.CLUSTER_AFFINITY: OF.F_CLUSTER_AFFINITY,
}
_SCORE_INDEX = {
    T.TAINT_TOLERATION: OS.S_TAINT,
    T.CLUSTER_RESOURCES_BALANCED: OS.S_BALANCED,
    T.CLUSTER_RESOURCES_LEAST: OS.S_LEAST,
    T.CLUSTER_AFFINITY: OS.S_AFFINITY,
    T.CLUSTER_RESOURCES_MOST: OS.S_MOST,
}


class ClusterView:
    """Per-tick tensor view of the member clusters.

    Build once per tick (cluster state changes far less often than
    objects); reused across every batch chunk.
    """

    def __init__(self, clusters: Sequence[T.ClusterState], scalar_resources: Sequence[str] = ()):
        self.clusters = list(clusters)
        self.names = [c.name for c in self.clusters]
        self.index = {n: i for i, n in enumerate(self.names)}
        self.scalar_resources = list(scalar_resources)
        c = len(self.clusters)
        r = OF.NUM_FIXED_RESOURCES + len(self.scalar_resources)

        self.alloc = np.zeros((c, r), np.int64)
        self.avail = np.zeros((c, r), np.int64)
        self.cpu_alloc = np.zeros(c, np.int64)
        self.cpu_avail = np.zeros(c, np.int64)
        for i, cl in enumerate(self.clusters):
            self.alloc[i] = self._res_row(cl.allocatable, r)
            self.avail[i] = self._res_row(cl.available, r)
            # Quantity.Value() semantics: cores rounded up (rsp.go weights).
            self.cpu_alloc[i] = -(-cl.allocatable.get("cpu", 0) // 1000)
            self.cpu_avail[i] = -(-cl.available.get("cpu", 0) // 1000)
        self.used = self.alloc - self.avail

        # Dedup ids for taint sets and label sets.
        self.taint_sets: list[tuple[T.Taint, ...]] = []
        taint_ids: dict[tuple[T.Taint, ...], int] = {}
        self.taint_id = np.zeros(c, np.int64)
        for i, cl in enumerate(self.clusters):
            key = tuple(cl.taints)
            if key not in taint_ids:
                taint_ids[key] = len(self.taint_sets)
                self.taint_sets.append(key)
            self.taint_id[i] = taint_ids[key]

        self.label_keys: list[frozenset] = []
        label_ids: dict[frozenset, int] = {}
        self.label_id = np.zeros(c, np.int64)
        for i, cl in enumerate(self.clusters):
            key = frozenset(cl.labels.items())
            if key not in label_ids:
                label_ids[key] = len(self.label_keys)
                self.label_keys.append(key)
            self.label_id[i] = label_ids[key]

        # FNV-1 state after hashing each cluster name (planner tie-breaks
        # extend this with the object key — hashing.fnv32_extend).
        self.name_hash_state = np.array(
            [fnv32(n.encode()) for n in self.names], np.uint32
        )
        self._tiebreak_cache: dict[str, np.ndarray] = {}

    @staticmethod
    def _res_row(res: dict[str, int], r: int) -> np.ndarray:
        row = np.zeros(r, np.int64)
        row[OF.R_CPU] = res.get("cpu", 0)
        row[OF.R_MEM] = res.get("memory", 0)
        return row

    def tiebreak_row(self, key: str) -> np.ndarray:
        row = self._tiebreak_cache.get(key)
        if row is None:
            row = uint32_to_sortable_int32(
                fnv32_extend(self.name_hash_state, key.encode())
            )
            self._tiebreak_cache[key] = row
        return row

    def tiebreak_rows(self, keys: list[str]) -> np.ndarray:
        """[len(keys), C] tie-break hashes; uncached keys are extended in
        one vectorized sweep over byte positions instead of per key."""
        c = len(self.names)
        out = np.empty((len(keys), c), np.int32)
        missing: dict[str, list[int]] = {}
        for i, key in enumerate(keys):
            row = self._tiebreak_cache.get(key)
            if row is None:
                missing.setdefault(key, []).append(i)
            else:
                out[i] = row
        if missing:
            uniq = list(missing)
            encoded = [k.encode() for k in uniq]
            max_len = max(len(e) for e in encoded)
            lens = np.array([len(e) for e in encoded])
            byte_mat = np.zeros((len(uniq), max_len), np.uint32)
            for j, e in enumerate(encoded):
                byte_mat[j, : len(e)] = np.frombuffer(e, np.uint8)
            states = np.broadcast_to(
                self.name_hash_state, (len(uniq), c)
            ).astype(np.uint32)
            prime = np.uint32(16777619)
            with np.errstate(over="ignore"):
                for p in range(max_len):
                    active = lens > p
                    if not active.all():
                        upd = states[active] * prime ^ byte_mat[active, p][:, None]
                        states[active] = upd
                    else:
                        states = states * prime ^ byte_mat[:, p][:, None]
            rows = uint32_to_sortable_int32(states)
            for j, k in enumerate(uniq):
                self._tiebreak_cache[k] = rows[j]
                for i in missing[k]:
                    out[i] = rows[j]
        return out


def featurize_signature(su: T.SchedulingUnit) -> tuple:
    """Equality-comparable digest of every unit field the featurizer
    reads — the tensor-level analogue of the reference's scheduling
    trigger hash (reference: scheduler/schedulingtriggers.go:106-148).
    Two units with equal signatures featurize to identical rows against
    the same cluster topology, which is what lets the engine patch only
    changed rows into a cached chunk across ticks.

    Memoized on the unit: SchedulingUnit's contract is immutability
    after construction (models/types.py), so the digest is computed once
    per object — at 100k rows the signature pass was a measurable slice
    of the steady-tick host floor."""
    sig = getattr(su, "_featurize_sig", None)
    if sig is not None:
        return sig
    am = su.auto_migration
    # The immutability contract is load-bearing here: a caller that
    # mutates a unit's nested dicts AFTER the first signature call will
    # not be detected (the memo is permanent).  Controllers build fresh
    # units from API objects every reconcile, which satisfies it.
    sig = (
        su.key,
        su.gvk,
        su.scheduling_mode,
        su.desired_replicas,
        su.sticky_cluster,
        su.avoid_disruption,
        su.max_clusters,
        tuple(sorted(su.resource_request.items())),
        su.tolerations,
        tuple(sorted(su.cluster_selector.items())),
        su.cluster_names,
        su.affinity,
        tuple(sorted(su.current_clusters.items(), key=lambda kv: kv[0])),
        tuple(sorted(su.min_replicas.items())),
        tuple(sorted(su.max_replicas.items())),
        tuple(sorted(su.weights.items())),
        (am.keep_unschedulable_replicas, tuple(sorted(am.estimated_capacity.items())))
        if am is not None
        else None,
        su.enabled_filters,
        su.enabled_scores,
    )
    object.__setattr__(su, "_featurize_sig", sig)
    return sig


def _build_cluster_view(clusters, units) -> ClusterView:
    scalars: list[str] = []
    seen = set()
    for su in units:
        for name in su.resource_request:
            if name not in ("cpu", "memory", "ephemeral-storage") and name not in seen:
                seen.add(name)
                scalars.append(name)
    view = ClusterView(clusters, sorted(scalars))
    # Fill scalar columns.
    for i, cl in enumerate(view.clusters):
        for j, rname in enumerate(view.scalar_resources):
            col = OF.NUM_FIXED_RESOURCES + j
            view.alloc[i, col] = cl.allocatable.get(rname, 0)
            view.avail[i, col] = cl.available.get(rname, 0)
    view.used = view.alloc - view.avail
    return view


def _dedup(items, key_fn):
    ids, keys, uniq = [], {}, []
    for it in items:
        k = key_fn(it)
        if k not in keys:
            keys[k] = len(uniq)
            uniq.append(it)
        ids.append(keys[k])
    return np.array(ids, np.int64), uniq


@dataclass
class FeaturizedBatch:
    inputs: TickInputs
    units: list
    view: ClusterView


def featurize(
    units: Sequence[T.SchedulingUnit],
    clusters: Sequence[T.ClusterState],
    view: Optional[ClusterView] = None,
    webhook_eval=None,
) -> FeaturizedBatch:
    """Pack a batch of scheduling units against the member clusters.

    ``webhook_eval(unit, clusters) -> (ok_row, score_row) | None`` is the
    host-side hook for out-of-process scheduler plugins (reference:
    scheduler/extensions/webhook): their per-(object, cluster) HTTP
    results enter the fused tick as an extra mask and score plane."""
    units = list(units)
    if view is None:
        view = _build_cluster_view(clusters, units)
    b, c = len(units), len(view.clusters)
    r = view.alloc.shape[1]

    webhook_ok = np.ones((b, c), bool)
    webhook_scores = np.zeros((b, c), np.int32)
    if webhook_eval is not None:
        int32_info = np.iinfo(np.int32)
        for i, su in enumerate(units):
            result = webhook_eval(su, view.clusters)
            if result is not None:
                webhook_ok[i], scores_row = result
                # Free-form HTTP responses are clamped to int32: the
                # tick's score outputs travel as int32 to keep the
                # device->host transfer small, and an unclamped 2**31
                # webhook score would wrap.
                webhook_scores[i] = np.clip(
                    scores_row, int32_info.min // 2, int32_info.max // 2
                )

    # --- plugin enablement ---
    filter_enabled = np.zeros((b, OF.NUM_FILTER_PLUGINS), bool)
    score_enabled = np.zeros((b, OS.NUM_SCORE_PLUGINS), bool)
    for i, su in enumerate(units):
        for name in su.enabled_filters if su.enabled_filters is not None else T.DEFAULT_FILTERS:
            idx = _FILTER_INDEX.get(name)
            if idx is not None:
                filter_enabled[i, idx] = True
        for name in su.enabled_scores if su.enabled_scores is not None else T.DEFAULT_SCORES:
            idx = _SCORE_INDEX.get(name)
            if idx is not None:
                score_enabled[i, idx] = True

    # --- API resources: unique GVKs x clusters ---
    gvk_ids, gvks = _dedup(units, lambda su: su.gvk)
    api_matrix = np.zeros((len(gvks), c), bool)
    for gi, su in enumerate(gvks):
        for ci, cl in enumerate(view.clusters):
            api_matrix[gi, ci] = su.gvk in cl.api_resources
    api_ok = api_matrix[gvk_ids]

    # --- taints: unique toleration sets x unique taint sets ---
    tol_ids, tol_units = _dedup(units, lambda su: tuple(su.tolerations))
    u_tol, u_taint = len(tol_units), len(view.taint_sets)
    ok_new = np.ones((u_tol, u_taint), bool)
    ok_cur = np.ones((u_tol, u_taint), bool)
    prefer = np.zeros((u_tol, u_taint), np.int32)
    for ti, su in enumerate(tol_units):
        tols = su.tolerations
        prefer_tols = [t for t in tols if not t.effect or t.effect == T.PREFER_NO_SCHEDULE]
        for si, taints in enumerate(view.taint_sets):
            for taint in taints:
                tolerated = any(t.tolerates(taint) for t in tols)
                if not tolerated:
                    if taint.effect in (T.NO_SCHEDULE, T.NO_EXECUTE):
                        ok_new[ti, si] = False
                    if taint.effect == T.NO_EXECUTE:
                        ok_cur[ti, si] = False
                if taint.effect == T.PREFER_NO_SCHEDULE and not any(
                    t.tolerates(taint) for t in prefer_tols
                ):
                    prefer[ti, si] += 1
    taint_ok_new = ok_new[tol_ids][:, view.taint_id]
    taint_ok_cur = ok_cur[tol_ids][:, view.taint_id]
    taint_counts = prefer[tol_ids][:, view.taint_id]

    # --- selectors / affinity: unique specs x clusters ---
    def sel_key(su):
        aff = su.affinity
        req = aff.required if aff is not None else None
        return (frozenset(su.cluster_selector.items()), req)

    sel_ids, sel_units = _dedup(units, sel_key)
    sel_matrix = np.zeros((len(sel_units), c), bool)
    for si, su in enumerate(sel_units):
        memo: dict[tuple, bool] = {}
        uses_fields = su.affinity is not None and su.affinity.required and any(
            t.match_fields for t in su.affinity.required
        )
        for ci, cl in enumerate(view.clusters):
            mk = (view.label_id[ci], cl.name if uses_fields else "")
            if mk not in memo:
                memo[mk] = L.cluster_feasible(
                    cl.labels, cl.name, su.cluster_selector, su.affinity
                )
            sel_matrix[si, ci] = memo[mk]
    selector_ok = sel_matrix[sel_ids]

    def pref_key(su):
        return su.affinity.preferred if su.affinity is not None else ()

    pref_ids, pref_units = _dedup(units, pref_key)
    pref_matrix = np.zeros((len(pref_units), c), np.int32)
    for pi, su in enumerate(pref_units):
        if su.affinity is None or not su.affinity.preferred:
            continue
        memo = {}
        for ci, cl in enumerate(view.clusters):
            mk = view.label_id[ci]
            if mk not in memo:
                memo[mk] = L.preferred_score(cl.labels, cl.name, su.affinity)
            pref_matrix[pi, ci] = memo[mk]
    affinity_scores = pref_matrix[pref_ids]

    # --- explicit placements ---
    place_ids, place_units = _dedup(units, lambda su: su.cluster_names)
    place_matrix = np.zeros((len(place_units), c), bool)
    for pi, su in enumerate(place_units):
        for ci, n in enumerate(view.names):
            place_matrix[pi, ci] = n in su.cluster_names
    placement_ok = place_matrix[place_ids]
    placement_has = np.array([len(su.cluster_names) > 0 for su in units])

    # --- resources ---
    request = np.zeros((b, r), np.int64)
    for i, su in enumerate(units):
        request[i, OF.R_CPU] = su.resource_request.get("cpu", 0)
        request[i, OF.R_MEM] = su.resource_request.get("memory", 0)
        for j, rname in enumerate(view.scalar_resources):
            request[i, OF.NUM_FIXED_RESOURCES + j] = su.resource_request.get(rname, 0)

    # --- per-(object, cluster) policy grids ---
    def grid(get_map, dtype, fill):
        out = np.full((b, c), fill, dtype)
        for i, su in enumerate(units):
            m = get_map(su)
            for cname, v in m.items():
                ci = view.index.get(cname)
                if ci is not None:
                    out[i, ci] = v
        return out

    min_replicas = grid(lambda su: su.min_replicas, np.int32, 0)
    max_replicas = grid(lambda su: su.max_replicas, np.int32, INT32_INF)
    weights = grid(lambda su: su.weights, np.int32, 0)
    capacity = np.full((b, c), INT32_INF, np.int32)
    keep = np.zeros(b, bool)
    for i, su in enumerate(units):
        am = su.auto_migration
        if am is not None:
            keep[i] = am.keep_unschedulable_replicas
            for cname, cap in am.estimated_capacity.items():
                ci = view.index.get(cname)
                if ci is not None and cap >= 0:
                    capacity[i, ci] = cap

    current_mask = np.zeros((b, c), bool)
    current_replicas = np.full((b, c), NIL_REPLICAS, np.int32)
    for i, su in enumerate(units):
        for cname, reps in su.current_clusters.items():
            ci = view.index.get(cname)
            if ci is None:
                continue
            current_mask[i, ci] = True
            if reps is not None:
                current_replicas[i, ci] = reps

    tiebreak = view.tiebreak_rows([su.key for su in units]) if b else np.zeros((0, c), np.int32)

    total = np.array(
        [su.desired_replicas or 0 for su in units], np.int32
    )
    validate_ranges(total, weights.astype(np.int64))
    # Objects without static weights get dynamic RSP weights on device
    # (normalized to sum 1000, plus a rounding residual), so the planner's
    # int32 contract must also hold for an effective weight of ~2000.
    weights_given = np.array([len(su.weights) > 0 for su in units])
    dyn_totals = np.asarray(
        [su.desired_replicas or 0 for su, given in zip(units, weights_given) if not given],
        np.int64,
    )
    if dyn_totals.size and int(dyn_totals.max()) * 2048 >= 2**31:
        worst = max(
            (su for su, given in zip(units, weights_given) if not given),
            key=lambda su: su.desired_replicas or 0,
        )
        raise OverflowError(
            f"desired replicas {worst.desired_replicas} of {worst.key} exceeds "
            f"the planner's int32 range with dynamic weights (max ~1M replicas)"
        )

    inputs = TickInputs(
        filter_enabled=filter_enabled,
        api_ok=api_ok,
        taint_ok_new=taint_ok_new,
        taint_ok_cur=taint_ok_cur,
        selector_ok=selector_ok,
        placement_has=placement_has,
        placement_ok=placement_ok,
        request=request,
        alloc=view.alloc,
        used=view.used,
        score_enabled=score_enabled,
        taint_counts=taint_counts,
        affinity_scores=affinity_scores,
        webhook_ok=webhook_ok,
        webhook_scores=webhook_scores,
        max_clusters=np.array(
            [INT32_INF if su.max_clusters is None else su.max_clusters for su in units],
            np.int32,
        ),
        mode_divide=np.array(
            [su.scheduling_mode == T.MODE_DIVIDE for su in units]
        ),
        sticky=np.array([su.sticky_cluster for su in units]),
        current_mask=current_mask,
        current_replicas=current_replicas,
        total=total,
        weights_given=weights_given,
        weights=weights,
        min_replicas=min_replicas,
        max_replicas=max_replicas,
        scale_max=max_replicas.copy(),
        capacity=capacity,
        keep_unschedulable=keep,
        avoid_disruption=np.array([su.avoid_disruption for su in units]),
        tiebreak=tiebreak.astype(np.int32),
        cpu_alloc=view.cpu_alloc,
        cpu_avail=view.cpu_avail,
        cluster_valid=np.ones(c, bool),
    )
    return FeaturizedBatch(inputs=inputs, units=units, view=view)
