"""Streaming scheduler front-end: coalesced row-slab micro-batches.

The engine's tick loop is batch-shaped — give it the whole pending set,
get the whole decision set.  A live control plane is not: watch/informer
churn arrives as discrete events, and cluster capacity drifts while the
world runs.  This module converts between the two WITHOUT reintroducing
a stop-the-world revalidation edge:

* **Row slabs.**  Object upserts/deletes accumulate into a bounded slab
  (size watermark ``KT_SLAB_ROWS``, age watermark ``KT_SLAB_AGE_MS``).
  A flush applies the slab to the canonical unit list and re-schedules
  through :meth:`SchedulerEngine.schedule`, whose incremental machinery
  featurizes ONLY the changed rows and rides the sub-batch narrow path
  — a flush costs O(slab), not O(world).

* **Column-wise drift absorption.**  Cluster-capacity events swap the
  cluster list; the engine's drift gate diffs the changed columns
  against the device-resident planes and re-solves only the rows whose
  decisions can actually move (most through the sort-free
  ``drift_resolve`` program).  The full-revalidation path is never
  re-entered while the topology holds.

* **Fixed row geometry.**  New objects land in pre-grown placeholder
  slots (inert rows that schedule nowhere) so arrivals do not shift the
  chunk geometry; the placeholder pool grows in blocks when exhausted
  (one amortized tail-chunk re-featurize per block).

Interleaved streaming is bit-identical to a stop-the-world replay of
the same event log by construction: each flush IS an engine tick over
the post-event world, and the engine's incremental paths are certified
exact (tests/test_streaming.py drives the randomized differential).

Knobs: ``KT_SLAB_ROWS`` (default 1024), ``KT_SLAB_AGE_MS`` (default
50), ``KT_SLAB_GROW`` (placeholder block, default 1024).  See
docs/operations.md ("Streaming tick").
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional, Sequence

from kubeadmiral_tpu.models import types as T
from kubeadmiral_tpu.runtime import lockcheck
from kubeadmiral_tpu.runtime import slo as SLO
from kubeadmiral_tpu.runtime import tenancy
from kubeadmiral_tpu.runtime import trace
from kubeadmiral_tpu.runtime.metrics import Metrics, null_metrics

log = logging.getLogger("kubeadmiral.streaming")

# Stream stage/latency buckets (ISSUE 13 satellite): the engine/apply
# stages are ms-scale, but the `queued` stage legitimately reaches
# SECONDS under slab-age coalescing and backpressure — the default
# ladder's 10s top bucket would saturate to +Inf on a backed-up stream
# and percentile interpolation would lose the tail.  One extended
# ladder for the whole family keeps the series comparable while giving
# the queued stage finite buckets out to 120s.
STREAM_STAGE_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

# A gvk no member cluster serves: the row fails the APIResources filter
# everywhere, selects nothing, and carries no policy structure — the
# engine treats it like a padded row that happens to be real.
PLACEHOLDER_GVK = "kubeadmiral.io/v0/SlabPlaceholder"


def make_placeholder(slot: int) -> T.SchedulingUnit:
    """An inert unit occupying one pre-grown row slot."""
    return T.SchedulingUnit(
        gvk=PLACEHOLDER_GVK,
        namespace="__slab__",
        name=f"slot-{slot}",
        scheduling_mode=T.MODE_DUPLICATE,
    )


def is_placeholder(unit: T.SchedulingUnit) -> bool:
    return unit.gvk == PLACEHOLDER_GVK


@dataclass
class _Event:
    kind: str  # "upsert" | "delete" | "capacity"
    payload: object
    t: float


@lockcheck.shared_field_guard
class StreamingScheduler:
    """Always-on front-end over a :class:`SchedulerEngine`.

    Thread-safe for one producer + one pump thread (a lock guards the
    event queue; flushes serialize on the engine's own schedule lock).
    Results for the whole world are exposed as :attr:`results`, aligned
    with :attr:`units`; per-event placement-visible latency is recorded
    to the ``engine_stream_latency_seconds`` histogram and the bounded
    :attr:`latencies` deque (bench percentiles)."""

    # The producer<->pump surface: watch/informer threads append
    # events, the pump drains them (ktlint lock-discipline +
    # runtime/lockcheck.py).  World/result state (_units, results) is
    # pump-thread-only by contract and stays undeclared.
    _shared_fields_ = {"_pending": "_lock"}

    def __init__(
        self,
        engine,
        clusters: Sequence[T.ClusterState],
        units: Sequence[T.SchedulingUnit] = (),
        slab_rows: Optional[int] = None,
        slab_age_ms: Optional[float] = None,
        grow_block: Optional[int] = None,
        metrics: Optional[Metrics] = None,
        follower_index=None,
        clock=time.monotonic,
    ):
        self.engine = engine
        self.metrics = metrics if metrics is not None else (
            getattr(engine, "metrics", None) or null_metrics()
        )
        if slab_rows is None:
            slab_rows = int(os.environ.get("KT_SLAB_ROWS", "1024"))
            # Per-device slab sizing (ISSUE 12): the churn revalidation
            # slabs ride the engine's rows-sharded dispatch, so a meshed
            # engine's row watermark scales with the objects-axis device
            # count — a 1024-row slab spread over 8 devices is 128 rows
            # each, below the padding knee the watermark exists to
            # clear.  An explicit slab_rows arg or KT_SLAB_ROWS is
            # taken verbatim... the env knob scales too (it is the
            # per-device number, like KT_CELL_BUDGET); only the
            # constructor arg is absolute.
            mesh = getattr(engine, "mesh", None)
            if mesh is not None:
                slab_rows *= int(mesh.devices.shape[0])
        self.slab_rows = int(slab_rows)
        self.slab_age_ms = (
            float(os.environ.get("KT_SLAB_AGE_MS", "50"))
            if slab_age_ms is None
            else float(slab_age_ms)
        )
        if grow_block is None:
            env = os.environ.get("KT_SLAB_GROW")
            if env is not None:
                grow_block = int(env)
            else:
                # Grow in whole engine chunks: appending a full chunk of
                # placeholders leaves every existing chunk's cache entry
                # untouched, so a growth step costs ONE tail-chunk
                # featurize instead of re-featurizing the tail chunk on
                # every sub-chunk extension.
                try:
                    grow_block = engine._tick_geometry(len(clusters))[1]
                except Exception:
                    grow_block = getattr(engine, "chunk_size", 1024)
        self.grow_block = max(1, int(grow_block))
        self.follower_index = follower_index
        self.clock = clock
        self._lock = lockcheck.make_lock("streaming")
        self._pending: deque[_Event] = deque()
        self._units: list[T.SchedulingUnit] = list(units)
        self._clusters: list[T.ClusterState] = list(clusters)
        self._row_of: dict[str, int] = {
            u.key: i for i, u in enumerate(self._units)
        }
        self._free: list[int] = [
            i for i, u in enumerate(self._units) if is_placeholder(u)
        ]
        self.results: list = []
        self.flush_stats = {"rows": 0, "age": 0, "manual": 0, "capacity": 0}
        self.events_total = {"upsert": 0, "delete": 0, "capacity": 0}
        self.rows_flushed = 0
        self.flushes = 0
        # Monotonic flush correlation id: stamped on the stream.flush
        # span (with the engine tick id it produced) so /debug/trace
        # shows one connected event -> placement-written timeline.
        self._flush_seq = 0
        self.last_flush_id = 0
        # Delta-featurization hint plumbing: the engine tick counter as
        # of OUR last flush — the dirty-row hint is only sound when no
        # other caller ticked the engine in between (their world would
        # have replaced the cached unit rows the hint promises are
        # unchanged).
        self._last_engine_tick: Optional[int] = None
        # Bounded recent event->placement-visible latencies (seconds).
        self.latencies: deque[float] = deque(maxlen=200_000)

    # -- event ingestion --------------------------------------------------
    def offer(self, unit: T.SchedulingUnit) -> None:
        """Object add/update (a watch upsert)."""
        with trace.hot_span("stream.offer", kind="upsert", key=unit.key):
            with self._lock:
                self._pending.append(_Event("upsert", unit, self.clock()))
                self.events_total["upsert"] += 1
                self._note_depth()

    def remove(self, key: str) -> None:
        """Object delete: the row reverts to an inert placeholder."""
        with trace.hot_span("stream.offer", kind="delete", key=key):
            with self._lock:
                self._pending.append(_Event("delete", key, self.clock()))
                self.events_total["delete"] += 1
                self._note_depth()

    def offer_capacity(self, clusters: Sequence[T.ClusterState]) -> None:
        """Whole-fleet capacity snapshot (cheap: the engine diffs it
        column-wise against the previous view)."""
        with trace.hot_span("stream.offer", kind="capacity"):
            with self._lock:
                self._pending.append(
                    _Event("capacity", list(clusters), self.clock())
                )
                self.events_total["capacity"] += 1
                self._note_depth()

    def update_cluster(self, cluster: T.ClusterState) -> None:
        """Single-member capacity update — the common drift event."""
        with trace.hot_span("stream.offer", kind="capacity", key=cluster.name):
            with self._lock:
                base = self._pending_clusters_locked()
                fleet = [
                    cluster if c.name == cluster.name else c for c in base
                ]
                self._pending.append(_Event("capacity", fleet, self.clock()))
                self.events_total["capacity"] += 1
                self._note_depth()

    def _pending_clusters_locked(self) -> list[T.ClusterState]:
        for ev in reversed(self._pending):
            if ev.kind == "capacity":
                return ev.payload
        return self._clusters

    def _note_depth(self) -> None:
        self.metrics.store("engine_stream_slab_depth", len(self._pending))

    # -- watermarks -------------------------------------------------------
    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def oldest_age(self) -> float:
        """Seconds the oldest pending event has waited (0 when empty)."""
        with self._lock:
            if not self._pending:
                return 0.0
            return self.clock() - self._pending[0].t

    def should_flush(self) -> bool:
        with self._lock:
            if not self._pending:
                return False
            if len(self._pending) >= self.slab_rows:
                return True
            return (
                (self.clock() - self._pending[0].t) * 1e3 >= self.slab_age_ms
            )

    # -- flushing ---------------------------------------------------------
    def pump(self) -> Optional[list]:
        """Flush when a watermark trips; returns the fresh results list
        or None when below both watermarks."""
        with self._lock:
            if not self._pending:
                return None
            by_rows = len(self._pending) >= self.slab_rows
            by_age = (
                (self.clock() - self._pending[0].t) * 1e3 >= self.slab_age_ms
            )
            if not (by_rows or by_age):
                return None
            trigger = "rows" if by_rows else "age"
        return self._flush(trigger)

    def flush(self) -> list:
        """Unconditional flush (empty slab = plain re-tick)."""
        return self._flush("manual")

    def _grow_locked(self, extra: int) -> None:
        base = len(self._units)
        blocks = -(-extra // self.grow_block)
        for i in range(blocks * self.grow_block):
            slot = base + i
            ph = make_placeholder(slot)
            self._units.append(ph)
            self._free.append(slot)
        self.metrics.store("engine_stream_world_rows", len(self._units))

    def _flush(self, trigger: str) -> list:
        t_flush = self.clock()
        with self._lock:
            self._flush_seq += 1
            fid = self._flush_seq
        with trace.span("stream.flush", flush=fid, trigger=trigger) as f_span:
            with self._lock:
                drained = list(self._pending)
                self._pending.clear()
                self.metrics.store("engine_stream_slab_depth", 0)
                had_capacity = False
                world0 = len(self._units)
                dirty: set[int] = set()
                for ev in drained:
                    if ev.kind == "capacity":
                        self._clusters = list(ev.payload)
                        had_capacity = True
                        continue
                    if ev.kind == "delete":
                        row = self._row_of.pop(ev.payload, None)
                        if row is not None:
                            self._units[row] = make_placeholder(row)
                            self._free.append(row)
                            dirty.add(row)
                        continue
                    unit = ev.payload
                    row = self._row_of.get(unit.key)
                    if row is None:
                        if not self._free:
                            self._grow_locked(1)
                        row = self._free.pop()
                        self._row_of[unit.key] = row
                    self._units[row] = unit
                    dirty.add(row)
                # Fresh list: the engine's no-op gate treats the container
                # as immutable (content-identity replays still work).
                units = list(self._units)
                clusters = self._clusters
                # Every pre-grown placeholder row past the previous
                # world length is new to the engine too.
                if len(self._units) > world0:
                    dirty.update(range(world0, len(self._units)))
            # SLO provenance through the slab: upsert events carrying a
            # token close their coalesce ("slab") stage at flush start
            # and their "engine" stage when the solve returns.
            upsert_keys = (
                [ev.payload.key for ev in drained if ev.kind == "upsert"]
                if SLO.active()
                else ()
            )
            SLO.mark_many(upsert_keys, "slab", t_flush)
            t_engine = self.clock()
            # The event log knows EXACTLY which rows moved — hand the
            # engine that set so its featurize identity walk is
            # O(changed), not O(world).  Sound only when this scheduler
            # was also the engine's previous caller (tick counter
            # unchanged since our last flush); anything else falls back
            # to the full walk.
            dirty_rows = (
                sorted(dirty)
                if self._last_engine_tick == self.engine.tick_seq
                else None
            )
            results = self.engine.schedule(
                units, clusters, follower_index=self.follower_index,
                dirty_rows=dirty_rows,
            )
            self._last_engine_tick = self.engine.tick_seq
            now = self.clock()
            SLO.mark_many(upsert_keys, "engine", now)
            tick_id = getattr(self.engine, "last_tick_id", 0)
            # Correlate the flush with the engine tick it produced: the
            # engine.schedule span nests under this one on the thread,
            # and the shared tick id links the /debug/waterfall entry.
            f_span.set(
                events=len(drained), tick=tick_id,
                engine_ms=round((now - t_engine) * 1e3, 3),
            )
            with self._lock:
                self.results = results
                self.flushes += 1
                self.last_flush_id = fid
                n_rows = sum(1 for ev in drained if ev.kind != "capacity")
                self.rows_flushed += n_rows
                self.flush_stats[trigger] = self.flush_stats.get(trigger, 0) + 1
                if had_capacity:
                    self.flush_stats["capacity"] += 1
                m = self.metrics
                m.counter("engine_stream_flushes_total", trigger=trigger)
                # Stage-decomposed event latency: how long events sat
                # coalescing in the slab vs the engine solve itself vs
                # the publish bookkeeping — the split the e2e p99 budget
                # is tuned against (docs/observability.md).
                m.histogram(
                    "engine_stream_stage_seconds",
                    max(0.0, t_engine - t_flush),
                    buckets=STREAM_STAGE_BUCKETS,
                    stage="apply",
                )
                m.histogram(
                    "engine_stream_stage_seconds",
                    max(0.0, now - t_engine),
                    buckets=STREAM_STAGE_BUCKETS,
                    stage="engine",
                )
                for ev in drained:
                    m.counter("engine_stream_events_total", kind=ev.kind)
                    lat = now - ev.t
                    m.histogram(
                        "engine_stream_latency_seconds", lat,
                        buckets=STREAM_STAGE_BUCKETS,
                    )
                    m.histogram(
                        "engine_stream_stage_seconds",
                        max(0.0, t_flush - ev.t),
                        buckets=STREAM_STAGE_BUCKETS,
                        stage="queued",
                    )
                    self.latencies.append(lat)
                m.store("engine_stream_slab_rows", n_rows)
                m.histogram(
                    "engine_stream_flush_seconds", now - t_flush
                )
            # Per-tenant flush accounting (runtime/tenancy.py; no-op
            # unless a ledger is installed) — outside the slab lock: the
            # ledger takes its own lock and needs nothing of ours.
            if tenancy.active():
                by_tenant: dict[str, int] = {}
                for ev in drained:
                    if ev.kind == "capacity":
                        continue
                    t_name = tenancy.tenant_of_key(
                        getattr(ev.payload, "key", "") or ""
                    )
                    by_tenant[t_name] = by_tenant.get(t_name, 0) + 1
                for t_name, rows in by_tenant.items():
                    tenancy.note_flush(t_name, rows)
        if log.isEnabledFor(logging.DEBUG):
            log.debug(
                "flush=%d tick=%d trigger=%s events=%d rows=%d "
                "capacity=%s engine_ms=%.1f",
                fid, tick_id, trigger, len(drained), n_rows, had_capacity,
                (now - t_engine) * 1e3,
            )
        return results

    def drain(self, deadline_s: float = 5.0) -> Optional[list]:
        """Graceful-shutdown drain: flush whatever is coalescing in the
        slab so the final pre-exit snapshot describes the post-event
        world, bounded by ``deadline_s`` (a flush that cannot finish in
        budget is abandoned — the events are NOT lost, they are already
        reflected in the canonical unit list and the successor's relist
        re-derives them).  Returns the final results list, or None when
        nothing was pending."""
        with self._lock:
            if not self._pending:
                return None
        done: list = []

        def run():
            try:
                done.append(self._flush("manual"))
            except Exception:
                log.warning("shutdown drain flush failed", exc_info=True)

        t = threading.Thread(target=run, name="stream-drain", daemon=True)
        t.start()
        t.join(max(0.0, deadline_s))
        if t.is_alive():
            log.warning(
                "shutdown drain exceeded %.1fs; abandoning the in-flight "
                "flush (successor relist re-derives the slab)", deadline_s,
            )
            return None
        return done[0] if done else None

    # -- introspection ----------------------------------------------------
    @property
    def units(self) -> list[T.SchedulingUnit]:
        with self._lock:
            return list(self._units)

    @property
    def clusters(self) -> list[T.ClusterState]:
        with self._lock:
            return list(self._clusters)

    def result_of(self, key: str):
        """The current placement of one object (None when unknown)."""
        with self._lock:
            row = self._row_of.get(key)
            if row is None or row >= len(self.results):
                return None
            return self.results[row]
