"""ExtensionService: host a scheduler webhook plugin over HTTP.

The serving side of the webhook protocol (reference precedent:
examples/scheduler/webhook/main.go — a standalone HTTP server
implementing filter/score/select against the JSON payload schema of
pkg/apis/schedulerwebhook/v1alpha1).  Plug in Python callables:

    service = ExtensionService(
        filter_fn=lambda req: {"selected": ...},
        score_fn=lambda req: {"score": ...},
        select_fn=lambda req: {"selectedClusterNames": [...]},
    )
    port = service.start()

Each callable receives the decoded request dict ({schedulingUnit,
cluster} for filter/score, {schedulingUnit, clusterScores} for select)
and returns the response dict; raising maps to the protocol's ``error``
field.  This is also how a TPU-backed scoring sidecar is exposed to a
non-TPU control plane: run the engine inside ``score_fn``/``select_fn``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

Handler = Callable[[dict], dict]


def _batch_wrap(fn: Handler, pair_field: str, batch_field: str) -> Handler:
    """Serve the batch protocol on top of a per-pair handler: the whole
    (units x clusters) grid is evaluated server-side in ONE HTTP round
    trip instead of O(B x C) requests."""

    def handler(request: dict) -> dict:
        units = request.get("schedulingUnits", [])
        clusters = request.get("clusters", [])
        rows = [
            [
                fn({"schedulingUnit": su, "cluster": cluster}).get(pair_field)
                for cluster in clusters
            ]
            for su in units
        ]
        return {batch_field: rows}

    return handler


class ExtensionService:
    FILTER_PATH = "/filter"
    SCORE_PATH = "/score"
    SELECT_PATH = "/select"
    FILTER_BATCH_PATH = "/filter-batch"
    SCORE_BATCH_PATH = "/score-batch"

    def __init__(
        self,
        filter_fn: Optional[Handler] = None,
        score_fn: Optional[Handler] = None,
        select_fn: Optional[Handler] = None,
        filter_batch_fn: Optional[Handler] = None,
        score_batch_fn: Optional[Handler] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        serve_batch: bool = True,
        tls_cert_file: str = "",
        tls_key_file: str = "",
        tls_client_ca_file: str = "",
    ):
        """With ``serve_batch`` (default), per-pair handlers also serve
        their "-batch" sibling endpoints; pass explicit
        ``filter_batch_fn``/``score_batch_fn`` for a vectorized
        implementation (e.g. a TPU-backed scorer evaluating the whole
        grid in one dispatch).  ``serve_batch=False`` emulates a
        reference-protocol server (per-pair endpoints only)."""
        self.handlers: dict[str, Handler] = {}
        if filter_fn:
            self.handlers[self.FILTER_PATH] = filter_fn
        if score_fn:
            self.handlers[self.SCORE_PATH] = score_fn
        if select_fn:
            self.handlers[self.SELECT_PATH] = select_fn
        if filter_batch_fn:
            self.handlers[self.FILTER_BATCH_PATH] = filter_batch_fn
        elif filter_fn and serve_batch:
            self.handlers[self.FILTER_BATCH_PATH] = _batch_wrap(
                filter_fn, "selected", "selected"
            )
        if score_batch_fn:
            self.handlers[self.SCORE_BATCH_PATH] = score_batch_fn
        elif score_fn and serve_batch:
            self.handlers[self.SCORE_BATCH_PATH] = _batch_wrap(
                score_fn, "score", "scores"
            )
        self._host = host
        self._port = port
        # TLS serving (the server half of the webhook TLSConfig round
        # trip): cert+key enable https; a client CA additionally demands
        # a client certificate (mTLS, TLSConfig.CertData/KeyData).
        self._tls_cert_file = tls_cert_file
        self._tls_key_file = tls_key_file
        self._tls_client_ca_file = tls_client_ca_file
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def url_prefix(self) -> str:
        assert self._server is not None, "service not started"
        scheme = "https" if self._tls_cert_file else "http"
        return f"{scheme}://{self._host}:{self._server.server_address[1]}"

    def start(self) -> int:
        handlers = self.handlers

        class RequestHandler(BaseHTTPRequestHandler):
            def do_POST(self) -> None:  # noqa: N802 (http.server API)
                handler = handlers.get(self.path)
                if handler is None:
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    request = json.loads(self.rfile.read(length) or b"{}")
                    response = handler(request)
                except Exception as e:  # -> protocol error field
                    response = {"error": str(e)}
                body = json.dumps(response).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:  # quiet
                pass

        self._server = ThreadingHTTPServer((self._host, self._port), RequestHandler)
        if self._tls_cert_file:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self._tls_cert_file, self._tls_key_file or None)
            if self._tls_client_ca_file:
                ctx.load_verify_locations(self._tls_client_ca_file)
                ctx.verify_mode = ssl.CERT_REQUIRED
            # Handshake on the HANDLER thread, not in accept(): with
            # do_handshake_on_connect a stalled client (port scanner,
            # plain-HTTP probe) would block the single accept loop and
            # starve every other webhook call.
            self._server.socket = ctx.wrap_socket(
                self._server.socket,
                server_side=True,
                do_handshake_on_connect=False,
            )

            server = self._server

            class _HandshakeHandler(RequestHandler):
                def setup(self) -> None:
                    # self.request is the raw (wrapped, un-handshaken)
                    # SSL socket; self.connection only exists after
                    # super().setup().
                    self.request.settimeout(10.0)
                    self.request.do_handshake()
                    super().setup()

            server.RequestHandlerClass = _HandshakeHandler
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="extension-service", daemon=True
        )
        self._thread.start()
        return self._server.server_address[1]

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def webhook_configuration(self, name: str) -> dict:
        """The SchedulerPluginWebhookConfiguration object registering this
        service (for tests and local setups)."""
        spec: dict = {
            "urlPrefix": self.url_prefix,
            "payloadVersions": ["v1alpha1"],
        }
        if self.FILTER_PATH in self.handlers:
            spec["filterPath"] = self.FILTER_PATH
        if self.SCORE_PATH in self.handlers:
            spec["scorePath"] = self.SCORE_PATH
        if self.SELECT_PATH in self.handlers:
            spec["selectPath"] = self.SELECT_PATH
        return {
            "apiVersion": "core.kubeadmiral.io/v1alpha1",
            "kind": "SchedulerPluginWebhookConfiguration",
            "metadata": {"name": name},
            "spec": spec,
        }
