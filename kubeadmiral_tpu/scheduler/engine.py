"""The scheduling engine: batch in, placements out.

Public entry point for the control plane: take every pending
SchedulingUnit, featurize against the current member clusters, run the
fused XLA tick (chunked over the object axis to bound device memory and
shape-bucketed to bound recompiles), and decode placements.

Where the reference schedules one object at a time inside worker
goroutines (reference: pkg/controllers/scheduler/scheduler.go:246-521),
this engine schedules the whole pending set per tick in O(B/chunk)
device dispatches.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeadmiral_tpu.models import types as T
from kubeadmiral_tpu.ops.pipeline import NIL_REPLICAS, TickInputs, schedule_tick
from kubeadmiral_tpu.scheduler.featurize import (
    ClusterView,
    FeaturizedBatch,
    featurize,
    featurize_signature,
)

# TickInputs fields carrying cluster-axis-only state: always taken from
# the freshest ClusterView (resource drift must never hit the cache).
_CLUSTER_ONLY_FIELDS = ("alloc", "used", "cpu_alloc", "cpu_avail", "cluster_valid")

# Duplicate-mode placements carry no replica count.
DUPLICATE = None


@dataclass
class ScheduleResult:
    """Placement decision for one object: cluster -> replicas (None in
    Duplicate mode), mirroring core.ScheduleResult.SuggestedClusters.
    ``scores`` carries the post-normalize totals of the selected clusters
    (consumed by webhook select plugins)."""

    clusters: dict[str, Optional[int]]
    scores: dict[str, int] = field(default_factory=dict)

    @property
    def cluster_set(self) -> set[str]:
        return set(self.clusters)


def _round_up(n: int, step: int) -> int:
    return max(step, ((n + step - 1) // step) * step)


def _pad_batch(inputs: TickInputs, b_pad: int) -> TickInputs:
    """Pad the object axis with inert rows (no members, Duplicate mode)."""
    b = inputs.total.shape[0]
    if b == b_pad:
        return inputs
    extra = b_pad - b

    def pad(x, fill):
        shape = (extra,) + x.shape[1:]
        return np.concatenate([x, np.full(shape, fill, x.dtype)])

    per_object_fill = {
        "filter_enabled": False,
        "api_ok": False,
        "taint_ok_new": False,
        "taint_ok_cur": False,
        "selector_ok": False,
        "placement_has": False,
        "placement_ok": False,
        "request": 0,
        "score_enabled": False,
        "taint_counts": 0,
        "affinity_scores": 0,
        "webhook_ok": True,
        "webhook_scores": 0,
        "max_clusters": 0,
        "mode_divide": False,
        "sticky": False,
        "current_mask": False,
        "current_replicas": NIL_REPLICAS,
        "total": 0,
        "weights_given": True,
        "weights": 0,
        "min_replicas": 0,
        "max_replicas": np.iinfo(np.int32).max,
        "scale_max": np.iinfo(np.int32).max,
        "capacity": np.iinfo(np.int32).max,
        "keep_unschedulable": False,
        "avoid_disruption": False,
        "tiebreak": 0,
    }
    fields = {}
    for name, arr in inputs._asdict().items():
        if name in per_object_fill:
            fields[name] = pad(np.asarray(arr), per_object_fill[name])
        else:
            fields[name] = arr  # cluster-axis tensors are shared
    return TickInputs(**fields)


# Fill values for padded cluster slots, per [.., C, ..] field.
_CLUSTER_AXIS_FILL = {
    "api_ok": False,
    "taint_ok_new": False,
    "taint_ok_cur": False,
    "selector_ok": False,
    "placement_ok": False,
    "taint_counts": 0,
    "affinity_scores": 0,
    "webhook_ok": True,
    "webhook_scores": 0,
    "current_mask": False,
    "current_replicas": NIL_REPLICAS,
    "weights": 0,
    "min_replicas": 0,
    "max_replicas": np.iinfo(np.int32).max,
    "scale_max": np.iinfo(np.int32).max,
    "capacity": np.iinfo(np.int32).max,
    "tiebreak": 0,
    "alloc": 0,
    "used": 0,
    "cpu_alloc": 0,
    "cpu_avail": 0,
    "cluster_valid": False,
}


def _pad_clusters(inputs: TickInputs, c_pad: int) -> TickInputs:
    """Pad the cluster axis with invalid slots (cluster_valid=False)."""
    c = inputs.cluster_valid.shape[0]
    if c == c_pad:
        return inputs
    extra = c_pad - c
    fields = {}
    for name, arr in inputs._asdict().items():
        fill = _CLUSTER_AXIS_FILL.get(name)
        if fill is None:
            fields[name] = arr
            continue
        arr = np.asarray(arr)
        # The cluster axis is the first axis for [C]/[C,R] tensors and the
        # second for [B,C] tensors.
        axis = 0 if name in ("alloc", "used", "cpu_alloc", "cpu_avail", "cluster_valid") else 1
        pad_shape = list(arr.shape)
        pad_shape[axis] = extra
        fields[name] = np.concatenate(
            [arr, np.full(pad_shape, fill, arr.dtype)], axis=axis
        )
    return TickInputs(**fields)


def _pow2_bucket(n: int, minimum: int, cap: int) -> int:
    b = minimum
    while b < n:
        b *= 2
    return min(b, max(cap, minimum))


def _cluster_bucket(n: int, minimum: int) -> int:
    """Cluster-axis bucket: power-of-two up to 512, then the next
    multiple of 512 — 5k clusters must not pad to 8192 (pow2 padding
    wastes up to 2x compute AND compile time on the widest axis)."""
    if n <= 512:
        return _pow2_bucket(n, minimum, 1 << 30)
    return ((n + 511) // 512) * 512


@dataclass
class _CachedChunk:
    """A previous tick's featurized chunk, patchable row-by-row."""

    sigs: list
    units: list  # identity fast-path: `is`-compare before sig-compare
    inputs: TickInputs
    topo_fp: tuple
    nbytes: int
    # Device-resident copies of the padded per-object tensors: a clean
    # re-tick skips the host->device transfer entirely (the dominant
    # cost over a tunneled TPU backend).
    device_per_object: Optional[dict] = None
    padded_shape: Optional[tuple] = None
    # Previous tick's outputs (device) + decoded results (host) for the
    # delta fetch: unchanged rows are never pulled off the device again.
    prev_out: Optional[tuple] = None
    prev_results: Optional[list] = None
    # The ClusterView those results were computed against: identical
    # view + clean hit = identical outputs, no dispatch needed at all.
    prev_view: Optional[object] = None
    # (changed row indices, their featurized rows) from the last patch,
    # consumed once by schedule()'s sub-batch fast path.
    last_patch: Optional[tuple] = None


# jit helpers for the delta fetch -------------------------------------
@jax.jit
def _tick_with_delta(inp: TickInputs, psel, prep, pcnt):
    """The fused tick plus an on-device diff against the previous tick's
    outputs, in ONE dispatch: over a high-latency link (the tunneled TPU
    backend) every dispatch costs a round trip, so the changed-rows mask
    ships with the tick instead of as a follow-up program."""
    out = schedule_tick.__wrapped__(inp)
    diff = (out.selected != psel) | (out.replicas != prep) | (out.counted != pcnt)
    return out, diff.any(axis=1).astype(jnp.int8)


@jax.jit
def _gather_rows(sel, rep, cnt, idx):
    return sel[idx], rep[idx], cnt[idx]


@jax.jit
def _tick_packed(inp: TickInputs):
    """The fused tick with its three placement outputs packed into ONE
    int32 array: over a high-latency link each device->host transfer
    costs a round trip, and the sub-batch path's outputs are tiny, so
    one packed fetch beats three small ones."""
    out = schedule_tick.__wrapped__(inp)
    return jnp.concatenate(
        [
            out.selected.astype(jnp.int32),
            out.replicas,
            out.counted.astype(jnp.int32),
        ],
        axis=1,
    )


class SchedulerEngine:
    """Chunked, shape-bucketed driver around ops.pipeline.schedule_tick.

    Featurization is incremental across ticks: each chunk's assembled
    TickInputs is cached keyed by per-unit featurize signatures (the
    tensor analogue of the reference's scheduling-trigger hash,
    schedulingtriggers.go:106-148) and the cluster topology; a
    steady-state re-tick with 1% churn re-featurizes only the changed
    rows and memcpy-patches them into the cached arrays.  Cluster
    *resources* (the fast-drifting part) live in cluster-axis tensors
    taken fresh from the ClusterView every tick, so they never
    invalidate cached rows."""

    def __init__(
        self,
        chunk_size: int = 4096,
        min_bucket: int = 64,
        min_cluster_bucket: int = 8,
        cache_bytes: int = 16 << 30,
        cell_budget: int = 4096 * 512,
    ):
        self.chunk_size = chunk_size
        # XLA compile time for the fused tick grows with the b x C cell
        # count (measured on TPU: [8,2048] 42s, [1024,2048] 373s), while
        # execution stays ~0.1s; bounding cells per chunk keeps compiles
        # tractable at 2k-5k clusters and the steady-state sub-batch path
        # shares the same (small) program.
        self.cell_budget = cell_budget
        self.min_bucket = min_bucket
        self.min_cluster_bucket = min_cluster_bucket
        self._view_cache: tuple[Optional[tuple], Optional[ClusterView]] = (None, None)
        self.cache_bytes = cache_bytes
        self._chunk_cache: dict[int, _CachedChunk] = {}
        self._cache_used = 0
        self.cache_stats = {"hit": 0, "patch": 0, "miss": 0}
        # Fetch path counters: "noop" = dispatch skipped entirely
        # (identical inputs), "subbatch" = only changed rows scheduled
        # (row independence), "skip" = no rows changed (mask only),
        # "delta" = changed rows gathered, "full" = whole chunk pulled.
        self.fetch_stats = {"noop": 0, "subbatch": 0, "skip": 0, "delta": 0, "full": 0}
        # Per-stage wall time of the last schedule() call: featurize
        # (host encoding), device (dispatch + on-device compute, incl.
        # host->device input transfer), fetch (device->host result
        # transfer), decode (placement dict construction).
        self.timings: dict[str, float] = {}

    @staticmethod
    def _cluster_fingerprint(clusters, scalar_resources: tuple) -> tuple:
        return (
            tuple(
                (
                    c.name,
                    tuple(sorted(c.labels.items())),
                    c.taints,
                    tuple(sorted(c.allocatable.items())),
                    tuple(sorted(c.available.items())),
                    c.api_resources,
                )
                for c in clusters
            ),
            scalar_resources,
        )

    def _cached_view(self, units, clusters) -> ClusterView:
        """Reuse the per-tick cluster tensors (and the tie-break hash cache,
        which is the expensive part) while cluster state is unchanged."""
        scalars = tuple(
            sorted(
                {
                    r
                    for su in units
                    for r in su.resource_request
                    if r not in ("cpu", "memory", "ephemeral-storage")
                }
            )
        )
        fp = self._cluster_fingerprint(clusters, scalars)
        cached_fp, cached_view = self._view_cache
        if cached_fp == fp and cached_view is not None:
            return cached_view
        from kubeadmiral_tpu.scheduler.featurize import _build_cluster_view

        view = _build_cluster_view(clusters, units)
        # Tie-break hashes depend only on the cluster-name list, which
        # changes far less often than resource usage: carry the FNV cache
        # across view rebuilds so steady-state resource updates don't
        # re-hash every (object, cluster) pair.
        if cached_view is not None and cached_view.names == view.names:
            view._tiebreak_cache = cached_view._tiebreak_cache
        self._view_cache = (fp, view)
        return view

    @staticmethod
    def _topo_fingerprint(view: ClusterView) -> tuple:
        """Cluster-topology identity: everything cached rows depend on
        (names, taints, labels, api resources, scalar columns) but NOT
        resource quantities, which flow through cluster-axis tensors."""
        fp = getattr(view, "_topo_fp", None)
        if fp is None:
            fp = (
                tuple(view.names),
                tuple(view.taint_sets),
                view.taint_id.tobytes(),
                tuple(view.label_keys),
                view.label_id.tobytes(),
                tuple(frozenset(c.api_resources) for c in view.clusters),
                tuple(view.scalar_resources),
            )
            view._topo_fp = fp
        return fp

    def _featurize_chunk(
        self, idx: int, chunk, clusters, view: ClusterView, webhook_eval
    ) -> tuple[FeaturizedBatch, str, Optional[_CachedChunk]]:
        """Returns (batch, status, cache entry); status is one of
        "hit" (rows unchanged), "patch" (few rows re-featurized),
        "miss" (full featurize), "nocache" (caching not applicable)."""
        if webhook_eval is not None:
            # Webhook planes are per-tick HTTP results; never cached.
            fb = featurize(chunk, clusters, view=view, webhook_eval=webhook_eval)
            return fb, "nocache", None

        topo_fp = self._topo_fingerprint(view)
        cached = self._chunk_cache.get(idx)
        sigs = None
        if (
            cached is not None
            and cached.topo_fp == topo_fp
            and len(cached.units) == len(chunk)
        ):
            # Identity fast-path: the controller hands the engine freshly
            # built (effectively immutable) SchedulingUnits; identical
            # objects mean identical rows without computing signatures.
            if all(a is b for a, b in zip(chunk, cached.units)):
                changed = []
            else:
                sigs = [featurize_signature(su) for su in chunk]
                changed = [i for i, s in enumerate(sigs) if s != cached.sigs[i]]
            refreshed = cached.inputs._replace(
                alloc=view.alloc,
                used=view.used,
                cpu_alloc=view.cpu_alloc,
                cpu_avail=view.cpu_avail,
            )
            cached.inputs = refreshed
            if not changed:
                cached.units = list(chunk)
                self.cache_stats["hit"] += 1
                return (
                    FeaturizedBatch(inputs=refreshed, units=list(chunk), view=view),
                    "hit",
                    cached,
                )
            if len(changed) <= max(1, len(chunk) // 4):
                sub = featurize(
                    [chunk[i] for i in changed], clusters, view=view
                )
                rows = np.asarray(changed)
                for name, arr in refreshed._asdict().items():
                    if name in _CLUSTER_ONLY_FIELDS:
                        continue
                    np.asarray(arr)[rows] = np.asarray(getattr(sub.inputs, name))
                for i in changed:
                    cached.sigs[i] = sigs[i]
                cached.units = list(chunk)
                # Handed to schedule(): the freshly featurized changed
                # rows enable the sub-batch fast path (row independence).
                cached.last_patch = (changed, sub.inputs)
                self.cache_stats["patch"] += 1
                return (
                    FeaturizedBatch(inputs=refreshed, units=list(chunk), view=view),
                    "patch",
                    cached,
                )

        fb = featurize(chunk, clusters, view=view)
        self.cache_stats["miss"] += 1
        if cached is not None:
            self._cache_used -= cached.nbytes
            del self._chunk_cache[idx]
        host_bytes = sum(
            np.asarray(arr).nbytes
            for name, arr in fb.inputs._asdict().items()
            if name not in _CLUSTER_ONLY_FIELDS
        )
        # Budget charge covers everything the entry pins, not just the
        # host arrays: a device-resident copy of the (padded, so up to
        # 2x along each axis) per-object tensors, plus the previous
        # tick's device outputs (i8+i32+i8 = 6 bytes/cell).  Decoded
        # result dicts are small relative to the tensor planes.
        b = len(chunk)
        c = np.asarray(fb.inputs.api_ok).shape[1]
        nbytes = host_bytes * 3 + b * c * 6 * 4
        entry = None
        if self._cache_used + nbytes <= self.cache_bytes:
            if sigs is None:
                sigs = [featurize_signature(su) for su in chunk]
            entry = _CachedChunk(
                sigs=sigs,
                units=list(chunk),
                inputs=fb.inputs,
                topo_fp=topo_fp,
                nbytes=nbytes,
            )
            self._chunk_cache[idx] = entry
            self._cache_used += nbytes
        return fb, "miss", entry

    def schedule(
        self,
        units: Sequence[T.SchedulingUnit],
        clusters: Sequence[T.ClusterState],
        view: Optional[ClusterView] = None,
        webhook_eval=None,
        want_scores: bool = False,
    ) -> list[ScheduleResult]:
        """``want_scores`` additionally decodes per-cluster score dicts
        (only webhook select plugins consume them; decoding hundreds of
        placements per Duplicate-mode object is the engine's main
        host-side cost, so it's opt-in)."""
        units = list(units)
        if not units:
            return []
        if view is None:
            view = self._cached_view(units, clusters)
        # One chunk at a time: dispatching all chunks before pulling
        # measured SLOWER on the tunneled TPU backend (transfers queue
        # behind every outstanding program), so keep dispatch->pull
        # strictly sequential per chunk.
        chunk_results: list[Optional[list[ScheduleResult]]] = []
        pending_sub: list[tuple[int, _CachedChunk, list[int], TickInputs]] = []
        timings = {"featurize": 0.0, "device": 0.0, "fetch": 0.0, "decode": 0.0}
        self.timings = timings
        # Cell-budget chunking: compile time grows with b x C, so wide
        # cluster axes get proportionally shorter chunks (the sub-batch
        # fast path then shares the same small program).
        c_bucket = _cluster_bucket(len(view.clusters), self.min_cluster_bucket)
        max_rows = max(self.min_bucket, self.cell_budget // max(1, c_bucket))
        eff_chunk = min(self.chunk_size, 1 << (max_rows.bit_length() - 1))
        for chunk_idx, start in enumerate(range(0, len(units), eff_chunk)):
            chunk = units[start : start + eff_chunk]
            t0 = time.perf_counter()
            fb, status, entry = self._featurize_chunk(
                chunk_idx, chunk, clusters, view, webhook_eval
            )
            patch_info = None
            if entry is not None:
                patch_info, entry.last_patch = entry.last_patch, None

            # No-op shortcut: a clean cache hit against the very same
            # cluster view is byte-identical input — the deterministic
            # tick would reproduce the previous outputs, so skip the
            # dispatch entirely (the engine-level analogue of the
            # reference's trigger-hash skip, schedulingtriggers.go:64-67).
            prev_valid = (
                not want_scores
                and entry is not None
                and entry.prev_results is not None
                and entry.prev_view is view
                and len(entry.prev_results) == len(chunk)
            )
            if status == "hit" and prev_valid:
                self.fetch_stats["noop"] += 1
                timings["featurize"] += time.perf_counter() - t0
                t3 = time.perf_counter()
                chunk_results.append(
                    [
                        ScheduleResult(dict(r.clusters), dict(r.scores))
                        for r in entry.prev_results
                    ]
                )
                timings["decode"] += time.perf_counter() - t3
                continue

            # Sub-batch fast path: the tick is row-independent (every
            # object's outputs depend only on its own row + the shared
            # cluster tensors), so when ONLY rows changed and the
            # cluster view is identical, scheduling just those rows and
            # merging is exact — O(changed) device work and transfer
            # instead of O(chunk).
            if status == "patch" and prev_valid and patch_info is not None:
                changed_rows, sub_inputs = patch_info
                pending_sub.append(
                    (len(chunk_results), entry, changed_rows, sub_inputs)
                )
                chunk_results.append(None)  # filled by the sub-batch pass
                self.fetch_stats["subbatch"] += 1
                timings["featurize"] += time.perf_counter() - t0
                continue

            padded = _pad_batch(
                fb.inputs, _pow2_bucket(len(chunk), self.min_bucket, eff_chunk)
            )
            n_clusters = padded.cluster_valid.shape[0]
            padded = _pad_clusters(
                padded, _cluster_bucket(n_clusters, self.min_cluster_bucket)
            )
            t1 = time.perf_counter()
            timings["featurize"] += t1 - t0
            device_in = self._device_inputs(entry, padded, status)
            out_shape = np.asarray(padded.api_ok).shape
            delta_ok = (
                not want_scores
                and entry is not None
                and entry.prev_out is not None
                and entry.prev_results is not None
                and len(entry.prev_results) == len(chunk)
                and entry.prev_out[0].shape == out_shape
            )
            if delta_ok:
                out, mask_dev = _tick_with_delta(device_in, *entry.prev_out)
            else:
                out, mask_dev = schedule_tick(device_in), None
            jax.block_until_ready(out)
            t2 = time.perf_counter()
            timings["device"] += t2 - t1
            chunk_results.append(
                self._fetch_decode(
                    entry,
                    out,
                    mask_dev,
                    fb.view.names,
                    len(chunk),
                    want_scores,
                    timings,
                    view,
                )
            )

        if pending_sub:
            self._run_sub_batch(pending_sub, chunk_results, view, timings)

        results: list[ScheduleResult] = []
        for part in chunk_results:
            results.extend(part)
        return results

    def _run_sub_batch(self, pending, chunk_results, view, timings) -> None:
        """One small dispatch for every changed row across all patched
        chunks; results merge into the cached decodes."""
        t0 = time.perf_counter()
        per_object = [
            name for name in TickInputs._fields if name not in _CLUSTER_ONLY_FIELDS
        ]
        combined = {
            name: np.concatenate(
                [np.asarray(getattr(sub, name)) for _, _, _, sub in pending]
            )
            for name in per_object
        }
        c = len(view.names)
        inputs = TickInputs(
            **combined,
            alloc=view.alloc,
            used=view.used,
            cpu_alloc=view.cpu_alloc,
            cpu_avail=view.cpu_avail,
            cluster_valid=np.ones(c, bool),
        )
        total = inputs.total.shape[0]
        # Uncapped bucket: the combined changed rows of many chunks can
        # exceed chunk_size (bounded by sum of len(chunk)//4).
        padded = _pad_batch(
            inputs, _pow2_bucket(total, self.min_bucket, 1 << 30)
        )
        padded = _pad_clusters(
            padded, _cluster_bucket(c, self.min_cluster_bucket)
        )
        t1 = time.perf_counter()
        timings["featurize"] += t1 - t0
        packed_dev = _tick_packed(padded)
        jax.block_until_ready(packed_dev)
        t2 = time.perf_counter()
        timings["device"] += t2 - t1
        packed = np.asarray(packed_dev)[:total]
        c_pad = packed.shape[1] // 3
        selected = packed[:, :c_pad]
        replicas = packed[:, c_pad : 2 * c_pad]
        counted = packed[:, 2 * c_pad :]
        t3 = time.perf_counter()
        timings["fetch"] += t3 - t2
        decoded = self._decode_rows(selected, replicas, counted, view.names)
        offset = 0
        for slot, entry, changed_rows, _sub in pending:
            merged = list(entry.prev_results)
            for j, row in enumerate(changed_rows):
                merged[row] = decoded[offset + j]
            offset += len(changed_rows)
            entry.prev_results = merged
            entry.prev_view = view
            # The device input copy is stale for the patched rows, and
            # prev_out no longer matches prev_results (the delta path's
            # baseline invariant) — drop both; the next full dispatch
            # re-uploads and does a full fetch.
            entry.device_per_object = None
            entry.prev_out = None
            chunk_results[slot] = [
                ScheduleResult(dict(r.clusters), dict(r.scores)) for r in merged
            ]
        timings["decode"] += time.perf_counter() - t3

    def _device_inputs(
        self, entry: Optional[_CachedChunk], padded: TickInputs, status: str
    ) -> TickInputs:
        """Per-object tensors live on device across ticks: a clean re-tick
        ("hit") reuses last tick's device buffers and transfers nothing
        but the (tiny) cluster-axis tensors.  Patched or fresh chunks are
        re-uploaded and re-cached."""
        fields = padded._asdict()
        per_object = {
            name: arr
            for name, arr in fields.items()
            if name not in _CLUSTER_ONLY_FIELDS
        }
        shape = np.asarray(padded.api_ok).shape
        if (
            entry is not None
            and status == "hit"
            and entry.device_per_object is not None
            and entry.padded_shape == shape
        ):
            per_object = entry.device_per_object
        else:
            per_object = jax.device_put(per_object)
            if entry is not None:
                entry.device_per_object = per_object
                entry.padded_shape = shape
        return TickInputs(
            **per_object,
            **{name: fields[name] for name in _CLUSTER_ONLY_FIELDS},
        )

    def _decode_rows(
        self, selected, replicas, counted, names, scores=None
    ) -> list[ScheduleResult]:
        """Vectorized decode: one nonzero over the rows, then per-row
        dict(zip(...)) at C speed — no per-placement Python."""
        rows, cols = np.nonzero(selected)
        bounds = np.searchsorted(rows, np.arange(selected.shape[0] + 1))
        reps_obj = replicas[rows, cols].astype(object)
        reps_obj[counted[rows, cols] == 0] = DUPLICATE
        names_arr = np.asarray(names, dtype=object)
        sel_names = names_arr[cols].tolist()
        reps_list = reps_obj.tolist()
        score_list = scores[rows, cols].tolist() if scores is not None else None
        out = []
        for i in range(selected.shape[0]):
            s, e = bounds[i], bounds[i + 1]
            out.append(
                ScheduleResult(
                    clusters=dict(zip(sel_names[s:e], reps_list[s:e])),
                    scores=dict(zip(sel_names[s:e], score_list[s:e]))
                    if score_list is not None
                    else {},
                )
            )
        return out

    def _fetch_decode(
        self, entry, out, mask_dev, names, n: int, want_scores: bool, timings, view
    ) -> list[ScheduleResult]:
        """Pull results off the device — as a delta against the previous
        tick when possible: the on-device row diff (i8[B] mask computed
        inside the tick dispatch, a few KB to fetch) decides which rows
        to gather, so a steady-state tick transfers near-nothing
        (VERDICT r1 #6; the device-side analogue of the reference's
        trigger-hash skip)."""
        t2 = time.perf_counter()
        if mask_dev is not None:
            mask = np.asarray(mask_dev)[:n]
            idx = np.nonzero(mask)[0]
            if idx.size <= max(16, n // 4):
                new_out = (out.selected, out.replicas, out.counted)
                if idx.size == 0:
                    self.fetch_stats["skip"] += 1
                    merged = entry.prev_results
                else:
                    self.fetch_stats["delta"] += 1
                    k = _pow2_bucket(idx.size, 16, 1 << 30)
                    padded_idx = np.zeros(k, np.int32)
                    padded_idx[: idx.size] = idx
                    sel_k, rep_k, cnt_k = _gather_rows(
                        out.selected, out.replicas, out.counted, padded_idx
                    )
                    sel_k = np.asarray(sel_k)[: idx.size]
                    rep_k = np.asarray(rep_k)[: idx.size]
                    cnt_k = np.asarray(cnt_k)[: idx.size]
                    t3 = time.perf_counter()
                    timings["fetch"] += t3 - t2
                    changed_results = self._decode_rows(sel_k, rep_k, cnt_k, names)
                    merged = list(entry.prev_results)
                    for row, res in zip(idx.tolist(), changed_results):
                        merged[row] = res
                    entry.prev_out = new_out
                    entry.prev_results = merged
                    entry.prev_view = view
                    out_copy = [
                        ScheduleResult(dict(r.clusters), dict(r.scores))
                        for r in merged
                    ]
                    timings["decode"] += time.perf_counter() - t3
                    return out_copy
                entry.prev_out = new_out
                entry.prev_view = view
                t3 = time.perf_counter()
                timings["fetch"] += t3 - t2
                out_copy = [
                    ScheduleResult(dict(r.clusters), dict(r.scores))
                    for r in merged
                ]
                timings["decode"] += time.perf_counter() - t3
                return out_copy
            # fall through to a full fetch for mass changes

        self.fetch_stats["full"] += 1
        selected = np.asarray(out.selected)[:n]
        replicas = np.asarray(out.replicas)[:n]
        counted = np.asarray(out.counted)[:n]
        scores = np.asarray(out.scores)[:n] if want_scores else None
        t3 = time.perf_counter()
        timings["fetch"] += t3 - t2
        results = self._decode_rows(selected, replicas, counted, names, scores)
        if entry is not None and not want_scores:
            entry.prev_out = (out.selected, out.replicas, out.counted)
            entry.prev_results = results
            entry.prev_view = view
            results = [
                ScheduleResult(dict(r.clusters), dict(r.scores)) for r in results
            ]
        timings["decode"] += time.perf_counter() - t3
        return results
