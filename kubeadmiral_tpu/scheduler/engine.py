"""The scheduling engine: batch in, placements out.

Public entry point for the control plane: take every pending
SchedulingUnit, featurize against the current member clusters, run the
fused XLA tick (chunked over the object axis to bound device memory and
shape-bucketed to bound recompiles), and decode placements.

Where the reference schedules one object at a time inside worker
goroutines (reference: pkg/controllers/scheduler/scheduler.go:246-521),
this engine schedules the whole pending set per tick in O(B/chunk)
device dispatches.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np

from kubeadmiral_tpu.models import types as T
from kubeadmiral_tpu.ops.pipeline import NIL_REPLICAS, TickInputs, schedule_tick
from kubeadmiral_tpu.scheduler.featurize import (
    ClusterView,
    FeaturizedBatch,
    featurize,
    featurize_signature,
)

# TickInputs fields carrying cluster-axis-only state: always taken from
# the freshest ClusterView (resource drift must never hit the cache).
_CLUSTER_ONLY_FIELDS = ("alloc", "used", "cpu_alloc", "cpu_avail", "cluster_valid")

# Duplicate-mode placements carry no replica count.
DUPLICATE = None


@dataclass
class ScheduleResult:
    """Placement decision for one object: cluster -> replicas (None in
    Duplicate mode), mirroring core.ScheduleResult.SuggestedClusters.
    ``scores`` carries the post-normalize totals of the selected clusters
    (consumed by webhook select plugins)."""

    clusters: dict[str, Optional[int]]
    scores: dict[str, int] = field(default_factory=dict)

    @property
    def cluster_set(self) -> set[str]:
        return set(self.clusters)


def _round_up(n: int, step: int) -> int:
    return max(step, ((n + step - 1) // step) * step)


def _pad_batch(inputs: TickInputs, b_pad: int) -> TickInputs:
    """Pad the object axis with inert rows (no members, Duplicate mode)."""
    b = inputs.total.shape[0]
    if b == b_pad:
        return inputs
    extra = b_pad - b

    def pad(x, fill):
        shape = (extra,) + x.shape[1:]
        return np.concatenate([x, np.full(shape, fill, x.dtype)])

    per_object_fill = {
        "filter_enabled": False,
        "api_ok": False,
        "taint_ok_new": False,
        "taint_ok_cur": False,
        "selector_ok": False,
        "placement_has": False,
        "placement_ok": False,
        "request": 0,
        "score_enabled": False,
        "taint_counts": 0,
        "affinity_scores": 0,
        "webhook_ok": True,
        "webhook_scores": 0,
        "max_clusters": 0,
        "mode_divide": False,
        "sticky": False,
        "current_mask": False,
        "current_replicas": NIL_REPLICAS,
        "total": 0,
        "weights_given": True,
        "weights": 0,
        "min_replicas": 0,
        "max_replicas": np.iinfo(np.int32).max,
        "scale_max": np.iinfo(np.int32).max,
        "capacity": np.iinfo(np.int32).max,
        "keep_unschedulable": False,
        "avoid_disruption": False,
        "tiebreak": 0,
    }
    fields = {}
    for name, arr in inputs._asdict().items():
        if name in per_object_fill:
            fields[name] = pad(np.asarray(arr), per_object_fill[name])
        else:
            fields[name] = arr  # cluster-axis tensors are shared
    return TickInputs(**fields)


# Fill values for padded cluster slots, per [.., C, ..] field.
_CLUSTER_AXIS_FILL = {
    "api_ok": False,
    "taint_ok_new": False,
    "taint_ok_cur": False,
    "selector_ok": False,
    "placement_ok": False,
    "taint_counts": 0,
    "affinity_scores": 0,
    "webhook_ok": True,
    "webhook_scores": 0,
    "current_mask": False,
    "current_replicas": NIL_REPLICAS,
    "weights": 0,
    "min_replicas": 0,
    "max_replicas": np.iinfo(np.int32).max,
    "scale_max": np.iinfo(np.int32).max,
    "capacity": np.iinfo(np.int32).max,
    "tiebreak": 0,
    "alloc": 0,
    "used": 0,
    "cpu_alloc": 0,
    "cpu_avail": 0,
    "cluster_valid": False,
}


def _pad_clusters(inputs: TickInputs, c_pad: int) -> TickInputs:
    """Pad the cluster axis with invalid slots (cluster_valid=False)."""
    c = inputs.cluster_valid.shape[0]
    if c == c_pad:
        return inputs
    extra = c_pad - c
    fields = {}
    for name, arr in inputs._asdict().items():
        fill = _CLUSTER_AXIS_FILL.get(name)
        if fill is None:
            fields[name] = arr
            continue
        arr = np.asarray(arr)
        # The cluster axis is the first axis for [C]/[C,R] tensors and the
        # second for [B,C] tensors.
        axis = 0 if name in ("alloc", "used", "cpu_alloc", "cpu_avail", "cluster_valid") else 1
        pad_shape = list(arr.shape)
        pad_shape[axis] = extra
        fields[name] = np.concatenate(
            [arr, np.full(pad_shape, fill, arr.dtype)], axis=axis
        )
    return TickInputs(**fields)


def _pow2_bucket(n: int, minimum: int, cap: int) -> int:
    b = minimum
    while b < n:
        b *= 2
    return min(b, max(cap, minimum))


@dataclass
class _CachedChunk:
    """A previous tick's featurized chunk, patchable row-by-row."""

    sigs: list
    inputs: TickInputs
    topo_fp: tuple
    nbytes: int


class SchedulerEngine:
    """Chunked, shape-bucketed driver around ops.pipeline.schedule_tick.

    Featurization is incremental across ticks: each chunk's assembled
    TickInputs is cached keyed by per-unit featurize signatures (the
    tensor analogue of the reference's scheduling-trigger hash,
    schedulingtriggers.go:106-148) and the cluster topology; a
    steady-state re-tick with 1% churn re-featurizes only the changed
    rows and memcpy-patches them into the cached arrays.  Cluster
    *resources* (the fast-drifting part) live in cluster-axis tensors
    taken fresh from the ClusterView every tick, so they never
    invalidate cached rows."""

    def __init__(
        self,
        chunk_size: int = 4096,
        min_bucket: int = 64,
        min_cluster_bucket: int = 8,
        cache_bytes: int = 16 << 30,
    ):
        self.chunk_size = chunk_size
        self.min_bucket = min_bucket
        self.min_cluster_bucket = min_cluster_bucket
        self._view_cache: tuple[Optional[tuple], Optional[ClusterView]] = (None, None)
        self.cache_bytes = cache_bytes
        self._chunk_cache: dict[int, _CachedChunk] = {}
        self._cache_used = 0
        self.cache_stats = {"hit": 0, "patch": 0, "miss": 0}
        # Per-stage wall time of the last schedule() call: featurize
        # (host encoding), device (dispatch + on-device compute, incl.
        # host->device input transfer), fetch (device->host result
        # transfer), decode (placement dict construction).
        self.timings: dict[str, float] = {}

    @staticmethod
    def _cluster_fingerprint(clusters, scalar_resources: tuple) -> tuple:
        return (
            tuple(
                (
                    c.name,
                    tuple(sorted(c.labels.items())),
                    c.taints,
                    tuple(sorted(c.allocatable.items())),
                    tuple(sorted(c.available.items())),
                    c.api_resources,
                )
                for c in clusters
            ),
            scalar_resources,
        )

    def _cached_view(self, units, clusters) -> ClusterView:
        """Reuse the per-tick cluster tensors (and the tie-break hash cache,
        which is the expensive part) while cluster state is unchanged."""
        scalars = tuple(
            sorted(
                {
                    r
                    for su in units
                    for r in su.resource_request
                    if r not in ("cpu", "memory", "ephemeral-storage")
                }
            )
        )
        fp = self._cluster_fingerprint(clusters, scalars)
        cached_fp, cached_view = self._view_cache
        if cached_fp == fp and cached_view is not None:
            return cached_view
        from kubeadmiral_tpu.scheduler.featurize import _build_cluster_view

        view = _build_cluster_view(clusters, units)
        # Tie-break hashes depend only on the cluster-name list, which
        # changes far less often than resource usage: carry the FNV cache
        # across view rebuilds so steady-state resource updates don't
        # re-hash every (object, cluster) pair.
        if cached_view is not None and cached_view.names == view.names:
            view._tiebreak_cache = cached_view._tiebreak_cache
        self._view_cache = (fp, view)
        return view

    def _bucket(self, n: int) -> int:
        """Next power-of-two bucket (caps recompiles at log2 distinct B)."""
        return _pow2_bucket(n, self.min_bucket, self.chunk_size)

    @staticmethod
    def _topo_fingerprint(view: ClusterView) -> tuple:
        """Cluster-topology identity: everything cached rows depend on
        (names, taints, labels, api resources, scalar columns) but NOT
        resource quantities, which flow through cluster-axis tensors."""
        fp = getattr(view, "_topo_fp", None)
        if fp is None:
            fp = (
                tuple(view.names),
                tuple(view.taint_sets),
                view.taint_id.tobytes(),
                tuple(view.label_keys),
                view.label_id.tobytes(),
                tuple(frozenset(c.api_resources) for c in view.clusters),
                tuple(view.scalar_resources),
            )
            view._topo_fp = fp
        return fp

    def _featurize_chunk(
        self, idx: int, chunk, clusters, view: ClusterView, webhook_eval
    ) -> FeaturizedBatch:
        if webhook_eval is not None:
            # Webhook planes are per-tick HTTP results; never cached.
            return featurize(chunk, clusters, view=view, webhook_eval=webhook_eval)

        topo_fp = self._topo_fingerprint(view)
        sigs = [featurize_signature(su) for su in chunk]
        cached = self._chunk_cache.get(idx)
        if (
            cached is not None
            and cached.topo_fp == topo_fp
            and len(cached.sigs) == len(sigs)
        ):
            refreshed = cached.inputs._replace(
                alloc=view.alloc,
                used=view.used,
                cpu_alloc=view.cpu_alloc,
                cpu_avail=view.cpu_avail,
            )
            cached.inputs = refreshed
            changed = [i for i, s in enumerate(sigs) if s != cached.sigs[i]]
            if not changed:
                self.cache_stats["hit"] += 1
                return FeaturizedBatch(inputs=refreshed, units=list(chunk), view=view)
            if len(changed) <= max(1, len(chunk) // 4):
                sub = featurize(
                    [chunk[i] for i in changed], clusters, view=view
                )
                rows = np.asarray(changed)
                for name, arr in refreshed._asdict().items():
                    if name in _CLUSTER_ONLY_FIELDS:
                        continue
                    np.asarray(arr)[rows] = np.asarray(getattr(sub.inputs, name))
                for i in changed:
                    cached.sigs[i] = sigs[i]
                self.cache_stats["patch"] += 1
                return FeaturizedBatch(inputs=refreshed, units=list(chunk), view=view)

        fb = featurize(chunk, clusters, view=view)
        self.cache_stats["miss"] += 1
        if cached is not None:
            self._cache_used -= cached.nbytes
            del self._chunk_cache[idx]
        nbytes = sum(
            np.asarray(arr).nbytes
            for name, arr in fb.inputs._asdict().items()
            if name not in _CLUSTER_ONLY_FIELDS
        )
        if self._cache_used + nbytes <= self.cache_bytes:
            self._chunk_cache[idx] = _CachedChunk(
                sigs=sigs, inputs=fb.inputs, topo_fp=topo_fp, nbytes=nbytes
            )
            self._cache_used += nbytes
        return fb

    def schedule(
        self,
        units: Sequence[T.SchedulingUnit],
        clusters: Sequence[T.ClusterState],
        view: Optional[ClusterView] = None,
        webhook_eval=None,
        want_scores: bool = False,
    ) -> list[ScheduleResult]:
        """``want_scores`` additionally decodes per-cluster score dicts
        (only webhook select plugins consume them; decoding hundreds of
        placements per Duplicate-mode object is the engine's main
        host-side cost, so it's opt-in)."""
        units = list(units)
        if not units:
            return []
        if view is None:
            view = self._cached_view(units, clusters)
        # One chunk at a time: dispatching all chunks before pulling
        # measured SLOWER on the tunneled TPU backend (transfers queue
        # behind every outstanding program), so keep dispatch->pull
        # strictly sequential per chunk.
        results: list[ScheduleResult] = []
        timings = {"featurize": 0.0, "device": 0.0, "fetch": 0.0, "decode": 0.0}
        self.timings = timings
        for chunk_idx, start in enumerate(range(0, len(units), self.chunk_size)):
            chunk = units[start : start + self.chunk_size]
            t0 = time.perf_counter()
            fb = self._featurize_chunk(chunk_idx, chunk, clusters, view, webhook_eval)
            padded = _pad_batch(fb.inputs, self._bucket(len(chunk)))
            n_clusters = padded.cluster_valid.shape[0]
            padded = _pad_clusters(
                padded, _pow2_bucket(n_clusters, self.min_cluster_bucket, 1 << 30)
            )
            t1 = time.perf_counter()
            out = schedule_tick(padded)
            jax.block_until_ready(out)
            t2 = time.perf_counter()
            selected = np.asarray(out.selected)[: len(chunk)]
            replicas = np.asarray(out.replicas)[: len(chunk)]
            counted = np.asarray(out.counted)[: len(chunk)]
            t3 = time.perf_counter()
            timings["featurize"] += t1 - t0
            timings["device"] += t2 - t1
            timings["fetch"] += t3 - t2
            names = fb.view.names
            # Vectorized decode: one nonzero over the whole chunk, then
            # per-row dict(zip(...)) at C speed — no per-placement Python.
            rows, cols = np.nonzero(selected)
            bounds = np.searchsorted(rows, np.arange(len(chunk) + 1))
            reps_obj = replicas[rows, cols].astype(object)
            reps_obj[counted[rows, cols] == 0] = DUPLICATE
            names_arr = np.asarray(names, dtype=object)
            sel_names = names_arr[cols].tolist()
            reps_list = reps_obj.tolist()
            score_list = None
            if want_scores:
                totals = np.asarray(out.scores)[: len(chunk)]
                score_list = totals[rows, cols].tolist()
            for i in range(len(chunk)):
                s, e = bounds[i], bounds[i + 1]
                results.append(
                    ScheduleResult(
                        clusters=dict(zip(sel_names[s:e], reps_list[s:e])),
                        scores=dict(zip(sel_names[s:e], score_list[s:e]))
                        if score_list is not None
                        else {},
                    )
                )
            timings["decode"] += time.perf_counter() - t3
        return results
