"""The scheduling engine: batch in, placements out.

Public entry point for the control plane: take every pending
SchedulingUnit, featurize against the current member clusters, run the
fused XLA tick (chunked over the object axis to bound device memory and
shape-bucketed to bound recompiles), and decode placements.

Where the reference schedules one object at a time inside worker
goroutines (reference: pkg/controllers/scheduler/scheduler.go:246-521),
this engine schedules the whole pending set per tick in O(B/chunk)
device dispatches.  When more than one device is visible the tick runs
SPMD over an (objects, clusters) jax.sharding.Mesh — the TPU analogue of
the reference's ``--worker-count`` goroutines
(pkg/controllers/util/worker/worker.go:132-134), except the workers are
mesh slices and the cross-worker reduction is ICI, not a mutex.

Program-count discipline: ONE jitted tick (the fused pipeline plus an
on-device diff against the previous outputs) serves the cold path, the
steady-state delta path and the sub-batch path alike, and row counts are
bucketed to a short ladder at wide cluster axes — so a given topology
compiles a handful of programs, not one per batch size.  ``prewarm()``
compiles them in a background thread before the first real tick.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeadmiral_tpu.models import types as T
from kubeadmiral_tpu.ops import pallas_slab as pallas_slab_mod
from kubeadmiral_tpu.ops import pipeline as pipeline_mod
from kubeadmiral_tpu.ops.pipeline import (
    DRIFT_FITFLIP,
    DRIFT_RECOMPUTE,
    DRIFT_REFINE_MAX_COLS,
    DRIFT_WCHECK,
    NIL_REPLICAS,
    PackedRows,
    TickInputs,
    TickOutputs,
    drift_gate_compact,
    drift_gate_dense,
    drift_replan,
    drift_resolve,
    drift_scoreonly,
    drift_survivor,
    drift_wcheck,
    expand_compact,
    fnv_tiebreak_plane,
    pack_wire,
    schedule_tick,
    schedule_tick_narrow,
    unpack_wire,
)
from kubeadmiral_tpu.ops.planner import INT32_INF
from kubeadmiral_tpu.runtime import devprof as devprof_mod
from kubeadmiral_tpu.runtime import flightrec as flightrec_mod
from kubeadmiral_tpu.runtime import lockcheck
from kubeadmiral_tpu.scheduler import aot as aot_mod
from kubeadmiral_tpu.runtime import trace
from kubeadmiral_tpu.runtime.metrics import Metrics, null_metrics
from kubeadmiral_tpu.scheduler import compact as Cmp
from kubeadmiral_tpu.scheduler.compact import (
    CompactInputs,
    CompactVocab,
    VocabOverflow,
    featurize_compact,
)
from kubeadmiral_tpu.scheduler.featurize import (
    ClusterView,
    featurize,
    featurize_signature,
)

log = logging.getLogger("kubeadmiral.engine")

# TickInputs fields carrying cluster-axis-only state: always taken from
# the freshest ClusterView (resource drift must never hit the cache).
_CLUSTER_ONLY_FIELDS = ("alloc", "used", "cpu_alloc", "cpu_avail", "cluster_valid")

# Duplicate-mode placements carry no replica count.
DUPLICATE = None

# Bits of the on-device per-row diff mask.
_DIFF_PLACEMENT = 1
_DIFF_SCORES = 2


class _FrozenDict(dict):
    """Read-only mapping for shared ScheduleResults.  The engine returns
    its cached decodes BY REFERENCE — rebuilding 100k result dicts every
    tick was the config-5 host floor (VERDICT r4 #1a) — so the handed-out
    mappings refuse mutation instead of being defensively copied."""

    __slots__ = ()

    def _blocked(self, *a, **k):
        raise TypeError(
            "ScheduleResult mappings are read-only views of the engine "
            "cache; build a new dict instead of mutating"
        )

    __setitem__ = __delitem__ = __ior__ = _blocked
    clear = pop = popitem = setdefault = update = _blocked

    def __reduce__(self):  # deepcopy/pickle detach to a plain dict
        return (dict, (dict(self),))


@dataclass(frozen=True)
class ScheduleResult:
    """Placement decision for one object: cluster -> replicas (None in
    Duplicate mode), mirroring core.ScheduleResult.SuggestedClusters.
    ``scores`` carries the post-normalize totals of the selected clusters
    (consumed by webhook select plugins).

    Frozen, with read-only mappings: results returned by
    :meth:`SchedulerEngine.schedule` share the engine's cached decodes,
    so neither the attributes nor the dicts may be mutated — derive
    changed placements with a fresh ``ScheduleResult``."""

    clusters: dict[str, Optional[int]]
    scores: dict[str, int] = field(default_factory=dict)

    def __post_init__(self):
        if type(self.clusters) is not _FrozenDict:
            object.__setattr__(self, "clusters", _FrozenDict(self.clusters))
        if type(self.scores) is not _FrozenDict:
            object.__setattr__(self, "scores", _FrozenDict(self.scores))

    @property
    def cluster_set(self) -> set[str]:
        return set(self.clusters)


def _round_up(n: int, step: int) -> int:
    return max(step, ((n + step - 1) // step) * step)


def _pad_batch(inputs: TickInputs, b_pad: int) -> TickInputs:
    """Pad the object axis with inert rows (no members, Duplicate mode)."""
    b = inputs.total.shape[0]
    if b == b_pad:
        return inputs
    extra = b_pad - b

    def pad(x, fill):
        shape = (extra,) + x.shape[1:]
        return np.concatenate([x, np.full(shape, fill, x.dtype)])

    per_object_fill = {
        "filter_enabled": False,
        "api_ok": False,
        "taint_ok_new": False,
        "taint_ok_cur": False,
        "selector_ok": False,
        "placement_has": False,
        "placement_ok": False,
        "request": 0,
        "score_enabled": False,
        "taint_counts": 0,
        "affinity_scores": 0,
        "webhook_ok": True,
        "webhook_scores": 0,
        "max_clusters": 0,
        "mode_divide": False,
        "sticky": False,
        "current_mask": False,
        "current_replicas": NIL_REPLICAS,
        "total": 0,
        "weights_given": True,
        "weights": 0,
        "min_replicas": 0,
        "max_replicas": np.iinfo(np.int32).max,
        "scale_max": np.iinfo(np.int32).max,
        "capacity": np.iinfo(np.int32).max,
        "keep_unschedulable": False,
        "avoid_disruption": False,
        "tiebreak": 0,
    }
    fields = {}
    for name, arr in inputs._asdict().items():
        if name in per_object_fill:
            fields[name] = pad(np.asarray(arr), per_object_fill[name])
        else:
            fields[name] = arr  # cluster-axis tensors are shared
    return TickInputs(**fields)


# Fill values for padded cluster slots, per [.., C, ..] field.
_CLUSTER_AXIS_FILL = {
    "api_ok": False,
    "taint_ok_new": False,
    "taint_ok_cur": False,
    "selector_ok": False,
    "placement_ok": False,
    "taint_counts": 0,
    "affinity_scores": 0,
    "webhook_ok": True,
    "webhook_scores": 0,
    "current_mask": False,
    "current_replicas": NIL_REPLICAS,
    "weights": 0,
    "min_replicas": 0,
    "max_replicas": np.iinfo(np.int32).max,
    "scale_max": np.iinfo(np.int32).max,
    "capacity": np.iinfo(np.int32).max,
    "tiebreak": 0,
    "alloc": 0,
    "used": 0,
    "cpu_alloc": 0,
    "cpu_avail": 0,
    "cluster_valid": False,
}


def _pad_clusters(inputs: TickInputs, c_pad: int, skip: tuple = ()) -> TickInputs:
    """Pad the cluster axis with invalid slots (cluster_valid=False).
    Fields named in ``skip`` pass through untouched (the engine swaps
    them for shared pre-padded device copies at dispatch)."""
    c = inputs.cluster_valid.shape[0]
    if c == c_pad:
        return inputs
    extra = c_pad - c
    fields = {}
    for name, arr in inputs._asdict().items():
        fill = _CLUSTER_AXIS_FILL.get(name)
        if fill is None or name in skip:
            fields[name] = arr
            continue
        arr = np.asarray(arr)
        # The cluster axis is the first axis for [C]/[C,R] tensors and the
        # second for [B,C] tensors.
        axis = 0 if name in ("alloc", "used", "cpu_alloc", "cpu_avail", "cluster_valid") else 1
        pad_shape = list(arr.shape)
        pad_shape[axis] = extra
        fields[name] = np.concatenate(
            [arr, np.full(pad_shape, fill, arr.dtype)], axis=axis
        )
    return TickInputs(**fields)


def _pow2_bucket(n: int, minimum: int, cap: int) -> int:
    b = minimum
    while b < n:
        b *= 2
    return min(b, max(cap, minimum))


def _cluster_bucket(n: int, minimum: int) -> int:
    """Cluster-axis bucket: power-of-two up to 512, then the next
    multiple of 512 — 5k clusters must not pad to 8192 (pow2 padding
    wastes up to 2x compute AND compile time on the widest axis)."""
    if n <= 512:
        return _pow2_bucket(n, minimum, 1 << 30)
    return ((n + 511) // 512) * 512


@dataclass
class _CachedChunk:
    """A previous tick's featurized chunk, patchable row-by-row."""

    sigs: list
    units: list  # identity fast-path: `is`-compare before sig-compare
    inputs: object  # TickInputs (dense) or CompactInputs
    fmt: str  # "compact" | "dense"
    topo_fp: tuple
    nbytes: int
    # Which CompactVocab instance the cached ids were issued by (0 for
    # dense): ids are meaningless against a different instance's tables,
    # even for the same topology fingerprint.
    vocab_uid: int = 0
    # Device-resident copies of the padded per-object tensors: a clean
    # re-tick skips the host->device transfer entirely (the dominant
    # cost over a tunneled TPU backend).
    device_per_object: Optional[dict] = None
    padded_shape: Optional[tuple] = None
    # Previous tick's outputs (device: selected/replicas/counted/scores)
    # + decoded results (host) for the delta fetch: unchanged rows are
    # never pulled off the device again.
    prev_out: Optional[tuple] = None
    # Previous tick's feasibility plane (device i8[B, C]): the drift
    # gate's substrate — which rows a cluster-capacity drift can
    # actually move is a function of feasibility at the changed columns.
    prev_feas: Optional[object] = None
    # Previous tick's reason plane (device i32[B, C]): the drift-resolve
    # substrate — the old select-stage selection is recovered as
    # "feasible with no MAX_CLUSTERS bit", and survivor rows' filter
    # reasons are provably unchanged without a fit flip, so the
    # sort-free resolve can emit exact reason planes too.
    prev_reasons: Optional[object] = None
    # Cached per-row feasible-column counts (device i32[B]): maintained
    # alongside prev_feas (stored by every prev-plane store, patched by
    # every row repair, derived at restore) so the drift gate reads a
    # [B] vector instead of running a [B, C] pf.sum pass per drift tick
    # (~4.9s of c5 gate device time at r11).
    prev_nfeas: Optional[object] = None
    prev_results: Optional[list] = None
    # Whether prev_results carry decoded score dicts — a want_scores
    # consumer can only ride the noop/delta/sub-batch fast paths when
    # the cached decodes carry scores too.
    prev_has_scores: bool = False
    # The ClusterView those results were computed against: identical
    # view + clean hit = identical outputs, no dispatch needed at all.
    prev_view: Optional[object] = None
    # (changed row indices, their featurized rows) from the last patch,
    # consumed once by schedule()'s sub-batch fast path.
    last_patch: Optional[tuple] = None
    # Rows whose device-resident input copy is stale (patched host-side
    # since the last upload); repaired lazily by a K-row scatter the
    # next time the device copy is actually needed, instead of paying a
    # full chunk re-upload after every churn tick.
    stale_rows: Optional[list] = None
    # Rows whose prev_out device planes are outdated (their decisions
    # were merged host-side by the sub-batch pass): the next delta fetch
    # force-gathers them, everything else still rides the device diff.
    stale_out_rows: Optional[list] = None
    # Device-resident planner tie-break plane (i32[B_pad, C_pad], compact
    # format only): precomputed once per per-object upload and patched
    # row-wise on churn, so the drift survivor kernels (resolve / replan
    # / score-only / unified) never re-run expand_compact's FNV byte scan.
    tiebreak_dev: Optional[object] = None
    # Rows whose tiebreak_dev rows are pending an FNV re-patch: the
    # eager churn-tick input repair defers the (relatively expensive)
    # tie-break FNV recompute off the steady path — _tiebreak_plane
    # patches these lazily before any survivor kernel consumes the
    # plane (the only consumer).
    tb_stale_rows: Optional[list] = None
    # Entry was rebuilt from a durable snapshot and has not yet had a
    # full identity/signature walk: the delta-featurization dirty-row
    # hint must not skip rows for it (every row still needs snapshot-
    # signature verification).
    restored: bool = False
    # Adaptive packed-export K hint: pow2 over the chunk's observed
    # nsel distribution (see SchedulerEngine._observe_nsel); 0 = no
    # observation yet, use the static maxClusters bound.
    pack_k_hint: int = 0
    # Shrink hysteresis: consecutive observations whose byte-optimal K
    # was below the standing hint.  The hint only decays after two in a
    # row, so one narrow-selecting batch can't whipsaw K down and force
    # the next ordinary batch through the overflow re-fetch.
    pack_shrink_votes: int = 0
    # Per-row score-plane exactness vector (device i8[B], KT_SCORE_F16
    # only): 1 = the stored f16 score row round-trips to the true i32
    # scores bit-exactly, 0 = quantization was lossy for the row.
    # Inexact rows are FORCED out of every score-consuming fast path
    # (drift-gate skip classification, delta-diff replay) into the
    # recompute machinery — the same cert->dense-fallback contract the
    # narrow solve uses, so compression can cost a re-solve, never a
    # wrong placement.  None = unknown: treat every row as inexact.
    prev_sco_exact: Optional[object] = None
    # Host cache of the inexact row indices (np.int64), read lazily from
    # prev_sco_exact once per store generation; None = not read yet.
    sco_inexact_host: Optional[object] = None


class _SnapshotView:
    """The cluster-tensor face of a ClusterView, reconstructed from a
    durable snapshot (runtime/snapshot.py).  Restored chunk entries hold
    one of these as ``prev_view`` when the relisted world's cluster
    tensors differ from the snapshot's: the drift machinery only reads
    ``names`` plus the four resource planes (``_drift_delta``,
    ``_wcheck_cpu_device``), so a stale-but-recent snapshot resumes
    through the exact drift-gate path a live capacity drift uses."""

    __slots__ = ("names", "alloc", "used", "cpu_alloc", "cpu_avail")

    def __init__(self, names, alloc, used, cpu_alloc, cpu_avail):
        self.names = list(names)
        self.alloc = np.asarray(alloc)
        self.used = np.asarray(used)
        self.cpu_alloc = np.asarray(cpu_alloc)
        self.cpu_avail = np.asarray(cpu_avail)


# Placeholder members of a restored chunk entry's ``units`` list: never
# identical to a live unit object, so the hit path's identity fast-check
# always falls through to the signature comparison — every row of a
# restored chunk is verified against its snapshot signature before the
# snapshot's outputs are trusted for it.
_RESTORE_SENTINEL = object()

SNAPSHOT_STATE_VERSION = 1


def _diff_bits(out, prev: tuple):
    """Per-row diff mask vs the previous tick's output planes:
    _DIFF_PLACEMENT when any of selected/replicas/counted changed,
    _DIFF_SCORES when the score plane changed (only consulted by
    want_scores consumers, so resource drift that shifts scores without
    moving placements stays on the skip path)."""
    psel, prep, pcnt, psco = prev
    place_diff = (
        (out.selected != psel) | (out.replicas != prep) | (out.counted != pcnt)
    ).any(axis=1)
    score_diff = (out.scores != psco).any(axis=1)
    return place_diff.astype(jnp.int8) * _DIFF_PLACEMENT + score_diff.astype(
        jnp.int8
    ) * _DIFF_SCORES


def _tick_with_diff(inp: TickInputs, prev: tuple):
    """The fused tick plus an on-device diff against the previous tick's
    outputs, in ONE dispatch: over a high-latency link (the tunneled TPU
    backend) every dispatch costs a round trip, so the changed-rows mask
    ships with the tick instead of as a follow-up program.  This single
    program serves cold, steady-state and sub-batch dispatches alike —
    the engine's whole per-shape compile budget is this plus the (tiny)
    gather program."""
    out = schedule_tick.__wrapped__(inp)
    return out, _diff_bits(out, prev)


def _tick_compact_with_diff(ci: CompactInputs, prev: tuple):
    """The compact-format tick: device-side plane expansion (table
    gathers, sparse scatters, on-device FNV tie-breaks) feeding the same
    fused pipeline + diff.  This is the PRIMARY production program — the
    dense variant serves webhook ticks and vocabulary-overflow
    fallbacks."""
    return _tick_with_diff(expand_compact(ci), prev)


def _gather_packed(sel, rep, cnt, sco, idx):
    """Gather the given rows of all four output planes into ONE int32
    array: over a high-latency link each device->host transfer costs a
    round trip, so changed rows ship as a single packed fetch."""
    return jnp.concatenate(
        [
            sel[idx].astype(jnp.int32),
            rep[idx],
            cnt[idx].astype(jnp.int32),
            sco[idx],
        ],
        axis=1,
    )


def _gather_packed3(sel, rep, cnt, idx):
    """Scores-free variant: plain consumers never pay the score plane's
    bytes on the fetch path."""
    return jnp.concatenate(
        [sel[idx].astype(jnp.int32), rep[idx], cnt[idx].astype(jnp.int32)],
        axis=1,
    )


def _gather_packed5(sel, rep, cnt, sco, rsn, idx):
    """Flight-recorder variant: score + reason planes ride the SAME
    packed transfer as the selection planes — the decision audit costs
    extra bytes on rows already being fetched, never an extra
    device->host round trip."""
    return jnp.concatenate(
        [
            sel[idx].astype(jnp.int32),
            rep[idx],
            cnt[idx].astype(jnp.int32),
            sco[idx],
            rsn[idx],
        ],
        axis=1,
    )


def _patch_rows(dev: dict, rows: dict, idx):
    """Scatter freshly featurized rows into the cached device tensors
    (idx is padded with out-of-range values; mode='drop' ignores them) —
    a K-row upload instead of re-uploading the whole chunk."""
    return {
        name: dev[name].at[idx].set(rows[name], mode="drop") for name in dev
    }


def _pack_full_wire(sel, rep, cnt, sco, rsn, k: int):
    """Packed placement export of a whole chunk: top-k-compact every row
    on device and ship ONE i32[B, 4K+2+NR] array instead of five dense
    [B, C] planes (ops/pipeline.pack_wire documents the layout)."""
    return pack_wire(sel, rep, cnt, sco, rsn, k)


def _gather_packed_wire(sel, rep, cnt, sco, rsn, idx, k: int):
    """Delta-fetch variant: row gather + top-k compaction in one device
    program — the packed wire rows for just the changed rows."""
    return pack_wire(sel[idx], rep[idx], cnt[idx], sco[idx], rsn[idx], k)


def _bitpack_bool(x):
    """bool[N, C] -> i32[N, ceil(C/32)] little-endian bit words — the
    selection/counted planes cost 1 bit per cluster on the wire instead
    of 32."""
    n, c = x.shape
    pad = (-c) % 32
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad)))
    x = x.reshape(n, -1, 32).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    words = (x * weights).sum(axis=-1, dtype=jnp.uint32)
    return jax.lax.bitcast_convert_type(words, jnp.int32)


def _gather_overflow3(sel, cnt, rep, idx):
    """K-overflow row fetch: bit-packed selected/counted masks + the
    replica plane for the given rows in ONE transfer — ~1/4 of the
    dense 3-plane row (C/32 + C/32 + C words vs 3C)."""
    return jnp.concatenate(
        [
            _bitpack_bool(sel[idx] != 0),
            _bitpack_bool(cnt[idx] != 0),
            rep[idx],
        ],
        axis=1,
    )


def _gather_overflow4(sel, cnt, rep, sco, idx):
    """Score-carrying variant, for want_scores consumers whose overflow
    rows must decode per-cluster score dicts."""
    return jnp.concatenate(
        [
            _bitpack_bool(sel[idx] != 0),
            _bitpack_bool(cnt[idx] != 0),
            rep[idx],
            sco[idx],
        ],
        axis=1,
    )


def _unpack_bits(words: np.ndarray, c: int) -> np.ndarray:
    """Host inverse of _bitpack_bool: i32[N, ceil(C/32)] -> uint8[N, C]."""
    u8 = np.ascontiguousarray(words.astype("<i4")).view(np.uint8)
    bits = np.unpackbits(
        u8.reshape(words.shape[0], -1), axis=1, bitorder="little"
    )
    return bits[:, :c]


@lockcheck.shared_field_guard
class SchedulerEngine:
    """Chunked, shape-bucketed driver around ops.pipeline.schedule_tick.

    Featurization is incremental across ticks: each chunk's assembled
    TickInputs is cached keyed by per-unit featurize signatures (the
    tensor analogue of the reference's scheduling-trigger hash,
    schedulingtriggers.go:106-148) and the cluster topology; a
    steady-state re-tick with 1% churn re-featurizes only the changed
    rows and memcpy-patches them into the cached arrays.  Cluster
    *resources* (the fast-drifting part) live in cluster-axis tensors
    taken fresh from the ClusterView every tick, so they never
    invalidate cached rows.

    ``mesh="auto"`` builds an (objects, clusters) mesh whenever more
    than one device is visible; pass an explicit jax.sharding.Mesh or
    ``None`` (single-device) to override."""

    # Cross-thread staging surface (manager boot thread vs the
    # streaming pump's ticks); everything else on the engine is
    # serialized by _schedule_lock's with-block in schedule() or is
    # builder-cache state safe under the GIL's dict atomicity
    # (ktlint lock-discipline + runtime/lockcheck.py).
    _shared_fields_ = {
        "_pending_restore": "_schedule_lock",
        "restore_info": "_schedule_lock",
    }

    def __init__(
        self,
        chunk_size: int = 4096,
        min_bucket: int = 64,
        min_cluster_bucket: int = 8,
        cache_bytes: int = 16 << 30,
        cell_budget: Optional[int] = None,
        megachunk_rows: Optional[int] = None,
        donate: Optional[bool] = None,
        mesh="auto",
        canonical_c: int = 256,
        vocab_caps: Optional[dict] = None,
        metrics: Optional[Metrics] = None,
        flight_recorder="default",
        fetch_format: Optional[str] = None,
        pack_k_min: Optional[int] = None,
        narrow: Optional[bool] = None,
        narrow_m: Optional[int] = None,
        devprof="default",
    ):
        self.chunk_size = chunk_size
        # Result-fetch wire format: "packed" (default) ships [B, K]
        # top-k-compacted placement rows (ops/pipeline.pack_wire) and
        # falls back to dense row gathers only for K-overflow rows;
        # "dense" ships the full [B, C] planes (the pre-packed behavior,
        # kept for A/B comparison and full-fidelity /debug/explain).
        # Knobs: KT_FETCH_FORMAT, KT_PACK_K (minimum K bucket).
        if fetch_format is None:
            fetch_format = os.environ.get("KT_FETCH_FORMAT", "packed")
        if fetch_format not in ("packed", "dense"):
            raise ValueError(
                f"fetch_format must be 'packed' or 'dense', got {fetch_format!r}"
            )
        self.fetch_format = fetch_format
        self.pack_k_min = (
            int(os.environ.get("KT_PACK_K", "16"))
            if pack_k_min is None
            else pack_k_min
        )
        # Narrow solve (KT_NARROW, default on; KT_NARROW=0 reverts to
        # the dense program): the tick's expensive select/planner stages
        # run over M candidate columns per row instead of the full
        # cluster axis (ops/pipeline.schedule_tick_narrow), with a
        # per-row exactness certificate; uncertified rows re-solve
        # through the dense program as a sub-batch (bit-identical
        # placements by construction).  KT_NARROW_M floors M (default
        # 128 — capacity-spill headroom over the finite maxClusters
        # bound); narrow engages only when M < the cluster bucket.
        if narrow is None:
            narrow = os.environ.get("KT_NARROW", "1") not in ("0", "false", "no")
        self.narrow = bool(narrow)
        self.narrow_m = (
            int(os.environ.get("KT_NARROW_M", "128"))
            if narrow_m is None
            else int(narrow_m)
        )
        # rows = rows solved (and certified) by the narrow program,
        # fallback = uncertified rows re-solved dense; narrow_last_m is
        # the most recent chunk's candidate width (bench detail).
        self.narrow_stats = {"rows": 0, "fallback": 0}
        self.narrow_last_m = 0
        # Cumulative device->host result-transfer volume and packed-
        # overflow rows (rows whose selected set exceeded K and were
        # re-fetched through the dense path); per-tick deltas land in
        # engine_fetch_bytes_total / engine_fetch_overflow_rows_total.
        self.fetch_bytes_total = 0
        self.overflow_rows_total = 0
        # Host->device transfer volume, split by plane family: "object"
        # counts the cached per-object tensors (full uploads + row
        # scatter-repairs + sub-batch slab inputs), "cluster" counts the
        # shared cluster-axis planes and vocabulary tables.  On a drift
        # tick only the cluster planes changed, so the object counter
        # must stay flat (tests/test_drift_tick.py pins this).
        self.upload_bytes = {"object": 0, "cluster": 0}
        # Drift-gate row classification totals (see _drain_drift_gates):
        # skip = provably identical, wcheck = dynamic-weight check rows
        # (wcheck_changed of them actually recomputed), recompute = rows
        # re-scheduled (resolve of them through the sort-free
        # drift-resolve program, resolve_fallback of THOSE failing its
        # certificate and dropping to the slab path; the rest slab
        # directly).
        self.drift_stats = {
            "gated": 0, "skip": 0, "wcheck": 0, "wcheck_changed": 0,
            "recompute": 0, "resolve": 0, "resolve_fallback": 0,
            "replan": 0, "replan_fallback": 0,
            "score_only": 0, "score_only_fallback": 0,
            "unified": 0, "unified_fallback": 0,
            "fallback": 0,
        }
        # Sort-free drift resolve (KT_DRIFT_RESOLVE=0 opts out): gate
        # survivors without a fit flip re-solve from stored planes in
        # one pass instead of riding full-width narrow slabs.
        self.drift_resolve = os.environ.get(
            "KT_DRIFT_RESOLVE", "1"
        ) not in ("0", "false", "no")
        # Selection-known replan + score-only phase 1 (KT_REPLAN=0 opts
        # out): fit-flip gate survivors re-solve from stored reason
        # planes — kinf rows through the sort-free drift_replan kernel,
        # finite-K rows through the score-only narrow solve — instead of
        # riding full phase-1 slabs.  Cert failures drop to the slab
        # path (counted replan_fallback / score_only_fallback).
        self.replan = os.environ.get("KT_REPLAN", "1") not in (
            "0", "false", "no",
        )
        # Unified survivor kernel (KT_SURVIVOR_UNIFIED=0 reverts to the
        # three-stream resolve/replan/score_only dispatch): EVERY drift-
        # gate survivor of a chunk rides ONE greedy-grouped
        # drift_survivor stream (ops/pipeline.py) — the score-only solve
        # provably subsumes the other two, so the per-chunk cross-stream
        # padding (~1.6x at c5) and two of three dispatch ladders
        # disappear.  Per-row modes (resolve/replan/score_only) are kept
        # host-side for attribution; cert failures still drop to the
        # slab path bit-identically.
        self.survivor_unified = os.environ.get(
            "KT_SURVIVOR_UNIFIED", "1"
        ) not in ("0", "false", "no")
        # Unified-kernel shape accounting (bench detail.survivor_kernel):
        # rows = survivors dispatched, groups = greedy row-groups,
        # padded_rows = group-padded row total (padding_ratio =
        # padded_rows/rows), fallback_rows = cert failures (slab).
        self.survivor_stats = {
            "rows": 0, "groups": 0, "padded_rows": 0, "fallback_rows": 0,
        }
        # f16 score-plane compression (KT_SCORE_F16=1 opts in, default
        # off): the resident prev SCORE plane ([B, C], the largest
        # numeric plane after replicas) is stored float16 with a per-row
        # exactness vector; rows whose i32 scores don't round-trip
        # through f16 bit-exactly are forced out of every score-
        # consuming fast path into the recompute machinery (the existing
        # cert->dense-fallback contract), so placements stay bit-
        # identical to the uncompressed engine by construction.  The c6
        # memory census (runtime/census.py) is what flips this on: at
        # 1M x 10k the score plane is ~40% of the numeric resident
        # bytes.  Side constraints while compressed: want_scores chunks
        # skip the delta-diff replay (full refetch), and the legacy
        # three-stream resolve/replan paths (which consume stored
        # scores directly) are disabled — the default unified survivor
        # kernel needs no stored scores and is unaffected.
        self.score_f16 = os.environ.get("KT_SCORE_F16", "0") in (
            "1", "true", "yes",
        )
        # Survivor-stream row sharding (KT_SURVIVOR_ROWSHARD=0 reverts
        # to replicated gathers): under a mesh, the gathered [G, ...]
        # survivor/replan/resolve/narrow-fallback sub-problems constrain
        # to rows-first shardings instead of full replication, so each
        # {256,128,64}-row group's row axis partitions across the
        # objects mesh axis — N devices solve G/N rows each instead of
        # all solving all G.  This is what turns the drift tick's ~74
        # serial survivor-group executions into ~74/N device-parallel
        # waves (ISSUE 12); per-row math is row-independent and the
        # cluster/candidate axes stay whole per shard (the pack-sort
        # rule), so outputs are bit-identical either way (enforced by
        # tests/test_multidevice.py and the dryrun parity blocks).
        self.survivor_rowshard = os.environ.get(
            "KT_SURVIVOR_ROWSHARD", "1"
        ) not in ("0", "false", "no")
        # Pallas slab front (KT_PALLAS=1 opts in, default off): the
        # narrow programs compute phase 1 with the fused
        # ops/pallas_slab.py kernel instead of the XLA pass —
        # interpreter mode off-TPU (a parity harness, not a fast path),
        # compiled Mosaic on TPU.  Meshed engines keep the XLA path
        # (pallas_call under GSPMD needs shard_map; ROADMAP item 1).
        self.pallas = pallas_slab_mod.pallas_enabled()
        # Stale-input repair accounting per phase (engine_stale_rows_
        # total): churn = rows repaired eagerly inside the tick that
        # made them stale (the ISSUE 11 satellite), drift = rows a
        # drift gate still had to repair first (must stay 0 with eager
        # repair on), dispatch = repairs at full-dispatch upload.
        self.stale_repair_rows = {"churn": 0, "drift": 0, "dispatch": 0}
        # i32 phase-1 arithmetic (KT_PHASE1_I32=0 opts out): demote the
        # narrow select composite keys (per-row cert-guarded) and the
        # drift weight-check arithmetic (host range-guarded) from int64
        # — on CPU the i64 forms are ~2x the bytes through the sort and
        # reduction floors.
        self.phase1_i32 = os.environ.get("KT_PHASE1_I32", "1") not in (
            "0", "false", "no",
        )
        # Delta featurization (KT_DELTA_FEAT=0 opts out): row-wise
        # featurize patches + the streaming dirty-row hint.  Off forces
        # every changed chunk through the full featurizer (ops escape
        # hatch; correctness is identical either way).
        self.delta_feat = os.environ.get("KT_DELTA_FEAT", "1") not in (
            "0", "false", "no",
        )
        # Rows featurized per path: "full" = whole-chunk (cold boot,
        # topology change, vocab overflow, webhook ticks, restore),
        # "delta" = row-wise patches.  engine_featurize_rows_total
        # mirrors these as counters; bench.py attributes them per phase
        # so a silent return of the full [B, C] rebuild is visible.
        self.featurize_rows = {"full": 0, "delta": 0}
        # Raw device-dispatch count (the number bench.py reports for the
        # cold/drift dispatch-count acceptance): every tick/gather/pack/
        # gate program launch increments it.
        self.dispatches_total = 0
        # Decision flight recorder (runtime/flightrec.py): fed from the
        # host-side arrays the fetch stage pulls anyway, so /debug/explain
        # can name the rejecting filter for any (object, cluster) pair
        # without re-running the solver.  "default" = the process-wide
        # recorder (disabled via KT_FLIGHTREC=0); pass None to opt out.
        self.flightrec = (
            flightrec_mod.get_default()
            if flight_recorder == "default"
            else flight_recorder
        )
        self._tick_rec = None
        # Dispatch ledger (runtime/devprof.py): every program launch is
        # observed through the _obs_wrap proxies below, so per-tick
        # waterfalls decompose the host stage timers into device-
        # attributed per-program costs.  "default" = the process-wide
        # ledger behind GET /debug/waterfall (KT_DEVPROF=0 disables);
        # pass a DispatchLedger (or None) to isolate/opt out.
        if devprof == "default":
            devprof = devprof_mod.get_default()
        self.devprof = devprof or devprof_mod.DispatchLedger(enabled=False)
        # Monotonic engine tick counter: stamped on spans and logs so
        # /debug/trace, /debug/waterfall and the structured logs share
        # one correlation id per schedule() call.
        self.tick_seq = 0
        self.last_tick_id = 0
        # Telemetry registry (runtime/metrics.py): stage histograms,
        # compile-cache and fetch-path counters land here alongside the
        # raw dict stats below.  The manager passes its shared registry;
        # standalone engines get a private one.
        self.metrics = metrics or null_metrics()
        # XLA compile time for the fused tick grows with the b x C cell
        # count (measured on TPU: [8,2048] 42s, [1024,2048] 373s), while
        # execution stays ~0.1s; bounding cells per chunk keeps compiles
        # tractable at 2k-5k clusters and the steady-state sub-batch path
        # shares the same (small) program.
        #
        # MEGACHUNK sizing (KT_CELL_BUDGET / KT_MEGACHUNK_ROWS): the
        # budget defaults to 4096 x 5120 cells, so even the widest bench
        # cluster axis keeps full 4096-row chunks — a 100k x 5k full
        # revalidation is ~25 dispatches instead of the 391 that a
        # 2M-cell budget produced (each tiny dispatch paid Python
        # featurize-check + cluster re-upload + a ~0.4s round trip on
        # the tunneled TPU link; BENCH_DETAIL_c5_tpu_r05).  The one-time
        # trace cost of the bigger programs is absorbed by the prewarm
        # ladder + persistent compile cache.  KT_MEGACHUNK_ROWS caps the
        # row axis independently for HBM-tight deployments.
        if cell_budget is None:
            cell_budget = int(
                os.environ.get("KT_CELL_BUDGET", str(4096 * 5120))
            )
        self.cell_budget = cell_budget
        if megachunk_rows is None:
            megachunk_rows = int(os.environ.get("KT_MEGACHUNK_ROWS", "4096"))
        self.megachunk_rows = max(1, megachunk_rows)
        # Buffer donation (KT_DONATE=0 opts out): the tick programs
        # donate their `prev` planes, so a full dispatch stops double-
        # buffering [B, C] output state — XLA aliases the donated
        # buffers into the new outputs instead of allocating a second
        # copy per chunk.
        if donate is None:
            donate = os.environ.get("KT_DONATE", "1") not in ("0", "false", "no")
        self.donate = bool(donate)
        self.min_bucket = min_bucket
        self.min_cluster_bucket = min_cluster_bucket
        # Cluster-axis width from which row counts are bucketed to the
        # short ladder (eff/16, eff/4, eff) instead of free pow2: wide-C
        # programs are the expensive compiles, so their count is capped.
        self.canonical_c = canonical_c
        # Overriding the compact vocabulary caps is a test/ops knob (e.g.
        # forcing the dense fallback); production uses CompactVocab's
        # defaults.  Validate keys here so a typo fails at construction,
        # not as a TypeError deep inside the first scheduling tick.
        self._vocab_caps = dict(vocab_caps or {})
        # Chunk pipelining depth: with depth D the engine keeps up to D
        # chunks' programs in flight, featurizing/dispatching while the
        # device computes, then drains the whole window in BATCHED
        # transfers (_drain_fetch_window): one stacked fetch for every
        # chunk's diff mask, one per plane-group for the delta gathers,
        # one per output plane for full refetches.  Per-transfer latency
        # dominates multi-chunk ticks over the tunneled chip (config 5:
        # 391 chunk masks x ~18ms = 7.0s of a 8.9s tick), so the window
        # amortizes round trips ~D-fold; in-flight memory is D x the
        # chunk's output planes (D=16 at [256, 5120] i32 ~ 340MB).
        # KT_PIPELINE_DEPTH is PER-DEVICE (ISSUE 12): a meshed engine
        # multiplies it by the objects-axis device count after mesh
        # resolution below, so every device's queue holds the same
        # in-flight window a single-device engine would — N devices
        # drain N x the chunks per window, keeping all queues full.
        self.pipeline_depth_per_device = max(
            1, int(os.environ.get("KT_PIPELINE_DEPTH", "16"))
        )
        self.pipeline_depth = self.pipeline_depth_per_device
        # Adaptive-K observation buffer: (entry id) -> [entry, c_bucket,
        # [nsel arrays]].  Per-device window drains read the same
        # chunk's wire in several device-local pieces; votes must be
        # cast ONCE per tick on the aggregate, not per piece (a piece-
        # wise shrink-vote double-counts and whipsaws K) — flushed by
        # _flush_nsel at the end of every _schedule_impl.
        self._nsel_pending: dict[int, list] = {}
        # Distinct (fmt, rows, clusters) program shapes dispatched — the
        # observable program count the bucket ladder promises to bound
        # (each unique shape is one XLA compile, amortized by the
        # persistent cache).
        self.program_shapes: set[tuple] = set()
        unknown = set(self._vocab_caps) - Cmp.CAP_NAMES
        if unknown:
            raise ValueError(
                f"unknown vocab_caps keys {sorted(unknown)}; "
                f"valid: {sorted(Cmp.CAP_NAMES)}"
            )
        self._view_cache: tuple[Optional[tuple], Optional[ClusterView]] = (None, None)
        self.cache_bytes = cache_bytes
        self._chunk_cache: dict[int, _CachedChunk] = {}
        self._cache_used = 0
        self.cache_stats = {"hit": 0, "patch": 0, "miss": 0}
        # Fetch path counters: "noop" = dispatch skipped entirely
        # (identical inputs), "subbatch" = only changed rows scheduled
        # (row independence), "skip" = no rows changed (mask only),
        # "delta" = changed rows gathered, "full" = whole chunk pulled.
        self.fetch_stats = {"noop": 0, "subbatch": 0, "skip": 0, "delta": 0, "full": 0}
        # Per-stage wall time of the last schedule() call: featurize
        # (host encoding), device (dispatch + on-device compute, incl.
        # host->device input transfer), fetch (device->host result
        # transfer), decode (placement dict construction).
        self.timings: dict[str, float] = {}
        # Global row indices whose placement may have changed in the
        # last schedule() call ([] = none, None = unknown/all); set by
        # every call including the empty-batch early return.
        self.last_changed: Optional[list[int]] = None
        # Whole-batch no-op gate (see _schedule_impl): one atomic entry
        # (units_list, row id array, view, want_scores, follower_index,
        # results, n_chunks), or None.  Same-list replays are O(1);
        # fresh lists of the same row objects replay via the vectorized
        # id comparison.
        self._noop_gate: Optional[tuple] = None
        # schedule() is serialized: the chunk cache, the per-tick
        # recorder arm (_tick_rec), timings and last_changed are all
        # engine-level state keyed by chunk INDEX — two overlapping
        # ticks would validate/patch each other's cache entries and can
        # persist wrong (even empty) placements.  Multi-threaded batch
        # workers (worker.run(workers=N)) gain nothing from overlap
        # anyway: the device serializes, and each tick schedules the
        # whole pending set.
        self._schedule_lock = lockcheck.make_lock("engine-schedule")

        # Persistent XLA compilation-cache telemetry (the cache itself
        # is enabled in kubeadmiral_tpu.__init__; KT_COMPILE_CACHE_DIR
        # overrides the location): entry-count deltas around observed
        # traces attribute each trace to a disk hit or a real compile.
        self._pcache_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
        self._pcache_count = self._pcache_entries()

        self.mesh = self._resolve_mesh(mesh)
        if self.mesh is not None:
            # Per-device in-flight windows (see pipeline_depth_per_device
            # above): N objects-axis devices -> N x the window.
            from kubeadmiral_tpu.parallel.mesh import objects_axis_size

            self.pipeline_depth = (
                self.pipeline_depth_per_device * objects_axis_size(self.mesh)
            )
        # AOT program store (scheduler/aot.py, KT_AOT): program builders
        # route through it so a warm boot preloads jax.export artifacts
        # instead of re-tracing the prewarm ladder; cold processes
        # export as a side effect and keep their own (donating) live
        # jits.  Exports pin the device topology (the manifest guard
        # carries device count + platform), so MESHED engines run in
        # live-trace-only mode: every (program, shape) resolution is
        # recorded honestly as ``traced`` in engine_aot_programs_total
        # instead of the store silently claiming a preload it cannot
        # perform — a warm boot at N>1 pays the trace ladder and SAYS
        # so (the restart bench reports the measured cost).  Documented
        # trade: warm boots' PRELOADED programs do not donate prev
        # buffers (export drops donation) — correctness is unaffected
        # (the engine already treats donated inputs as dead), HBM-tight
        # deployments can set KT_AOT=0.
        self._aot = aot_mod.AotStore(
            metrics=self.metrics,
            live_trace_only=self.mesh is not None,
        )
        # Staged crash-recovery state (runtime/snapshot.py): consumed by
        # the FIRST _schedule_impl call, which has the units + clusters
        # a restore must verify against.  restore_info records what the
        # consumption decided (bench/tests assert on it).
        self._pending_restore: Optional[tuple] = None
        self.restore_info: Optional[dict] = None
        # Post-tick hook (SnapshotManager): invoked at the end of every
        # schedule() call, still under the schedule lock, so a snapshot
        # captures the converged tick's planes.
        self.post_tick = None
        self._build_programs()
        # Device-time attribution: route the shared jitted programs
        # through the dispatch ledger (per-key program caches wrap at
        # creation inside their builders).  The ledger emits into this
        # engine's registry from here on.
        self.devprof.attach(self.metrics)
        self._instrument_programs()
        # (B, C) -> device-resident zero "previous outputs" (created by a
        # trivial on-device program, NOT a host upload): the unified tick
        # always takes a prev argument; cold chunks diff against zeros
        # and the mask is simply ignored.  Under donation only the
        # builder fns are cached (the tick consumes the buffers).
        self._zero_prev: dict[tuple, tuple] = {}
        self._zero_fns: dict[tuple, object] = {}
        self._prewarm_thread: Optional[threading.Thread] = None
        # Once-per-tick shared cluster-plane upload: the padded cluster-
        # axis tensors (alloc/used/cpu/cluster_valid) are device_put ONCE
        # per (view, c_bucket) and reused by every chunk dispatch — on a
        # drift tick these are the only bytes that changed, so the whole
        # tick's host->device traffic is a few [C, R] arrays instead of
        # per-chunk re-pads and re-uploads.  (One entry: views change
        # wholesale per tick; the tuple holds the view to keep its id
        # stable.)
        self._cluster_device: Optional[tuple] = None
        # Same idea for the PREVIOUS view's cpu planes (the drift
        # wcheck's old side).
        self._old_cpu_device: Optional[tuple] = None
        # Compact-format state: one vocabulary per cluster topology
        # (None = topology overflowed a cap; dense fallback), kept for a
        # few recent topologies so an A->B->A flap reuses A's vocabulary
        # (cache entries record the vocab uid they were built against —
        # ids from one instance are meaningless in another's tables).
        # Plus a device-resident copy of the current padded tables keyed
        # by (vocab uid, version, padded C).
        self._vocabs: dict[tuple, Optional[CompactVocab]] = {}
        self._device_tables: Optional[tuple] = None

    # -- mesh / program construction -------------------------------------
    def _resolve_mesh(self, mesh):
        if mesh != "auto":
            return mesh or None
        devices = jax.devices()
        n = len(devices)
        if n <= 1:
            return None
        # Auto mode must never refuse to start: build the largest
        # power-of-two grid whose axes divide every row/cluster bucket
        # (non-pow2 device counts leave the remainder idle; explicit
        # meshes are validated strictly in _build_programs instead).
        # Objects axis first: cluster-axis sharding turns the per-object
        # reductions (normalize maxima, top-K, planner sorts) into
        # all-to-all-heavy collectives — measured ~11x slower at
        # config-5 shapes on the virtual mesh (see parallel/mesh.py
        # make_mesh).  Only when the objects axis is capped by the
        # bucket size do the remaining devices go to the cluster axis
        # (idle devices are worse than cluster collectives).
        usable = 1 << (n.bit_length() - 1)
        obj = min(usable, self.min_bucket)
        clus = min(usable // obj, self.min_cluster_bucket)
        from kubeadmiral_tpu.parallel.mesh import make_mesh

        return make_mesh(devices[: obj * clus], objects_axis=obj)

    def _obs_wrap(self, kind: str, fn):
        """The dispatch ledger's central wrapper: every jitted program
        the engine launches funnels through one of these proxies, so
        device-time attribution covers every dispatch site without
        touching the sites themselves.  Overhead per dispatch is one
        perf_counter read + a deque append (see runtime/devprof.py);
        compile time stays out of the attribution because jit tracing
        happens synchronously inside ``fn`` and the observation
        timestamp is taken after it returns (= enqueue time)."""
        ledger = self.devprof

        def observed(*args, **kwargs):
            out = fn(*args, **kwargs)
            ledger.observe(kind, out)
            return out

        return observed

    def _instrument_programs(self) -> None:
        """Wrap the shared programs _build_programs assigned (the
        per-key caches — narrow/fallback/pack/gate/wcheck/resolve/
        repair — wrap at creation in their builders)."""
        self._stack = self._obs_wrap("stack", self._stack)
        self._concat = self._obs_wrap("stack", self._concat)
        self._tick = self._obs_wrap("tick", self._tick)
        self._tick_compact = self._obs_wrap("tick", self._tick_compact)
        self._gather = self._obs_wrap("gather", self._gather)
        self._gather3 = self._obs_wrap("gather", self._gather3)
        self._gather5 = self._obs_wrap("gather", self._gather5)
        self._gather_over3 = self._obs_wrap("overflow", self._gather_over3)
        self._gather_over4 = self._obs_wrap("overflow", self._gather_over4)
        self._patch = self._obs_wrap("patch", self._patch)
        self._patch_compact = self._obs_wrap("patch", self._patch_compact)

    def _build_programs(self) -> None:
        # Window-drain stacker: one device-side stack of same-shape
        # buffers -> ONE host transfer for the whole window (jax traces
        # a variant per (arity, shape); arities are bounded by the
        # pipeline depth and shapes by the bucket ladder).  AOT-routed
        # like every other program: a warm boot preloads the window
        # shapes its prewarm ladder drained instead of re-tracing them.
        self._stack = self._aot.wrap("stack", jax.jit(lambda *xs: jnp.stack(xs)))
        # Device-side concat (the sub-batch write-back repair stacks
        # hetero-height slabs); jax traces one variant per shape tuple.
        self._concat = self._aot.wrap(
            "concat", jax.jit(lambda *xs: jnp.concatenate(xs))
        )
        # Per-shape program caches for the drift gate, its dynamic-
        # weight check, the sort-free survivor resolve, the fit-flip
        # replan / score-only solves, the precomputed tie-break plane,
        # and the prev-plane scatter repair.
        self._gate_programs: dict[tuple, object] = {}
        self._wcheck_program_cache: dict[tuple, object] = {}
        self._resolve_programs: dict[tuple, object] = {}
        self._replan_programs: dict[tuple, object] = {}
        self._scoreonly_programs: dict[tuple, object] = {}
        self._survivor_programs: dict[tuple, object] = {}
        self._nfeas_cache: dict[str, object] = {}
        self._tb_program_cache: dict[str, object] = {}
        self._repair_program_cache: dict[tuple, object] = {}
        # Narrow-solve programs: the (fmt, M) tick variants, the dense
        # row re-solve for uncertified rows, and the 4-plane scatter
        # that repairs the narrow output planes in place.
        self._narrow_programs: dict[tuple, object] = {}
        self._fallback_programs: dict[str, object] = {}
        self._cert_repair_cache: dict[str, object] = {}
        # f16 score-plane compression programs (KT_SCORE_F16): the
        # compress (+exactness) store companion and the i32 upcast the
        # diff/gate paths feed from the stored plane.
        self._sco_cache: dict[str, object] = {}
        # Donating `prev` (argnums 1) lets XLA alias the previous tick's
        # output planes into the new ones: full dispatches stop holding
        # two [B, C] output generations live at once.
        donate = (1,) if self.donate else ()
        aot = self._aot.wrap
        if self.mesh is None:
            self._tick = aot("tick", jax.jit(_tick_with_diff, donate_argnums=donate))
            self._tick_compact = aot(
                "tick_compact",
                jax.jit(_tick_compact_with_diff, donate_argnums=donate),
            )
            self._cluster_shardings = None
            self._gather = aot("gather", jax.jit(_gather_packed))
            self._gather3 = aot("gather3", jax.jit(_gather_packed3))
            self._gather5 = aot("gather5", jax.jit(_gather_packed5))
            self._gather_over3 = aot("over3", jax.jit(_gather_overflow3))
            self._gather_over4 = aot("over4", jax.jit(_gather_overflow4))
            self._patch = aot("patch", jax.jit(_patch_rows))
            self._patch_compact = aot("patch_compact", jax.jit(_patch_rows))
            self._per_object_shardings = None
            self._per_object_shardings_compact = None
            self._table_shardings = None
            self._grid_sharding = None
            self._replicated = None
            self._rows_only_sharding = None
            self._rows_first = None
            self._pack_programs: dict[tuple, object] = {}
            return
        from kubeadmiral_tpu.parallel import mesh as M

        obj_dim, clus_dim = self.mesh.devices.shape
        if obj_dim > self.min_bucket or clus_dim > self.min_cluster_bucket:
            raise ValueError(
                f"mesh {self.mesh.devices.shape} larger than minimum "
                f"buckets ({self.min_bucket}, {self.min_cluster_bucket})"
            )
        grid = M.grid_sharding(self.mesh)
        self._grid_sharding = grid
        self._per_object_shardings = M.field_shardings(
            self.mesh,
            [n for n in TickInputs._fields if n not in _CLUSTER_ONLY_FIELDS],
        )
        in_shardings = (
            M.input_shardings(self.mesh),
            (grid, grid, grid, grid),
        )
        out_shardings = (
            M.output_shardings(self.mesh),
            M.rows_sharding(self.mesh),
        )
        self._tick = aot("tick", jax.jit(
            _tick_with_diff,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            donate_argnums=donate,
        ))
        self._cluster_shardings = M.field_shardings(
            self.mesh, _CLUSTER_ONLY_FIELDS
        )
        self._per_object_shardings_compact = M.compact_field_shardings(
            self.mesh, Cmp.PER_OBJECT_FIELDS
        )
        self._table_shardings = M.compact_field_shardings(
            self.mesh, Cmp.TABLE_FIELDS
        )
        self._tick_compact = aot("tick_compact", jax.jit(
            _tick_compact_with_diff,
            in_shardings=(
                M.compact_input_shardings(self.mesh),
                (grid, grid, grid, grid),
            ),
            out_shardings=out_shardings,
            donate_argnums=donate,
        ))
        rep = M.replicated(self.mesh)
        self._replicated = rep
        self._rows_only_sharding = M.rows_only_sharding(self.mesh)
        # Survivor-stream layout (KT_SURVIVOR_ROWSHARD): rank -> rows-
        # first sharding for the gathered sub-problems; None keeps the
        # pre-ISSUE-12 replicated gathers.
        if self.survivor_rowshard:
            mesh_ref = self.mesh
            rf_cache: dict[int, object] = {}

            def _rows_first(ndim: int):
                sh = rf_cache.get(ndim)
                if sh is None:
                    sh = M.rows_first_sharding(mesh_ref, ndim)
                    rf_cache[ndim] = sh
                return sh

            self._rows_first = _rows_first
        else:
            self._rows_first = None
        self._pack_programs = {}
        self._gather = aot("gather", jax.jit(
            _gather_packed,
            in_shardings=(grid, grid, grid, grid, rep),
            out_shardings=rep,
        ))
        self._gather3 = aot("gather3", jax.jit(
            _gather_packed3,
            in_shardings=(grid, grid, grid, rep),
            out_shardings=rep,
        ))
        self._gather5 = aot("gather5", jax.jit(
            _gather_packed5,
            in_shardings=(grid, grid, grid, grid, grid, rep),
            out_shardings=rep,
        ))
        # Overflow gathers bit-pack via a reshape+sum along the cluster
        # axis: like the pack sort, the gathered rows must be replicated
        # before that (GSPMD mis-combines reshapes of sharded axes).
        def _over3_meshed(sel, cnt, rep_p, idx):
            rows = tuple(
                jax.lax.with_sharding_constraint(x[idx], rep)
                for x in (sel, cnt, rep_p)
            )
            return jnp.concatenate(
                [
                    _bitpack_bool(rows[0] != 0),
                    _bitpack_bool(rows[1] != 0),
                    rows[2],
                ],
                axis=1,
            )

        def _over4_meshed(sel, cnt, rep_p, sco, idx):
            rows = tuple(
                jax.lax.with_sharding_constraint(x[idx], rep)
                for x in (sel, cnt, rep_p, sco)
            )
            return jnp.concatenate(
                [
                    _bitpack_bool(rows[0] != 0),
                    _bitpack_bool(rows[1] != 0),
                    rows[2],
                    rows[3],
                ],
                axis=1,
            )

        self._gather_over3 = aot("over3", jax.jit(
            _over3_meshed,
            in_shardings=(grid, grid, grid, rep),
            out_shardings=rep,
        ))
        self._gather_over4 = aot("over4", jax.jit(
            _over4_meshed,
            in_shardings=(grid, grid, grid, grid, rep),
            out_shardings=rep,
        ))
        self._patch = aot("patch", jax.jit(
            _patch_rows,
            in_shardings=(self._per_object_shardings, rep, rep),
            out_shardings=self._per_object_shardings,
        ))
        self._patch_compact = aot("patch_compact", jax.jit(
            _patch_rows,
            in_shardings=(self._per_object_shardings_compact, rep, rep),
            out_shardings=self._per_object_shardings_compact,
        ))

    def _zeros_for(self, shape: tuple) -> tuple:
        """Device-resident zero prev planes.  Under donation the tick
        CONSUMES its prev argument, so every call returns fresh buffers
        (the jitted builder is cached per shape; materializing zeros is
        a trivial on-device program, not a host upload); without
        donation the arrays themselves are cached."""
        if not self.donate:
            cached = self._zero_prev.get(shape)
            if cached is not None:
                return cached
        fn = self._zero_fns.get(shape)
        if fn is None:
            def make():
                return (
                    jnp.zeros(shape, jnp.int8),
                    jnp.zeros(shape, jnp.int32),
                    jnp.zeros(shape, jnp.int8),
                    jnp.zeros(shape, jnp.int32),
                )

            sharding = self._grid_sharding
            fn = (
                jax.jit(make, out_shardings=(sharding,) * 4)
                if sharding is not None
                else jax.jit(make)
            )
            fn = self._aot.wrap(f"zeros:{shape}", fn)
            fn = self._obs_wrap("zeros", fn)
            self._zero_fns[shape] = fn
        zp = fn()
        if not self.donate:
            self._zero_prev[shape] = zp
        return zp

    # -- narrow-solve programs -------------------------------------------
    def _narrow_m(self, inputs, c_bucket: int) -> Optional[int]:
        """The chunk's candidate width M, or None for the dense solve:
        pow2 over the finite maxClusters bound, floored at KT_NARROW_M
        (capacity-spill headroom — the planner's remainder cascade
        touches ~total-replicas columns, which the certificate verifies
        per row).  Narrow only pays off when M is actually narrower
        than the cluster bucket."""
        if not self.narrow:
            return None
        mc = np.asarray(inputs.max_clusters)
        finite = mc[(mc >= 0) & (mc < INT32_INF)]
        bound = int(finite.max()) if finite.size else 0
        m = _pow2_bucket(max(bound, self.narrow_m), 8, 1 << 30)
        return m if m < c_bucket else None

    def _narrow_program(self, fmt: str, m: int):
        """Jitted narrow tick per (format, M): phase-1 dense + top-M
        candidate solve + diff-vs-prev + per-row certificate, one
        dispatch — the narrow analogue of _tick_with_diff (same prev
        donation, same output shardings, plus the i8[B] cert plane)."""
        key = (fmt, m)
        fn = self._narrow_programs.get(key)
        if fn is not None:
            return fn
        rows_only = self._rows_only_sharding
        donate = (1,) if self.donate else ()

        i32_keys = self.phase1_i32
        # KT_PALLAS: the fused ops/pallas_slab.py kernel computes
        # phase 1 in one VMEM-resident pass per row block (bit-identical
        # to the XLA _phase1 — see the module's parity contract); the
        # narrow select/planner + certificates are unchanged, so cert
        # failures still re-solve through the dense (non-Pallas)
        # fallback.  Meshed engines keep the XLA path (pallas_call under
        # GSPMD needs shard_map — ROADMAP item 1's on-chip round).
        use_pallas = self.pallas and self.mesh is None

        def impl(inp, prev, _m=m, _fmt=fmt):
            if _fmt == "compact":
                inp = expand_compact(inp)
            phase1 = (
                pallas_slab_mod.phase1_slab(inp) if use_pallas else None
            )
            out, cert = schedule_tick_narrow(
                inp, _m, rows_only=rows_only, i32_keys=i32_keys,
                phase1=phase1,
            )
            return out, _diff_bits(out, prev), cert

        if self.mesh is None:
            fn = jax.jit(impl, donate_argnums=donate)
        else:
            from kubeadmiral_tpu.parallel import mesh as M

            grid = self._grid_sharding
            rows = M.rows_sharding(self.mesh)
            in_sh = (
                M.compact_input_shardings(self.mesh)
                if fmt == "compact"
                else M.input_shardings(self.mesh),
                (grid, grid, grid, grid),
            )
            fn = jax.jit(
                impl,
                in_shardings=in_sh,
                out_shardings=(M.output_shardings(self.mesh), rows, rows),
                donate_argnums=donate,
            )
        # A Pallas narrow program must not be served by (or write into)
        # a non-Pallas manifest entry: the AOT key carries the variant.
        suffix = ":pl" if use_pallas else ""
        fn = self._aot.wrap(f"tick_narrow:{fmt}:m{m}{suffix}", fn)
        fn = self._obs_wrap("tick_narrow", fn)
        self._narrow_programs[key] = fn
        return fn

    def _gather_constrainer(self, per_object):
        """Sharding-constraint closure for gathered [G, ...] sub-problems
        (narrow fallback, survivor / replan / resolve streams): returns
        ``constrain(sub, extras) -> (sub, extras)`` for use INSIDE the
        jitted impls, or None off-mesh.

        Default (KT_SURVIVOR_ROWSHARD): the gathered per-object rows
        (and the extra gathered row-planes — reasons / scores /
        feasibility / tie-break) constrain to ROWS-FIRST shardings, so
        the group's row axis partitions across the objects mesh axis and
        N devices each solve G/N rows of the (row-independent) solve —
        the per-device chunk-stream layout that turns serial survivor
        group executions into device-parallel waves.  Cluster planes and
        vocabulary tables replicate (tiny, and their axes must be whole
        per shard for the full-width sorts).  KT_SURVIVOR_ROWSHARD=0
        reverts to replicating the whole sub-problem (the pre-ISSUE-12
        behavior); outputs are bit-identical either way."""
        replicated = self._replicated
        if replicated is None:
            return None
        rows_first = self._rows_first
        per_set = frozenset(per_object)

        def constrain(sub, extras=()):
            if rows_first is None:
                sub = type(sub)(
                    *(
                        jax.lax.with_sharding_constraint(x, replicated)
                        for x in sub
                    )
                )
                extras = tuple(
                    jax.lax.with_sharding_constraint(x, replicated)
                    if x is not None
                    else None
                    for x in extras
                )
                return sub, extras
            sub = type(sub)(
                *(
                    jax.lax.with_sharding_constraint(
                        x,
                        rows_first(x.ndim) if name in per_set else replicated,
                    )
                    for name, x in zip(sub._fields, sub)
                )
            )
            extras = tuple(
                jax.lax.with_sharding_constraint(x, rows_first(x.ndim))
                if x is not None
                else None
                for x in extras
            )
            return sub, extras

        return constrain

    def _fallback_program(self, fmt: str):
        """Dense re-solve of uncertified narrow rows, straight from the
        chunk's device-resident inputs: gather the rows, run the full-
        width tick on [K, C], return the planes the narrow solve may
        have gotten wrong (scores/feasible come from the shared phase 1
        and are exact by construction).  jax re-traces per (K, B, C)
        shape; K is pow2-bucketed by the caller.  Under a mesh the
        gathered rows ride the rows-first survivor layout (see
        _gather_constrainer) — the full-width sorts run along the
        CLUSTER axis, which stays whole per shard either way."""
        fn = self._fallback_programs.get(fmt)
        if fn is not None:
            return fn
        per_object = tuple(self._per_object_fields(fmt))
        constrain = self._gather_constrainer(per_object)

        def impl(device_in, idx, _fmt=fmt):
            rows = {name: getattr(device_in, name)[idx] for name in per_object}
            sub = device_in._replace(**rows)
            if constrain is not None:
                sub, _ = constrain(sub)
            inp = expand_compact(sub) if _fmt == "compact" else sub
            out = schedule_tick.__wrapped__(inp)
            return out.selected, out.replicas, out.counted, out.reasons

        fn = self._aot.wrap(f"narrow_fallback:{fmt}", jax.jit(impl))
        fn = self._obs_wrap("narrow_fallback", fn)
        self._fallback_programs[fmt] = fn
        return fn

    def _cert_repair_program(self):
        """4-plane scatter writing the dense re-solve's rows back into
        the narrow output planes (selected/replicas/counted/reasons) —
        donated, so the repair happens in place.  Out-of-range dst rows
        (the pow2 padding) drop."""
        fn = self._cert_repair_cache.get("repair")
        if fn is None:

            def impl(planes, fb, dst):
                return tuple(
                    p.at[dst].set(f, mode="drop") for p, f in zip(planes, fb)
                )

            donate = (0,) if self.donate else ()
            fn = self._aot.wrap("cert_repair", jax.jit(impl, donate_argnums=donate))
            fn = self._obs_wrap("repair", fn)
            self._cert_repair_cache["repair"] = fn
        return fn

    def _apply_cert_fallback(
        self, out, cert_np: np.ndarray, device_in, fmt: str, n: int, timings
    ):
        """Resolve one narrow dispatch's certificate: certified rows
        stand as-is (bit-identical to the dense solve by the kernel's
        proof), uncertified rows re-solve through the dense program and
        scatter-repair the output planes BEFORE anything downstream
        (wire packing, prev stores, the flight recorder) reads them.
        Returns (possibly repaired out, fallback row indices or None)."""
        rows = np.nonzero(cert_np[:n] == 0)[0]
        self.narrow_stats["rows"] += int(n - rows.size)
        if rows.size == 0:
            return out, None
        t0 = time.perf_counter()
        self.narrow_stats["fallback"] += int(rows.size)
        b_pad = out.selected.shape[0]
        k = _pow2_bucket(rows.size, 16, 1 << 30)
        # One index array serves both sides: the gather clamps the pad
        # rows (wasted lanes), the repair scatter drops them.
        idx = np.full(k, b_pad, np.int32)
        idx[: rows.size] = rows
        self.dispatches_total += 1
        fb = self._fallback_program(fmt)(device_in, idx)
        planes = self._cert_repair_program()(
            (out.selected, out.replicas, out.counted, out.reasons), fb, idx
        )
        out = out._replace(
            selected=planes[0],
            replicas=planes[1],
            counted=planes[2],
            reasons=planes[3],
        )
        timings["narrow_fallback"] = (
            timings.get("narrow_fallback", 0.0) + time.perf_counter() - t0
        )
        return out, rows

    # -- packed export programs ------------------------------------------
    def _pack_program(self, kind: str, k: int):
        """Jitted packed-export program per (kind, K): "full" compacts a
        whole chunk's planes, "gather" compacts just the given rows.
        K is a closure constant (one cheap XLA program per K bucket)."""
        key = (kind, k)
        fn = self._pack_programs.get(key)
        if fn is not None:
            return fn
        rows_only = self._rows_only_sharding
        if kind == "full":
            def impl(sel, rep, cnt, sco, rsn, _k=k):
                if rows_only is not None:
                    # The per-row sort needs the WHOLE cluster axis on
                    # every shard (see parallel/mesh.rows_only_sharding)
                    # — keep rows sharded, replicate clusters.
                    sel, rep, cnt, sco, rsn = (
                        jax.lax.with_sharding_constraint(x, rows_only)
                        for x in (sel, rep, cnt, sco, rsn)
                    )
                return _pack_full_wire(sel, rep, cnt, sco, rsn, _k)

            if self._grid_sharding is not None:
                fn = jax.jit(
                    impl,
                    in_shardings=(self._grid_sharding,) * 5,
                    out_shardings=self._replicated,
                )
            else:
                fn = jax.jit(impl)
        else:
            replicated = self._replicated

            def impl(sel, rep, cnt, sco, rsn, idx, _k=k):
                rows = (sel[idx], rep[idx], cnt[idx], sco[idx], rsn[idx])
                if replicated is not None:
                    # Gathered rows are few: replicate them before the
                    # sort rather than sorting a sharded axis.
                    rows = tuple(
                        jax.lax.with_sharding_constraint(x, replicated)
                        for x in rows
                    )
                return pack_wire(*rows, _k)

            if self._grid_sharding is not None:
                fn = jax.jit(
                    impl,
                    in_shardings=(self._grid_sharding,) * 5 + (self._replicated,),
                    out_shardings=self._replicated,
                )
            else:
                fn = jax.jit(impl)
        fn = self._aot.wrap(f"pack:{kind}:k{k}", fn)
        fn = self._obs_wrap("pack", fn)
        self._pack_programs[key] = fn
        return fn

    def _pack_k(self, inputs, c_bucket: int, hint: int = 0) -> int:
        """The chunk's packed-slot count K.  With an adaptive ``hint``
        (cached on the chunk entry from the observed nsel distribution,
        see _observe_nsel) K follows what rows ACTUALLY select — the
        static maxClusters-bound pow2 both under-shoots (unlimited
        Divide rows selecting hundreds of clusters overflowed 55k rows
        per c5 run into the wide dense re-fetch) and over-shoots (a
        bound of 19 pads to 32 slots nobody fills).  Cold chunks fall
        back to the static bound: pow2 of the largest finite
        maxClusters, floored at pack_k_min, capped at the cluster
        bucket (K = C is lossless).  Rows whose selected set exceeds K
        raise the overflow flag and ride the dense re-fetch either way —
        the hint tunes bytes, never correctness."""
        if hint:
            return min(max(hint, 8), c_bucket)
        mc = np.asarray(inputs.max_clusters)
        finite = mc[(mc >= 0) & (mc < INT32_INF)]
        bound = int(finite.max()) if finite.size else 0
        k = _pow2_bucket(max(bound, self.pack_k_min), 8, 1 << 30)
        return min(k, c_bucket)

    def _observe_nsel(self, entry, nsel, c_bucket: int) -> None:
        """Buffer one fetched batch's true selected counts for the
        chunk's adaptive pack-K hint.  Observations are NOT applied
        here: a tick's wire crosses in several device-local pieces
        (window drains, survivor groups, overflow re-fetches), and
        applying the shrink-vote state machine per piece double-counts
        votes — e.g. two narrow pieces of one batch would cast two
        consecutive shrink votes and halve K where the aggregate batch
        casts one (the per-device-safety loose end of ISSUE 12).
        _flush_nsel aggregates every piece per entry and commits ONE
        vote per tick."""
        if entry is None:
            return
        nsel = np.asarray(nsel)
        if nsel.size == 0:
            return
        slot = self._nsel_pending.get(id(entry))
        if slot is None:
            self._nsel_pending[id(entry)] = [entry, c_bucket, [nsel]]
        else:
            slot[1] = max(slot[1], c_bucket)
            slot[2].append(nsel)

    def _flush_nsel(self) -> None:
        """Commit the tick's buffered nsel observations: one aggregated
        vote per touched chunk entry (see _observe_nsel)."""
        if not self._nsel_pending:
            return
        pending, self._nsel_pending = self._nsel_pending, {}
        for entry, c_bucket, pieces in pending.values():
            self._commit_nsel(
                entry,
                pieces[0] if len(pieces) == 1 else np.concatenate(pieces),
                c_bucket,
            )

    def _commit_nsel(self, entry, nsel, c_bucket: int) -> None:
        """Feed a tick's aggregated selected counts into the chunk's
        adaptive pack-K hint: pick the pow2 K minimizing expected wire
        bytes over the OBSERVED distribution — every row pays the
        (4K+2)-int wire width, overflow rows additionally pay the
        bit-packed [n, C] re-fetch (~4.25·C bytes: two C-bit masks plus
        the i32 replica plane).  A c5-style workload whose rows select
        a few dozen clusters lands on the K that puts overflow under
        ~1%; a workload whose rows select nearly everything keeps K at
        the floor (inflating K toward C would cost more wire than the
        re-fetch it avoids).  The hint decays by halving, so a
        shrinking distribution eventually shrinks the wire rows while
        a widening one raises K immediately.

        Two guards close the adaptive loop's loose ends (ISSUE 7):

        * **Widen-once escape**: when the byte-optimal K still leaves
          more than KT_PACK_OVERFLOW_PCT (default 1%) of rows
          overflowing, K widens to the smallest pow2 that meets the
          target — but only if that costs at most KT_PACK_WIDEN
          (default 1.25x) of the byte-optimal wire volume.  Narrow-
          selecting workloads thus hold overflow under the target
          without a meaningful byte regression; a heavy-Divide tail
          whose capture would inflate every wire row (c5: widening K
          costs more than the re-fetch it avoids) stays put, by
          design — the gate watches the emitted overflow deltas
          instead.
        * **Shrink hysteresis**: the halving decay engages only after
          two consecutive shrink votes, so alternating batch mixes
          can't oscillate K and re-pay the overflow path every other
          tick."""
        if entry is None:
            return
        nsel = np.asarray(nsel)
        if nsel.size == 0:
            return
        over_bytes = 4.25 * c_bucket

        def cost_at(k_eff: int) -> float:
            return nsel.size * (4 * k_eff + 2) * 4 + float(
                (nsel > k_eff).sum()
            ) * over_bytes

        best_k, best_cost = None, None
        k = _pow2_bucket(self.pack_k_min, 8, 1 << 30)
        while True:
            k_eff = min(k, c_bucket)
            cost = cost_at(k_eff)
            if best_cost is None or cost < best_cost:
                best_k, best_cost = k_eff, cost
            if k_eff >= c_bucket:
                break
            k *= 2
        target = float(os.environ.get("KT_PACK_OVERFLOW_PCT", "0.01"))
        widen_cap = float(os.environ.get("KT_PACK_WIDEN", "1.25"))
        if float((nsel > best_k).mean()) > target:
            k2 = best_k
            while k2 < c_bucket:
                k2 = min(k2 * 2, c_bucket)
                if float((nsel > k2).mean()) <= target:
                    break
            if cost_at(k2) <= best_cost * widen_cap:
                best_k = k2
        if best_k >= entry.pack_k_hint:
            entry.pack_k_hint = best_k
            entry.pack_shrink_votes = 0
        else:
            entry.pack_shrink_votes += 1
            if entry.pack_shrink_votes >= 2:
                entry.pack_k_hint = max(best_k, entry.pack_k_hint // 2)
                entry.pack_shrink_votes = 0

    def _pcache_entries(self) -> int:
        """Entry count of the persistent XLA compilation cache directory
        (0 when disabled/absent) — the miss detector's substrate."""
        d = self._pcache_dir
        if not d or not os.path.isdir(d):
            return 0
        try:
            return len(os.listdir(d))
        except OSError:
            return 0

    def _read_np(self, dev) -> np.ndarray:
        """Blocking device->host read with fetch-byte accounting — every
        result transfer funnels through here so engine_fetch_bytes_total
        (and bench.py's fetch_bytes) reflect real wire volume.  Host
        arrays pass through uncounted (already fetched once)."""
        if isinstance(dev, np.ndarray):
            return dev
        arr = np.asarray(dev)
        self.fetch_bytes_total += arr.nbytes
        return arr

    # -- shape policy ----------------------------------------------------
    def _tick_geometry(self, n_clusters: int) -> tuple[int, int, Optional[list]]:
        """(c_bucket, eff_chunk, row ladder or None).

        Cell-budget chunking: runtime memory (not compile time — the
        persistent cache + prewarm ladder absorb traces) bounds cells
        per chunk, so wide cluster axes get proportionally shorter
        chunks only past KT_CELL_BUDGET; KT_MEGACHUNK_ROWS caps the row
        axis independently.  The default budget keeps full 4096-row
        megachunks through C=5120 (~25 dispatches for a 100k-object
        full revalidation).  At wide C the row buckets are a fixed
        3-rung ladder so the number of distinct (expensive) programs is
        bounded; at narrow C free pow2 buckets are fine (those compiles
        are cheap).

        Device-count-aware layout (ISSUE 12): KT_CELL_BUDGET and
        KT_MEGACHUNK_ROWS are PER-DEVICE limits — a mesh with N devices
        on the objects axis multiplies both, because every [B, C] chunk
        dispatches rows-sharded so each device resides only B/N rows of
        it.  At c6 shapes (1M x 10k) a single device's budget would
        shrink chunks ~4x (and quadruple the dispatch count); 4 devices
        keep the full 4096-row megachunk.  Row buckets stay pow2 and
        the objects axis is pow2 <= min_bucket, so every rung divides
        evenly across the mesh."""
        c_bucket = _cluster_bucket(n_clusters, self.min_cluster_bucket)
        n_dev = 1 if self.mesh is None else int(self.mesh.devices.shape[0])
        max_rows = max(
            self.min_bucket,
            min(
                self.megachunk_rows * n_dev,
                (self.cell_budget * n_dev) // max(1, c_bucket),
            ),
        )
        eff_chunk = min(self.chunk_size, 1 << (max_rows.bit_length() - 1))
        ladder = None
        if c_bucket >= self.canonical_c:
            ladder = sorted(
                {
                    max(self.min_bucket, eff_chunk // 16),
                    max(self.min_bucket, eff_chunk // 4),
                    eff_chunk,
                }
            )
        return c_bucket, eff_chunk, ladder

    def _bucket_rows(
        self, n: int, ladder: Optional[list], eff_chunk: int, full: bool
    ) -> int:
        if ladder is None:
            return _pow2_bucket(n, self.min_bucket, eff_chunk)
        if full:
            # Multi-chunk batches pad every chunk (incl. the last
            # partial) to the canonical full-chunk shape: one program.
            return eff_chunk
        for rung in ladder:
            if n <= rung:
                return rung
        return eff_chunk

    # -- cluster view caching --------------------------------------------
    @staticmethod
    def _cluster_fingerprint(clusters, scalar_resources: tuple) -> tuple:
        return (
            tuple(
                (
                    c.name,
                    tuple(sorted(c.labels.items())),
                    c.taints,
                    tuple(sorted(c.allocatable.items())),
                    tuple(sorted(c.available.items())),
                    c.api_resources,
                )
                for c in clusters
            ),
            scalar_resources,
        )

    def _cached_view(self, units, clusters) -> ClusterView:
        """Reuse the per-tick cluster tensors (and the tie-break hash cache,
        which is the expensive part) while cluster state is unchanged."""
        scalars = tuple(
            sorted(
                {
                    r
                    for su in units
                    for r in su.resource_request
                    if r not in ("cpu", "memory", "ephemeral-storage")
                }
            )
        )
        fp = self._cluster_fingerprint(clusters, scalars)
        cached_fp, cached_view = self._view_cache
        if cached_fp == fp and cached_view is not None:
            return cached_view
        from kubeadmiral_tpu.scheduler.featurize import _build_cluster_view

        view = _build_cluster_view(clusters, units)
        # Tie-break hashes depend only on the cluster-name list, which
        # changes far less often than resource usage: carry the FNV cache
        # across view rebuilds so steady-state resource updates don't
        # re-hash every (object, cluster) pair.
        if cached_view is not None and cached_view.names == view.names:
            view._tiebreak_cache = cached_view._tiebreak_cache
        self._view_cache = (fp, view)
        return view

    @staticmethod
    def _topo_fingerprint(view: ClusterView) -> tuple:
        """Cluster-topology identity: everything cached rows depend on
        (names, taints, labels, api resources, scalar columns) but NOT
        resource quantities, which flow through cluster-axis tensors."""
        fp = getattr(view, "_topo_fp", None)
        if fp is None:
            fp = (
                tuple(view.names),
                tuple(view.taint_sets),
                view.taint_id.tobytes(),
                tuple(view.label_keys),
                view.label_id.tobytes(),
                tuple(frozenset(c.api_resources) for c in view.clusters),
                tuple(view.scalar_resources),
            )
            view._topo_fp = fp
        return fp

    # -- incremental featurization ---------------------------------------
    def _vocab_for(self, view: ClusterView, topo_fp: tuple) -> Optional[CompactVocab]:
        """The (engine-wide) compact vocabulary for this topology; None
        when the topology itself overflows a cap (dense fallback)."""
        if topo_fp in self._vocabs:
            return self._vocabs[topo_fp]
        try:
            vocab = CompactVocab(view, **self._vocab_caps)
        except VocabOverflow:
            self.metrics.counter("engine_vocab_overflow_total", scope="topology")
            vocab = None
        while len(self._vocabs) >= 4:  # a few recent topologies
            self._vocabs.pop(next(iter(self._vocabs)))
        self._vocabs[topo_fp] = vocab
        return vocab

    def _per_object_fields(self, fmt: str) -> Sequence[str]:
        if fmt == "compact":
            return Cmp.PER_OBJECT_FIELDS
        return [n for n in TickInputs._fields if n not in _CLUSTER_ONLY_FIELDS]

    def _featurize_rows(self, units, clusters, view, vocab, cached):
        """Featurize just the changed rows in the cached entry's format,
        aligned to its sparse/key widths; None = cannot patch (widths
        grew or vocabulary overflowed) — caller does a full miss."""
        if cached.fmt == "dense":
            return featurize(units, clusters, view=view).inputs
        if vocab is None:
            return None
        try:
            sub = featurize_compact(units, view, vocab)
        except VocabOverflow:
            self.metrics.counter("engine_vocab_overflow_total", scope="patch")
            return None
        p_cached = np.asarray(cached.inputs.sparse_idx).shape[1]
        l_cached = np.asarray(cached.inputs.key_bytes).shape[1]
        if (
            np.asarray(sub.sparse_idx).shape[1] > p_cached
            or np.asarray(sub.key_bytes).shape[1] > l_cached
        ):
            return None
        sub = Cmp.pad_axis1(sub, Cmp.SPARSE_FILLS, p_cached)
        sub = Cmp.pad_axis1(sub, {"key_bytes": 0}, l_cached)
        return sub

    def _featurize_full(self, chunk, clusters, view, vocab):
        """(inputs, fmt): compact unless the vocabulary overflows."""
        if vocab is not None:
            try:
                return featurize_compact(chunk, view, vocab), "compact"
            except VocabOverflow:
                self.metrics.counter("engine_vocab_overflow_total", scope="chunk")
        return featurize(chunk, clusters, view=view).inputs, "dense"

    def _featurize_chunk(
        self, idx: int, chunk, clusters, view: ClusterView, webhook_eval,
        vocab, dirty: Optional[list] = None,
    ) -> tuple[object, str, Optional[_CachedChunk], str]:
        """Returns (inputs, status, cache entry, fmt); status is one of
        "hit" (rows unchanged), "patch" (few rows re-featurized),
        "miss" (full featurize), "nocache" (caching not applicable).

        ``dirty`` (LOCAL chunk row indices) is the delta-featurization
        hint: the caller asserts every row OUTSIDE it is the identical
        object handed to the previous schedule() call (the streaming
        scheduler owns the canonical list, so it knows exactly which
        rows its events touched) — the identity/signature walk then
        visits only the hinted rows instead of the whole chunk.
        Ignored for snapshot-restored entries (every row still needs
        its signature verified against the snapshot) and under
        KT_DELTA_FEAT=0."""
        if webhook_eval is not None:
            # Webhook planes are per-tick HTTP results; never cached.
            fb = featurize(chunk, clusters, view=view, webhook_eval=webhook_eval)
            self.featurize_rows["full"] += len(chunk)
            return fb.inputs, "nocache", None, "dense"

        topo_fp = self._topo_fingerprint(view)
        cached = self._chunk_cache.get(idx)
        if (
            cached is not None
            and cached.topo_fp == topo_fp
            and len(cached.units) == len(chunk)
            and (
                cached.fmt == "dense"
                or (vocab is not None and cached.vocab_uid == vocab.uid)
            )
        ):
            # Identity fast-path, per ROW: identical objects mean
            # identical rows without computing signatures (SchedulingUnit
            # is immutable), so a 1%-churn tick signature-checks only the
            # replaced objects — not the whole chunk; with a dirty-row
            # hint, only the hinted rows.
            if dirty is not None and self.delta_feat and not cached.restored:
                changed = [
                    i
                    for i in dirty
                    if chunk[i] is not cached.units[i]
                    and featurize_signature(chunk[i]) != cached.sigs[i]
                ]
            else:
                changed = [
                    i
                    for i, (a, b) in enumerate(zip(chunk, cached.units))
                    if a is not b and featurize_signature(a) != cached.sigs[i]
                ]
                cached.restored = False
            refreshed = cached.inputs._replace(
                alloc=view.alloc,
                used=view.used,
                cpu_alloc=view.cpu_alloc,
                cpu_avail=view.cpu_avail,
            )
            cached.inputs = refreshed
            if not changed:
                cached.units = list(chunk)
                self.cache_stats["hit"] += 1
                return refreshed, "hit", cached, cached.fmt
            if self.delta_feat and len(changed) <= max(1, len(chunk) // 4):
                sub = self._featurize_rows(
                    [chunk[i] for i in changed], clusters, view, vocab, cached
                )
                if sub is not None:
                    rows = np.asarray(changed)
                    for name in self._per_object_fields(cached.fmt):
                        np.asarray(getattr(refreshed, name))[rows] = np.asarray(
                            getattr(sub, name)
                        )
                    for i in changed:
                        cached.sigs[i] = featurize_signature(chunk[i])
                    cached.units = list(chunk)
                    # Handed to schedule(): the freshly featurized
                    # changed rows enable the sub-batch fast path.
                    cached.last_patch = (changed, sub)
                    self.cache_stats["patch"] += 1
                    self.featurize_rows["delta"] += len(changed)
                    return refreshed, "patch", cached, cached.fmt

        inputs, fmt = self._featurize_full(chunk, clusters, view, vocab)
        self.cache_stats["miss"] += 1
        self.featurize_rows["full"] += len(chunk)
        if cached is not None:
            self._cache_used -= cached.nbytes
            del self._chunk_cache[idx]
        host_bytes = sum(
            np.asarray(getattr(inputs, name)).nbytes
            for name in self._per_object_fields(fmt)
        )
        # Budget charge covers everything the entry pins, not just the
        # host arrays: a device-resident copy of the (padded, so up to
        # 2x along each axis) per-object tensors, plus the previous
        # tick's device outputs (i8+i32+i8+i32 = 10 bytes/cell), the
        # drift gate's feasibility plane (+1 byte/cell) and the
        # drift-resolve reason plane (+4 bytes/cell).
        # Decoded result dicts are small relative to the tensor planes.
        b = len(chunk)
        c = np.asarray(inputs.cluster_valid).shape[0]
        # prev_out device planes live at PADDED shape — charge for it.
        b_pad = _pow2_bucket(b, self.min_bucket, 1 << 30)
        c_pad = _cluster_bucket(c, self.min_cluster_bucket)
        nbytes = host_bytes * 3 + b_pad * c_pad * 15
        entry = None
        if self._cache_used + nbytes <= self.cache_bytes:
            entry = _CachedChunk(
                sigs=[featurize_signature(su) for su in chunk],
                units=list(chunk),
                inputs=inputs,
                fmt=fmt,
                topo_fp=topo_fp,
                nbytes=nbytes,
                vocab_uid=vocab.uid if (fmt == "compact" and vocab) else 0,
            )
            prev_names = getattr(cached.prev_view, "names", None) if cached else None
            if (
                cached is not None
                and cached.fmt == fmt
                and len(cached.units) == len(chunk)
                and cached.prev_results is not None
                and len(cached.prev_results) == len(chunk)
                and prev_names is not None
                and list(prev_names) == list(view.names)
            ):
                # Carry the previous tick's outputs across the miss —
                # reached on topology-changing re-featurizes with a
                # stable fleet (label/taint churn) and on mass row churn
                # past the patch threshold; capacity-only drift is a
                # cache HIT and rides the hit-path delta machinery.  The
                # delta fetch diffs NEW device outputs against the
                # carried planes, transferring only rows whose decisions
                # actually moved (VERDICT r3 #3).
                # Sound ONLY while the cluster-name order is unchanged:
                # the diff mask compares raw output columns, so a
                # renamed/reordered fleet with a coincidentally identical
                # output pattern would otherwise reuse decodes that map
                # indices to the WRONG cluster names.
                entry.prev_out = cached.prev_out
                entry.prev_feas = cached.prev_feas
                entry.prev_reasons = cached.prev_reasons
                entry.prev_nfeas = cached.prev_nfeas
                entry.prev_results = cached.prev_results
                entry.prev_has_scores = cached.prev_has_scores
                entry.stale_out_rows = cached.stale_out_rows
            self._chunk_cache[idx] = entry
            self._cache_used += nbytes
        return inputs, "miss", entry, fmt

    # -- the tick ---------------------------------------------------------
    def schedule(
        self,
        units: Sequence[T.SchedulingUnit],
        clusters: Sequence[T.ClusterState],
        view: Optional[ClusterView] = None,
        webhook_eval=None,
        want_scores: bool = False,
        follower_index=None,
        dirty_rows=None,
    ) -> list[ScheduleResult]:
        """``want_scores`` additionally decodes per-cluster score dicts
        (only webhook select plugins consume them).  Scores ride the
        same cache/delta machinery as placements — a want_scores
        consumer pays score decoding, not a fast-path bypass.

        ``follower_index`` (an :class:`ops.follower.FollowerIndex`)
        applies follower-scheduling unions over the returned rows
        incrementally, driven by this tick's changed-row set.

        ``dirty_rows`` (GLOBAL row indices) is the delta-featurization
        hint: callers that know exactly which rows changed since their
        previous schedule() call over this unit list (the streaming
        scheduler's event log) pass them so the featurizer's
        identity/signature walk is O(changed), not O(world).  Rows
        outside the hint MUST be the identical unit objects of that
        previous call — the contract is the caller's to keep."""
        if not units:
            self.last_changed = []
            return []
        # One tick at a time (see _schedule_lock): overlapping ticks
        # from multi-threaded batch workers would race the chunk cache.
        with self._schedule_lock:
            cache0 = dict(self.cache_stats)
            fetch0 = dict(self.fetch_stats)
            bytes0 = self.fetch_bytes_total
            overflow0 = self.overflow_rows_total
            upload0 = dict(self.upload_bytes)
            drift0 = dict(self.drift_stats)
            narrow0 = dict(self.narrow_stats)
            feat0 = dict(self.featurize_rows)
            stale0 = dict(self.stale_repair_rows)
            # Arm the flight recorder for this tick: record sites (the
            # fetch/decode helpers) consume _tick_rec; ticks riding the
            # noop/skip fast paths record nothing and the previous
            # records stay current (the tick provably reproduced the
            # previous outputs).
            rec = self.flightrec if (self.flightrec is not None and self.flightrec.enabled) else None
            self._tick_rec = rec
            if rec is not None:
                rec.begin_tick(len(units), len(clusters))
            self.tick_seq += 1
            # One correlation id per tick, shared by the trace span, the
            # dispatch-ledger waterfall and the structured logs.
            tick_id = self.devprof.begin_tick(
                engine_tick=self.tick_seq,
                objects=len(units),
                clusters=len(clusters),
            ) or self.tick_seq
            self.last_tick_id = tick_id
            t_start = time.perf_counter()
            try:
                with trace.span(
                    "engine.schedule", objects=len(units),
                    clusters=len(clusters), tick=tick_id,
                ):
                    results = self._schedule_impl(
                        units, clusters, view=view, webhook_eval=webhook_eval,
                        want_scores=want_scores, follower_index=follower_index,
                        dirty_rows=dirty_rows,
                    )
            finally:
                if rec is not None:
                    rec.end_tick()
                self.devprof.end_tick(self.timings)
            wall = time.perf_counter() - t_start
            self._emit_tick_metrics(
                len(units), wall, cache0, fetch0,
                bytes0, overflow0, upload0, drift0, narrow0, feat0,
                stale0,
            )
            if self.post_tick is not None:
                # Durable-snapshot hook (runtime/snapshot.py): runs
                # under the schedule lock so the captured planes belong
                # to THIS converged tick.  A persistence failure logs,
                # never breaks scheduling.
                try:
                    self.post_tick(self)
                except Exception:
                    log.warning("post-tick hook failed", exc_info=True)
            if log.isEnabledFor(logging.DEBUG):
                log.debug(
                    "tick=%d objects=%d clusters=%d wall_ms=%.1f stages=%s "
                    "fetch_paths=%s",
                    tick_id, len(units), len(clusters), wall * 1e3,
                    {k: round(v * 1e3, 1) for k, v in self.timings.items()},
                    {
                        k: v - fetch0.get(k, 0)
                        for k, v in self.fetch_stats.items()
                        if v - fetch0.get(k, 0)
                    },
                )
            return results

    def _emit_tick_metrics(
        self, n_units: int, wall: float, cache0: dict, fetch0: dict,
        bytes0: int = 0, overflow0: int = 0,
        upload0: Optional[dict] = None, drift0: Optional[dict] = None,
        narrow0: Optional[dict] = None, feat0: Optional[dict] = None,
        stale0: Optional[dict] = None,
    ) -> None:
        """Per-tick telemetry: stage-latency histograms, cache/fetch path
        counters (as deltas of the raw dict stats over this call), true
        XLA recompile events drained from ops.pipeline, and shape-count
        gauges — the measurement substrate every perf PR reads."""
        m = self.metrics
        m.counter("engine_ticks_total")
        m.store("engine_tick_objects", n_units)
        m.histogram("engine_tick_seconds", wall)
        for stage, secs in self.timings.items():
            m.histogram("engine_tick_stage_seconds", secs, stage=stage)
        for key, value in self.cache_stats.items():
            delta = value - cache0.get(key, 0)
            if delta:
                m.counter("engine_chunk_cache_total", delta, result=key)
        for key, value in self.fetch_stats.items():
            delta = value - fetch0.get(key, 0)
            if delta:
                m.counter("engine_fetch_total", delta, path=key)
        bytes_delta = self.fetch_bytes_total - bytes0
        if bytes_delta:
            m.counter(
                "engine_fetch_bytes_total", bytes_delta, format=self.fetch_format
            )
        overflow_delta = self.overflow_rows_total - overflow0
        if overflow_delta:
            m.counter("engine_fetch_overflow_rows_total", overflow_delta)
        for plane, value in self.upload_bytes.items():
            delta = value - (upload0 or {}).get(plane, 0)
            if delta:
                m.counter("engine_upload_bytes_total", delta, plane=plane)
        for kind in (
            "skip", "wcheck", "wcheck_changed", "recompute", "resolve",
            "resolve_fallback", "replan", "replan_fallback",
            "score_only", "score_only_fallback",
            "unified", "unified_fallback",
        ):
            delta = self.drift_stats[kind] - (drift0 or {}).get(kind, 0)
            if delta:
                m.counter("engine_drift_rows_total", delta, kind=kind)
        for phase, value in self.stale_repair_rows.items():
            delta = value - (stale0 or {}).get(phase, 0)
            if delta:
                m.counter("engine_stale_rows_total", delta, phase=phase)
        for path, value in self.featurize_rows.items():
            delta = value - (feat0 or {}).get(path, 0)
            if delta:
                m.counter("engine_featurize_rows_total", delta, path=path)
        for key, path in (("rows", "narrow"), ("fallback", "fallback")):
            delta = self.narrow_stats[key] - (narrow0 or {}).get(key, 0)
            if delta:
                m.counter("engine_narrow_rows_total", delta, path=path)
        events = pipeline_mod.drain_trace_events()
        for program, b, c in events:
            m.counter("engine_xla_compiles_total", program=program, shape=f"{b}x{c}")
        if events:
            # Persistent-cache attribution: a trace that WROTE a new
            # on-disk cache entry was a real compile (miss); one that
            # didn't was served from the persistent cache (hit).  Entry
            # counting is approximate under the concurrent prewarm
            # thread, but per-tick deltas are exact in steady state.
            new_count = self._pcache_entries()
            misses = max(0, min(len(events), new_count - self._pcache_count))
            self._pcache_count = new_count
            if misses:
                m.counter("engine_persistent_cache_total", misses, result="miss")
            if len(events) - misses:
                m.counter(
                    "engine_persistent_cache_total",
                    len(events) - misses,
                    result="hit",
                )
        m.store("engine_program_shapes", len(self.program_shapes))
        if self._tick_rec is not None:
            st = self._tick_rec.stats()
            m.store("flightrec_records", st["records"])
            m.store("flightrec_bytes", st["bytes"])
            m.store("flightrec_ring_ticks", st["ring_ticks"])

    def _count_dispatch(self, fmt: str, b_pad: int, c_bucket: int) -> None:
        """Program-shape cache accounting for one device dispatch: a
        shape's first dispatch is the compile-cache "miss" (it traces a
        new XLA program), every later one a "hit"."""
        shape_key = (fmt, b_pad, c_bucket)
        shape = f"{fmt}:{b_pad}x{c_bucket}"
        self.metrics.counter(
            "engine_compile_cache_total",
            result="hit" if shape_key in self.program_shapes else "miss",
            shape=shape,
        )
        self.metrics.counter("engine_dispatches_total", shape=shape)
        self.dispatches_total += 1
        self.program_shapes.add(shape_key)


    # -- crash recovery: durable snapshots (runtime/snapshot.py) ----------
    def _snapshot_config(self) -> dict:
        """The engine-shape fingerprint a snapshot must match to be
        restorable: anything that changes the chunk split, the padded
        plane shapes, or the solve structure.  A mismatch rejects the
        snapshot (cold boot) — restore never reinterprets planes."""
        return {
            "version": SNAPSHOT_STATE_VERSION,
            "chunk_size": self.chunk_size,
            "cell_budget": self.cell_budget,
            "megachunk_rows": self.megachunk_rows,
            "min_bucket": self.min_bucket,
            "min_cluster_bucket": self.min_cluster_bucket,
            "canonical_c": self.canonical_c,
            "fetch_format": self.fetch_format,
            "narrow": self.narrow,
            "narrow_m": self.narrow_m,
            "mesh": None if self.mesh is None else tuple(self.mesh.devices.shape),
            "score_f16": self.score_f16,
        }

    def snapshot_state(self) -> Optional[dict]:
        """Host-side image of the engine's resumable working set: per
        converged chunk the prev output planes (placements / scores /
        feasibility / reasons), row signatures and adaptive-K hints,
        plus the cluster tensors they were computed against.  None when
        there is nothing coherent to persist (no converged tick yet, or
        the cache is mid-transition).  Callers serialize ticks around
        this (the SnapshotManager hook runs under the schedule lock)."""
        entries = sorted(self._chunk_cache.items())
        if not entries:
            return None
        view = None
        for _idx, e in entries:
            if e.prev_view is not None:
                view = e.prev_view
                break
        if view is None or getattr(view, "names", None) is None:
            return None
        chunks: dict[int, dict] = {}
        rows = 0
        for idx, e in entries:
            if (
                e.prev_view is not view
                or e.prev_out is None
                or e.prev_feas is None
                or e.prev_reasons is None
                or e.prev_results is None
                or len(e.prev_results) != len(e.units)
                or e.stale_out_rows  # device planes disagree with decodes
            ):
                continue
            # np.asarray on a sharded device array gathers the shards
            # host-side — capture works identically at any device count
            # (the sharded-engine round trip is pinned by
            # tests/test_multidevice.py).
            sel, rep, cnt, sco = (np.asarray(p) for p in e.prev_out)
            chunks[idx] = {
                "n": len(e.units),
                "fmt": e.fmt,
                "sigs": list(e.sigs),
                "has_scores": e.prev_has_scores,
                "pack_k_hint": e.pack_k_hint,
                "pack_shrink_votes": e.pack_shrink_votes,
                "sel": sel,
                "rep": rep,
                "cnt": cnt,
                "sco": sco,
                "feas": np.asarray(e.prev_feas),
                "rsn": np.asarray(e.prev_reasons),
            }
            if self.score_f16:
                # The exactness vector cannot be re-derived from the
                # f16 plane alone (the true i32 scores are gone), so it
                # rides the snapshot; a missing vector restores as
                # all-inexact (conservative).
                chunks[idx]["sco_exact"] = (
                    np.asarray(e.prev_sco_exact)
                    if e.prev_sco_exact is not None
                    else None
                )
            rows += len(e.units)
        if not chunks:
            return None
        return {
            "version": SNAPSHOT_STATE_VERSION,
            "config": self._snapshot_config(),
            "tick": self.tick_seq,
            "names": list(view.names),
            "topo_fp": self._topo_fingerprint(view)
            if not isinstance(view, _SnapshotView)
            else None,
            "view": {
                "alloc": np.asarray(view.alloc).copy(),
                "used": np.asarray(view.used).copy(),
                "cpu_alloc": np.asarray(view.cpu_alloc).copy(),
                "cpu_avail": np.asarray(view.cpu_avail).copy(),
            },
            "rows": rows,
            "chunks": chunks,
        }

    def resident_state_bytes(self) -> dict:
        """Walk the chunk cache and sum the ACTUAL device bytes of the
        resident working set, by plane family — the live half of the c6
        memory census (runtime/census.py projects the same inventory
        analytically to 1M x 10k and validates its model against this).
        ``per_device`` divides rows-sharded planes by the objects-axis
        device count and books replicated planes whole on every device
        — the number the HBM budget knob is compared against."""
        n_dev = 1 if self.mesh is None else int(self.mesh.devices.shape[0])

        def nbytes(x) -> int:
            return int(getattr(x, "nbytes", 0) or 0)

        fams = {
            "prev_planes": 0,     # sel/rep/cnt/sco + feas + reasons [B, C]
            "per_object": 0,      # cached per-object input tensors
            "tiebreak": 0,        # precomputed planner tie-break planes
            "vectors": 0,         # nfeas / sco_exact [B] companions
        }
        for e in self._chunk_cache.values():
            if e.prev_out is not None:
                fams["prev_planes"] += sum(nbytes(p) for p in e.prev_out)
            fams["prev_planes"] += nbytes(e.prev_feas) + nbytes(e.prev_reasons)
            if e.device_per_object is not None:
                fams["per_object"] += sum(
                    nbytes(a) for a in e.device_per_object.values()
                )
            fams["tiebreak"] += nbytes(e.tiebreak_dev)
            fams["vectors"] += nbytes(e.prev_nfeas) + nbytes(e.prev_sco_exact)
        total = sum(fams.values())
        # Rows-sharded [B, ...] planes divide across the objects axis;
        # the [B] vectors are replicated per device.
        sharded = total - fams["vectors"]
        per_device = sharded // n_dev + fams["vectors"]
        for family, v in fams.items():
            self.metrics.store("engine_resident_bytes", v, family=family)
        self.metrics.store("engine_resident_bytes_per_device", per_device)
        return {
            "by_family": fams,
            "total": total,
            "device_count": n_dev,
            "per_device": per_device,
            "score_dtype": "f16" if self.score_f16 else "i32",
            "chunks": len(self._chunk_cache),
        }

    def stage_restore(self, payload: Optional[dict], assume_fresh: bool = False) -> None:
        """Stage a snapshot payload for consumption at the next tick
        (the first ``schedule()`` call has the relisted units + clusters
        the restore must verify against).  ``assume_fresh`` records that
        the caller's resourceVersion watermarks matched the relist —
        telemetry only: freshness is RE-PROVEN inside the engine by
        cluster-tensor equality plus the per-row signature walk, so a
        lying watermark can cost a re-solve, never a wrong placement."""
        # Under the schedule lock: the manager stages from its boot
        # thread while a streaming pump may already be ticking — an
        # unlocked swap could hand _consume_restore a torn pair.
        with self._schedule_lock:
            if payload is None:
                self._pending_restore = None
                return
            self._pending_restore = (payload, bool(assume_fresh))

    @lockcheck.assumes_held("_schedule_lock")
    def _consume_restore(self, units, clusters, view: ClusterView) -> None:
        payload, assume_fresh = self._pending_restore
        self._pending_restore = None
        info = {
            "result": "rejected", "fresh": False, "chunks": 0, "rows": 0,
            "watermarks_matched": assume_fresh,
        }
        self.restore_info = info
        try:
            self._restore_impl(payload, units, clusters, view, info)
        except Exception:
            log.warning("snapshot restore failed; falling back cold", exc_info=True)
            info["result"] = "rejected"
        result = info["result"]
        if result == "loaded":
            result = "loaded_fresh" if info["fresh"] else "loaded_stale"
        self.metrics.counter("engine_snapshot_total", result=result)
        log.info(
            "snapshot restore: %s chunks=%d rows=%d fresh=%s",
            result, info["chunks"], info["rows"], info["fresh"],
        )

    def _restore_impl(self, payload, units, clusters, view, info) -> None:
        if payload.get("version") != SNAPSHOT_STATE_VERSION:
            return
        if payload.get("config") != self._snapshot_config():
            return
        topo_fp = self._topo_fingerprint(view)
        if payload.get("topo_fp") != topo_fp:
            return  # labels/taints/api-resources moved: rows invalid
        if payload.get("names") != list(view.names):
            return
        snap_view = payload["view"]
        if np.asarray(snap_view["alloc"]).shape != np.asarray(view.alloc).shape:
            return
        # Freshness is decided by CONTENT, not by trust: bit-identical
        # cluster tensors + the per-row signature walk below mean the
        # snapshot world IS the relisted world, and the first tick rides
        # the O(B) no-op replay.  Anything else resumes as a capacity
        # drift against the snapshot view.
        fresh = all(
            np.array_equal(np.asarray(snap_view[k]), np.asarray(getattr(view, k)))
            for k in ("alloc", "used", "cpu_alloc", "cpu_avail")
        )
        old_view = (
            view
            if fresh
            else _SnapshotView(
                payload["names"], snap_view["alloc"], snap_view["used"],
                snap_view["cpu_alloc"], snap_view["cpu_avail"],
            )
        )
        c_bucket, eff_chunk, ladder = self._tick_geometry(len(view.clusters))
        multi_chunk = len(units) > eff_chunk
        vocab = self._vocab_for(view, topo_fp)
        snap_chunks = payload.get("chunks") or {}
        restored = rows = 0
        for chunk_idx, start in enumerate(range(0, len(units), eff_chunk)):
            cs = snap_chunks.get(chunk_idx)
            chunk = units[start : start + eff_chunk]
            if cs is None or cs["n"] != len(chunk):
                continue
            b_pad = self._bucket_rows(len(chunk), ladder, eff_chunk, multi_chunk)
            if tuple(cs["sel"].shape) != (b_pad, c_bucket):
                continue
            inputs, fmt = self._featurize_full(chunk, clusters, view, vocab)
            self.featurize_rows["full"] += len(chunk)
            if fmt != cs["fmt"]:
                continue
            if cs["has_scores"] and self.score_f16:
                # The serialized score plane is f16: lossy rows' score
                # DICTS cannot be replayed bit-exactly — cold-solve the
                # chunk instead (want_scores consumers are rare).
                continue
            host_bytes = sum(
                np.asarray(getattr(inputs, name)).nbytes
                for name in self._per_object_fields(fmt)
            )
            c = np.asarray(inputs.cluster_valid).shape[0]
            nbytes = (
                host_bytes * 3
                + _pow2_bucket(len(chunk), self.min_bucket, 1 << 30)
                * _cluster_bucket(c, self.min_cluster_bucket)
                * 15
            )
            if self._cache_used + nbytes > self.cache_bytes:
                continue
            entry = _CachedChunk(
                sigs=list(cs["sigs"]),
                units=[_RESTORE_SENTINEL] * len(chunk),
                inputs=inputs,
                fmt=fmt,
                topo_fp=topo_fp,
                nbytes=nbytes,
                vocab_uid=vocab.uid if (fmt == "compact" and vocab) else 0,
            )
            # Device residency: the per-object planes (the drift gate /
            # sub-batch substrate) and the prev output planes.  This is
            # the cold upload cost, paid at restore instead of inside
            # the first tick's critical path.
            padded = self._pad_for_dispatch(
                inputs, fmt, b_pad, c_bucket, skip_cluster_fields=True
            )
            fields = padded._asdict()
            per_object = {
                name: fields[name] for name in self._per_object_fields(fmt)
            }
            if fmt == "compact":
                shape = (
                    b_pad, c_bucket,
                    np.asarray(padded.sparse_idx).shape[1],
                    np.asarray(padded.key_bytes).shape[1],
                )
                shardings = self._per_object_shardings_compact
            else:
                shape = (b_pad, c_bucket)
                shardings = self._per_object_shardings
            self.upload_bytes["object"] += sum(
                np.asarray(a).nbytes for a in per_object.values()
            )
            entry.device_per_object = (
                jax.device_put(per_object, shardings)
                if shardings is not None
                else jax.device_put(per_object)
            )
            entry.padded_shape = shape
            entry.restored = True
            # No tie-break plane build here: a fresh resume must stay
            # ZERO dispatches (the no-op replay guarantee); a stale
            # resume's first drift builds it lazily (_tiebreak_plane).
            grid = self._grid_sharding

            def put(arr, dtype):
                # Under a mesh the planes re-device_put straight into
                # the grid (rows x clusters) layout every consumer
                # program expects — restore never leaves a plane
                # committed to one device of a multi-device engine.
                arr = np.ascontiguousarray(np.asarray(arr), dtype=dtype)
                return (
                    jax.device_put(arr, grid) if grid is not None else jax.device_put(arr)
                )

            def put_rep(arr):
                # [B] companion vectors are replicated per device (the
                # layout _nfeas_program / the repair scatter emit).
                return (
                    jax.device_put(arr, self._replicated)
                    if self._replicated is not None
                    else jax.device_put(arr)
                )

            sel, rep = cs["sel"], cs["rep"]
            cnt, sco = cs["cnt"], cs["sco"]
            entry.prev_out = (
                put(sel, np.int8), put(rep, np.int32),
                put(cnt, np.int8),
                put(sco, np.float16 if self.score_f16 else np.int32),
            )
            entry.prev_feas = put(cs["feas"], np.int8)
            entry.prev_reasons = put(cs["rsn"], np.int32)
            if self.score_f16:
                se = cs.get("sco_exact")
                if se is not None:
                    entry.prev_sco_exact = put_rep(
                        np.ascontiguousarray(se, dtype=np.int8)
                    )
                entry.sco_inexact_host = None
            # The cached nfeas vector is DERIVED, not serialized: a
            # host-side row sum at restore keeps the snapshot format
            # stable and the zero-dispatch fresh-resume guarantee intact.
            entry.prev_nfeas = put_rep(
                (np.asarray(cs["feas"]) != 0).sum(axis=1).astype(np.int32)
            )
            n = len(chunk)
            entry.prev_results = self._decode_rows(
                np.asarray(sel)[:n], np.asarray(rep)[:n], np.asarray(cnt)[:n],
                view.names,
                scores=np.asarray(sco)[:n] if cs["has_scores"] else None,
            )
            entry.prev_has_scores = bool(cs["has_scores"])
            entry.prev_view = old_view
            entry.pack_k_hint = int(cs.get("pack_k_hint", 0))
            entry.pack_shrink_votes = int(cs.get("pack_shrink_votes", 0))
            existing = self._chunk_cache.pop(chunk_idx, None)
            if existing is not None:
                self._cache_used -= existing.nbytes
            self._chunk_cache[chunk_idx] = entry
            self._cache_used += nbytes
            restored += 1
            rows += n
        info.update(
            result="loaded" if restored else "rejected",
            fresh=fresh and bool(restored),
            chunks=restored,
            rows=rows,
        )

    def _schedule_impl(
        self,
        units: Sequence[T.SchedulingUnit],
        clusters: Sequence[T.ClusterState],
        view: Optional[ClusterView] = None,
        webhook_eval=None,
        want_scores: bool = False,
        follower_index=None,
        dirty_rows=None,
    ) -> list[ScheduleResult]:
        units_arg = units
        units = list(units)
        if not units:
            self.last_changed = []
            return []
        if view is None:
            view = self._cached_view(units, clusters)
        if self._pending_restore is not None:
            # Crash recovery: a staged snapshot (stage_restore) is
            # consumed HERE, where the relisted units + clusters it must
            # be verified against exist.  Restored chunks then ride the
            # ordinary hit/noop/drift/sub-batch machinery below — the
            # snapshot only ever seeds ``prev`` state, never outputs.
            self._consume_restore(units, clusters, view)
        # O(1)/O(B) whole-batch no-op gate: the SAME units list object
        # against the SAME cluster view is byte-identical input (units
        # are frozen by contract, and the list container must be treated
        # as immutable too — derive changed batches as fresh lists,
        # exactly like the controllers and the bench churn do), so the
        # previous results replay without even the per-chunk signature
        # walk — at 100k x 5k that walk alone costs ~0.6s per no-op tick
        # across the chunks.  A FRESH list holding the same row objects
        # replays too, via the content-identity arm: the stored id array
        # is compared against the new list's ids in one vectorized pass
        # (~5ms at 100k rows; sound because the gate keeps the original
        # objects alive, so a live id() match IS object identity).
        # Webhook ticks never arm or hit the gate (their plugin set is
        # outside the key).
        if webhook_eval is None and self._noop_gate is not None:
            g_units, g_ids, g_view, g_ws, g_fidx, g_results, g_chunks = (
                self._noop_gate
            )
            replay = (
                units_arg is g_units
                and view is g_view
                and want_scores == g_ws
                and follower_index is g_fidx
            )
            if (
                not replay
                and view is g_view
                and want_scores == g_ws
                and follower_index is g_fidx
                and len(units) == len(g_units)
            ):
                ids = np.fromiter(map(id, units), np.int64, count=len(units))
                if np.array_equal(ids, g_ids):
                    replay = True
                    # Re-arm on the new container so the O(1) identity
                    # check works for its re-submissions too.
                    self._noop_gate = (
                        units_arg, g_ids, g_view, g_ws, g_fidx, g_results,
                        g_chunks,
                    )
            if replay:
                self.fetch_stats["noop"] += g_chunks
                self.last_changed = []
                self.timings = {
                    "featurize": 0.0, "device": 0.0, "fetch": 0.0, "decode": 0.0,
                }
                # Fresh list: callers may post-process their copy without
                # corrupting future replays (rows are shared + frozen).
                return list(g_results)
        # Chunk pipelining: with KT_PIPELINE_DEPTH > 1 (default 16) up
        # to that many chunks' programs stay in flight — featurize/
        # dispatch continues while the device computes — and the window
        # is then drained with BATCHED per-wire-shape transfers
        # (_drain_fetch_window / _drain_window_packed).  Depth 1 keeps
        # the old strictly-sequential dispatch->pull per chunk, which
        # only wins when per-transfer latency is negligible AND memory
        # for in-flight output planes is tight (docs/operations.md
        # documents the knob and the sizing math).
        chunk_results: list[Optional[list[ScheduleResult]]] = []
        # Per chunk: LOCAL row indices whose placement may have changed
        # this tick ([] = none, None = unknown/all) — consumed by
        # follower union and exposed as ``last_changed``.
        chunk_changed: list[Optional[list[int]]] = []
        # (slot, entry, changed rows, featurized rows, inputs_stale):
        # consumed by the shared sub-batch slab pass.  inputs_stale says
        # whether the rows' HOST inputs changed (churn patches) — drift
        # recomputes reuse unchanged inputs, so their device copies are
        # not marked stale.
        pending_sub: list[tuple] = []
        pending_fetch: list[tuple] = []
        # Drift-gated chunks awaiting their row classification masks.
        pending_gate: list[tuple] = []
        drift_cache: dict[int, object] = {}
        timings = {"featurize": 0.0, "device": 0.0, "fetch": 0.0, "decode": 0.0}
        self.timings = timings
        c_bucket, eff_chunk, ladder = self._tick_geometry(len(view.clusters))
        multi_chunk = len(units) > eff_chunk
        vocab = (
            self._vocab_for(view, self._topo_fingerprint(view))
            if webhook_eval is None
            else None
        )
        dirty_sorted = (
            np.asarray(sorted(dirty_rows), dtype=np.int64)
            if dirty_rows is not None
            else None
        )
        for chunk_idx, start in enumerate(range(0, len(units), eff_chunk)):
            chunk = units[start : start + eff_chunk]
            dirty_chunk = None
            if dirty_sorted is not None:
                lo = np.searchsorted(dirty_sorted, start)
                hi = np.searchsorted(dirty_sorted, start + len(chunk))
                dirty_chunk = (dirty_sorted[lo:hi] - start).tolist()
            t0 = time.perf_counter()
            with trace.span(
                "engine.featurize", chunk=chunk_idx, rows=len(chunk)
            ) as f_span:
                inputs, status, entry, fmt = self._featurize_chunk(
                    chunk_idx, chunk, clusters, view, webhook_eval, vocab,
                    dirty=dirty_chunk,
                )
                f_span.set(status=status, fmt=fmt)
            patch_info = None
            if entry is not None:
                patch_info, entry.last_patch = entry.last_patch, None

            # The cached decode is reusable only if it carries at least
            # what this tick needs (scores included when want_scores).
            prev_valid = (
                entry is not None
                and entry.prev_results is not None
                and len(entry.prev_results) == len(chunk)
                and (entry.prev_has_scores or not want_scores)
            )

            # No-op shortcut: a clean cache hit against the very same
            # cluster view is byte-identical input — the deterministic
            # tick would reproduce the previous outputs, so skip the
            # dispatch entirely (the engine-level analogue of the
            # reference's trigger-hash skip, schedulingtriggers.go:64-67).
            if status == "hit" and prev_valid and entry.prev_view is view:
                self.fetch_stats["noop"] += 1
                timings["featurize"] += time.perf_counter() - t0
                # Shared by reference: results are frozen (see
                # ScheduleResult), so no defensive copy.
                chunk_results.append(entry.prev_results)
                chunk_changed.append([])
                continue

            # Sub-batch fast path: the tick is row-independent (every
            # object's outputs depend only on its own row + the shared
            # cluster tensors), so when ONLY rows changed and the
            # cluster view is identical, scheduling just those rows and
            # merging is exact — O(changed) device work and transfer
            # instead of O(chunk).
            if (
                status == "patch"
                and prev_valid
                and entry.prev_view is view
                and patch_info is not None
            ):
                changed_rows, sub_inputs = patch_info
                pending_sub.append(
                    (len(chunk_results), entry, changed_rows, sub_inputs, True)
                )
                chunk_results.append(None)  # filled by the sub-batch pass
                chunk_changed.append(list(changed_rows))
                self.fetch_stats["subbatch"] += 1
                timings["featurize"] += time.perf_counter() - t0
                continue

            b_pad = self._bucket_rows(len(chunk), ladder, eff_chunk, multi_chunk)
            pack_k = self._pack_k(
                inputs, c_bucket, entry.pack_k_hint if entry is not None else 0
            )

            drift_info = None
            if (
                status == "hit"
                and entry is not None
                and entry.prev_view is not None
                and entry.prev_view is not view
            ):
                drift_info = self._drift_delta(
                    entry.prev_view, view, drift_cache
                )
            drift_ok = drift_info is not None
            if drift_ok and drift_info["empty"] and prev_valid:
                # The views differ only in ways that round to identical
                # cluster tensors: every row provably reproduces its
                # previous outputs — no device work at all.
                self.fetch_stats["skip"] += 1
                self.drift_stats["gated"] += 1
                self.drift_stats["skip"] += len(chunk)
                entry.prev_view = view
                chunk_results.append(entry.prev_results)
                chunk_changed.append([])
                timings["featurize"] += time.perf_counter() - t0
                continue

            # Drift fast path: a clean cache hit whose ONLY change is
            # cluster resource quantities classifies rows exactly (cheap
            # gate program over the cached device planes) instead of
            # re-running select+planner math over the whole chunk.
            if (
                status == "hit"
                and drift_ok
                and prev_valid
                and not want_scores
                and not entry.prev_has_scores
                and entry.prev_out is not None
                and entry.prev_feas is not None
                and entry.device_per_object is not None
                and entry.prev_out[0].shape == (b_pad, c_bucket)
                and entry.prev_feas.shape == (b_pad, c_bucket)
                and entry.padded_shape is not None
                and entry.padded_shape[0] == b_pad
            ):
                gate_dev = self._dispatch_drift_gate(
                    entry, fmt, c_bucket, drift_info, vocab, view
                )
                pending_gate.append(
                    (len(chunk_results), entry, len(chunk), gate_dev, fmt,
                     b_pad, pack_k, drift_info)
                )
                chunk_results.append(None)
                chunk_changed.append(None)
                timings["featurize"] += time.perf_counter() - t0
                continue

            padded = self._pad_for_dispatch(
                inputs, fmt, b_pad, c_bucket, skip_cluster_fields=True
            )
            t1 = time.perf_counter()
            timings["featurize"] += t1 - t0
            with trace.span(
                "engine.device_dispatch",
                chunk=chunk_idx,
                shape=f"{fmt}:{b_pad}x{c_bucket}",
            ):
                device_in = self._device_inputs(
                    entry, padded, status, fmt, vocab, c_bucket,
                    self._cluster_planes_device(view, c_bucket),
                )
                out_shape = (b_pad, c_bucket)
                delta_ok = (
                    prev_valid
                    and entry.prev_out is not None
                    and entry.prev_out[0].shape == out_shape
                    # Compressed score planes can't replay score dicts
                    # bit-exactly for lossy rows; want_scores chunks do
                    # a full refetch instead of trusting the diff.
                    and not (self.score_f16 and entry.prev_has_scores)
                )
                prev = (
                    self._prev_for_diff(entry)
                    if delta_ok
                    else self._zeros_for(out_shape)
                )
                narrow_m = self._narrow_m(inputs, c_bucket)
                self._count_dispatch(fmt, b_pad, c_bucket)
                if narrow_m is not None:
                    self.narrow_last_m = narrow_m
                    out, mask_dev, cert_dev = self._narrow_program(
                        fmt, narrow_m
                    )(device_in, prev)
                else:
                    tick = self._tick_compact if fmt == "compact" else self._tick
                    out, mask_dev = tick(device_in, prev)
                    cert_dev = None
                if delta_ok and self.donate:
                    # The donated prev buffers are dead; every drain
                    # path stores the fresh outputs before they're
                    # consulted again.
                    entry.prev_out = None
            if self.pipeline_depth > 1:
                # Async dispatch: leave the program in flight and go
                # featurize the next chunk; the wait lands in the fetch
                # stage when this chunk is drained.
                timings["device"] += time.perf_counter() - t1
                pending_fetch.append(
                    (
                        len(chunk_results),
                        entry,
                        out,
                        mask_dev if delta_ok else None,
                        len(chunk),
                        pack_k,
                        cert_dev,
                        device_in if cert_dev is not None else None,
                        fmt,
                    )
                )
                chunk_results.append(None)
                chunk_changed.append(None)  # filled by the drain
                if len(pending_fetch) >= self.pipeline_depth:
                    with trace.span(
                        "engine.fetch_window", chunks=len(pending_fetch)
                    ):
                        self._drain_fetch_window(
                            pending_fetch, chunk_results, chunk_changed,
                            view, want_scores, timings,
                        )
                    pending_fetch.clear()
                continue
            jax.block_until_ready(out)
            t2 = time.perf_counter()
            timings["device"] += t2 - t1
            mask_host = None
            if cert_dev is not None:
                out, fb_rows = self._apply_cert_fallback(
                    out, self._read_np(cert_dev), device_in, fmt, len(chunk),
                    timings,
                )
                if fb_rows is not None and delta_ok:
                    # The diff mask was computed against the NARROW
                    # outputs; re-solved rows must be fetched regardless
                    # of what it says.
                    mask_host = self._read_np(mask_dev)[: len(chunk)].copy()
                    mask_host[fb_rows] |= _DIFF_PLACEMENT
            part, changed = self._fetch_decode(
                entry,
                out,
                (mask_host if mask_host is not None else mask_dev)
                if delta_ok
                else None,
                view.names,
                len(chunk),
                want_scores,
                timings,
                view,
                pack_k,
            )
            chunk_results.append(part)
            chunk_changed.append(changed)

        if pending_fetch:
            with trace.span("engine.fetch_window", chunks=len(pending_fetch)):
                self._drain_fetch_window(
                    pending_fetch, chunk_results, chunk_changed, view,
                    want_scores, timings,
                )
            pending_fetch.clear()
        if pending_gate:
            with trace.span("engine.drift_gate", chunks=len(pending_gate)):
                self._drain_drift_gates(
                    pending_gate, chunk_results, chunk_changed, view,
                    want_scores, timings, pending_sub, c_bucket, eff_chunk,
                    ladder, vocab,
                )
            pending_gate.clear()
        if pending_sub:
            with trace.span("engine.sub_batch", chunks=len(pending_sub)):
                self._run_sub_batch(
                    pending_sub, chunk_results, view, timings, eff_chunk,
                    ladder, c_bucket, vocab,
                )
        # One aggregated adaptive-K vote per chunk per tick (the pieces
        # arrived across window drains / survivor groups above).
        self._flush_nsel()

        results: list[ScheduleResult] = []
        for part in chunk_results:
            results.extend(part)
        # Global row indices whose placement may have changed this tick
        # (None = unknown: at least one chunk was fully re-decoded).
        # Incremental consumers (follower union, persist) key off this.
        if any(ch is None for ch in chunk_changed):
            self.last_changed: Optional[list[int]] = None
        else:
            self.last_changed = [
                slot * eff_chunk + row
                for slot, ch in enumerate(chunk_changed)
                for row in ch
            ]
        if follower_index is not None:
            t_f = time.perf_counter()
            follower_index.apply(results, self.last_changed)
            timings["follower"] = time.perf_counter() - t_f
        # Arm the O(1) no-op gate (see the top of this method) — never
        # after a webhook tick: its plugin set is outside the gate key,
        # and replaying webhook-filtered placements for a plain call
        # would be wrong.
        self._noop_gate = (
            (units_arg,
             np.fromiter(map(id, units), np.int64, count=len(units)),
             view, want_scores, follower_index, results,
             len(chunk_results))
            if webhook_eval is None
            else None
        )
        return results

    def _pad_for_dispatch(
        self,
        inputs,
        fmt: str,
        b_pad: int,
        c_bucket: int,
        skip_cluster_fields: bool = False,
    ):
        """Format-aware shape bucketing: the dense format pads its [B, C]
        planes; the compact one additionally buckets the sparse-entry
        and key-byte widths (pow2) so those axes don't leak unbounded
        program shapes either.

        ``skip_cluster_fields=True`` (every engine dispatch path) leaves
        the cluster-axis-only tensors untouched: they are replaced by
        the shared once-per-tick device copies (_cluster_planes_device)
        at dispatch, so per-chunk re-padding + re-upload of cluster
        state is never paid.  Prewarm keeps the self-contained padding.
        """
        if fmt == "dense":
            skip = _CLUSTER_ONLY_FIELDS if skip_cluster_fields else ()
            return _pad_clusters(_pad_batch(inputs, b_pad), c_bucket, skip=skip)
        padded = Cmp.pad_rows(inputs, b_pad)
        p = np.asarray(padded.sparse_idx).shape[1]
        padded = Cmp.pad_axis1(
            padded, Cmp.SPARSE_FILLS, _pow2_bucket(p, 8, 1 << 30)
        )
        l = np.asarray(padded.key_bytes).shape[1]
        padded = Cmp.pad_axis1(
            padded, {"key_bytes": 0}, _pow2_bucket(l, 64, 1 << 30)
        )
        # Vocabulary tables (multi-MB at wide C) are NOT padded here:
        # _tables_device pads them once per actual upload, not per
        # dispatch — steady state reuses the device copy.
        skip = Cmp.TABLE_FIELDS + (
            Cmp.CLUSTER_FIELDS if skip_cluster_fields else ()
        )
        return Cmp.pad_clusters(padded, c_bucket, skip=skip)

    def _tables_device(self, vocab: CompactVocab, c_bucket: int):
        """Device-resident vocabulary tables, re-uploaded (and re-padded)
        only when the vocabulary version or cluster padding changes."""
        key = (vocab.uid, vocab.version, c_bucket)
        if self._device_tables is not None and self._device_tables[0] == key:
            return self._device_tables[1]
        tables = Cmp.pad_tables(vocab.tables(), c_bucket)
        if self._table_shardings is not None:
            dev = jax.device_put(tables, self._table_shardings)
        else:
            dev = jax.device_put(tables)
        self.upload_bytes["cluster"] += sum(
            np.asarray(t).nbytes for t in tables.values()
        )
        self._device_tables = (key, dev)
        return dev

    @staticmethod
    def _pad_cluster_axis(arr, c_pad: int, fill):
        arr = np.asarray(arr)
        extra = c_pad - arr.shape[0]
        if extra <= 0:
            return arr
        pad_shape = (extra,) + arr.shape[1:]
        return np.concatenate([arr, np.full(pad_shape, fill, arr.dtype)])

    def _cluster_planes_device(self, view: ClusterView, c_bucket: int) -> dict:
        """The padded cluster-axis tensors, uploaded ONCE per (view,
        c_bucket) and shared by every chunk dispatch of the tick — the
        same five fields in both formats (_CLUSTER_ONLY_FIELDS ==
        compact.CLUSTER_FIELDS, identical mesh layouts).  On a drift
        tick these few [C, R] arrays are the only host->device bytes."""
        key = (id(view), c_bucket)
        if self._cluster_device is not None and self._cluster_device[0] == key:
            return self._cluster_device[2]
        c = len(view.names)
        host = {
            "alloc": self._pad_cluster_axis(view.alloc, c_bucket, 0),
            "used": self._pad_cluster_axis(view.used, c_bucket, 0),
            "cpu_alloc": self._pad_cluster_axis(view.cpu_alloc, c_bucket, 0),
            "cpu_avail": self._pad_cluster_axis(view.cpu_avail, c_bucket, 0),
            "cluster_valid": self._pad_cluster_axis(
                np.ones(c, bool), c_bucket, False
            ),
        }
        if self._cluster_shardings is not None:
            dev = jax.device_put(host, self._cluster_shardings)
        else:
            dev = jax.device_put(host)
        self.upload_bytes["cluster"] += sum(a.nbytes for a in host.values())
        # The view reference keeps id(view) stable for the cache key.
        self._cluster_device = (key, view, dev)
        return dev

    def _wcheck_cpu_device(self, old_view: ClusterView, c_bucket: int) -> dict:
        """The PREVIOUS view's cpu planes (padded, device) — the old
        side of the drift wcheck's dynamic-weight comparison."""
        key = (id(old_view), c_bucket)
        if self._old_cpu_device is not None and self._old_cpu_device[0] == key:
            return self._old_cpu_device[2]
        host = {
            "cpu_alloc": self._pad_cluster_axis(old_view.cpu_alloc, c_bucket, 0),
            "cpu_avail": self._pad_cluster_axis(old_view.cpu_avail, c_bucket, 0),
        }
        if self._cluster_shardings is not None:
            dev = jax.device_put(
                host,
                {name: self._cluster_shardings[name] for name in host},
            )
        else:
            dev = jax.device_put(host)
        self.upload_bytes["cluster"] += sum(a.nbytes for a in host.values())
        self._old_cpu_device = (key, old_view, dev)
        return dev

    def _run_sub_batch(
        self, pending, chunk_results, view, timings, eff_chunk, ladder,
        c_bucket, vocab,
    ) -> None:
        """One small dispatch (per eff_chunk-sized slab) for every
        changed row across all patched chunks; results merge into the
        cached decodes.  Uses the SAME tick programs as full dispatches
        (zero-prev diff, output gather) so no extra shapes compile.
        Chunks are grouped by format (a dense-fallback chunk can coexist
        with compact ones)."""
        compact_group = [p for p in pending if p[1].fmt == "compact"]
        dense_group = [p for p in pending if p[1].fmt == "dense"]
        for group, fmt in ((compact_group, "compact"), (dense_group, "dense")):
            if group:
                self._run_sub_batch_group(
                    group, fmt, chunk_results, view, timings, eff_chunk,
                    ladder, c_bucket, vocab,
                )

    def _slice_rows(self, entry: _CachedChunk, rows: list[int]):
        """The given rows of a cached chunk's (refreshed) host inputs,
        as a sub-batch piece in the entry's own format — the drift
        recompute's input source (rows are unchanged since the cache
        was built, so no re-featurization happens)."""
        idx = np.asarray(rows)
        per_object = set(self._per_object_fields(entry.fmt))
        cls = CompactInputs if entry.fmt == "compact" else TickInputs
        return cls(
            **{
                name: np.asarray(arr)[idx] if name in per_object else arr
                for name, arr in entry.inputs._asdict().items()
            }
        )

    def _run_sub_batch_group(
        self, pending, fmt, chunk_results, view, timings, eff_chunk, ladder,
        c_bucket, vocab,
    ) -> None:
        t0 = time.perf_counter()
        per_object = self._per_object_fields(fmt)
        subs = [sub for _, _, _, sub, _ in pending]
        if fmt == "compact":
            # Align sparse/key widths across chunks before concatenating.
            p_max = max(np.asarray(s.sparse_idx).shape[1] for s in subs)
            l_max = max(np.asarray(s.key_bytes).shape[1] for s in subs)
            subs = [
                Cmp.pad_axis1(
                    Cmp.pad_axis1(s, Cmp.SPARSE_FILLS, p_max),
                    {"key_bytes": 0},
                    l_max,
                )
                for s in subs
            ]
        combined = {
            name: np.concatenate([np.asarray(getattr(s, name)) for s in subs])
            for name in per_object
        }
        c = len(view.names)
        # The cluster-axis tensors come from the shared once-per-tick
        # device copy; host placeholders only complete the NamedTuple
        # for the row/width padding below.
        cluster_dev = self._cluster_planes_device(view, c_bucket)
        shared = dict(
            alloc=view.alloc,
            used=view.used,
            cpu_alloc=view.cpu_alloc,
            cpu_avail=view.cpu_avail,
            cluster_valid=np.ones(c, bool),
        )
        if fmt == "compact":
            inputs = CompactInputs(
                **combined,
                **{name: getattr(subs[0], name) for name in Cmp.TABLE_FIELDS},
                **shared,
            )
        else:
            inputs = TickInputs(**combined, **shared)
        total = inputs.total.shape[0]
        want_scores = any(e.prev_has_scores for _, e, _, _, _ in pending)
        record = self._tick_rec is not None
        packed_mode = self.fetch_format == "packed"
        # Adaptive K: the widest per-chunk hint across the group (the
        # combined slab serves rows from every chunk), falling back to
        # the static maxClusters bound for unobserved chunks — without
        # it, drift recomputes of unlimited-maxClusters rows packed at
        # the K floor and re-fetched most survivors through the wide
        # [n, C] overflow path.
        pack_k = (
            self._pack_k(
                inputs, c_bucket,
                max(p[1].pack_k_hint for p in pending),
            )
            if packed_mode
            else 0
        )
        planes = 5 if record else (4 if want_scores else 3)
        cls = CompactInputs if fmt == "compact" else TickInputs
        # Cross-slab pipelining: EVERY slab's tick + fetch program is
        # enqueued before the first blocking read, so slab t+1's device
        # work overlaps slab t's transfer (the window pattern the
        # full-dispatch path uses), instead of dispatch->block->read per
        # slab.
        # Slab cut: a sub-eff_chunk batch is cut at the ladder rung that
        # minimizes padded cells (ties -> fewer dispatches).  Without
        # this, e.g. 1988 changed rows at a 256/1024/4096 ladder would
        # pad a single slab to 4096 — 2x the device math of two
        # 1024-row slabs.
        slab_cut = eff_chunk
        if ladder is not None and total < eff_chunk:
            best_cells = -(-total // eff_chunk) * eff_chunk
            for rung in ladder:
                cells = -(-total // rung) * rung
                if cells < best_cells or (
                    cells == best_cells and rung > slab_cut
                ):
                    slab_cut, best_cells = rung, cells
        # Narrow-solve the slabs like full dispatches: sub-batch rows are
        # few, but their select/planner sorts still run over the full
        # cluster axis — at wide C (drift recomputes route through here)
        # the narrow program is where the dispatch time goes.
        narrow_m = self._narrow_m(inputs, c_bucket)
        ticked: list[list] = []  # [n, out, device_in, cert_dev]
        for start in range(0, total, slab_cut):
            piece = cls(
                **{
                    name: (
                        np.asarray(arr)[start : start + slab_cut]
                        if name in combined
                        else arr
                    )
                    for name, arr in inputs._asdict().items()
                }
            )
            n = piece.total.shape[0]
            b_pad = self._bucket_rows(n, ladder, eff_chunk, False)
            padded = self._pad_for_dispatch(
                piece, fmt, b_pad, c_bucket, skip_cluster_fields=True
            )
            t1 = time.perf_counter()
            timings["featurize"] += t1 - t0
            shape = (b_pad, c_bucket)
            self._count_dispatch(fmt, b_pad, c_bucket)
            self.upload_bytes["object"] += sum(
                np.asarray(getattr(padded, name)).nbytes for name in per_object
            )
            if fmt == "compact":
                device_in = padded._replace(
                    **self._tables_device(vocab, c_bucket), **cluster_dev
                )
            else:
                device_in = padded._replace(**cluster_dev)
            cert_dev = None
            if narrow_m is not None:
                self.narrow_last_m = narrow_m
                out, _mask, cert_dev = self._narrow_program(fmt, narrow_m)(
                    device_in, self._zeros_for(shape)
                )
            elif fmt == "compact":
                out, _mask = self._tick_compact(device_in, self._zeros_for(shape))
            else:
                out, _mask = self._tick(device_in, self._zeros_for(shape))
            ticked.append([n, out, device_in, cert_dev])
            timings["device"] += time.perf_counter() - t1
            t0 = time.perf_counter()

        # Narrow certificates resolve BEFORE the gathers are enqueued —
        # the wire must carry the (possibly dense re-solved) exact
        # planes.  Every slab's tick is already in flight, so the cert
        # reads overlap the remaining device queue.
        if any(t[3] is not None for t in ticked):
            t1 = time.perf_counter()
            certs = [
                self._read_np(t[3]) if t[3] is not None else None
                for t in ticked
            ]
            timings["fetch"] += time.perf_counter() - t1
            for t, cert in zip(ticked, certs):
                if cert is not None:
                    t[1], _fb = self._apply_cert_fallback(
                        t[1], cert, t[2], fmt, t[0], timings
                    )

        slabs: list[tuple] = []  # (n, out, fetch_dev)
        t1 = time.perf_counter()
        for n, out, _device_in, _cert in ticked:
            if packed_mode:
                # Row-bucketed gather-pack, not the whole padded slab:
                # n changed rows bucket to pow2(n) wire rows instead of
                # b_pad.
                kp = _pow2_bucket(n, 16, 1 << 30)
                idx = np.zeros(kp, np.int32)
                idx[:n] = np.arange(n)
                fetch_dev = self._pack_program("gather", pack_k)(
                    out.selected, out.replicas, out.counted, out.scores,
                    out.reasons, idx,
                )
            else:
                kp = _pow2_bucket(n, 16, 1 << 30)
                idx = np.zeros(kp, np.int32)
                idx[:n] = np.arange(n)
                if planes == 5:
                    fetch_dev = self._gather5(
                        out.selected, out.replicas, out.counted, out.scores,
                        out.reasons, idx,
                    )
                elif planes == 4:
                    fetch_dev = self._gather(
                        out.selected, out.replicas, out.counted, out.scores, idx
                    )
                else:
                    fetch_dev = self._gather3(
                        out.selected, out.replicas, out.counted, idx
                    )
            slabs.append((n, out, fetch_dev))

        # All slabs are in flight; wait for device completion ONCE (the
        # last program's completion implies the whole queue), so the
        # reads below measure pure transfer — same stage attribution as
        # the pre-pipelined per-slab block.
        if slabs:
            jax.block_until_ready(slabs[-1][2])
            timings["device"] += time.perf_counter() - t1

        # Drain: blocking reads (+ packed-overflow re-fetches), decode.
        decoded: list[ScheduleResult] = []
        rec_reasons: list[np.ndarray] = []   # dense-mode recorder rows
        rec_scores: list[np.ndarray] = []
        rec_counts: list[np.ndarray] = []    # packed-mode recorder fields
        rec_feas: list[np.ndarray] = []
        rec_ti: list[np.ndarray] = []
        rec_ts: list[np.ndarray] = []
        all_nsel: list[np.ndarray] = []
        for n, out, fetch_dev in slabs:
            t2 = time.perf_counter()
            arr = self._read_np(fetch_dev)[:n]
            if packed_mode:
                packed = unpack_wire(arr, pack_k)
                all_nsel.append(np.asarray(packed.nsel))
                over_pos = np.nonzero(np.asarray(packed.nsel) > pack_k)[0]
                over_dense = None
                if over_pos.size:
                    over_dense = self._fetch_overflow(
                        out, over_pos.astype(np.int64), want_scores, timings
                    )
                t3 = time.perf_counter()
                timings["fetch"] += t3 - t2
                decoded.extend(
                    self._decode_packed_mixed(
                        packed, over_pos, over_dense, view.names, want_scores
                    )
                )
                if record:
                    ti, ts = self._packed_record_fields(
                        packed, self._tick_rec.topk
                    )
                    rec_counts.append(np.asarray(packed.rsum))
                    rec_feas.append(np.asarray(packed.nfeas))
                    rec_ti.extend(ti)
                    rec_ts.extend(ts)
                timings["decode"] += time.perf_counter() - t3
            else:
                c_pad = arr.shape[1] // planes
                sco = arr[:, 3 * c_pad : 4 * c_pad] if planes >= 4 else None
                if planes == 5:
                    rec_reasons.append(arr[:, 4 * c_pad : 5 * c_pad])
                    rec_scores.append(sco)
                t3 = time.perf_counter()
                timings["fetch"] += t3 - t2
                decoded.extend(
                    self._decode_rows(
                        arr[:, :c_pad],
                        arr[:, c_pad : 2 * c_pad],
                        arr[:, 2 * c_pad : 3 * c_pad],
                        view.names,
                        scores=sco if want_scores else None,
                    )
                )
                timings["decode"] += time.perf_counter() - t3

        offset = 0
        t3 = time.perf_counter()
        eager_repairs: list = []
        all_reasons = np.concatenate(rec_reasons) if rec_reasons else None
        all_scores = np.concatenate(rec_scores) if rec_scores else None
        all_counts = np.concatenate(rec_counts) if rec_counts else None
        all_feas = np.concatenate(rec_feas) if rec_feas else None
        nsel_all = np.concatenate(all_nsel) if all_nsel else None
        for slot, entry, changed_rows, _sub, inputs_stale in pending:
            merged = list(entry.prev_results)
            res_rows = []
            for j, row in enumerate(changed_rows):
                res = decoded[offset + j]
                if not entry.prev_has_scores:
                    res = ScheduleResult(res.clusters, {})
                merged[row] = res
                res_rows.append(res)
            span = slice(offset, offset + len(changed_rows))
            if nsel_all is not None:
                self._observe_nsel(entry, nsel_all[span], c_bucket)
            if all_reasons is not None:
                self._record_decisions(
                    entry, changed_rows, res_rows, all_reasons[span],
                    all_scores[span] if all_scores is not None else None,
                    view, program=f"{fmt}:sub",
                )
            elif all_counts is not None and self._tick_rec is not None:
                self._tick_rec.record_rows(
                    [entry.units[r].key for r in changed_rows],
                    [res.clusters for res in res_rows],
                    None, None, view.names, program=f"{fmt}:sub",
                    reason_counts=all_counts[span],
                    feasible_n=all_feas[span],
                    topk_idx=rec_ti[span], topk_scores=rec_ts[span],
                )
            entry.prev_results = merged
            entry.prev_view = view
            if inputs_stale:
                # The device INPUT copy is stale for the patched rows.
                # Record them, then repair EAGERLY after this loop — in
                # the same tick that created them (ISSUE 11 satellite) —
                # so drift gates never pay the repair on their critical
                # path and never see a gate-blind row (PR 7 measured
                # ~30% of drift recompute as stale-row artifacts before
                # the gate-time repair; this moves the scatter off the
                # drift tick entirely).  Rows the eager pass cannot
                # reach (no device copy) stay marked for the gate-time
                # backstop.
                entry.stale_rows = sorted(
                    set(entry.stale_rows or ()) | set(changed_rows)
                )
                eager_repairs.append(entry)
            # Device write-back: scatter the slab's fresh output planes
            # into the chunk's cached prev planes, so the prev state
            # stays exact row-for-row — later drift gates and delta
            # diffs then need no forced fetches.  Falls back to the
            # stale_out_rows marking (VERDICT r3 #3: forced gather on
            # the next full dispatch) when shapes don't line up.
            if not self._repair_prev_planes(
                entry, changed_rows, offset, slabs, slab_cut
            ):
                entry.stale_out_rows = sorted(
                    set(entry.stale_out_rows or ()) | set(changed_rows)
                )
            offset += len(changed_rows)
            # Shared by reference (frozen results): the cached list is
            # fresh this tick and rows are immutable.
            chunk_results[slot] = merged
        timings["decode"] += time.perf_counter() - t3
        if eager_repairs:
            # Eager stale-input repair: scatter the churned rows' fresh
            # host inputs (+ tie-break rows) into the cached device
            # tensors NOW, attributed to this tick's featurize stage —
            # engine_stale_rows_total{phase="churn"} counts them, and
            # the drift-gate backstop (phase="drift") must stay at 0.
            t4 = time.perf_counter()
            for entry in eager_repairs:
                self._repair_stale_inputs(
                    entry, fmt, c_bucket, vocab=vocab, phase="churn",
                    patch_tiebreak=False,
                )
            timings["featurize"] += time.perf_counter() - t4

    def _repair_program(self):
        """Jitted 7-plane scatter: the six prev planes
        .at[dst].set(slab[src]) (dst padded out-of-range -> mode='drop')
        plus the cached nfeas vector, whose repaired rows are re-summed
        from the slab's feasibility plane IN the same dispatch — the
        cached count can never go stale across a repair.  The planes
        are DONATED: XLA updates them in place instead of copying ~20MB
        of [B, C] state per repaired chunk (the engine re-references
        the returned planes; nothing else holds the old ones)."""
        compressed = self.score_f16
        key = ("repair", compressed)
        fn = self._repair_program_cache.get(key)
        if fn is None:
            def impl(planes, slab, src, dst, nfeas, sco_exact=None):
                # .astype(p.dtype) is a no-op for matching dtypes; under
                # KT_SCORE_F16 it casts the slab's fresh i32 scores into
                # the stored f16 plane.
                out = tuple(
                    p.at[dst].set(s[src].astype(p.dtype), mode="drop")
                    for p, s in zip(planes, slab)
                )
                # slab[4] is the slab's feasibility plane.  The nfeas
                # vector argument is deliberately NOT donated: it is
                # [B] i32 (copy cost ~nothing next to the ~20MB plane
                # scatters), and chain-donating it proved hazardous —
                # the tiny buffer also sits in the dispatch ledger's
                # smallest-leaf watch set, and recycling it under an
                # outstanding reference let a later allocation clobber
                # the live vector (caught by the nfeas-consistency
                # differential as an all-zero cached count).
                nf_rows = jnp.sum(slab[4][src] != 0, axis=1, dtype=jnp.int32)
                res = out + (nfeas.at[dst].set(nf_rows, mode="drop"),)
                if sco_exact is not None:
                    # Repaired rows carry truly fresh scores: their
                    # exactness resets from the f16 round-trip of the
                    # slab's i32 plane (same rule as the store-side
                    # compressor).
                    s3 = slab[3][src]
                    ex_rows = jnp.all(
                        s3.astype(jnp.float16).astype(s3.dtype) == s3,
                        axis=1,
                    ).astype(jnp.int8)
                    res = res + (sco_exact.at[dst].set(ex_rows, mode="drop"),)
                return res

            donate = (0,) if self.donate else ()
            if self._grid_sharding is not None:
                grid, rep = self._grid_sharding, self._replicated
                in_sh = ((grid,) * 6, (grid,) * 6, rep, rep, rep)
                out_sh = (grid,) * 6 + (rep,)
                if compressed:
                    in_sh = in_sh + (rep,)
                    out_sh = out_sh + (rep,)
                fn = jax.jit(
                    impl,
                    in_shardings=in_sh,
                    out_shardings=out_sh,
                    donate_argnums=donate,
                )
            else:
                fn = jax.jit(impl, donate_argnums=donate)
            fn = self._aot.wrap(f"repair:{'f16' if compressed else 'f32'}", fn)
            fn = self._obs_wrap("repair", fn)
            self._repair_program_cache[key] = fn
        return fn

    def _ensure_sco_exact_vec(self, entry):
        """The entry's device exactness vector for repair dispatches —
        a missing vector materializes as all-zero (every row inexact),
        which only ever forces extra recomputes, never a wrong skip."""
        b_pad = entry.prev_out[0].shape[0]
        vec = entry.prev_sco_exact
        if vec is None or tuple(vec.shape) != (b_pad,):
            zeros = np.zeros(b_pad, np.int8)
            vec = (
                jax.device_put(zeros, self._replicated)
                if self._replicated is not None
                else jax.device_put(zeros)
            )
            entry.prev_sco_exact = vec
            entry.sco_inexact_host = None
        return vec

    def _repair_prev_planes(
        self, entry, changed_rows, offset: int, slabs, slab_cut: int
    ) -> bool:
        """Write the sub-batch slab outputs for this chunk's rows back
        into entry.prev_out/prev_feas/prev_reasons on device.  Returns
        False (caller keeps the stale-marking fallback) when the cached
        planes are absent or any touched slab's cluster axis disagrees."""
        if (
            entry.prev_out is None
            or entry.prev_feas is None
            or entry.prev_reasons is None
            or not changed_rows
        ):
            return (
                entry.prev_out is not None
                and entry.prev_feas is not None
                and entry.prev_reasons is not None
            )
        c_pad = entry.prev_out[0].shape[1]
        b_pad = entry.prev_out[0].shape[0]
        if entry.prev_feas.shape != (b_pad, c_pad):
            return False
        if entry.prev_reasons.shape != (b_pad, c_pad):
            return False
        # Split this chunk's combined-array span into per-slab segments.
        segments: dict[int, tuple[list, list]] = {}
        for j, dst in enumerate(changed_rows):
            if dst >= b_pad:
                return False
            pos = offset + j
            srcs, dsts = segments.setdefault(pos // slab_cut, ([], []))
            srcs.append(pos % slab_cut)
            dsts.append(dst)
        for s in segments:
            if s >= len(slabs) or slabs[s][1].selected.shape[1] != c_pad:
                return False
        planes = entry.prev_out + (entry.prev_feas, entry.prev_reasons)
        nfeas = self._ensure_nfeas(entry)
        fn = self._repair_program()
        sco_exact = (
            self._ensure_sco_exact_vec(entry) if self.score_f16 else None
        )
        for s, (srcs, dsts) in segments.items():
            out = slabs[s][1]
            slab_planes = (
                out.selected, out.replicas, out.counted, out.scores,
                out.feasible, out.reasons,
            )
            # FIXED 128-row scatter groups, not a pow2 index bucket:
            # the repair program then has exactly one index shape per
            # (chunk, slab) plane pair — prewarmed — so a drift/churn
            # tick can never stall on a scatter-program trace (the
            # scatters are in-place under donation; extra dispatches
            # are cheap next to one compile).
            for g in range(0, len(srcs), 128):
                src = np.zeros(128, np.int32)
                seg = srcs[g : g + 128]
                src[: len(seg)] = seg
                dst = np.full(128, b_pad, np.int32)  # pad scatters drop
                dseg = dsts[g : g + 128]
                dst[: len(dseg)] = dseg
                self.dispatches_total += 1
                if sco_exact is not None:
                    out8 = fn(planes, slab_planes, src, dst, nfeas, sco_exact)
                    planes, nfeas, sco_exact = out8[:6], out8[6], out8[7]
                else:
                    out7 = fn(planes, slab_planes, src, dst, nfeas)
                    planes, nfeas = out7[:6], out7[6]
        entry.prev_out = planes[:4]
        entry.prev_feas = planes[4]
        entry.prev_reasons = planes[5]
        entry.prev_nfeas = nfeas
        if sco_exact is not None:
            entry.prev_sco_exact = sco_exact
            entry.sco_inexact_host = None
        entry.stale_out_rows = (
            sorted(set(entry.stale_out_rows) - set(changed_rows))
            if entry.stale_out_rows
            else entry.stale_out_rows
        )
        return True

    # -- drift fast path ---------------------------------------------------
    def _drift_delta(self, old_view, view: ClusterView, cache: dict):
        """Which cluster columns changed between the view a chunk's
        outputs were computed against and the current one.  None = the
        tick is not drift-shaped (different topology/shapes, or so many
        columns moved that gating would cost more than recomputing);
        {"empty": True} = the tensors are bit-identical (the views
        differ only in ways that round away)."""
        key = id(old_view)
        if key in cache:
            return cache[key]
        info = None
        if (
            getattr(old_view, "names", None) == view.names
            and np.asarray(old_view.alloc).shape == np.asarray(view.alloc).shape
        ):
            dcpu_col = (old_view.cpu_alloc != view.cpu_alloc) | (
                old_view.cpu_avail != view.cpu_avail
            )
            diff = (
                (old_view.alloc != view.alloc).any(axis=1)
                | (old_view.used != view.used).any(axis=1)
                | dcpu_col
            )
            cols = np.nonzero(diff)[0]
            c = len(view.names)
            if cols.size == 0:
                info = {"empty": True}
            elif cols.size <= max(8, c // 4):
                # Delta-axis bucket: EXACT 1 for the single-column case
                # (the dominant live drift — one member's capacity
                # moved), pow2 floored at 8 otherwise.  The gate's
                # rank-count refinement and the resolve's entrant loop
                # are O(D) fused [rows, C] passes over the PADDED delta
                # axis, so an 8-slot pad on a 1-column drift was 8x the
                # compare work for nothing (prewarm covers both the
                # 1- and 8-slot program shapes).
                nb = 1 if cols.size == 1 else _pow2_bucket(cols.size, 8, 1 << 30)
                # Padded slots carry an out-of-range index: gathers are
                # clamped-and-masked, the score write-back drops them.
                didx = np.full(nb, 1 << 30, np.int32)
                didx[: cols.size] = cols
                dvalid = np.zeros(nb, bool)
                dvalid[: cols.size] = True
                dcpu = np.zeros(nb, bool)
                dcpu[: cols.size] = dcpu_col[cols]

                def slice_cols(arr):
                    arr = np.asarray(arr)
                    out = np.zeros((nb,) + arr.shape[1:], arr.dtype)
                    out[: cols.size] = arr[cols]
                    return out

                info = {
                    "empty": False, "didx": didx, "dvalid": dvalid,
                    "dcpu": dcpu,
                    "alloc_old_d": slice_cols(old_view.alloc),
                    "used_old_d": slice_cols(old_view.used),
                    "alloc_new_d": slice_cols(view.alloc),
                    "used_new_d": slice_cols(view.used),
                }
        cache[key] = info
        return info

    def _nfeas_program(self):
        """Jitted feasible-count reduce: i8[B, C] prev_feas -> i32[B].
        Dispatched once per prev-plane STORE (full dispatches, restore
        misses) instead of once per drift GATE — the r11 gate re-derived
        this count with a [B, C] pf.sum pass on every drift tick."""
        fn = self._nfeas_cache.get("nfeas")
        if fn is None:

            def impl(feas):
                return jnp.sum(feas != 0, axis=1, dtype=jnp.int32)

            if self._grid_sharding is not None:
                fn = jax.jit(
                    impl,
                    in_shardings=self._grid_sharding,
                    out_shardings=self._replicated,
                )
            else:
                fn = jax.jit(impl)
            fn = self._aot.wrap("nfeas", fn)
            fn = self._obs_wrap("nfeas", fn)
            self._nfeas_cache["nfeas"] = fn
        return fn

    def _store_nfeas(self, entry, feas) -> None:
        """Maintain the cached per-row feasible-count vector alongside a
        fresh prev_feas store (one tiny async reduce, off the drift
        tick's critical path)."""
        self.dispatches_total += 1
        entry.prev_nfeas = self._nfeas_program()(feas)

    def _ensure_nfeas(self, entry):
        """The chunk's cached nfeas vector, derived lazily when a store
        site predates the cache (restored snapshots, revert knobs)."""
        b_pad = entry.prev_feas.shape[0]
        nf = entry.prev_nfeas
        if nf is None or tuple(nf.shape) != (b_pad,):
            self.dispatches_total += 1
            nf = self._nfeas_program()(entry.prev_feas)
            entry.prev_nfeas = nf
        return nf

    # -- f16 score-plane compression (KT_SCORE_F16, ISSUE 12) -------------
    def _sco_compress_program(self, with_old: bool):
        """Jitted store-side compressor: i32[B, C] scores -> (f16[B, C],
        i8[B] exactness).  A row is exact iff every score round-trips
        i32 -> f16 -> i32 bit-identically; ``with_old`` ANDs a previous
        exactness vector in (the drift gate's changed-column refresh
        writes THROUGH the stored plane, so a row once lossy stays
        flagged until a recompute stores truly fresh scores)."""
        key = f"compress:{int(with_old)}"
        fn = self._sco_cache.get(key)
        if fn is None:
            if with_old:
                def impl(sco, old):
                    f16 = sco.astype(jnp.float16)
                    exact = jnp.all(
                        f16.astype(jnp.int32) == sco, axis=1
                    ).astype(jnp.int8)
                    return f16, exact * old
            else:
                def impl(sco):
                    f16 = sco.astype(jnp.float16)
                    exact = jnp.all(
                        f16.astype(jnp.int32) == sco, axis=1
                    ).astype(jnp.int8)
                    return f16, exact

            if self._grid_sharding is not None:
                grid, rep = self._grid_sharding, self._replicated
                in_sh = (grid, rep) if with_old else (grid,)
                fn = jax.jit(
                    impl, in_shardings=in_sh, out_shardings=(grid, rep)
                )
            else:
                fn = jax.jit(impl)
            fn = self._aot.wrap(key, fn)
            fn = self._obs_wrap("score_pack", fn)
            self._sco_cache[key] = fn
        return fn

    def _sco_upcast_program(self):
        """f16[B, C] stored scores -> i32[B, C] for the diff / gate
        programs (exact rows upcast bit-identically; inexact rows are
        forced out of every consumer that could act on the difference)."""
        fn = self._sco_cache.get("upcast")
        if fn is None:
            def impl(f16):
                return f16.astype(jnp.int32)

            if self._grid_sharding is not None:
                fn = jax.jit(
                    impl,
                    in_shardings=self._grid_sharding,
                    out_shardings=self._grid_sharding,
                )
            else:
                fn = jax.jit(impl)
            fn = self._aot.wrap("sco_upcast", fn)
            fn = self._obs_wrap("score_pack", fn)
            self._sco_cache["upcast"] = fn
        return fn

    def _compress_scores(self, entry, sco_dev, and_old: bool = False):
        """Store one fresh f32/i32 score plane compressed on the entry:
        sets the f16 plane + exactness vector, invalidates the host
        cache of inexact rows.  Returns the f16 plane."""
        self.dispatches_total += 1
        if and_old and entry.prev_sco_exact is not None:
            f16, exact = self._sco_compress_program(True)(
                sco_dev, entry.prev_sco_exact
            )
        else:
            f16, exact = self._sco_compress_program(False)(sco_dev)
        entry.prev_sco_exact = exact
        entry.sco_inexact_host = None
        return f16

    def _sco_inexact_rows(self, entry) -> np.ndarray:
        """Host indices of rows whose stored f16 scores are lossy (the
        rows every score-consuming fast path must treat as unknown).
        Missing vector = every row inexact — conservative, never wrong."""
        cached = entry.sco_inexact_host
        if cached is not None:
            return cached
        if entry.prev_sco_exact is None:
            b = (
                entry.prev_out[0].shape[0]
                if entry.prev_out is not None
                else 0
            )
            rows = np.arange(b, dtype=np.int64)
        else:
            rows = np.nonzero(
                self._read_np(entry.prev_sco_exact) == 0
            )[0].astype(np.int64)
        entry.sco_inexact_host = rows
        return rows

    def _prev_for_diff(self, entry) -> tuple:
        """The prev planes in the dtype the tick's diff expects: the
        stored f16 score plane upcasts to i32 on device (exact rows
        reproduce the true scores, so their diff bits behave exactly
        like the uncompressed engine's; lossy rows flag as score-
        changed and simply re-fetch)."""
        prev = entry.prev_out
        if not self.score_f16 or prev[3].dtype != jnp.float16:
            return prev
        self.dispatches_total += 1
        return prev[:3] + (self._sco_upcast_program()(prev[3]),)

    def _store_prev(self, entry, out) -> None:
        """Central prev-plane store: every fetch path that adopts a
        fresh TickOutputs as the chunk's resident state funnels through
        here, so the nfeas companion vector, the optional f16 score
        compression (+ exactness vector) and the stale-marking reset
        can never drift apart across store sites."""
        if self.score_f16:
            sco = self._compress_scores(entry, out.scores)
        else:
            sco = out.scores
        entry.prev_out = (out.selected, out.replicas, out.counted, sco)
        entry.prev_feas = out.feasible
        entry.prev_reasons = out.reasons
        self._store_nfeas(entry, out.feasible)
        entry.stale_out_rows = None

    def _gate_program(self, fmt: str):
        """Jitted drift gate per format (jax re-traces per shape; the
        gate is a cheap filter-slice program, so the trace cost is
        negligible next to the tick programs it replaces).  The stored
        score plane is DONATED: the gate's changed-column refresh then
        scatters in place instead of copying the whole [B, C] plane
        (~84 MB per c5 chunk — measured as half the gate's device
        time); the engine swaps the refreshed plane into prev_out right
        after the mask read, so the donated buffer is dead by design."""
        fn = self._gate_programs.get(fmt)
        if fn is not None:
            return fn
        if fmt == "compact":
            cur_absent = Cmp.CUR_ABSENT
            donate = (3,) if self.donate else ()

            def impl(per_object, tables, prev_feas, prev_scores, ao, uo,
                     an, un, didx, dvalid, dcpu, fin_idx, nfeas):
                return drift_gate_compact(
                    per_object, tables, prev_feas, prev_scores, ao, uo,
                    an, un, didx, dvalid, dcpu, fin_idx, nfeas, cur_absent,
                )

            if self._grid_sharding is not None:
                rep = self._replicated
                grid = self._grid_sharding
                fn = jax.jit(
                    impl,
                    in_shardings=(
                        self._per_object_shardings_compact,
                        self._table_shardings,
                        grid, grid,
                        rep, rep, rep, rep, rep, rep, rep, rep, rep,
                    ),
                    out_shardings=(rep, grid),
                    donate_argnums=donate,
                )
            else:
                fn = jax.jit(impl, donate_argnums=donate)
        else:
            impl = drift_gate_dense
            donate = (2,) if self.donate else ()
            if self._grid_sharding is not None:
                rep = self._replicated
                grid = self._grid_sharding
                fn = jax.jit(
                    impl,
                    in_shardings=(
                        self._per_object_shardings,
                        grid, grid,
                        rep, rep, rep, rep, rep, rep, rep, rep, rep,
                    ),
                    out_shardings=(rep, grid),
                    donate_argnums=donate,
                )
            else:
                fn = jax.jit(impl, donate_argnums=donate)
        fn = self._aot.wrap(f"gate:{fmt}", fn)
        fn = self._obs_wrap("gate", fn)
        self._gate_programs[fmt] = fn
        return fn

    def _wcheck_program(self, i32: bool = False):
        key = ("wcheck", i32)
        fn = self._wcheck_program_cache.get(key)
        if fn is None:
            dtype = jnp.int32 if i32 else jnp.int64

            def impl(prev_feas, rows_idx, ao, vo, an, vn, _d=dtype):
                return drift_wcheck(
                    prev_feas, rows_idx, ao, vo, an, vn, compute_dtype=_d
                )

            if self._grid_sharding is not None:
                rep = self._replicated
                cl = self._cluster_shardings
                fn = jax.jit(
                    impl,
                    in_shardings=(
                        self._grid_sharding, rep,
                        cl["cpu_alloc"], cl["cpu_avail"],
                        cl["cpu_alloc"], cl["cpu_avail"],
                    ),
                    out_shardings=rep,
                )
            else:
                fn = jax.jit(impl)
            fn = self._aot.wrap(f"wcheck:{'i32' if i32 else 'i64'}", fn)
            fn = self._obs_wrap("wcheck", fn)
            self._wcheck_program_cache[key] = fn
        return fn

    def _wcheck_i32_ok(self, old_view, view, c_bucket: int) -> bool:
        """Host range guard for the i32 weight-check demotion: the worst
        intermediate in ops.weights.dynamic_weights is
        ``2*max_cpu*(SUPPLY_LIMIT_NUM + C)`` (the x1.4 supply-limit
        round over the allocatable sum), so i32 is exact iff that stays
        under 2**31 for BOTH cpu plane generations."""
        if not self.phase1_i32:
            return False
        from kubeadmiral_tpu.ops.weights import SUPPLY_LIMIT_NUM

        mx = 0
        for v in (old_view, view):
            for plane in (v.cpu_alloc, v.cpu_avail):
                arr = np.asarray(plane)
                if arr.size:
                    mx = max(mx, int(np.abs(arr).max()))
        return 2 * mx * (SUPPLY_LIMIT_NUM + c_bucket) < 2**31

    def _fin_rows(self, entry, b_pad: int) -> np.ndarray:
        """The chunk's finite-maxClusters row indices, padded with
        out-of-range fill — the only rows whose top-K cut can engage, so
        the gate's rank-count refinement gathers them instead of
        scanning every row (at bench mixes ~20% of rows are finite-K,
        which is most of the gate program's former cost).  The pad
        bucket is a TWO-rung ladder (b_pad/4, b_pad), not free pow2: the
        gate program traces per fin shape, and a drift tick must never
        stall on a gate compile the prewarm ladder didn't cover."""
        mc = np.asarray(entry.inputs.max_clusters)
        fin = np.nonzero((mc >= 0) & (mc < INT32_INF))[0]
        cap = max(64, b_pad // 4)
        nb = cap if fin.size <= cap else b_pad
        idx = np.full(nb, 1 << 30, np.int32)
        idx[: fin.size] = fin
        return idx

    def _repair_stale_inputs(
        self, entry, fmt: str, c_bucket: int, vocab=None,
        phase: str = "dispatch", patch_tiebreak: bool = True,
    ) -> None:
        """Scatter just the stale rows' host inputs into the cached
        device per-object tensors (width-aligned to the cached padded
        shape).  Row-sliced, never a whole-chunk pad, and scattered in
        FIXED 128-row groups — one prewarmable patch-program shape, so
        neither a drift tick nor a churn tick can stall on a scatter
        trace whatever the churned-row count.  The precomputed
        tie-break plane rides the same groups (its FNV rows recompute
        on device from the patched key bytes), so churn never forces a
        whole-chunk rescan before the next drift.

        ``phase`` labels the engine_stale_rows_total counter: "churn"
        (eager repair inside the tick that created the stale rows),
        "drift" (gate-path backstop; must stay 0 under eager repair),
        "dispatch" (full-dispatch upload path).

        ``patch_tiebreak=False`` (the eager churn path) repairs the
        per-object planes but DEFERS the tie-break FNV recompute: the
        plane's only consumers are the drift survivor kernels, and the
        FNV patch is ~10x the plain input scatter (measured ~12ms per
        c3 steady tick when run eagerly) — the deferred rows are
        recorded on the entry and flushed by _tiebreak_plane before any
        survivor dispatch reads the plane."""
        stale = entry.stale_rows
        if not stale or entry.device_per_object is None:
            return
        self.stale_repair_rows[phase] = (
            self.stale_repair_rows.get(phase, 0) + len(stale)
        )
        b_pad = entry.padded_shape[0]
        n = len(stale)
        idx = np.full(-(-n // 128) * 128, stale[0], np.int64)  # pad: valid row
        idx[:n] = stale
        piece = self._slice_rows(entry, idx.tolist())
        if fmt == "compact":
            _b, _c, p_pad, l_pad = entry.padded_shape
            piece = Cmp.pad_axis1(piece, Cmp.SPARSE_FILLS, p_pad)
            piece = Cmp.pad_axis1(piece, {"key_bytes": 0}, l_pad)
            patch = self._patch_compact
        else:
            piece = _pad_clusters(piece, c_bucket, skip=_CLUSTER_ONLY_FIELDS)
            patch = self._patch
        per_object = self._per_object_fields(fmt)
        arrays = {
            name: np.asarray(getattr(piece, name)) for name in per_object
        }
        dst_all = np.full(idx.shape[0], b_pad, np.int32)  # pad scatters drop
        dst_all[:n] = stale
        dev = entry.device_per_object
        tb = entry.tiebreak_dev
        tb_live = (
            fmt == "compact"
            and vocab is not None
            and tb is not None
            and tb.shape == (b_pad, c_bucket)
        )
        tb_ok = tb_live and patch_tiebreak
        state_dev = (
            self._tables_device(vocab, c_bucket)["name_hash_state"]
            if tb_ok
            else None
        )
        for g in range(0, idx.shape[0], 128):
            rows = {
                name: np.ascontiguousarray(arr[g : g + 128])
                for name, arr in arrays.items()
            }
            self.upload_bytes["object"] += sum(
                a.nbytes for a in rows.values()
            )
            dst = dst_all[g : g + 128]
            dev = patch(dev, rows, dst)
            if tb_ok:
                self.dispatches_total += 1
                tb = self._tb_program("patch")(
                    tb, rows["key_bytes"], rows["key_len"], state_dev, dst
                )
        entry.device_per_object = dev
        if fmt == "compact":
            if tb_ok:
                entry.tiebreak_dev = tb
            elif tb_live:
                # Deferred: keep the plane, mark the rows for the lazy
                # FNV re-patch at first survivor use.
                entry.tb_stale_rows = sorted(
                    set(entry.tb_stale_rows or ()) | set(stale)
                )
            else:
                entry.tiebreak_dev = None
                entry.tb_stale_rows = None
        entry.stale_rows = None

    def _dispatch_drift_gate(
        self, entry, fmt: str, c_bucket: int, info: dict, vocab, view,
    ):
        """Launch the drift gate for one chunk (async; the masks are
        drained incrementally in _drain_drift_gates so survivor work
        dispatches while later gates still compute).  Returns the
        (mask, refreshed score plane) device pair."""
        gate = self._gate_program(fmt)
        b_pad = entry.padded_shape[0]
        if entry.stale_rows:
            # Rows churned since the last full dispatch left stale
            # device INPUT copies — scatter-repair them now so the gate
            # classifies them like everyone else.  With eager churn-tick
            # repair on (the ISSUE 11 satellite) this arm never fires
            # (engine_stale_rows_total{phase="drift"} stays 0); it is
            # kept as the correctness backstop for paths that cannot
            # repair eagerly (no device copy at churn time).
            self._repair_stale_inputs(
                entry, fmt, c_bucket, vocab=vocab, phase="drift"
            )
        self.dispatches_total += 1
        slices = (
            info["alloc_old_d"], info["used_old_d"],
            info["alloc_new_d"], info["used_new_d"],
        )
        self.upload_bytes["cluster"] += sum(a.nbytes for a in slices)
        fin_idx = self._fin_rows(entry, b_pad)
        nfeas = self._ensure_nfeas(entry)
        # Compressed score plane: the gate consumes (and donates) an
        # i32 plane — upcast the stored f16 copy.  Exact rows classify
        # identically to the uncompressed engine; lossy rows are forced
        # into the recompute set at drain time (_drain_drift_gates), so
        # a quantized rank compare can never decide a skip.
        prev_sco = entry.prev_out[3]
        if self.score_f16 and prev_sco.dtype == jnp.float16:
            self.dispatches_total += 1
            prev_sco = self._sco_upcast_program()(prev_sco)
        if fmt == "compact":
            return gate(
                entry.device_per_object,
                self._tables_device(vocab, c_bucket),
                entry.prev_feas,
                prev_sco,
                *slices,
                info["didx"], info["dvalid"], info["dcpu"], fin_idx,
                nfeas,
            )
        return gate(
            entry.device_per_object,
            entry.prev_feas,
            prev_sco,
            *slices,
            info["didx"], info["dvalid"], info["dcpu"], fin_idx,
            nfeas,
        )

    def _tb_program(self, kind: str):
        """Jitted tie-break plane builders (compact format only): "full"
        computes a chunk's whole [B, C] plane from its key bytes (one
        FNV byte scan, enqueued asynchronously at per-object upload
        time — cold/miss paths, where it amortizes); "patch" recomputes
        fixed 128-row groups and scatters them in place (donated), so
        churned rows keep the plane fresh without a whole-chunk rescan.
        The drift survivor kernels then pass the plane into
        expand_compact and never pay the scan on the drift floor."""
        fn = self._tb_program_cache.get(kind)
        if fn is not None:
            return fn
        if kind == "full":

            def impl(key_bytes, key_len, state):
                return fnv_tiebreak_plane(key_bytes, key_len, state)

            if self._grid_sharding is not None:
                po = self._per_object_shardings_compact
                fn = jax.jit(
                    impl,
                    in_shardings=(
                        po["key_bytes"], po["key_len"],
                        self._table_shardings["name_hash_state"],
                    ),
                    out_shardings=self._grid_sharding,
                )
            else:
                fn = jax.jit(impl)
        else:

            def impl(plane, key_bytes_rows, key_len_rows, state, dst):
                rows_tb = fnv_tiebreak_plane(
                    key_bytes_rows, key_len_rows, state
                )
                return plane.at[dst].set(rows_tb, mode="drop")

            donate = (0,) if self.donate else ()
            if self._grid_sharding is not None:
                rep = self._replicated
                fn = jax.jit(
                    impl,
                    in_shardings=(
                        self._grid_sharding, rep, rep,
                        self._table_shardings["name_hash_state"], rep,
                    ),
                    out_shardings=self._grid_sharding,
                    donate_argnums=donate,
                )
            else:
                fn = jax.jit(impl, donate_argnums=donate)
        fn = self._aot.wrap(f"tiebreak:{kind}", fn)
        fn = self._obs_wrap("tiebreak", fn)
        self._tb_program_cache[kind] = fn
        return fn

    def _tiebreak_plane(self, entry, fmt: str, vocab, c_bucket: int):
        """The chunk's device-resident tie-break plane (compact format),
        computed lazily when the upload-time build was skipped or the
        padded shape moved; rows whose FNV re-patch was deferred by the
        eager churn-tick repair are flushed HERE, before any survivor
        kernel reads the plane (its only consumer)."""
        if fmt != "compact" or entry.device_per_object is None:
            return None
        b_pad = entry.padded_shape[0]
        tb = entry.tiebreak_dev
        if tb is not None and tb.shape == (b_pad, c_bucket):
            if entry.tb_stale_rows:
                pend = [r for r in entry.tb_stale_rows]
                l_pad = entry.padded_shape[3]
                n = len(pend)
                idx = np.full(-(-n // 128) * 128, pend[0], np.int64)
                idx[:n] = pend
                piece = self._slice_rows(entry, idx.tolist())
                piece = Cmp.pad_axis1(piece, {"key_bytes": 0}, l_pad)
                kb = np.asarray(piece.key_bytes)
                kl = np.asarray(piece.key_len)
                state_dev = self._tables_device(vocab, c_bucket)[
                    "name_hash_state"
                ]
                dst_all = np.full(idx.shape[0], b_pad, np.int32)
                dst_all[:n] = pend
                for g in range(0, idx.shape[0], 128):
                    self.dispatches_total += 1
                    tb = self._tb_program("patch")(
                        tb,
                        np.ascontiguousarray(kb[g : g + 128]),
                        np.ascontiguousarray(kl[g : g + 128]),
                        state_dev,
                        dst_all[g : g + 128],
                    )
                entry.tiebreak_dev = tb
                entry.tb_stale_rows = None
            return tb
        tables = self._tables_device(vocab, c_bucket)
        self.dispatches_total += 1
        tb = self._tb_program("full")(
            entry.device_per_object["key_bytes"],
            entry.device_per_object["key_len"],
            tables["name_hash_state"],
        )
        entry.tiebreak_dev = tb
        entry.tb_stale_rows = None
        return tb

    def _resolve_program(self, fmt: str, m: int):
        """Jitted sort-free drift resolve per (format, M): gather the
        survivor rows' cached device inputs plus the stored prev planes,
        expand (compact) and run ops.pipeline.drift_resolve — select +
        planner from gate-refreshed state, no full-width sorts, no
        phase 1.  Like the narrow fallback, the gathered sub-problem
        rides the rows-first survivor layout under a mesh (see
        _gather_constrainer — each group's rows partition across the
        objects axis; KT_SURVIVOR_ROWSHARD=0 reverts to replication);
        the output planes are constrained back to the grid layout so both
        the in-place prev-plane repair and the (separately dispatched,
        cheap-to-trace) wire pack consume them directly.  The wire pack
        is NOT fused in here: its K comes from the per-chunk adaptive
        hint, and keying this kernel's (expensive) trace on K would
        recompile it mid-drift whenever the hint moves."""
        key = (fmt, m)
        fn = self._resolve_programs.get(key)
        if fn is not None:
            return fn
        per_object = tuple(self._per_object_fields(fmt))
        replicated = self._replicated
        grid = self._grid_sharding
        constrain = self._gather_constrainer(per_object)

        def impl(device_in, idx, prev_feas, prev_scores, prev_reasons,
                 ao, uo, an, un, didx, dvalid, tb=None, _fmt=fmt, _m=m):
            rows = {name: getattr(device_in, name)[idx] for name in per_object}
            sub = device_in._replace(**rows)
            feas_r = prev_feas[idx]
            sco_r = prev_scores[idx]
            rsn_r = prev_reasons[idx]
            tb_r = tb[idx] if tb is not None else None
            if constrain is not None:
                sub, (feas_r, sco_r, rsn_r, tb_r) = constrain(
                    sub, (feas_r, sco_r, rsn_r, tb_r)
                )
            inp = (
                expand_compact(sub, tiebreak=tb_r)
                if _fmt == "compact"
                else sub
            )
            out, cert = drift_resolve(
                inp, feas_r, sco_r, rsn_r, ao, uo, an, un, didx, dvalid, _m
            )
            # Fused wire pack (K = narrow M, stable + prewarm-known):
            # packing inside the kernel saves re-reading the five
            # [rows, C] output planes in a separate dispatch — at c5
            # the standalone packs were ~3s of the drift device time.
            k = min(_m, out.selected.shape[1])
            wire = pack_wire(
                out.selected, out.replicas, out.counted, out.scores,
                out.reasons, k,
            )
            if replicated is not None:
                wire = jax.lax.with_sharding_constraint(wire, replicated)
            if grid is not None:
                out = TickOutputs(
                    *(
                        jax.lax.with_sharding_constraint(x, grid)
                        for x in out
                    )
                )
            return out, cert, wire

        fn = self._aot.wrap(f"resolve:{fmt}:m{m}", jax.jit(impl))
        fn = self._obs_wrap("resolve", fn)
        self._resolve_programs[key] = fn
        return fn

    # Prewarm-known survivor row-group sizes (resolve / replan /
    # score-only / wcheck): greedy 256s then a 128/64 tail.  Fixed
    # sizes bound the padding waste (at c5 the ~130-survivors-per-chunk
    # case padded a 1024-row ladder rung — 8x the [rows, C] math)
    # without free-pow2 trace risk mid-drift.
    @staticmethod
    def _survivor_groups(rows: list) -> list[tuple[list, int]]:
        out = []
        i, n = 0, len(rows)
        while i < n:
            rem = n - i
            # Greedy minimal-padding decomposition over {256, 128, 64}:
            # e.g. 140 rows -> 128 + 64 (192 padded), never one 256.
            size = 256 if rem > 192 else (128 if rem > 64 else 64)
            out.append((rows[i : i + size], size))
            i += size
        return out

    def _dispatch_drift_resolve(
        self, pi: int, entry, n: int, fmt: str, b_pad: int, pack_k: int,
        info: dict, mask: np.ndarray, rec: set, forced: set, cluster_dev,
        vocab, c_bucket: int,
    ) -> list[dict]:
        """Dispatch the sort-free resolve for one gated chunk's eligible
        survivors (recompute rows without a fit flip, prev planes
        intact), or [] when the chunk cannot take it — narrow disabled,
        dense fetch format, wide delta, or no eligible rows.  The
        programs (and their wire packs) go into the device queue
        immediately, overlapping later chunks' gate compute; results are
        drained batched by _drain_drift_resolve."""
        if not self.drift_resolve or self.fetch_format != "packed":
            return []
        if self.score_f16:
            # The sort-free resolve consumes the stored score plane
            # directly; under compression those rows ride the unified
            # kernel (no stored scores needed) or the slab path instead.
            return []
        if (
            entry.prev_reasons is None
            or entry.device_per_object is None
            or entry.prev_reasons.shape != entry.prev_feas.shape
        ):
            return []
        if info["didx"].shape[0] > DRIFT_REFINE_MAX_COLS:
            return []
        m = self._narrow_m(entry.inputs, c_bucket)
        if m is None:
            return []
        fitflip = set(np.nonzero(mask & DRIFT_FITFLIP)[0].tolist())
        rows = sorted(rec - fitflip - forced)
        if not rows:
            return []
        # Resolve rows are all finite-K (kinf rows never reach the
        # refined recompute set), so the narrow candidate width M —
        # a pow2 at or above the finite maxClusters bound by
        # construction — covers every selection with zero overflow.
        # Unlike the adaptive hint it is stable across drift ticks AND
        # known to prewarm, so the wire pack program never traces
        # mid-drift.
        pack_k = min(m, c_bucket)
        if fmt == "compact":
            device_in = CompactInputs(
                **entry.device_per_object,
                **self._tables_device(vocab, c_bucket),
                **cluster_dev,
            )
            tb = self._tiebreak_plane(entry, fmt, vocab, c_bucket)
        else:
            device_in = TickInputs(**entry.device_per_object, **cluster_dev)
            tb = None
        jobs: list[dict] = []
        prog = self._resolve_program(fmt, m)
        for seg, kb in self._survivor_groups(rows):
            idx = np.full(kb, b_pad, np.int32)
            idx[: len(seg)] = seg
            self.dispatches_total += 1
            args = (
                device_in, idx, entry.prev_feas, entry.prev_out[3],
                entry.prev_reasons,
                info["alloc_old_d"], info["used_old_d"],
                info["alloc_new_d"], info["used_new_d"],
                info["didx"], info["dvalid"],
            )
            if tb is not None:
                args = args + (tb,)
            # The packed wire for every resolve slot ships fused inside
            # the program (uncertified slots are simply never decoded),
            # so the whole survivor settle overlaps the remaining gates
            # in the device queue.
            out, cert, wire = prog(*args)
            jobs.append({
                "pi": pi, "entry": entry, "rows": seg, "out": out,
                "cert": cert, "wire": wire, "pack_k": pack_k, "fmt": fmt,
                "kind": "resolve",
            })
        return jobs

    def _replan_program(self, fmt: str, m: int, scored: bool):
        """Jitted fit-flip survivor solve per (format, M, path): gather
        the survivor rows' cached device inputs plus the stored reason
        plane, expand (compact — with the precomputed tie-break plane,
        never the FNV scan) and run ops.pipeline.drift_replan
        (``scored=False``: sort-free selection-known replan for kinf
        rows) or drift_scoreonly (``scored=True``: stored-plane phase 1
        + the narrow select/planner for finite-K rows).  Mesh handling
        mirrors _resolve_program: the gathered sub-problem rides the
        rows-first survivor layout (_gather_constrainer), outputs
        constrain back to the grid for the in-place repair."""
        key = (fmt, m, scored)
        cache = self._scoreonly_programs if scored else self._replan_programs
        fn = cache.get(key)
        if fn is not None:
            return fn
        per_object = tuple(self._per_object_fields(fmt))
        replicated = self._replicated
        grid = self._grid_sharding
        i32_keys = self.phase1_i32
        constrain = self._gather_constrainer(per_object)

        def impl(device_in, idx, prev_reasons, prev_scores, tb=None,
                 _fmt=fmt, _m=m, _scored=scored):
            rows = {name: getattr(device_in, name)[idx] for name in per_object}
            sub = device_in._replace(**rows)
            rsn_r = prev_reasons[idx]
            sco_r = prev_scores[idx]
            tb_r = tb[idx] if tb is not None else None
            if constrain is not None:
                sub, (rsn_r, sco_r, tb_r) = constrain(
                    sub, (rsn_r, sco_r, tb_r)
                )
            inp = (
                expand_compact(sub, tiebreak=tb_r)
                if _fmt == "compact"
                else sub
            )
            if _scored:
                out, cert = drift_scoreonly(
                    inp, rsn_r, _m, i32_keys=i32_keys
                )
            else:
                out, cert = drift_replan(inp, rsn_r, sco_r, _m)
            # Fused wire pack — see _resolve_program.
            k = min(_m, out.selected.shape[1])
            wire = pack_wire(
                out.selected, out.replicas, out.counted, out.scores,
                out.reasons, k,
            )
            if replicated is not None:
                wire = jax.lax.with_sharding_constraint(wire, replicated)
            if grid is not None:
                out = TickOutputs(
                    *(
                        jax.lax.with_sharding_constraint(x, grid)
                        for x in out
                    )
                )
            return out, cert, wire

        name = "scoreonly" if scored else "replan"
        fn = self._aot.wrap(f"{name}:{fmt}:m{m}", jax.jit(impl))
        fn = self._obs_wrap(name, fn)
        cache[key] = fn
        return fn

    def _dispatch_drift_replans(
        self, pi: int, entry, n: int, fmt: str, b_pad: int,
        mask: np.ndarray, rec: set, forced: set, cluster_dev, vocab,
        c_bucket: int,
    ) -> list[dict]:
        """Dispatch the fit-flip survivor solves for one gated chunk:
        host-kinf rows (maxClusters unlimited or negative — the top-K
        cut provably cannot engage) through the sort-free replan,
        finite-maxClusters rows through the score-only narrow solve, in
        fixed 256-row groups.  Returns the dispatched jobs ([] when the
        chunk cannot take the path — replan disabled, dense fetch
        format, narrow disabled, or no eligible rows); cert failures
        stay in the recompute set and take the slab path."""
        if not self.replan or self.fetch_format != "packed":
            return []
        if self.score_f16:
            # The replan consumes the stored score plane; compressed
            # engines route fit-flip survivors through the unified
            # kernel / slab path instead (see _dispatch_drift_resolve).
            return []
        if (
            entry.prev_reasons is None
            or entry.device_per_object is None
            or entry.prev_feas is None
            or entry.prev_reasons.shape != entry.prev_feas.shape
        ):
            return []
        m = self._narrow_m(entry.inputs, c_bucket)
        if m is None:
            return []
        fitflip = set(np.nonzero(mask & DRIFT_FITFLIP)[0].tolist())
        rows = sorted((rec & fitflip) - forced)
        if not rows:
            return []
        mc = np.asarray(entry.inputs.max_clusters)
        kinf_host = (mc == INT32_INF) | (mc < 0)
        by_path = {
            False: [r for r in rows if kinf_host[r]],
            True: [r for r in rows if not kinf_host[r]],
        }
        # Same wire-pack K policy as the resolve: narrow M is stable
        # across drift ticks and prewarm-known, unlike the adaptive
        # hint (K-overflow rows ride the existing bit-packed re-fetch).
        pack_k = min(m, c_bucket)
        if fmt == "compact":
            device_in = CompactInputs(
                **entry.device_per_object,
                **self._tables_device(vocab, c_bucket),
                **cluster_dev,
            )
            tb = self._tiebreak_plane(entry, fmt, vocab, c_bucket)
        else:
            device_in = TickInputs(**entry.device_per_object, **cluster_dev)
            tb = None
        jobs: list[dict] = []
        for scored, path_rows in by_path.items():
            if not path_rows:
                continue
            prog = self._replan_program(fmt, m, scored)
            for seg, g in self._survivor_groups(path_rows):
                idx = np.full(g, b_pad, np.int32)
                idx[: len(seg)] = seg
                self.dispatches_total += 1
                args = (device_in, idx, entry.prev_reasons,
                        entry.prev_out[3])
                if tb is not None:
                    args = args + (tb,)
                out, cert, wire = prog(*args)
                jobs.append({
                    "pi": pi, "entry": entry, "rows": seg, "out": out,
                    "cert": cert, "wire": wire, "pack_k": pack_k,
                    "fmt": fmt,
                    "kind": "score_only" if scored else "replan",
                })
        return jobs

    def _survivor_program(self, fmt: str, m: int):
        """Jitted UNIFIED survivor solve per (format, M) — the ISSUE 11
        tentpole: gather the survivor rows' cached device inputs plus
        the stored reason plane, expand (compact — with the precomputed
        tie-break plane, never the FNV scan) and run
        ops.pipeline.drift_survivor, which subsumes the resolve /
        replan / score-only specializations exactly (see its
        docstring).  Needs NO stored score plane (scores recompute from
        stored filters) and NO delta-column info (wide drifts ride it
        too).  Mesh handling mirrors _resolve_program: the gathered
        sub-problem rides the rows-first survivor layout
        (_gather_constrainer — N devices each solve G/N rows of a
        group, the ISSUE 12 per-device stream), outputs constrain back
        to the grid for the in-place repair; the wire pack is fused at
        K = narrow M."""
        key = (fmt, m)
        fn = self._survivor_programs.get(key)
        if fn is not None:
            return fn
        per_object = tuple(self._per_object_fields(fmt))
        replicated = self._replicated
        grid = self._grid_sharding
        i32_keys = self.phase1_i32
        constrain = self._gather_constrainer(per_object)

        def impl(device_in, idx, prev_reasons, tb=None, _fmt=fmt, _m=m):
            rows = {name: getattr(device_in, name)[idx] for name in per_object}
            sub = device_in._replace(**rows)
            rsn_r = prev_reasons[idx]
            tb_r = tb[idx] if tb is not None else None
            if constrain is not None:
                sub, (rsn_r, tb_r) = constrain(sub, (rsn_r, tb_r))
            inp = (
                expand_compact(sub, tiebreak=tb_r)
                if _fmt == "compact"
                else sub
            )
            out, cert = drift_survivor(inp, rsn_r, _m, i32_keys=i32_keys)
            # Fused wire pack — see _resolve_program.
            k = min(_m, out.selected.shape[1])
            wire = pack_wire(
                out.selected, out.replicas, out.counted, out.scores,
                out.reasons, k,
            )
            if replicated is not None:
                wire = jax.lax.with_sharding_constraint(wire, replicated)
            if grid is not None:
                out = TickOutputs(
                    *(
                        jax.lax.with_sharding_constraint(x, grid)
                        for x in out
                    )
                )
            return out, cert, wire

        fn = self._aot.wrap(f"survivor:{fmt}:m{m}", jax.jit(impl))
        fn = self._obs_wrap("survivor", fn)
        self._survivor_programs[key] = fn
        return fn

    def _dispatch_drift_survivors(
        self, pi: int, entry, n: int, fmt: str, b_pad: int,
        mask: np.ndarray, rec: set, forced: set, cluster_dev, vocab,
        c_bucket: int,
    ) -> list[dict]:
        """Dispatch ONE unified survivor stream for a gated chunk: every
        recompute-classified row (fit flip or not, kinf or finite-K)
        rides the same greedy-grouped drift_survivor program, so the
        chunk pays one {256,128,64} padding ladder instead of three.
        The per-row mode vector (resolve/replan/score_only — what the
        three-stream dispatch would have picked) is carried host-side
        for attribution only.  Returns the dispatched jobs ([] when the
        chunk cannot take the path); cert failures stay in the
        recompute set and take the slab path."""
        if not self.survivor_unified or self.fetch_format != "packed":
            return []
        if (
            entry.prev_reasons is None
            or entry.device_per_object is None
            or entry.prev_feas is None
            or entry.prev_reasons.shape != entry.prev_feas.shape
        ):
            return []
        m = self._narrow_m(entry.inputs, c_bucket)
        if m is None:
            return []
        rows = sorted(rec - forced)
        if not rows:
            return []
        if mask is None:
            # Second-wave dispatch: weight-changed wcheck rows (kinf,
            # no fit flip — the gate already proved selection equals
            # the feasible set; only their dynamic-weight planner run
            # moves).  r11 sent these through full slabs.
            modes = {r: "wcheck" for r in rows}
        else:
            fitflip = set(np.nonzero(mask & DRIFT_FITFLIP)[0].tolist())
            mc = np.asarray(entry.inputs.max_clusters)
            kinf_host = (mc == INT32_INF) | (mc < 0)
            modes = {
                r: (
                    "resolve"
                    if r not in fitflip
                    else ("replan" if kinf_host[r] else "score_only")
                )
                for r in rows
            }
        # Same wire-pack K policy as the three-stream paths: narrow M is
        # stable across drift ticks and prewarm-known (K-overflow rows
        # ride the existing bit-packed re-fetch).
        pack_k = min(m, c_bucket)
        if fmt == "compact":
            device_in = CompactInputs(
                **entry.device_per_object,
                **self._tables_device(vocab, c_bucket),
                **cluster_dev,
            )
            tb = self._tiebreak_plane(entry, fmt, vocab, c_bucket)
        else:
            device_in = TickInputs(**entry.device_per_object, **cluster_dev)
            tb = None
        prog = self._survivor_program(fmt, m)
        jobs: list[dict] = []
        self.survivor_stats["rows"] += len(rows)
        for seg, g in self._survivor_groups(rows):
            idx = np.full(g, b_pad, np.int32)
            idx[: len(seg)] = seg
            self.dispatches_total += 1
            self.survivor_stats["groups"] += 1
            self.survivor_stats["padded_rows"] += g
            args = (device_in, idx, entry.prev_reasons)
            if tb is not None:
                args = args + (tb,)
            out, cert, wire = prog(*args)
            jobs.append({
                "pi": pi, "entry": entry, "rows": seg, "out": out,
                "cert": cert, "wire": wire, "pack_k": pack_k, "fmt": fmt,
                "kind": "unified",
                "modes": [modes[r] for r in seg],
            })
        return jobs

    def _repair_entry_rows(self, entry, out, src_pos, dst_rows) -> bool:
        """Scatter resolve-output rows back into the chunk's cached prev
        planes in place (the 6-plane donated repair: selection planes +
        feasibility + reasons).  Returns False when the cached planes
        cannot take the scatter (caller falls back to stale marking)."""
        if (
            entry.prev_out is None
            or entry.prev_feas is None
            or entry.prev_reasons is None
        ):
            return False
        b_pad, c_pad = entry.prev_out[0].shape
        if (
            entry.prev_feas.shape != (b_pad, c_pad)
            or entry.prev_reasons.shape != (b_pad, c_pad)
            or out.selected.shape[1] != c_pad
            or max(dst_rows, default=0) >= b_pad
        ):
            return False
        planes = entry.prev_out + (entry.prev_feas, entry.prev_reasons)
        nfeas = self._ensure_nfeas(entry)
        fn = self._repair_program()
        sco_exact = (
            self._ensure_sco_exact_vec(entry) if self.score_f16 else None
        )
        out_planes = (
            out.selected, out.replicas, out.counted, out.scores,
            out.feasible, out.reasons,
        )
        # Fixed 128-row scatter groups (see _repair_prev_planes): one
        # prewarmable index shape, never a trace stall mid-drift.
        for g in range(0, len(src_pos), 128):
            src = np.zeros(128, np.int32)
            seg = np.asarray(src_pos[g : g + 128])
            src[: seg.size] = seg
            dst = np.full(128, b_pad, np.int32)  # pad scatters drop
            dseg = np.asarray(dst_rows[g : g + 128])
            dst[: dseg.size] = dseg
            self.dispatches_total += 1
            if sco_exact is not None:
                out8 = fn(planes, out_planes, src, dst, nfeas, sco_exact)
                planes, nfeas, sco_exact = out8[:6], out8[6], out8[7]
            else:
                out7 = fn(planes, out_planes, src, dst, nfeas)
                planes, nfeas = out7[:6], out7[6]
        entry.prev_out = planes[:4]
        entry.prev_feas = planes[4]
        entry.prev_reasons = planes[5]
        entry.prev_nfeas = nfeas
        if sco_exact is not None:
            entry.prev_sco_exact = sco_exact
            entry.sco_inexact_host = None
        return True

    def _drain_drift_resolve(
        self, jobs, plans, plan_resolved, view, timings,
    ) -> None:
        """Drain the in-flight resolve programs: batched cert + wire
        reads, decode of certified rows, merge into the cached decodes,
        in-place prev-plane repair.  Cert failures stay in the chunk's
        recompute set and take the slab path."""
        t0 = time.perf_counter()
        cert_np: dict[int, np.ndarray] = {}
        wire_np: dict[int, np.ndarray] = {}
        for arrs, field in ((cert_np, "cert"), (wire_np, "wire")):
            groups: dict[tuple, list[int]] = {}
            for i, job in enumerate(jobs):
                groups.setdefault(tuple(job[field].shape), []).append(i)
            for _, members in groups.items():
                if len(members) == 1:
                    arrs[members[0]] = self._read_np(jobs[members[0]][field])
                else:
                    stacked = self._read_np(
                        self._stack(*[jobs[i][field] for i in members])
                    )
                    for j, i in enumerate(members):
                        arrs[i] = stacked[j]
        timings["fetch"] += time.perf_counter() - t0

        for i, job in enumerate(jobs):
            t0 = time.perf_counter()
            entry, rows, out, k = (
                job["entry"], job["rows"], job["out"], job["pack_k"]
            )
            kind = job.get("kind", "resolve")
            nr = len(rows)
            cert = cert_np[i][:nr]
            ok_pos = np.nonzero(cert != 0)[0]
            self.drift_stats[kind] += int(ok_pos.size)
            self.drift_stats[kind + "_fallback"] += int(nr - ok_pos.size)
            if kind == "unified":
                self.survivor_stats["fallback_rows"] += int(nr - ok_pos.size)
            handled = {rows[p] for p in ok_pos.tolist()}
            plans[job["pi"]][3] -= handled
            if not ok_pos.size:
                timings["decode"] += time.perf_counter() - t0
                continue
            full = unpack_wire(wire_np[i][:nr], k)
            packed = PackedRows(*(np.asarray(f)[ok_pos] for f in full))
            self._observe_nsel(entry, packed.nsel, out.selected.shape[1])
            over_pos = np.nonzero(np.asarray(packed.nsel) > k)[0]
            over_dense = None
            if over_pos.size:
                t1 = time.perf_counter()
                timings["decode"] += t1 - t0
                over_dense = self._fetch_overflow(
                    out, ok_pos[over_pos].astype(np.int64), False, timings
                )
                t0 = time.perf_counter()
            results = self._decode_packed_mixed(
                packed, over_pos, over_dense, view.names, False
            )
            res_rows = [rows[p] for p in ok_pos.tolist()]
            merged = list(entry.prev_results)
            for r, res in zip(res_rows, results):
                merged[r] = res
            entry.prev_results = merged
            self._record_packed(
                entry, res_rows, results, packed, over_pos, over_dense,
                view, program=f"{job['fmt']}:{kind}",
            )
            if not self._repair_entry_rows(entry, out, ok_pos, res_rows):
                entry.stale_out_rows = sorted(
                    set(entry.stale_out_rows or ()) | set(res_rows)
                )
            plan_resolved.setdefault(job["pi"], []).extend(res_rows)
            timings["decode"] += time.perf_counter() - t0

    def _drain_drift_gates(
        self, items, chunk_results, chunk_changed, view, want_scores: bool,
        timings, pending_sub, c_bucket, eff_chunk, ladder, vocab,
    ) -> None:
        """Resolve every gated chunk as a streaming pipeline, never
        stopping the world: gate masks are read IN DISPATCH ORDER (the
        read for chunk i blocks only on gate i — gates i+1.. keep
        computing), and each chunk's survivor work (the sort-free
        drift-resolve program, the dynamic-weight check) dispatches
        immediately after its classification, so the device queue flows
        gate -> survivors -> gate without a host-side barrier.  Only
        then are the survivor outputs drained (batched reads), cert
        failures and wcheck-changed rows folded into the slab path, and
        the remaining chunks settled as provable skips / slab
        recomputes / (mass change) fallback full dispatches."""
        if not items:
            return
        self.metrics.store("engine_gate_inflight", len(items))
        resolve_jobs: list[dict] = []
        plans: list[list] = []  # [slot, entry, n, recompute set, fmt, b_pad, k]
        wcheck_jobs: list[tuple] = []  # (plan index, wcheck rows, dev)
        plan_resolved: dict[int, list] = {}  # plan index -> merged rows
        newc = self._cluster_planes_device(view, c_bucket)
        for i, (slot, entry, n, devs, fmt, b_pad, pack_k, info) in enumerate(
            items
        ):
            # The mask rows are a few KB; this read blocks on gate i's
            # COMPUTE (gates past i and any already-dispatched survivor
            # programs keep running), so its wall time is attributed
            # separately (gate_wait) — bench/metrics split the drift
            # tick's fetch stage into its real phases.
            t0 = time.perf_counter()
            mask = self._read_np(devs[0])[:n]
            dt = time.perf_counter() - t0
            timings["gate_wait"] = timings.get("gate_wait", 0.0) + dt
            timings["fetch"] += dt
            t0 = time.perf_counter()
            self.drift_stats["gated"] += 1
            # Rows whose cached prev planes are unreliable (patched
            # without a successful device write-back) are gate-blind:
            # force them into the recompute set.  Under KT_SCORE_F16,
            # rows whose stored scores were quantized lossily are
            # equally gate-blind for the rank compare — forced too,
            # BEFORE the refreshed plane replaces the exactness vector.
            forced = set()
            if self.score_f16:
                forced.update(
                    int(r)
                    for r in self._sco_inexact_rows(entry)
                    if r < n
                )
            # The gate refreshed the changed columns of the stored score
            # plane (skipped rows stay exact for future drift gates;
            # recomputed rows are overwritten by the slab repair).
            if self.score_f16:
                entry.prev_out = entry.prev_out[:3] + (
                    self._compress_scores(entry, devs[1], and_old=True),
                )
            else:
                entry.prev_out = entry.prev_out[:3] + (devs[1],)
            rec = set(np.nonzero(mask & DRIFT_RECOMPUTE)[0].tolist())
            if entry.stale_out_rows:
                forced.update(r for r in entry.stale_out_rows if r < n)
            if entry.stale_rows:
                forced.update(r for r in entry.stale_rows if r < n)
            rec |= forced
            wrows = np.nonzero(mask & DRIFT_WCHECK)[0]
            if forced and wrows.size:
                wrows = wrows[~np.isin(wrows, sorted(forced))]
            plans.append([slot, entry, n, rec, fmt, b_pad, pack_k])
            if wrows.size:
                # Dispatch the weight check NOW; its result is read in
                # the batched drain below.  Rows go in FIXED 64- or
                # 256-row groups (one prewarmed program shape each, no
                # pow2-ladder padding waste — at c5 the ~270-rows-per-
                # chunk case padded a 1024-row rung, 4x the [rows, C]
                # weight math for nothing), with the i32 arithmetic
                # demotion behind the host range guard.
                self.drift_stats["wcheck"] += int(wrows.size)
                w_i32 = self._wcheck_i32_ok(entry.prev_view, view, c_bucket)
                wfn = self._wcheck_program(w_i32)
                oldc = self._wcheck_cpu_device(entry.prev_view, c_bucket)
                for seg_list, kb in self._survivor_groups(
                    wrows.tolist()
                ):
                    seg = np.asarray(seg_list, dtype=wrows.dtype)
                    ridx = np.zeros(kb, np.int32)
                    ridx[: seg.size] = seg
                    self.dispatches_total += 1
                    wcheck_jobs.append(
                        (len(plans) - 1, seg, wfn(
                            entry.prev_feas, ridx,
                            oldc["cpu_alloc"], oldc["cpu_avail"],
                            newc["cpu_alloc"], newc["cpu_avail"],
                        ))
                    )
            if self.survivor_unified:
                # ONE unified survivor stream per chunk (the ISSUE 11
                # tentpole): every recompute row — fit flip or not —
                # rides the same greedy-grouped drift_survivor program,
                # dispatched immediately so it overlaps the remaining
                # gates' compute.
                resolve_jobs.extend(
                    self._dispatch_drift_survivors(
                        len(plans) - 1, entry, n, fmt, b_pad, mask, rec,
                        forced, newc, vocab, c_bucket,
                    )
                )
            else:
                # KT_SURVIVOR_UNIFIED=0 revert: the r11 three-stream
                # dispatch (sort-free resolve for no-fit-flip rows,
                # selection-known replan for kinf fit-flips, score-only
                # narrow solve for finite-K fit-flips).
                resolve_jobs.extend(
                    self._dispatch_drift_resolve(
                        len(plans) - 1, entry, n, fmt, b_pad, pack_k,
                        info, mask, rec, forced, newc, vocab, c_bucket,
                    )
                )
                resolve_jobs.extend(
                    self._dispatch_drift_replans(
                        len(plans) - 1, entry, n, fmt, b_pad, mask, rec,
                        forced, newc, vocab, c_bucket,
                    )
                )
            timings["decode"] += time.perf_counter() - t0

        if resolve_jobs:
            self._drain_drift_resolve(
                resolve_jobs, plans, plan_resolved, view, timings,
            )

        if wcheck_jobs:
            t0 = time.perf_counter()
            wgroups: dict[tuple, list[int]] = {}
            for i, (_, _, dev) in enumerate(wcheck_jobs):
                wgroups.setdefault(tuple(dev.shape), []).append(i)
            warr: dict[int, np.ndarray] = {}
            for _, members in wgroups.items():
                if len(members) == 1:
                    warr[members[0]] = self._read_np(wcheck_jobs[members[0]][2])
                else:
                    stacked = self._read_np(
                        self._stack(*[wcheck_jobs[i][2] for i in members])
                    )
                    for j, i in enumerate(members):
                        warr[i] = stacked[j]
            changed_by_pi: dict[int, list] = {}
            for i, (pi, wrows, _dev) in enumerate(wcheck_jobs):
                changed = wrows[warr[i][: wrows.size] != 0]
                self.drift_stats["wcheck_changed"] += int(changed.size)
                plans[pi][3] |= set(changed.tolist())
                if changed.size:
                    changed_by_pi.setdefault(pi, []).extend(
                        changed.tolist()
                    )
            timings["gate_wait"] = (
                timings.get("gate_wait", 0.0) + time.perf_counter() - t0
            )
            timings["fetch"] += time.perf_counter() - t0
            if self.survivor_unified and changed_by_pi:
                # Weight-changed wcheck rows are unified-eligible too:
                # kinf, no fit flip, trustworthy stored reasons — the
                # kernel re-derives selection (= the feasible set) and
                # re-runs the planner with fresh dynamic weights,
                # cert-guarded like every survivor.  Dispatched ONLY
                # when the chunk's changed set is small (one greedy
                # group): that is the padding-waste regime the unified
                # stream exists for (130 rows in a 1024-row slab = 8x
                # the math); a LARGE changed set already packs a slab
                # near-perfectly, and the slab's one-dispatch drain
                # beats a multi-group survivor drain there (measured:
                # c3's 1000-row wcheck drift was 555ms via one slab vs
                # ~1050ms via 8 survivor groups).
                wave2: list[dict] = []
                for pi, rows_c in changed_by_pi.items():
                    if len(rows_c) > 256:
                        continue
                    _slot, entry, n, _rec, fmt, b_pad, _pk = plans[pi]
                    wave2.extend(
                        self._dispatch_drift_survivors(
                            pi, entry, n, fmt, b_pad, None, set(rows_c),
                            set(), newc, vocab, c_bucket,
                        )
                    )
                if wave2:
                    self._drain_drift_resolve(
                        wave2, plans, plan_resolved, view, timings,
                    )

        t0 = time.perf_counter()
        fallback: list[tuple] = []
        for pi, (slot, entry, n, rec, fmt, b_pad, pack_k) in enumerate(plans):
            rec = {r for r in rec if r < n}
            resolved = plan_resolved.get(pi, [])
            if not rec:
                entry.prev_view = view
                chunk_results[slot] = entry.prev_results
                if resolved:
                    # Every recompute row was settled by drift_resolve;
                    # the merged decodes already carry them.
                    self.fetch_stats["delta"] += 1
                    self.drift_stats["skip"] += n - len(resolved)
                    chunk_changed[slot] = sorted(resolved)
                else:
                    self.fetch_stats["skip"] += 1
                    self.drift_stats["skip"] += n
                    chunk_changed[slot] = []
            elif len(rec) > n // 2:
                # Mass change: the whole-chunk dispatch with the regular
                # delta fetch beats slabbing most of the chunk.
                self.drift_stats["fallback"] += 1
                fallback.append((slot, entry, n, fmt, b_pad, pack_k))
            else:
                rows = sorted(rec)
                self.fetch_stats["delta"] += 1
                self.drift_stats["recompute"] += len(rows)
                self.drift_stats["skip"] += n - len(rows) - len(resolved)
                pending_sub.append(
                    (slot, entry, rows, self._slice_rows(entry, rows), False)
                )
                chunk_changed[slot] = sorted(rec | set(resolved))
        timings["featurize"] += time.perf_counter() - t0
        self.metrics.store("engine_gate_inflight", 0)

        if fallback:
            t0 = time.perf_counter()
            fitems: list[tuple] = []
            cluster_dev = self._cluster_planes_device(view, c_bucket)
            for slot, entry, n, fmt, b_pad, pack_k in fallback:
                padded = self._pad_for_dispatch(
                    entry.inputs, fmt, b_pad, c_bucket,
                    skip_cluster_fields=True,
                )
                device_in = self._device_inputs(
                    entry, padded, "hit", fmt, vocab, c_bucket, cluster_dev
                )
                shape = (b_pad, c_bucket)
                delta_ok = (
                    entry.prev_out is not None
                    and entry.prev_out[0].shape == shape
                    and not (self.score_f16 and entry.prev_has_scores)
                )
                prev = (
                    self._prev_for_diff(entry)
                    if delta_ok
                    else self._zeros_for(shape)
                )
                narrow_m = self._narrow_m(entry.inputs, c_bucket)
                self._count_dispatch(fmt, b_pad, c_bucket)
                if narrow_m is not None:
                    self.narrow_last_m = narrow_m
                    out, mask_dev, cert_dev = self._narrow_program(
                        fmt, narrow_m
                    )(device_in, prev)
                else:
                    tick = (
                        self._tick_compact if fmt == "compact" else self._tick
                    )
                    out, mask_dev = tick(device_in, prev)
                    cert_dev = None
                if delta_ok and self.donate:
                    entry.prev_out = None
                fitems.append(
                    (slot, entry, out, mask_dev if delta_ok else None, n,
                     pack_k, cert_dev,
                     device_in if cert_dev is not None else None, fmt)
                )
            timings["device"] += time.perf_counter() - t0
            self._drain_fetch_window(
                fitems, chunk_results, chunk_changed, view, want_scores,
                timings,
            )

    def _device_inputs(
        self,
        entry: Optional[_CachedChunk],
        padded,
        status: str,
        fmt: str,
        vocab: Optional[CompactVocab],
        c_bucket: int,
        cluster_dev: dict,
    ):
        """Per-object tensors live on device across ticks: a clean re-tick
        ("hit") reuses last tick's device buffers and transfers nothing
        at all — the cluster-axis tensors come from the shared
        once-per-tick device copy (``cluster_dev``,
        _cluster_planes_device) instead of riding every dispatch.
        Patched or fresh chunks are re-uploaded and re-cached.  Under a
        mesh the upload lands pre-sharded in the tick's input layout.
        The compact format additionally sources its vocabulary tables
        from the shared device copy (uploaded once per vocab version)."""
        fields = padded._asdict()
        per_object_names = self._per_object_fields(fmt)
        per_object = {name: fields[name] for name in per_object_names}
        # The padded-shape key must capture every per-object axis that
        # participates in the program shape: (B, C) for dense, plus the
        # sparse-entry and key-byte widths for compact.
        b_pad = np.asarray(padded.total).shape[0]
        c_pad = c_bucket
        if fmt == "compact":
            shape = (
                b_pad,
                c_pad,
                np.asarray(padded.sparse_idx).shape[1],
                np.asarray(padded.key_bytes).shape[1],
            )
            shardings = self._per_object_shardings_compact
        else:
            shape = (b_pad, c_pad)
            shardings = self._per_object_shardings
        if (
            entry is not None
            and status == "hit"
            and entry.device_per_object is not None
            and entry.padded_shape == shape
        ):
            if entry.stale_rows:
                # Scatter-repair the rows churned since the last upload:
                # K rows over the link instead of the whole chunk, in
                # the shape-stable 128-row patch groups.
                self._repair_stale_inputs(entry, fmt, c_pad, vocab=vocab)
            per_object = entry.device_per_object
        else:
            self.upload_bytes["object"] += sum(
                np.asarray(a).nbytes for a in per_object.values()
            )
            if shardings is not None:
                per_object = jax.device_put(per_object, shardings)
            else:
                per_object = jax.device_put(per_object)
            if entry is not None:
                entry.device_per_object = per_object
                entry.padded_shape = shape
                entry.stale_rows = None
                entry.tiebreak_dev = None
                entry.tb_stale_rows = None
                if fmt == "compact" and vocab is not None:
                    # Precompute the tie-break plane off the fresh
                    # upload (async; amortizes into the cold/miss path
                    # so drift survivor kernels skip the FNV scan).
                    self.dispatches_total += 1
                    entry.tiebreak_dev = self._tb_program("full")(
                        per_object["key_bytes"],
                        per_object["key_len"],
                        self._tables_device(vocab, c_pad)[
                            "name_hash_state"
                        ],
                    )
        if fmt == "compact":
            return CompactInputs(
                **per_object,
                **self._tables_device(vocab, c_pad),
                **cluster_dev,
            )
        return TickInputs(**per_object, **cluster_dev)

    @staticmethod
    def _build_results(
        n_rows, rows, cols, replicas_at, counted_at, names, scores_at
    ) -> list[ScheduleResult]:
        """Shared decode tail: (row, col) placement pairs -> frozen
        ScheduleResults, one dict(zip(...)) per row — no per-placement
        Python.  ``*_at`` are the values already gathered at the pairs."""
        bounds = np.searchsorted(rows, np.arange(n_rows + 1))
        reps_obj = replicas_at.astype(object)
        reps_obj[counted_at == 0] = DUPLICATE
        names_arr = np.asarray(names, dtype=object)
        sel_names = names_arr[cols].tolist()
        reps_list = reps_obj.tolist()
        score_list = scores_at.tolist() if scores_at is not None else None
        out = []
        empty = _FrozenDict()
        for i in range(n_rows):
            s, e = bounds[i], bounds[i + 1]
            out.append(
                ScheduleResult(
                    clusters=_FrozenDict(zip(sel_names[s:e], reps_list[s:e])),
                    scores=_FrozenDict(zip(sel_names[s:e], score_list[s:e]))
                    if score_list is not None
                    else empty,
                )
            )
        return out

    def _decode_rows(
        self, selected, replicas, counted, names, scores=None
    ) -> list[ScheduleResult]:
        """Vectorized decode of dense [n, C] planes."""
        rows, cols = np.nonzero(selected)
        return self._build_results(
            selected.shape[0], rows, cols,
            replicas[rows, cols], counted[rows, cols], names,
            scores[rows, cols] if scores is not None else None,
        )

    def _decode_packed_rows(
        self, packed: PackedRows, names, scores: bool = False
    ) -> list[ScheduleResult]:
        """Decode packed [n, K] rows (slots score-ordered, PACK_FILL
        padded).  Dict content is identical to the dense decode —
        insertion order differs (score vs index order), which no
        consumer observes: persistence sorts placements and all
        comparisons are dict equality.  Callers must exclude overflow
        rows (nsel > K)."""
        idx = np.asarray(packed.idx)
        valid = idx >= 0
        rows, slots = np.nonzero(valid)
        return self._build_results(
            idx.shape[0], rows, idx[rows, slots],
            np.asarray(packed.rep)[rows, slots],
            np.asarray(packed.cnt)[rows, slots], names,
            np.asarray(packed.sco)[rows, slots] if scores else None,
        )

    def _drain_fetch(
        self, item, chunk_results, chunk_changed, view, want_scores: bool, timings
    ) -> None:
        """Complete one in-flight pipelined chunk (see pipeline_depth)."""
        slot, entry, out, mask_dev, n, pack_k = item[:6]
        cert_dev = item[6] if len(item) > 6 else None
        if cert_dev is not None:
            out, fb_rows = self._apply_cert_fallback(
                out, self._read_np(cert_dev), item[7], item[8], n, timings
            )
            if fb_rows is not None and mask_dev is not None:
                mask = self._read_np(mask_dev)[:n].copy()
                mask[fb_rows] |= _DIFF_PLACEMENT
                mask_dev = mask
        chunk_results[slot], chunk_changed[slot] = self._fetch_decode(
            entry, out, mask_dev, view.names, n, want_scores, timings, view,
            pack_k,
        )

    def _resolve_cert_window(self, items, timings) -> list[tuple]:
        """Resolve narrow certificates for a window of in-flight chunks
        and normalize every item to the 6-tuple (slot, entry, out, mask,
        n, pack_k) layout the drain helpers consume.  Cert planes are
        tiny i8[B] rows, so same-shape certs across the window stack
        into one transfer (the mask-read pattern); uncertified rows then
        re-solve + repair per chunk BEFORE any plane leaves the device,
        with the diff mask forced for re-solved rows."""
        if not any(len(it) > 6 and it[6] is not None for it in items):
            return [it[:6] for it in items]
        t0 = time.perf_counter()
        cert_np: dict[int, np.ndarray] = {}
        cgroups: dict[tuple, list[int]] = {}
        for i, it in enumerate(items):
            if len(it) > 6 and it[6] is not None:
                cgroups.setdefault(tuple(it[6].shape), []).append(i)
        for _, members in cgroups.items():
            if len(members) == 1:
                cert_np[members[0]] = self._read_np(items[members[0]][6])
            else:
                stacked = self._read_np(
                    self._stack(*[items[i][6] for i in members])
                )
                for j, i in enumerate(members):
                    cert_np[i] = stacked[j]
        timings["fetch"] += time.perf_counter() - t0
        out_items: list[tuple] = []
        for i, it in enumerate(items):
            slot, entry, out, mask_dev, n, pack_k = it[:6]
            if i in cert_np:
                out, fb_rows = self._apply_cert_fallback(
                    out, cert_np[i], it[7], it[8], n, timings
                )
                if fb_rows is not None and mask_dev is not None:
                    mask = self._read_np(mask_dev)[:n].copy()
                    mask[fb_rows] |= _DIFF_PLACEMENT
                    mask_dev = mask
            out_items.append((slot, entry, out, mask_dev, n, pack_k))
        return out_items

    def _drain_fetch_window(
        self, items, chunk_results, chunk_changed, view, want_scores: bool, timings
    ) -> None:
        """Drain a whole in-flight window with BATCHED transfers.

        Per-transfer latency, not payload, dominates multi-chunk ticks
        over the tunneled chip (each blocking device->host read is a
        round trip): instead of per-chunk mask + gather + plane reads,
        same-shape buffers across the window are stacked ON DEVICE and
        fetched in one transfer each — one read for all diff masks, one
        per plane-group for delta gathers, one per output plane group
        for full refetches — and every device dispatch is enqueued
        before the first blocking read.  Per-chunk semantics live in
        the helpers shared with _fetch_decode (_plan_delta /
        _note_skip / _apply_delta / _apply_full)."""
        if not items:
            return
        if len(items) == 1:
            self._drain_fetch(
                items[0], chunk_results, chunk_changed, view, want_scores, timings
            )
            return
        items = self._resolve_cert_window(items, timings)

        # Phase 1: one stacked transfer per mask shape.
        t0 = time.perf_counter()
        mask_np: dict[int, np.ndarray] = {}
        mgroups: dict[tuple, list] = {}
        for it in items:
            if it[3] is not None:
                mgroups.setdefault(tuple(it[3].shape), []).append(it)
        for _, group in mgroups.items():
            if len(group) == 1:
                mask_np[group[0][0]] = self._read_np(group[0][3])
            else:
                stacked = self._read_np(self._stack(*[g[3] for g in group]))
                for i, g in enumerate(group):
                    mask_np[g[0]] = stacked[i]
        timings["fetch"] += time.perf_counter() - t0

        # Phase 2: plan skip/delta/full per chunk from the host masks.
        delta_items: list[tuple] = []
        full_items: list[tuple] = []
        for slot, entry, out, mask_dev, n, pack_k in items:
            if mask_dev is None:
                full_items.append((slot, entry, out, n, pack_k))
                continue
            kind, idx = self._plan_delta(entry, mask_np[slot][:n], n)
            if kind == "skip":
                self._note_skip(entry, out, view)
                chunk_results[slot] = entry.prev_results
                chunk_changed[slot] = []
            elif kind == "full":
                full_items.append((slot, entry, out, n, pack_k))
            else:
                delta_items.append((slot, entry, out, idx, pack_k))

        if self.fetch_format == "packed":
            self._drain_window_packed(
                delta_items, full_items, chunk_results, chunk_changed,
                view, want_scores, timings,
            )
            return

        # Phase 3: enqueue ALL device work — delta gathers (idx bucketed
        # to the window max per plane-group so outputs stack) and full-
        # plane stacks — and only then run the blocking host reads, so
        # transfers overlap device execution instead of serializing.
        t0 = time.perf_counter()
        record = self._tick_rec is not None
        by_planes: dict[int, list] = {}
        for slot, entry, out, idx, _k in delta_items:
            self.fetch_stats["delta"] += 1
            planes = 5 if record else (4 if entry.prev_has_scores else 3)
            by_planes.setdefault(planes, []).append((slot, entry, out, idx))
        stacked_devs: dict[int, object] = {}
        for planes, group in by_planes.items():
            k_max = max(
                _pow2_bucket(idx.size, 16, 1 << 30) for _, _, _, idx in group
            )
            devs = []
            for slot, entry, out, idx in group:
                padded_idx = np.zeros(k_max, np.int32)
                padded_idx[: idx.size] = idx
                if planes == 5:
                    devs.append(
                        self._gather5(
                            out.selected, out.replicas, out.counted,
                            out.scores, out.reasons, padded_idx,
                        )
                    )
                elif planes == 4:
                    devs.append(
                        self._gather(
                            out.selected, out.replicas, out.counted,
                            out.scores, padded_idx,
                        )
                    )
                else:
                    devs.append(
                        self._gather3(
                            out.selected, out.replicas, out.counted, padded_idx
                        )
                    )
            stacked_devs[planes] = devs[0] if len(devs) == 1 else self._stack(*devs)
        want_score_plane = want_scores or record
        fstacks: list[tuple] = []
        fgroups: dict[tuple, list] = {}
        for slot, entry, out, n, _k in full_items:
            fgroups.setdefault(tuple(out.selected.shape), []).append(
                (slot, entry, out, n)
            )
        for _, group in fgroups.items():
            if len(group) == 1:
                g = group[0][2]
                fstacks.append(
                    (group, g.selected, g.replicas, g.counted,
                     g.scores if want_score_plane else None,
                     g.reasons if record else None)
                )
            else:
                fstacks.append(
                    (
                        group,
                        self._stack(*[g[2].selected for g in group]),
                        self._stack(*[g[2].replicas for g in group]),
                        self._stack(*[g[2].counted for g in group]),
                        self._stack(*[g[2].scores for g in group])
                        if want_score_plane
                        else None,
                        self._stack(*[g[2].reasons for g in group])
                        if record
                        else None,
                    )
                )
        packed_np = {p: self._read_np(d) for p, d in stacked_devs.items()}
        full_np = [
            (
                group,
                self._read_np(sel),
                self._read_np(rep),
                self._read_np(cnt),
                self._read_np(sco) if sco is not None else None,
                self._read_np(rsn) if rsn is not None else None,
            )
            for group, sel, rep, cnt, sco, rsn in fstacks
        ]
        timings["fetch"] += time.perf_counter() - t0

        # Phase 4: host-side decode + bookkeeping, per chunk.
        t0 = time.perf_counter()
        for planes, group in by_planes.items():
            arr = packed_np[planes]
            single = len(group) == 1
            for i, (slot, entry, out, idx) in enumerate(group):
                merged, idx_rows = self._apply_delta(
                    entry, out, idx, arr if single else arr[i], planes,
                    view.names, view, has_scores=entry.prev_has_scores,
                )
                chunk_results[slot] = merged
                chunk_changed[slot] = idx_rows
        for group, sel, rep, cnt, sco, rsn in full_np:
            single = len(group) == 1
            for i, (slot, entry, out, n) in enumerate(group):
                results = self._apply_full(
                    entry, out,
                    sel if single else sel[i],
                    rep if single else rep[i],
                    cnt if single else cnt[i],
                    (sco if single else sco[i]) if sco is not None else None,
                    n, view.names, want_scores, view,
                    reasons=(rsn if single else rsn[i]) if rsn is not None else None,
                )
                chunk_results[slot] = results
                chunk_changed[slot] = None
        timings["decode"] += time.perf_counter() - t0

    def _drain_window_packed(
        self, delta_items, full_items, chunk_results, chunk_changed, view,
        want_scores: bool, timings,
    ) -> None:
        """Packed-format window drain: every chunk's changed rows (or
        whole output set) ship as top-k-compacted wire rows — one
        stacked transfer per wire shape — followed by ONE batched dense
        re-fetch per plane-group for the rare K-overflow rows.  All
        device programs are enqueued before the first blocking read, so
        transfers overlap device execution across the window."""
        t0 = time.perf_counter()
        wire_devs: list[tuple] = []  # (kind, item, fetched-row count, dev)
        for slot, entry, out, idx, k in delta_items:
            self.fetch_stats["delta"] += 1
            kp = _pow2_bucket(idx.size, 16, 1 << 30)
            padded_idx = np.zeros(kp, np.int32)
            padded_idx[: idx.size] = idx
            dev = self._pack_program("gather", k)(
                out.selected, out.replicas, out.counted, out.scores,
                out.reasons, padded_idx,
            )
            wire_devs.append(("delta", (slot, entry, out, idx, k), idx.size, dev))
        for slot, entry, out, n, k in full_items:
            dev = self._pack_program("full", k)(
                out.selected, out.replicas, out.counted, out.scores, out.reasons
            )
            wire_devs.append(("full", (slot, entry, out, n, k), n, dev))
        wire_np: list[Optional[np.ndarray]] = [None] * len(wire_devs)
        wgroups: dict[tuple, list[int]] = {}
        for i, (_, _, _, dev) in enumerate(wire_devs):
            wgroups.setdefault(tuple(dev.shape), []).append(i)
        for _, members in wgroups.items():
            if len(members) == 1:
                wire_np[members[0]] = self._read_np(wire_devs[members[0]][3])
            else:
                stacked = self._read_np(
                    self._stack(*[wire_devs[i][3] for i in members])
                )
                for j, i in enumerate(members):
                    wire_np[i] = stacked[j]
        timings["fetch"] += time.perf_counter() - t0

        # K-overflow rows: plan per chunk, then gather + read batched
        # per (scores, shape) group across the whole window.
        t0 = time.perf_counter()
        parsed: list[tuple] = []  # (kind, item, packed, over_pos)
        over_jobs: list[tuple] = []  # (parsed idx, global row idx, with_scores)
        for i, (kind, item, rows, _dev) in enumerate(wire_devs):
            entry = item[1]
            k = item[4]
            packed = unpack_wire(wire_np[i][:rows], k)
            self._observe_nsel(entry, packed.nsel, item[2].selected.shape[1])
            over_pos = np.nonzero(np.asarray(packed.nsel) > k)[0]
            parsed.append((kind, item, packed, over_pos))
            if over_pos.size:
                if kind == "delta":
                    gidx = item[3][over_pos]
                    need_scores = bool(entry.prev_has_scores)
                else:
                    gidx = over_pos
                    need_scores = want_scores
                over_jobs.append((i, np.asarray(gidx, np.int64), need_scores))
        over_res: dict[int, tuple] = {}  # parsed idx -> (rows, c_pad, scores)
        ogroups: dict[tuple, list] = {}
        for pi, gidx, need_scores in over_jobs:
            c_pad = parsed[pi][1][2].selected.shape[1]
            ogroups.setdefault((need_scores, c_pad), []).append((pi, gidx))
        for (need_scores, c_pad), group in ogroups.items():
            kmax = max(_pow2_bucket(g[1].size, 16, 1 << 30) for g in group)
            devs = []
            for pi, gidx in group:
                pad = np.zeros(kmax, np.int32)
                pad[: gidx.size] = gidx
                out = parsed[pi][1][2]
                if need_scores:
                    devs.append(
                        self._gather_over4(
                            out.selected, out.counted, out.replicas,
                            out.scores, pad,
                        )
                    )
                else:
                    devs.append(
                        self._gather_over3(
                            out.selected, out.counted, out.replicas, pad
                        )
                    )
            arr = self._read_np(devs[0] if len(devs) == 1 else self._stack(*devs))
            for gi, (pi, gidx) in enumerate(group):
                over_res[pi] = (
                    arr if len(devs) == 1 else arr[gi], c_pad, need_scores,
                )
        if over_jobs:
            timings["overflow_fetch"] = (
                timings.get("overflow_fetch", 0.0) + time.perf_counter() - t0
            )
        timings["fetch"] += time.perf_counter() - t0

        t0 = time.perf_counter()
        for i, (kind, item, packed, over_pos) in enumerate(parsed):
            if kind == "delta":
                slot, entry, out, idx, k = item
                merged, idx_rows = self._apply_packed_delta(
                    entry, out, idx, packed, over_pos, over_res.get(i), view
                )
                chunk_results[slot] = merged
                chunk_changed[slot] = idx_rows
            else:
                slot, entry, out, n, k = item
                results = self._apply_packed_full(
                    entry, out, packed, over_pos, over_res.get(i), n, view,
                    want_scores,
                )
                chunk_results[slot] = results
                chunk_changed[slot] = None
        timings["decode"] += time.perf_counter() - t0

    # -- per-chunk fetch semantics (shared by the sequential path and the
    # -- batched window drain) --------------------------------------------
    def _plan_delta(self, entry, mask: np.ndarray, n: int):
        """('skip'|'delta'|'full', idx) from one chunk's host-side diff
        mask: bit 0 flags placement changes, bit 1 score-only changes
        (consulted only when the cached decode carries scores), rows
        patched by a sub-batch tick are force-fetched, and mass changes
        fall back to a full refetch."""
        relevant = mask & _DIFF_PLACEMENT
        if entry.prev_has_scores:
            relevant = relevant | (mask & _DIFF_SCORES)
        if entry.stale_out_rows:
            # prev_out rows patched by a sub-batch tick: the device diff
            # compares against pre-patch outputs there, so force-fetch
            # them regardless of what the mask says.
            stale = np.asarray(
                [r for r in entry.stale_out_rows if r < n], np.int64
            )
            if stale.size:
                relevant[stale] |= _DIFF_PLACEMENT
        idx = np.nonzero(relevant)[0]
        if idx.size > max(16, n // 4):
            return "full", None
        if idx.size == 0:
            return "skip", None
        return "delta", idx

    def _note_skip(self, entry, out, view) -> None:
        self.fetch_stats["skip"] += 1
        self._store_prev(entry, out)
        entry.prev_view = view

    def _record_decisions(
        self, entry, rows, results_rows, reasons_rows, scores_rows, view,
        program: str,
    ) -> None:
        """Feed the flight recorder from already-fetched host arrays —
        zero extra device->host traffic.  ``rows`` are LOCAL chunk row
        indices; entry.units maps them to object keys.  No-op without a
        recorder or a cache entry (webhook/nocache ticks carry no unit
        list)."""
        rec = self._tick_rec
        if rec is None or entry is None or reasons_rows is None:
            return
        units = entry.units
        rec.record_rows(
            [units[r].key for r in rows],
            [res.clusters for res in results_rows],
            reasons_rows,
            scores_rows,
            view.names,
            program=program,
        )

    def _apply_delta(
        self, entry, out, idx, packed: np.ndarray, planes: int, names, view,
        has_scores: bool,
    ):
        """Decode the gathered rows, merge into the cached decode, and
        record the fresh outputs; returns (merged, changed-rows).
        ``planes`` is the packed layout width (3 = sel/rep/cnt, 4 =
        +scores, 5 = +scores+reasons for the flight recorder);
        ``has_scores`` says whether the cached decode carries score
        dicts (scores may be fetched for the recorder alone)."""
        packed = packed[: idx.size]
        c_pad = packed.shape[1] // planes
        sco = packed[:, 3 * c_pad : 4 * c_pad] if planes >= 4 else None
        rsn = packed[:, 4 * c_pad : 5 * c_pad] if planes >= 5 else None
        changed_results = self._decode_rows(
            packed[:, :c_pad],
            packed[:, c_pad : 2 * c_pad],
            packed[:, 2 * c_pad : 3 * c_pad],
            names,
            scores=sco if has_scores else None,
        )
        idx_rows = idx.tolist()
        merged = list(entry.prev_results)
        for row, res in zip(idx_rows, changed_results):
            merged[row] = res
        self._record_decisions(
            entry, idx_rows, changed_results, rsn, sco, view,
            program=f"{entry.fmt}:{out.selected.shape[0]}x{out.selected.shape[1]}",
        )
        self._store_prev(entry, out)
        entry.prev_results = merged
        entry.prev_view = view
        return merged, idx_rows

    def _apply_full(
        self, entry, out, selected, replicas, counted, scores, n: int,
        names, want_scores: bool, view, reasons=None,
    ) -> list[ScheduleResult]:
        self.fetch_stats["full"] += 1
        results = self._decode_rows(
            selected[:n], replicas[:n], counted[:n], names,
            scores[:n] if (scores is not None and want_scores) else None,
        )
        self._record_decisions(
            entry, range(n), results,
            reasons[:n] if reasons is not None else None,
            scores[:n] if scores is not None else None,
            view,
            program=(
                f"{entry.fmt}:{out.selected.shape[0]}x{out.selected.shape[1]}"
                if entry is not None
                else ""
            ),
        )
        if entry is not None:
            # ALWAYS store the fresh outputs (including on want_scores
            # ticks): a tick that patched cached rows but skipped this
            # store would leave prev_results describing pre-patch
            # inputs, and the next tick's no-op shortcut would replay
            # stale placements (ADVICE r2).  The caller shares the
            # stored list's rows — frozen results make that safe.
            self._store_prev(entry, out)
            entry.prev_results = results
            entry.prev_has_scores = want_scores
            entry.prev_view = view
        return results

    # -- packed-format fetch helpers --------------------------------------
    @staticmethod
    def _split_overflow(arr: np.ndarray, c_pad: int, with_scores: bool):
        """Split one overflow-gather read back into plane views:
        (selected, replicas, counted, scores-or-None).  Layout:
        [sel bits | cnt bits | rep | sco?] with ceil(C/32)-word masks."""
        nw = -(-c_pad // 32)
        sel = _unpack_bits(arr[:, :nw], c_pad)
        cnt = _unpack_bits(arr[:, nw : 2 * nw], c_pad)
        rep = arr[:, 2 * nw : 2 * nw + c_pad]
        sco = (
            arr[:, 2 * nw + c_pad : 2 * nw + 2 * c_pad] if with_scores else None
        )
        return sel, rep, cnt, sco

    def _fetch_overflow(
        self, out, gidx: np.ndarray, with_scores: bool, timings=None
    ) -> tuple:
        """Re-fetch of K-overflow rows (the packed export's escape
        hatch): bit-packed selection/counted masks + the replica plane
        (+ scores only for want_scores consumers) in one transfer.
        The gather program + wide [n, C] read are the packed format's
        only cluster-width transfers, so their cost is attributed to
        the ``overflow_fetch`` sub-phase (inside the fetch stage) —
        the number the adaptive pack-K hint exists to drive to zero."""
        t0 = time.perf_counter()
        kp = _pow2_bucket(gidx.size, 16, 1 << 30)
        pad = np.zeros(kp, np.int32)
        pad[: gidx.size] = gidx
        if with_scores:
            dev = self._gather_over4(
                out.selected, out.counted, out.replicas, out.scores, pad
            )
        else:
            dev = self._gather_over3(
                out.selected, out.counted, out.replicas, pad
            )
        c_pad = out.selected.shape[1]
        arr = self._read_np(dev)
        if timings is not None:
            timings["overflow_fetch"] = (
                timings.get("overflow_fetch", 0.0) + time.perf_counter() - t0
            )
        return (arr, c_pad, with_scores)

    @staticmethod
    def _packed_record_fields(packed: PackedRows, topk: int):
        """Per-row recorder top-k: the wire slots are already ordered
        (score desc, index asc) over the selected clusters, so the
        first slots ARE the top-k — for overflow rows too (their first
        K slots are the global top-K by score)."""
        idx = np.asarray(packed.idx)[:, :topk]
        sco = np.asarray(packed.sco)[:, :topk]
        # One flat masked gather + split, not a per-row python loop —
        # at drift-recompute row counts the loop was the decode stage's
        # single biggest line.
        valid = idx >= 0
        counts = valid.sum(axis=1)
        splits = np.cumsum(counts)[:-1]
        topk_i = np.split(idx[valid].astype(np.int32), splits)
        topk_s = np.split(sco[valid].astype(np.int64), splits)
        return topk_i, topk_s

    def _record_packed(
        self, entry, rows, results_rows, packed: PackedRows, over_pos,
        over_dense, view, program: str,
    ) -> None:
        """Packed-mode flight-recorder feed: reason summaries and
        feasible counts come off the wire (no reason plane crosses the
        link), so records match the dense path's core fields exactly."""
        rec = self._tick_rec
        if rec is None or entry is None:
            return
        topk_i, topk_s = self._packed_record_fields(packed, rec.topk)
        units = entry.units
        rec.record_rows(
            [units[r].key for r in rows],
            [res.clusters for res in results_rows],
            None, None, view.names, program=program,
            reason_counts=np.asarray(packed.rsum),
            feasible_n=np.asarray(packed.nfeas),
            topk_idx=topk_i, topk_scores=topk_s,
        )

    def _decode_packed_mixed(
        self, packed: PackedRows, over_pos, over_dense, names, with_scores: bool
    ) -> list[ScheduleResult]:
        """Decode a packed fetch: packable rows from the wire slots,
        K-overflow rows from their dense re-fetch planes."""
        results = self._decode_packed_rows(packed, names, scores=with_scores)
        if over_pos is not None and over_pos.size:
            self.overflow_rows_total += int(over_pos.size)
            arr, c_pad, has_sco = over_dense
            sel, rep, cnt, sco = self._split_overflow(
                arr[: over_pos.size], c_pad, has_sco
            )
            over_results = self._decode_rows(
                sel, rep, cnt, names, scores=sco if with_scores else None
            )
            for p, r in zip(over_pos.tolist(), over_results):
                results[p] = r
        return results

    def _apply_packed_delta(
        self, entry, out, idx, packed: PackedRows, over_pos, over_dense, view
    ):
        """Packed analogue of _apply_delta: decode the wire rows, merge
        into the cached decode, feed the recorder, store fresh outputs."""
        results = self._decode_packed_mixed(
            packed, over_pos, over_dense, view.names, entry.prev_has_scores
        )
        idx_rows = idx.tolist()
        merged = list(entry.prev_results)
        for row, res in zip(idx_rows, results):
            merged[row] = res
        self._record_packed(
            entry, idx_rows, results, packed, over_pos, over_dense, view,
            program=f"{entry.fmt}:{out.selected.shape[0]}x{out.selected.shape[1]}",
        )
        self._store_prev(entry, out)
        entry.prev_results = merged
        entry.prev_view = view
        return merged, idx_rows

    def _apply_packed_full(
        self, entry, out, packed: PackedRows, over_pos, over_dense, n: int,
        view, want_scores: bool,
    ) -> list[ScheduleResult]:
        """Packed analogue of _apply_full (whole-chunk refetch)."""
        self.fetch_stats["full"] += 1
        results = self._decode_packed_mixed(
            packed, over_pos, over_dense, view.names, want_scores
        )
        self._record_packed(
            entry, range(n), results, packed, over_pos, over_dense, view,
            program=(
                f"{entry.fmt}:{out.selected.shape[0]}x{out.selected.shape[1]}"
                if entry is not None
                else ""
            ),
        )
        if entry is not None:
            self._store_prev(entry, out)
            entry.prev_results = results
            entry.prev_has_scores = want_scores
            entry.prev_view = view
        return results

    def _fetch_decode_packed(
        self, entry, out, mask_dev, names, n: int, want_scores: bool, timings,
        view, k: int,
    ) -> tuple[list[ScheduleResult], Optional[list[int]]]:
        """Packed-format result fetch for one chunk (the sequential
        drain path): same skip/delta/full semantics as _fetch_decode,
        but what crosses the link is the [*, 4K+2+NR] wire layout plus
        a bit-packed re-fetch of K-overflow rows only."""
        t2 = time.perf_counter()
        if mask_dev is not None:
            kind, idx = self._plan_delta(entry, self._read_np(mask_dev)[:n], n)
            if kind == "skip":
                self._note_skip(entry, out, view)
                timings["fetch"] += time.perf_counter() - t2
                return entry.prev_results, []
            if kind == "delta":
                self.fetch_stats["delta"] += 1
                kp = _pow2_bucket(idx.size, 16, 1 << 30)
                padded_idx = np.zeros(kp, np.int32)
                padded_idx[: idx.size] = idx
                wire = self._read_np(
                    self._pack_program("gather", k)(
                        out.selected, out.replicas, out.counted, out.scores,
                        out.reasons, padded_idx,
                    )
                )
                packed = unpack_wire(wire[: idx.size], k)
                self._observe_nsel(entry, packed.nsel, out.selected.shape[1])
                over_pos = np.nonzero(np.asarray(packed.nsel) > k)[0]
                over_dense = None
                if over_pos.size:
                    over_dense = self._fetch_overflow(
                        out, idx[over_pos], entry.prev_has_scores, timings
                    )
                t3 = time.perf_counter()
                timings["fetch"] += t3 - t2
                merged, idx_rows = self._apply_packed_delta(
                    entry, out, idx, packed, over_pos, over_dense, view
                )
                timings["decode"] += time.perf_counter() - t3
                return merged, idx_rows
            # fall through to a full packed fetch for mass changes
        wire = self._read_np(
            self._pack_program("full", k)(
                out.selected, out.replicas, out.counted, out.scores, out.reasons
            )
        )
        packed = unpack_wire(wire[:n], k)
        self._observe_nsel(entry, packed.nsel, out.selected.shape[1])
        over_pos = np.nonzero(np.asarray(packed.nsel) > k)[0]
        over_dense = None
        if over_pos.size:
            over_dense = self._fetch_overflow(
                out, over_pos.astype(np.int64), want_scores, timings
            )
        t3 = time.perf_counter()
        timings["fetch"] += t3 - t2
        results = self._apply_packed_full(
            entry, out, packed, over_pos, over_dense, n, view, want_scores
        )
        timings["decode"] += time.perf_counter() - t3
        return results, None

    def _fetch_decode(
        self, entry, out, mask_dev, names, n: int, want_scores: bool, timings,
        view, pack_k: Optional[int] = None,
    ) -> tuple[list[ScheduleResult], Optional[list[int]]]:
        """Returns (results, changed-local-rows or None for all).

        Pull results off the device — as a delta against the previous
        tick when possible: the on-device row diff (i8[B] mask computed
        inside the tick dispatch, a few KB to fetch) decides which rows
        to gather, so a steady-state tick transfers near-nothing
        (VERDICT r1 #6; the device-side analogue of the reference's
        trigger-hash skip).  Score planes ride the same delta: bit 1 of
        the mask flags score-only changes, consulted only when the
        cached decodes carry scores."""
        if self.fetch_format == "packed" and pack_k is not None:
            return self._fetch_decode_packed(
                entry, out, mask_dev, names, n, want_scores, timings, view,
                pack_k,
            )
        t2 = time.perf_counter()
        if mask_dev is not None:
            kind, idx = self._plan_delta(entry, self._read_np(mask_dev)[:n], n)
            if kind == "skip":
                self._note_skip(entry, out, view)
                timings["fetch"] += time.perf_counter() - t2
                return entry.prev_results, []
            if kind == "delta":
                self.fetch_stats["delta"] += 1
                k = _pow2_bucket(idx.size, 16, 1 << 30)
                padded_idx = np.zeros(k, np.int32)
                padded_idx[: idx.size] = idx
                if self._tick_rec is not None and entry is not None:
                    packed_dev = self._gather5(
                        out.selected, out.replicas, out.counted,
                        out.scores, out.reasons, padded_idx,
                    )
                    planes = 5
                elif entry.prev_has_scores:
                    packed_dev = self._gather(
                        out.selected, out.replicas, out.counted,
                        out.scores, padded_idx,
                    )
                    planes = 4
                else:
                    packed_dev = self._gather3(
                        out.selected, out.replicas, out.counted, padded_idx
                    )
                    planes = 3
                packed = self._read_np(packed_dev)
                t3 = time.perf_counter()
                timings["fetch"] += t3 - t2
                merged, idx_rows = self._apply_delta(
                    entry, out, idx, packed, planes, names, view,
                    has_scores=entry.prev_has_scores,
                )
                timings["decode"] += time.perf_counter() - t3
                return merged, idx_rows
            # fall through to a full fetch for mass changes

        record = self._tick_rec is not None and entry is not None
        selected = self._read_np(out.selected)
        replicas = self._read_np(out.replicas)
        counted = self._read_np(out.counted)
        scores = self._read_np(out.scores) if (want_scores or record) else None
        reasons = self._read_np(out.reasons) if record else None
        t3 = time.perf_counter()
        timings["fetch"] += t3 - t2
        results = self._apply_full(
            entry, out, selected, replicas, counted, scores, n, names,
            want_scores, view, reasons=reasons,
        )
        timings["decode"] += time.perf_counter() - t3
        return results, None

    # -- compile pre-warming ----------------------------------------------
    def _prewarm_ladder(
        self, n_objects, n_clusters, scalar_resources, key_len,
        policy_entries, webhooks,
    ) -> None:
        """The prewarm ladder body (see prewarm()): builds a
        representative world at the workload's program-shape drivers
        and exercises every program a live tick can dispatch.  Runs
        under the AOT store's export mode, so each traced program is
        also serialized into the warm-boot manifest."""
        gvk = "apps/v1/Deployment"
        alloc = {"cpu": "8", "memory": "16Gi"}
        avail = {"cpu": "4", "memory": "8Gi"}
        request = {"cpu": "100m"}
        for r in scalar_resources:
            alloc[r] = "8"
            avail[r] = "4"
            request[r] = "1"
        clusters = [
            T.ClusterState(
                name=f"warm-{j}",
                labels={},
                taints=(),
                allocatable=T.parse_resources(alloc),
                available=T.parse_resources(avail),
                api_resources=frozenset({gvk}),
            )
            for j in range(max(1, n_clusters))
        ]
        # The warm unit reproduces the workload's program-shape
        # drivers: a key padded to key_len (-> L bucket) and
        # policy entries over policy_entries clusters (-> P
        # bucket).
        name = "prewarm".ljust(max(1, key_len - len("prewarm/")), "x")
        unit = T.SchedulingUnit(
            gvk=gvk,
            namespace="prewarm",
            name=name,
            scheduling_mode=T.MODE_DIVIDE,
            desired_replicas=1,
            resource_request=T.parse_resources(request),
            min_replicas={
                f"warm-{j}": 0
                for j in range(
                    min(max(1, policy_entries), len(clusters))
                )
            },
        )
        from kubeadmiral_tpu.scheduler.featurize import (
            _build_cluster_view,
        )

        view = _build_cluster_view(clusters, [unit])
        vocab = CompactVocab(view, **self._vocab_caps)
        ci = featurize_compact([unit], view, vocab)
        c_bucket, eff_chunk, ladder = self._tick_geometry(len(clusters))
        if ladder is None:
            shapes = [
                self._bucket_rows(
                    min(max(1, n_objects), eff_chunk), None, eff_chunk, False
                )
            ]
        else:
            # All rungs: full chunks use the top, sub-batches the
            # lower ones.
            shapes = ladder
        outs: dict[int, object] = {}
        for b_pad in shapes:
            # The compact program is the production path; the
            # dense variant serves webhook ticks (warmed only
            # when the deployment has webhook plugins).
            padded = self._pad_for_dispatch(ci, "compact", b_pad, c_bucket)
            padded = padded._replace(
                **Cmp.pad_tables(vocab.tables(), c_bucket)
            )
            shape = (b_pad, c_bucket)
            out, mask = self._tick_compact(padded, self._zeros_for(shape))
            jax.block_until_ready(mask)
            # Narrow solve: at this geometry the narrow program
            # (not the dense tick above) is the production
            # dispatch — warm it plus its certificate machinery
            # (dense row re-solve + in-place plane repair), so a
            # first-tick fallback never stalls on a trace.
            narrow_m = self._narrow_m(ci, c_bucket)
            if narrow_m is not None:
                out_n, _mask_n, cert_n = self._narrow_program(
                    "compact", narrow_m
                )(padded, self._zeros_for(shape))
                jax.block_until_ready(cert_n)
                fb_idx = np.full(16, b_pad, np.int32)
                fb = self._fallback_program("compact")(padded, fb_idx)
                repaired = self._cert_repair_program()(
                    (out_n.selected, out_n.replicas, out_n.counted,
                     out_n.reasons),
                    fb, fb_idx,
                )
                jax.block_until_ready(repaired[0])
            if webhooks:
                dense = featurize([unit], clusters, view=view).inputs
                dense_padded = self._pad_for_dispatch(
                    dense, "dense", b_pad, c_bucket
                )
                out_d, mask_d = self._tick(
                    dense_padded, self._zeros_for(shape)
                )
                jax.block_until_ready(mask_d)
                if narrow_m is not None:
                    _o, _m, cert_nd = self._narrow_program(
                        "dense", narrow_m
                    )(dense_padded, self._zeros_for(shape))
                    jax.block_until_ready(cert_nd)
            idx = np.zeros(16, np.int32)
            jax.block_until_ready(
                self._gather(
                    out.selected, out.replicas, out.counted, out.scores, idx
                )
            )
            jax.block_until_ready(
                self._gather3(out.selected, out.replicas, out.counted, idx)
            )
            jax.block_until_ready(
                self._gather5(
                    out.selected, out.replicas, out.counted,
                    out.scores, out.reasons, idx,
                )
            )
            if self.fetch_format == "packed":
                pk = self._pack_k(ci, c_bucket)
                jax.block_until_ready(
                    self._pack_program("full", pk)(
                        out.selected, out.replicas, out.counted,
                        out.scores, out.reasons,
                    )
                )
                jax.block_until_ready(
                    self._pack_program("gather", pk)(
                        out.selected, out.replicas, out.counted,
                        out.scores, out.reasons, idx,
                    )
                )
                jax.block_until_ready(
                    self._gather_over3(
                        out.selected, out.counted, out.replicas, idx
                    )
                )
            # Drift-gate + weight-check programs: tiny traces,
            # but warming them keeps the FIRST capacity-drift
            # tick off the compile path too.
            per_object = {
                name: np.asarray(getattr(padded, name))
                for name in Cmp.PER_OBJECT_FIELDS
            }
            # Delta-axis shapes a live drift can produce: 1 (the
            # dominant single-member capacity drift — exact-size, no
            # 8-slot padding waste in the gate/resolve D loops) and the
            # 8-slot pow2 floor for multi-column drifts.
            delta_shapes = {}
            for nb in (1, 8):
                delta_shapes[nb] = (
                    np.full(nb, 1 << 30, np.int32),
                    np.zeros(nb, bool),
                    np.zeros(
                        (nb,) + np.asarray(padded.alloc).shape[1:],
                        np.asarray(padded.alloc).dtype,
                    ),
                )
            didx8, dflag8, slice8 = delta_shapes[8]
            # Both rungs of the gate's fin-row ladder (see
            # _fin_rows), at both delta shapes: a drift tick must
            # never stall on a gate compile, whatever the finite-K
            # row fraction or changed-column count.
            # The cached-nfeas reduce (prev-plane store sites).
            jax.block_until_ready(
                self._nfeas_program()(np.zeros(shape, np.int8))
            )
            for fin_n in sorted({max(64, b_pad // 4), b_pad}):
                fin_pad = np.full(fin_n, 1 << 30, np.int32)
                for nb in (1, 8):
                    didx, dflag, dslice = delta_shapes[nb]
                    jax.block_until_ready(
                        self._gate_program("compact")(
                            per_object,
                            Cmp.pad_tables(vocab.tables(), c_bucket),
                            np.zeros(shape, np.int8),
                            np.zeros(shape, np.int32),
                            dslice, dslice, dslice, dslice,
                            didx, dflag, dflag, fin_pad,
                            np.zeros(b_pad, np.int32),
                        )
                    )
            # The 128-row input-patch group (stale-row repair):
            # every churn/drift scatter-repair uses exactly this
            # shape (see _repair_stale_inputs).
            idx0 = np.zeros(128, np.int64)
            jax.block_until_ready(
                self._patch_compact(
                    per_object,
                    {
                        name: np.ascontiguousarray(
                            np.asarray(per_object[name])[idx0]
                        )
                        for name in Cmp.PER_OBJECT_FIELDS
                    },
                    np.full(128, b_pad, np.int32),
                )["total"]
            )
            # The precomputed tie-break plane (full build + 128-row
            # patch groups): survivor kernels consume it, uploads
            # build it, churn repairs it — all prewarm-known.
            tb_warm = self._tb_program("full")(
                per_object["key_bytes"], per_object["key_len"],
                np.asarray(padded.name_hash_state),
            )
            # The patch warm DONATES its plane argument — thread the
            # returned (repaired-in-place) plane forward so the
            # survivor-kernel warms below don't touch a dead buffer.
            tb_warm = self._tb_program("patch")(
                tb_warm,
                np.ascontiguousarray(per_object["key_bytes"][:1].repeat(128, 0)),
                np.zeros(128, np.int32),
                np.asarray(padded.name_hash_state),
                np.full(128, b_pad, np.int32),
            )
            jax.block_until_ready(tb_warm)
            if narrow_m is not None and self.drift_resolve:
                # The sort-free drift resolve (+ its wire pack)
                # is the FIRST capacity-drift tick's survivor
                # path — warm its row-bucket ladder at both delta
                # shapes so live drifts never stall on its trace.
                device_in_warm = padded._replace(
                    **Cmp.pad_tables(vocab.tables(), c_bucket)
                )
                # The live resolve wire packs at K = narrow M
                # (see _dispatch_drift_resolve) — warm exactly
                # that program.
                pk = (
                    min(narrow_m, c_bucket)
                    if self.fetch_format == "packed"
                    else 0
                )
                for kb in (64, 128, 256):
                    ridx = np.full(kb, b_pad, np.int32)
                    for nb in (1, 8):
                        didx, dflag, dslice = delta_shapes[nb]
                        r_out, r_cert, r_wire = self._resolve_program(
                            "compact", narrow_m
                        )(
                            device_in_warm, ridx,
                            np.zeros(shape, np.int8),
                            np.zeros(shape, np.int32),
                            np.zeros(shape, np.int32),
                            dslice, dslice, dslice, dslice,
                            didx, dflag, tb_warm,
                        )
                        jax.block_until_ready(r_wire)
            if narrow_m is not None and self.replan:
                # Fit-flip survivor solves (selection-known replan +
                # score-only narrow) run in fixed 256-row groups — one
                # shape each per (format, M) — plus their wire pack.
                device_in_warm = padded._replace(
                    **Cmp.pad_tables(vocab.tables(), c_bucket)
                )
                for scored in (False, True):
                    for g in (64, 128, 256):
                        gidx = np.full(g, b_pad, np.int32)
                        rp_out, rp_cert, rp_wire = self._replan_program(
                            "compact", narrow_m, scored
                        )(
                            device_in_warm, gidx,
                            np.zeros(shape, np.int32),
                            np.zeros(shape, np.int32), tb_warm,
                        )
                        jax.block_until_ready(rp_wire)
            if narrow_m is not None and self.survivor_unified:
                # The UNIFIED survivor kernel (the production drift
                # survivor path): its greedy {256,128,64} groups plus
                # the fused wire pack, so a live drift's single
                # survivor stream never stalls on a trace.
                device_in_warm = padded._replace(
                    **Cmp.pad_tables(vocab.tables(), c_bucket)
                )
                for g in (64, 128, 256):
                    gidx = np.full(g, b_pad, np.int32)
                    sv_out, sv_cert, sv_wire = self._survivor_program(
                        "compact", narrow_m
                    )(
                        device_in_warm, gidx,
                        np.zeros(shape, np.int32), tb_warm,
                    )
                    jax.block_until_ready(sv_wire)
            # Weight-check groups in both arithmetic widths — the i32
            # demotion is view-dependent, so a live drift may dispatch
            # either.
            for wn in (64, 128, 256):
                for w_i32 in (False, True):
                    jax.block_until_ready(
                        self._wcheck_program(w_i32)(
                            np.zeros(shape, np.int8),
                            np.zeros(wn, np.int32),
                            np.asarray(padded.cpu_alloc),
                            np.asarray(padded.cpu_avail),
                            np.asarray(padded.cpu_alloc),
                            np.asarray(padded.cpu_avail),
                        )
                    )
            outs[b_pad] = out
            log.info("prewarmed tick program %s", shape)
        # Sub-batch write-back repair: full-chunk planes get
        # slab rows scattered in — warm each (chunk, slab-rung)
        # shape pair so steady-state churn ticks never stall on
        # the scatter trace.  Planes are DONATED by the repair,
        # so the chain starts from freshly built zeros (never
        # from the slab outputs, which must stay alive as the
        # non-donated inputs) and threads each call's results.
        big = max(shapes)
        pshape = (big, c_bucket)
        # ktlint: ignore[aot-ledger-coverage] prewarm-only transient: runs once to seed the repair chain, is never dispatched by a tick (no ledger kind), and exporting a zeros builder per shape would bloat the AOT manifest for a program a warm boot never calls
        all_planes = jax.jit(
            lambda: (
                jnp.zeros(pshape, jnp.int8),
                jnp.zeros(pshape, jnp.int32),
                jnp.zeros(pshape, jnp.int8),
                jnp.zeros(pshape, jnp.int32),
                jnp.zeros(pshape, jnp.int8),
                jnp.zeros(pshape, jnp.int32),
                jnp.zeros(big, jnp.int32),  # cached nfeas vector
            )
        )()
        planes, nfeas = all_planes[:6], all_planes[6]
        src128 = np.zeros(128, np.int32)
        dst128 = np.full(128, big, np.int32)  # out of range: no-op
        for b_pad in shapes:
            slab = outs[b_pad]
            out7 = self._repair_program()(
                planes,
                (slab.selected, slab.replicas, slab.counted,
                 slab.scores, slab.feasible, slab.reasons),
                src128, dst128, nfeas,
            )
            planes, nfeas = out7[:6], out7[6]
            jax.block_until_ready(planes[0])

    def prewarm(
        self,
        n_objects: int,
        n_clusters: int,
        scalar_resources: Sequence[str] = (),
        key_len: int = 32,
        policy_entries: int = 1,
        webhooks: bool = False,
        wait: bool = False,
    ) -> threading.Thread:
        """Compile the tick/gather programs a (n_objects x n_clusters)
        workload will need, in a background thread — call at manager
        start (or ahead of an expected topology change) so the first
        real tick doesn't stall on XLA.  Compiles land in both the
        in-process jit cache and the persistent compilation cache
        (kubeadmiral_tpu.__init__ enables it), so later processes on the
        same libtpu can skip the compile entirely.

        Pass ``scalar_resources`` (e.g. ["nvidia.com/gpu"]) when the
        workload requests extended resources: the request tensor's R
        axis is part of the program shape, so a prewarm without them
        warms a different program than the real tick uses.  Likewise
        ``key_len`` (longest object key) and ``policy_entries`` (widest
        per-object policy/current cluster union) pick the compact
        format's key-byte and sparse-width buckets, and ``webhooks=True``
        additionally warms the dense program that webhook ticks use."""

        # The manifest records which prewarm worlds its export ladder
        # ran at; a matching warm boot replaces the ladder wholesale.
        world_key = repr((
            "prewarm-world", n_objects, n_clusters,
            tuple(scalar_resources), key_len, policy_entries, webhooks,
        ))

        def run():
            try:
                if self._aot.has_world(world_key):
                    # Warm boot: the AOT manifest was exported by a
                    # ladder at THIS world, so ahead-of-time compile
                    # every entry from its serialized avals — no Python
                    # trace, no example execution, XLA compiles served
                    # by the persistent cache — and skip the ladder.
                    # Entries that fail their guard fall back to live
                    # traces at first use (counted `rejected`).
                    n = self._aot.preload_all()
                    log.info(
                        "prewarm: AOT manifest preloaded %d programs; "
                        "trace ladder skipped", n,
                    )
                    if n:
                        return
                # Export mode: every program this ladder traces is
                # ALSO exported via jax.export into the AOT manifest
                # (scheduler/aot.py) — the next process deserializes
                # instead of tracing (engine_aot_programs_total).
                with self._aot.export_mode():
                    self._aot.note_world(world_key)
                    self._prewarm_ladder(
                        n_objects, n_clusters, scalar_resources,
                        key_len, policy_entries, webhooks,
                    )
            except Exception:
                log.warning("engine prewarm failed", exc_info=True)

        thread = threading.Thread(target=run, daemon=True, name="engine-prewarm")
        thread.start()
        self._prewarm_thread = thread
        if wait:
            thread.join()
        return thread
