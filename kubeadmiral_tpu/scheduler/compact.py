"""Compact featurization: O(B + C + vocab) host work and transfer.

The dense featurizer (featurize.py) dedups the string-matching world
into small vocabulary tables, then gathers them into [B, C] planes ON
THE HOST — at 100k objects x 5k clusters those planes are ~320 KB/row
(tens of GB), which no host cache, PCIe link or HBM wants.  This module
keeps the same dedup but ships only:

* per-object id vectors ([B] int32 into each vocabulary),
* the vocabulary tables themselves ([vocab_cap, C] — a few MB), and
* per-object SPARSE policy entries ([B, P] cluster-index/value pairs
  for min/max/weight/capacity/current, P = widest union in the chunk),

and performs the gather/scatter into [B, C] planes ON DEVICE inside the
fused tick (ops.pipeline.expand_compact), where HBM bandwidth is free
compared to the host link.  The planner tie-break hash — the one
inherently per-(object, cluster) input — is computed on device too, by
continuing each cluster-name FNV-1 state over the object key's bytes
(utils/hashing.fnv32_extend semantics, bit-exact).

Result: ~350 bytes/row crossing the link instead of ~320 KB/row, which
is what makes the 100k x 5k north-star config physically possible.

Vocabularies are capped (caps are engine constants so vocab sizes never
leak into XLA program shapes); a workload exceeding a cap raises
:class:`VocabOverflow` and the engine falls back to the dense path for
that chunk — correctness never depends on the caps.

Reference parity: the table rows are built by the same host matching
code the dense featurizer uses, so compact == dense == the Go oracle
(reference: pkg/controllers/scheduler/framework/runtime/framework.go
plugin loops) is enforced by differential tests.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import numpy as np

from kubeadmiral_tpu.models import types as T
from kubeadmiral_tpu.ops import filters as OF
from kubeadmiral_tpu.ops import scores as OS
from kubeadmiral_tpu.ops.planner import INT32_INF, validate_ranges
from kubeadmiral_tpu.scheduler.featurize import (
    ClusterView,
    _FILTER_INDEX,
    _SCORE_INDEX,
)
from kubeadmiral_tpu.utils import labels as L

# Sparse-entry "no cluster" sentinel: must stay out of range after ANY
# cluster-axis padding (scatter mode='drop' then ignores the entry).
EMPTY_SLOT = np.int32(1 << 30)
# sparse_cur markers.
CUR_ABSENT = np.int32(-2)
CUR_NIL = np.int32(-1)


# Cap keyword names CompactVocab.__init__ accepts (the engine validates
# its vocab_caps override against this — keep next to the constructor).
CAP_NAMES = frozenset(
    {"gvk_cap", "tol_cap", "taint_cap", "sel_cap", "pref_cap", "place_cap"}
)


class VocabOverflow(Exception):
    """A vocabulary exceeded its cap — use the dense path for this chunk."""


class CompactInputs(NamedTuple):
    """One chunk's scheduling problem in compact form.

    Three groups of fields (see the module-level constants): per-object
    rows, shared vocabulary tables, and fast-drifting cluster tensors.
    """

    # --- per-object rows [B, ...] ---
    gvk_id: object          # i32[B]
    tol_id: object          # i32[B]
    sel_id: object          # i32[B]
    pref_id: object         # i32[B]
    place_id: object        # i32[B]
    placement_has: object   # bool[B]
    filter_enabled: object  # bool[B, 5]
    score_enabled: object   # bool[B, 5]
    request: object         # i64[B, R]
    max_clusters: object    # i32[B]
    mode_divide: object     # bool[B]
    sticky: object          # bool[B]
    total: object           # i32[B]
    weights_given: object   # bool[B]
    keep_unschedulable: object  # bool[B]
    avoid_disruption: object    # bool[B]
    sparse_idx: object      # i32[B, P]; EMPTY_SLOT = unused entry
    sparse_min: object      # i32[B, P]
    sparse_max: object      # i32[B, P]
    sparse_weight: object   # i32[B, P]
    sparse_capacity: object  # i32[B, P]
    sparse_cur: object      # i32[B, P]; CUR_ABSENT / CUR_NIL / count
    key_bytes: object       # u8[B, L]
    key_len: object         # i32[B]
    # --- vocabulary tables (shared; re-uploaded on vocab growth) ---
    api_matrix: object      # bool[G_cap, C]
    taint_new: object       # bool[K_cap, T_cap]
    taint_cur: object       # bool[K_cap, T_cap]
    taint_prefer: object    # i32[K_cap, T_cap]
    sel_matrix: object      # bool[S_cap, C]
    pref_matrix: object     # i32[A_cap, C]
    place_matrix: object    # bool[V_cap, C]
    taint_set_id: object    # i32[C]
    name_hash_state: object  # u32[C]
    # --- fast-drifting cluster tensors (fresh from the view each tick) ---
    alloc: object           # i64[C, R]
    used: object            # i64[C, R]
    cpu_alloc: object       # i64[C]
    cpu_avail: object       # i64[C]
    cluster_valid: object   # bool[C]


PER_OBJECT_FIELDS = (
    "gvk_id", "tol_id", "sel_id", "pref_id", "place_id", "placement_has",
    "filter_enabled", "score_enabled", "request", "max_clusters",
    "mode_divide", "sticky", "total", "weights_given",
    "keep_unschedulable", "avoid_disruption",
    "sparse_idx", "sparse_min", "sparse_max", "sparse_weight",
    "sparse_capacity", "sparse_cur", "key_bytes", "key_len",
)
TABLE_FIELDS = (
    "api_matrix", "taint_new", "taint_cur", "taint_prefer",
    "sel_matrix", "pref_matrix", "place_matrix", "taint_set_id",
    "name_hash_state",
)
CLUSTER_FIELDS = ("alloc", "used", "cpu_alloc", "cpu_avail", "cluster_valid")

# Inert-row fills for object-axis padding: max_clusters=0 selects
# nothing, so every other value just has to be in-range.
ROW_FILL = {
    "gvk_id": 0, "tol_id": 0, "sel_id": 0, "pref_id": 0, "place_id": 0,
    "placement_has": False, "filter_enabled": False, "score_enabled": False,
    "request": 0, "max_clusters": 0, "mode_divide": False, "sticky": False,
    "total": 0, "weights_given": True, "keep_unschedulable": False,
    "avoid_disruption": False, "sparse_idx": EMPTY_SLOT, "sparse_min": 0,
    "sparse_max": INT32_INF, "sparse_weight": 0,
    "sparse_capacity": INT32_INF, "sparse_cur": CUR_ABSENT,
    "key_bytes": 0, "key_len": 0,
}
# Cluster-axis pads: cluster_valid=False masks everything downstream;
# table columns/cluster rows just need safe in-range values.
CLUSTER_AXIS_FILL = {
    "api_matrix": False, "sel_matrix": False, "pref_matrix": 0,
    "place_matrix": False, "taint_set_id": 0, "name_hash_state": 0,
    "alloc": 0, "used": 0, "cpu_alloc": 0, "cpu_avail": 0,
    "cluster_valid": False,
}


_VOCAB_UIDS = iter(range(1, 1 << 62))


class CompactVocab:
    """Engine-held vocabularies + tables for ONE cluster topology.

    Tables grow in place (rows are append-only, ids never change), so
    cached CompactInputs referencing these arrays stay valid as the
    vocabulary grows; ``version`` bumps on growth so device copies know
    to re-upload.  ``uid`` identifies this vocabulary INSTANCE — ids
    issued by one instance are meaningless against another's tables, so
    cache entries record the uid they were built against.  Caps bound
    table memory and keep vocabulary sizes out of XLA program shapes."""

    def __init__(
        self,
        view: ClusterView,
        gvk_cap: int = 32,
        tol_cap: int = 64,
        taint_cap: int = 64,
        sel_cap: int = 256,
        pref_cap: int = 256,
        place_cap: int = 256,
    ):
        self.view = view
        c = len(view.clusters)
        if len(view.taint_sets) > taint_cap:
            raise VocabOverflow(f"{len(view.taint_sets)} taint sets > {taint_cap}")
        self.uid = next(_VOCAB_UIDS)
        self.version = 0
        self.gvk_ids: dict[str, int] = {}
        self.tol_ids: dict[tuple, int] = {}
        self.sel_ids: dict[tuple, int] = {}
        self.pref_ids: dict[tuple, int] = {}
        self.place_ids: dict[tuple, int] = {}
        self.gvk_cap, self.tol_cap = gvk_cap, tol_cap
        self.sel_cap, self.pref_cap, self.place_cap = sel_cap, pref_cap, place_cap
        self.api_matrix = np.zeros((gvk_cap, c), bool)
        self.taint_new = np.ones((tol_cap, taint_cap), bool)
        self.taint_cur = np.ones((tol_cap, taint_cap), bool)
        self.taint_prefer = np.zeros((tol_cap, taint_cap), np.int32)
        self.sel_matrix = np.zeros((sel_cap, c), bool)
        self.pref_matrix = np.zeros((pref_cap, c), np.int32)
        self.place_matrix = np.zeros((place_cap, c), bool)
        self.taint_set_id = view.taint_id.astype(np.int32)
        self.name_hash_state = view.name_hash_state

    # -- row builders (the same matching code the dense path runs) -------
    def gvk(self, gvk: str) -> int:
        i = self.gvk_ids.get(gvk)
        if i is not None:
            return i
        if len(self.gvk_ids) >= self.gvk_cap:
            raise VocabOverflow(f"gvk vocab > {self.gvk_cap}")
        i = len(self.gvk_ids)
        self.gvk_ids[gvk] = i
        for ci, cl in enumerate(self.view.clusters):
            self.api_matrix[i, ci] = gvk in cl.api_resources
        self.version += 1
        return i

    def tolerations(self, tols: tuple) -> int:
        i = self.tol_ids.get(tols)
        if i is not None:
            return i
        if len(self.tol_ids) >= self.tol_cap:
            raise VocabOverflow(f"toleration vocab > {self.tol_cap}")
        i = len(self.tol_ids)
        self.tol_ids[tols] = i
        prefer_tols = [
            t for t in tols if not t.effect or t.effect == T.PREFER_NO_SCHEDULE
        ]
        for si, taints in enumerate(self.view.taint_sets):
            for taint in taints:
                tolerated = any(t.tolerates(taint) for t in tols)
                if not tolerated:
                    if taint.effect in (T.NO_SCHEDULE, T.NO_EXECUTE):
                        self.taint_new[i, si] = False
                    if taint.effect == T.NO_EXECUTE:
                        self.taint_cur[i, si] = False
                if taint.effect == T.PREFER_NO_SCHEDULE and not any(
                    t.tolerates(taint) for t in prefer_tols
                ):
                    self.taint_prefer[i, si] += 1
        self.version += 1
        return i

    def selector(self, su: T.SchedulingUnit) -> int:
        aff = su.affinity
        req = aff.required if aff is not None else None
        key = (frozenset(su.cluster_selector.items()), req)
        i = self.sel_ids.get(key)
        if i is not None:
            return i
        if len(self.sel_ids) >= self.sel_cap:
            raise VocabOverflow(f"selector vocab > {self.sel_cap}")
        i = len(self.sel_ids)
        self.sel_ids[key] = i
        memo: dict[tuple, bool] = {}
        uses_fields = req is not None and any(t.match_fields for t in req)
        for ci, cl in enumerate(self.view.clusters):
            mk = (self.view.label_id[ci], cl.name if uses_fields else "")
            if mk not in memo:
                memo[mk] = L.cluster_feasible(
                    cl.labels, cl.name, su.cluster_selector, su.affinity
                )
            self.sel_matrix[i, ci] = memo[mk]
        self.version += 1
        return i

    def preferred(self, su: T.SchedulingUnit) -> int:
        key = su.affinity.preferred if su.affinity is not None else ()
        i = self.pref_ids.get(key)
        if i is not None:
            return i
        if len(self.pref_ids) >= self.pref_cap:
            raise VocabOverflow(f"affinity vocab > {self.pref_cap}")
        i = len(self.pref_ids)
        self.pref_ids[key] = i
        if key:
            memo: dict = {}
            for ci, cl in enumerate(self.view.clusters):
                mk = self.view.label_id[ci]
                if mk not in memo:
                    memo[mk] = L.preferred_score(cl.labels, cl.name, su.affinity)
                self.pref_matrix[i, ci] = memo[mk]
        self.version += 1
        return i

    def placement(self, names: tuple) -> int:
        i = self.place_ids.get(names)
        if i is not None:
            return i
        if len(self.place_ids) >= self.place_cap:
            raise VocabOverflow(f"placement vocab > {self.place_cap}")
        i = len(self.place_ids)
        self.place_ids[names] = i
        wanted = set(names)
        for ci, n in enumerate(self.view.names):
            self.place_matrix[i, ci] = n in wanted
        self.version += 1
        return i

    def tables(self) -> dict:
        return {
            "api_matrix": self.api_matrix,
            "taint_new": self.taint_new,
            "taint_cur": self.taint_cur,
            "taint_prefer": self.taint_prefer,
            "sel_matrix": self.sel_matrix,
            "pref_matrix": self.pref_matrix,
            "place_matrix": self.place_matrix,
            "taint_set_id": self.taint_set_id,
            "name_hash_state": self.name_hash_state,
        }


def featurize_compact(
    units: Sequence[T.SchedulingUnit],
    view: ClusterView,
    vocab: CompactVocab,
    key_len_cap: int = 512,
) -> CompactInputs:
    """Pack a batch against the member clusters in compact form.

    Raises VocabOverflow when a vocabulary cap or the key-length cap is
    exceeded (the caller falls back to the dense featurizer)."""
    units = list(units)
    b = len(units)
    r = view.alloc.shape[1]

    gvk_id = np.zeros(b, np.int32)
    tol_id = np.zeros(b, np.int32)
    sel_id = np.zeros(b, np.int32)
    pref_id = np.zeros(b, np.int32)
    place_id = np.zeros(b, np.int32)
    placement_has = np.zeros(b, bool)
    filter_enabled = np.zeros((b, OF.NUM_FILTER_PLUGINS), bool)
    score_enabled = np.zeros((b, OS.NUM_SCORE_PLUGINS), bool)
    request = np.zeros((b, r), np.int64)
    max_clusters = np.zeros(b, np.int32)
    mode_divide = np.zeros(b, bool)
    sticky = np.zeros(b, bool)
    total = np.zeros(b, np.int32)
    weights_given = np.zeros(b, bool)
    keep = np.zeros(b, bool)
    avoid = np.zeros(b, bool)
    key_len = np.zeros(b, np.int32)

    encoded_keys = []
    sparse_entries: list[dict] = []
    p_max = 1
    for i, su in enumerate(units):
        gvk_id[i] = vocab.gvk(su.gvk)
        tol_id[i] = vocab.tolerations(tuple(su.tolerations))
        sel_id[i] = vocab.selector(su)
        pref_id[i] = vocab.preferred(su)
        place_id[i] = vocab.placement(su.cluster_names)
        placement_has[i] = len(su.cluster_names) > 0
        for name in (
            su.enabled_filters if su.enabled_filters is not None else T.DEFAULT_FILTERS
        ):
            idx = _FILTER_INDEX.get(name)
            if idx is not None:
                filter_enabled[i, idx] = True
        for name in (
            su.enabled_scores if su.enabled_scores is not None else T.DEFAULT_SCORES
        ):
            idx = _SCORE_INDEX.get(name)
            if idx is not None:
                score_enabled[i, idx] = True
        request[i, OF.R_CPU] = su.resource_request.get("cpu", 0)
        request[i, OF.R_MEM] = su.resource_request.get("memory", 0)
        for j, rname in enumerate(view.scalar_resources):
            request[i, OF.NUM_FIXED_RESOURCES + j] = su.resource_request.get(rname, 0)
        max_clusters[i] = INT32_INF if su.max_clusters is None else su.max_clusters
        mode_divide[i] = su.scheduling_mode == T.MODE_DIVIDE
        sticky[i] = su.sticky_cluster
        total[i] = su.desired_replicas or 0
        weights_given[i] = len(su.weights) > 0
        am = su.auto_migration
        if am is not None:
            keep[i] = am.keep_unschedulable_replicas
        avoid[i] = su.avoid_disruption

        enc = su.key.encode()
        if len(enc) > key_len_cap:
            raise VocabOverflow(f"key longer than {key_len_cap}: {su.key!r}")
        encoded_keys.append(enc)
        key_len[i] = len(enc)

        entries: dict[int, list] = {}

        def entry(cname):
            ci = view.index.get(cname)
            if ci is None:
                return None
            e = entries.get(ci)
            if e is None:
                # [min, max, weight, capacity, cur]
                e = entries[ci] = [0, INT32_INF, 0, INT32_INF, CUR_ABSENT]
            return e

        for cname, v in su.min_replicas.items():
            e = entry(cname)
            if e is not None:
                e[0] = v
        for cname, v in su.max_replicas.items():
            e = entry(cname)
            if e is not None:
                e[1] = v
        for cname, v in su.weights.items():
            e = entry(cname)
            if e is not None:
                e[2] = v
        if am is not None:
            for cname, cap in am.estimated_capacity.items():
                if cap >= 0:
                    e = entry(cname)
                    if e is not None:
                        e[3] = cap
        for cname, reps in su.current_clusters.items():
            e = entry(cname)
            if e is not None:
                e[4] = CUR_NIL if reps is None else reps
        sparse_entries.append(entries)
        p_max = max(p_max, len(entries))

    p = p_max
    sparse_idx = np.full((b, p), EMPTY_SLOT, np.int32)
    sparse_min = np.zeros((b, p), np.int32)
    sparse_max = np.full((b, p), INT32_INF, np.int32)
    sparse_weight = np.zeros((b, p), np.int32)
    sparse_capacity = np.full((b, p), INT32_INF, np.int32)
    sparse_cur = np.full((b, p), CUR_ABSENT, np.int32)
    for i, entries in enumerate(sparse_entries):
        for j, (ci, e) in enumerate(entries.items()):
            sparse_idx[i, j] = ci
            sparse_min[i, j], sparse_max[i, j] = e[0], e[1]
            sparse_weight[i, j], sparse_capacity[i, j] = e[2], e[3]
            sparse_cur[i, j] = e[4]

    max_len = max((len(e) for e in encoded_keys), default=1) or 1
    key_bytes = np.zeros((b, max_len), np.uint8)
    for i, enc in enumerate(encoded_keys):
        key_bytes[i, : len(enc)] = np.frombuffer(enc, np.uint8)

    # The planner's int32 contract (the sparse row-sums equal the dense
    # grid's row-sums, so this is the same check the dense path runs).
    validate_ranges(total, sparse_weight.astype(np.int64))
    dyn_totals = total[~weights_given].astype(np.int64)
    if dyn_totals.size and int(dyn_totals.max()) * 2048 >= 2**31:
        raise OverflowError(
            "desired replicas exceed the planner's int32 range with "
            "dynamic weights (max ~1M replicas)"
        )

    return CompactInputs(
        gvk_id=gvk_id,
        tol_id=tol_id,
        sel_id=sel_id,
        pref_id=pref_id,
        place_id=place_id,
        placement_has=placement_has,
        filter_enabled=filter_enabled,
        score_enabled=score_enabled,
        request=request,
        max_clusters=max_clusters,
        mode_divide=mode_divide,
        sticky=sticky,
        total=total,
        weights_given=weights_given,
        keep_unschedulable=keep,
        avoid_disruption=avoid,
        sparse_idx=sparse_idx,
        sparse_min=sparse_min,
        sparse_max=sparse_max,
        sparse_weight=sparse_weight,
        sparse_capacity=sparse_capacity,
        sparse_cur=sparse_cur,
        key_bytes=key_bytes,
        key_len=key_len,
        **vocab.tables(),
        alloc=view.alloc,
        used=view.used,
        cpu_alloc=view.cpu_alloc,
        cpu_avail=view.cpu_avail,
        cluster_valid=np.ones(len(view.clusters), bool),
    )


# -- padding helpers (engine shape-bucketing) ---------------------------
def pad_rows(ci: CompactInputs, b_pad: int) -> CompactInputs:
    """Pad the object axis with inert rows (max_clusters=0)."""
    b = ci.total.shape[0]
    if b == b_pad:
        return ci
    extra = b_pad - b
    fields = {}
    for name, arr in ci._asdict().items():
        fill = ROW_FILL.get(name)
        if fill is None:
            fields[name] = arr
            continue
        arr = np.asarray(arr)
        shape = (extra,) + arr.shape[1:]
        fields[name] = np.concatenate([arr, np.full(shape, fill, arr.dtype)])
    return CompactInputs(**fields)


def pad_axis1(ci: CompactInputs, field_fills: dict, width: int) -> CompactInputs:
    """Pad the trailing axis of the given per-object fields (sparse
    entries to the P bucket, key bytes to the L bucket)."""
    fields = ci._asdict()
    out = dict(fields)
    for name, fill in field_fills.items():
        arr = np.asarray(fields[name])
        if arr.shape[1] == width:
            continue
        if arr.shape[1] > width:
            raise ValueError(f"{name} wider than bucket {width}")
        pad = np.full((arr.shape[0], width - arr.shape[1]), fill, arr.dtype)
        out[name] = np.concatenate([arr, pad], axis=1)
    return CompactInputs(**out)


SPARSE_FILLS = {
    "sparse_idx": EMPTY_SLOT, "sparse_min": 0, "sparse_max": INT32_INF,
    "sparse_weight": 0, "sparse_capacity": INT32_INF, "sparse_cur": CUR_ABSENT,
}


def _pad_cluster_field(name: str, arr: np.ndarray, extra: int) -> np.ndarray:
    fill = CLUSTER_AXIS_FILL[name]
    axis = 1 if name in ("api_matrix", "sel_matrix", "pref_matrix", "place_matrix") else 0
    pad_shape = list(arr.shape)
    pad_shape[axis] = extra
    return np.concatenate([arr, np.full(pad_shape, fill, arr.dtype)], axis=axis)


def pad_clusters(ci: CompactInputs, c_pad: int, skip: tuple = ()) -> CompactInputs:
    """Pad the cluster axis with invalid slots (cluster_valid=False).
    ``skip`` omits fields (the engine skips the multi-MB vocabulary
    tables here and pads them only on an actual device upload)."""
    c = ci.cluster_valid.shape[0]
    if c == c_pad:
        return ci
    extra = c_pad - c
    fields = {}
    for name, arr in ci._asdict().items():
        if name not in CLUSTER_AXIS_FILL or name in skip:
            fields[name] = arr
            continue
        fields[name] = _pad_cluster_field(name, np.asarray(arr), extra)
    return CompactInputs(**fields)


def pad_tables(tables: dict, c_pad: int) -> dict:
    """Pad a vocab's tables to the engine's cluster bucket (upload time)."""
    out = {}
    for name, arr in tables.items():
        arr = np.asarray(arr)
        if name not in CLUSTER_AXIS_FILL:
            out[name] = arr  # taint tables have no cluster axis
            continue
        c = arr.shape[1 if name in (
            "api_matrix", "sel_matrix", "pref_matrix", "place_matrix"
        ) else 0]
        out[name] = (
            arr if c == c_pad else _pad_cluster_field(name, arr, c_pad - c)
        )
    return out
