"""AOT program serialization: warm restarts skip the prewarm trace.

A cold control-plane boot pays two compile-shaped costs per program
shape: the Python trace + jax lowering (the 141s prewarm at config 5)
and the XLA compile (absorbed by the persistent compilation cache,
kubeadmiral_tpu.__init__).  The persistent cache only removes the
second; a replacement process re-traces every ladder rung from Python.
This module removes the first: during the prewarm ladder the engine
exports every program it traces via ``jax.export`` into a versioned
on-disk manifest under ``KT_COMPILE_CACHE_DIR`` (``aot/<jax>-<platform>``),
and a warm boot deserializes the StableHLO artifact instead of tracing
— the XLA compile of the deserialized module then hits the persistent
cache, so a warm prewarm is disk reads, not compiler time.

Manifest entries are keyed by (program key, argument-shape signature)
and guarded by (jax version, platform, x64 flag) at the manifest level
plus a CRC per blob; ANY mismatch or failure falls back to the live
trace for that program — an AOT artifact can cost a trace, never
correctness.  Telemetry: ``engine_aot_programs_total{result=
loaded|traced|rejected}`` counts each (program, shape) resolution, and
the first call of a loaded program attributes its XLA compile to the
persistent cache (``engine_persistent_cache_total{result}``) by disk
entry delta — the restart harness asserts the ladder is 100% hits on a
second warm boot, catching silent cache-key drift.

Multi-device topology (ISSUE 12): jax.export pins the device topology a
program was exported at, so the manifest guard carries ``devices``
(visible device count) next to jax version / platform / x64 / code hash
— a warm boot at a different device count rejects the whole manifest
loudly instead of deserializing single-device programs into a mesh.
Meshed engines construct the store in ``live_trace_only`` mode: every
(program, shape) resolution is counted honestly as ``traced`` in
``engine_aot_programs_total`` (the deliberate live-trace record — warm
boots at N>1 pay the trace ladder and the telemetry SAYS so, instead of
a disabled store silently reporting nothing), and export / preload are
no-ops.  The restart bench measures that N>1 warm-boot cost explicitly
(detail.multidevice).  Knob: ``KT_AOT`` (default on; ``0`` disables).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import warnings
import zlib
from typing import Callable, Optional

import jax
import numpy as np

from kubeadmiral_tpu.runtime import lockcheck

log = logging.getLogger("kubeadmiral.aot")

MANIFEST_VERSION = 1
MANIFEST_NAME = "manifest.json"

_registered = False
_code_hash: Optional[str] = None


def code_fingerprint() -> str:
    """Hash of the kernel/engine sources an exported program's semantics
    depend on.  Part of the manifest guard: an AOT blob exported by a
    different code version would replay OLD kernel semantics silently —
    shapes alone cannot catch that, the source hash does."""
    global _code_hash
    if _code_hash is not None:
        return _code_hash
    import kubeadmiral_tpu

    root = os.path.dirname(os.path.abspath(kubeadmiral_tpu.__file__))
    h = hashlib.sha1()
    for rel in (
        "ops", os.path.join("scheduler", "engine.py"),
        os.path.join("scheduler", "compact.py"),
        os.path.join("scheduler", "featurize.py"),
        os.path.join("parallel", "mesh.py"),
    ):
        path = os.path.join(root, rel)
        files = []
        if os.path.isdir(path):
            files = sorted(
                os.path.join(path, f)
                for f in os.listdir(path)
                if f.endswith(".py")
            )
        elif os.path.isfile(path):
            files = [path]
        for f in files:
            h.update(f.encode())
            try:
                with open(f, "rb") as fh:
                    h.update(fh.read())
            except OSError:
                pass
    _code_hash = h.hexdigest()[:16]
    return _code_hash


def _register_pytrees() -> None:
    """Register the engine's NamedTuple pytypes for jax.export treedef
    serialization (idempotent; re-registration raises inside jax)."""
    global _registered
    if _registered:
        return
    from jax import export as jexport

    from kubeadmiral_tpu.ops.pipeline import PackedRows, TickInputs, TickOutputs
    from kubeadmiral_tpu.scheduler.compact import CompactInputs

    for cls, name in (
        (TickInputs, "kubeadmiral.TickInputs"),
        (TickOutputs, "kubeadmiral.TickOutputs"),
        (PackedRows, "kubeadmiral.PackedRows"),
        (CompactInputs, "kubeadmiral.CompactInputs"),
    ):
        try:
            jexport.register_namedtuple_serialization(cls, serialized_name=name)
        except ValueError:
            pass  # already registered (e.g. two engines in one process)
    _registered = True


def _sig_of(args: tuple) -> str:
    """Shape/dtype/structure signature of one positional argument list —
    what a jit cache keys on, minus weak types (the engine passes arrays
    only).  Non-array leaves (None, python scalars) key by repr."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts = []
    for x in leaves:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            parts.append(repr(x))
        else:
            parts.append(f"{np.dtype(dtype).str}{list(shape)}")
    return str(treedef) + "&" + "|".join(parts)


def _entry_id(key: str, sig: str) -> str:
    return hashlib.sha1(f"{key}\x00{sig}".encode()).hexdigest()[:20]


def default_dir() -> Optional[str]:
    """``<compile-cache-dir>/aot/<jax version>-<platform>`` — versioned
    next to the persistent XLA cache the blobs' compiles land in.
    ``KT_AOT_DIR`` overrides the root (bench isolation: a cold-boot
    measurement must not find a previous round's manifest)."""
    base = os.environ.get("KT_AOT_DIR") or getattr(
        jax.config, "jax_compilation_cache_dir", None
    )
    if not base:
        return None
    return os.path.join(
        base, "aot", f"{jax.__version__}-{jax.default_backend()}"
    )


@lockcheck.shared_field_guard
class AotStore:
    """One engine's AOT program manifest: route program calls through
    deserialized exports when a valid entry exists, export newly traced
    programs while :meth:`export_mode` is active (the prewarm ladder)."""

    # Manifest/route state shared by dispatch threads, the background
    # prewarm thread and preload_all workers; mutations must hold
    # _lock (ktlint rule lock-discipline + runtime/lockcheck.py).
    _shared_fields_ = {
        "_entries": "_lock",
        "_worlds": "_lock",
        "_preloaded": "_lock",
        "_dirty": "_lock",
        "stats": "_lock",
    }

    def __init__(
        self,
        metrics=None,
        cache_dir: Optional[str] = None,
        enabled: Optional[bool] = None,
        live_trace_only: bool = False,
    ):
        self.metrics = metrics
        if enabled is None:
            enabled = os.environ.get("KT_AOT", "1") not in ("0", "false", "no")
        self.dir = cache_dir if cache_dir is not None else default_dir()
        # Live-trace-only mode (meshed engines): resolutions are COUNTED
        # (engine_aot_programs_total{result=traced} — the deliberate
        # record that this topology runs without AOT artifacts) but
        # nothing is exported, loaded or preloaded.
        self.live_trace_only = bool(live_trace_only)
        self.enabled = bool(enabled) and (
            self.dir is not None or self.live_trace_only
        )
        self._lock = lockcheck.make_lock("aotstore")
        self._export_tls = threading.local()
        self._entries: dict[str, dict] = {}
        # Prewarm-world fingerprints the manifest's export ladder ran at
        # (see SchedulerEngine.prewarm): a warm boot whose world matches
        # one of these preloads the WHOLE manifest and skips the example
        # ladder — no trace, no compile, and no example execution.
        self._worlds: set[str] = set()
        # Ahead-of-time compiled executables by entry id (preload_all):
        # resolution routes straight to these, no per-call deserialize.
        self._preloaded: dict[str, Callable] = {}
        self._dirty = False
        self.stats = {"loaded": 0, "traced": 0, "rejected": 0}
        if self.enabled and not self.live_trace_only:
            _register_pytrees()
            self._load_manifest()

    # -- manifest ---------------------------------------------------------
    def _guard(self) -> dict:
        return {
            "jax": jax.__version__,
            "platform": jax.default_backend(),
            # Device topology: exports pin it, so a manifest from one
            # device count must not serve another (a 1-device export
            # deserialized into a 4-device mesh replays single-device
            # placement semantics silently).
            "devices": jax.device_count(),
            "x64": bool(jax.config.jax_enable_x64),
            "code": code_fingerprint(),
        }

    def _load_manifest(self) -> None:
        path = os.path.join(self.dir, MANIFEST_NAME)
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except FileNotFoundError:
            return
        except (OSError, json.JSONDecodeError, ValueError) as e:
            log.warning("AOT manifest unreadable (%s); ignoring", e)
            return
        if doc.get("version") != MANIFEST_VERSION or doc.get("guard") != self._guard():
            # A manifest exported by a different jax/platform/x64 world:
            # every program in it would deserialize into the wrong
            # runtime — treat as absent (the next export-mode prewarm
            # rewrites it for this world).
            log.warning(
                "AOT manifest guard mismatch (have %s, manifest %s); "
                "falling back to live traces",
                self._guard(), doc.get("guard"),
            )
            self._count("rejected")
            return
        with self._lock:
            self._entries = dict(doc.get("entries") or {})
            self._worlds = set(doc.get("worlds") or ())

    def save_manifest(self) -> None:
        """Atomically persist the manifest (blobs are already on disk:
        each was written tmp+rename before its entry existed)."""
        if not self.enabled or self.live_trace_only:
            return
        with self._lock:
            if not self._dirty:
                return
            doc = {
                "version": MANIFEST_VERSION,
                "guard": self._guard(),
                "worlds": sorted(self._worlds),
                "entries": self._entries,
            }
            os.makedirs(self.dir, exist_ok=True)
            tmp = os.path.join(self.dir, f".{MANIFEST_NAME}.tmp.{os.getpid()}")
            with open(tmp, "w") as fh:
                json.dump(doc, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, os.path.join(self.dir, MANIFEST_NAME))
            self._dirty = False

    # -- export mode ------------------------------------------------------
    class _ExportMode:
        def __init__(self, store):
            self._store = store

        def __enter__(self):
            self._store._export_tls.active = True
            return self._store

        def __exit__(self, *exc):
            self._store._export_tls.active = False
            self._store.save_manifest()
            return False

    def export_mode(self) -> "AotStore._ExportMode":
        """Context manager the prewarm ladder runs under: programs
        traced inside it (on this thread) are exported + persisted."""
        return AotStore._ExportMode(self)

    @property
    def exporting(self) -> bool:
        return bool(getattr(self._export_tls, "active", False))

    # -- program wrapping -------------------------------------------------
    def wrap(self, key: str, fn: Callable) -> Callable:
        """Route ``fn`` (a jax.jit function) through the store: per
        argument-shape signature, use a deserialized export when the
        manifest has one, export during export mode, live-trace
        otherwise.  Disabled stores return ``fn`` unchanged (zero
        overhead)."""
        if not self.enabled:
            return fn
        return _AotProgram(self, key, fn)

    def _count(self, result: str, n: int = 1) -> None:
        # Read-modify-write shared across prewarm + dispatch threads:
        # the un-locked form lost updates under the thread storm.
        with self._lock:
            self.stats[result] = self.stats.get(result, 0) + n
        if self.metrics is not None:
            self.metrics.counter("engine_aot_programs_total", n, result=result)

    def _pcache_entries(self) -> int:
        base = getattr(jax.config, "jax_compilation_cache_dir", None)
        if not base:
            return 0
        try:
            return sum(1 for _ in os.scandir(base) if _.is_file())
        except OSError:
            return 0

    def _note_pcache(self, before: int) -> None:
        """Attribute a loaded program's first-call XLA compile to the
        persistent cache: no new on-disk entry means the compile was a
        disk hit — the signal the restart harness gates on."""
        if self.metrics is None:
            return
        after = self._pcache_entries()
        result = "miss" if after > before else "hit"
        self.metrics.counter("engine_persistent_cache_total", result=result)

    # -- prewarm worlds / whole-manifest preload ---------------------------
    def note_world(self, world_key: str) -> None:
        """Record that the export ladder ran at this prewarm world, so a
        later boot at the same world may preload + skip the ladder."""
        if not self.enabled or self.live_trace_only:
            return
        with self._lock:
            if world_key not in self._worlds:
                self._worlds.add(world_key)
                self._dirty = True

    def has_world(self, world_key: str) -> bool:
        if self.live_trace_only:
            return False  # meshed prewarms always run the example ladder
        return self.enabled and world_key in self._worlds

    def preload_all(self) -> int:
        """Ahead-of-time compile EVERY manifest entry from its serialized
        avals — deserialize, ``jit(call).lower(avals).compile()`` — with
        no example inputs and no execution.  This is the warm-boot
        replacement for the prewarm trace ladder: the XLA compiles hit
        the persistent cache, and live dispatches route straight to the
        compiled executables.  Returns the number of programs now
        preloaded; individual failures count ``rejected`` and fall back
        to live traces at first use.  Live-trace-only stores (meshed
        topologies) preload NOTHING and return 0 — the honest number a
        warm boot at N>1 reports."""
        if not self.enabled or self.live_trace_only:
            return 0
        with self._lock:
            entries = dict(self._entries)
        todo = [
            (eid, e) for eid, e in entries.items() if eid not in self._preloaded
        ]
        n = len(entries) - len(todo)
        if not todo:
            return n
        # XLA compiles (and persistent-cache loads) release the GIL, so
        # the manifest preloads in parallel — the warm-boot ladder is
        # bounded by the slowest program, not the sum.
        from concurrent.futures import ThreadPoolExecutor

        workers = min(8, max(1, (os.cpu_count() or 2) - 1), len(todo))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            compiled_list = list(
                pool.map(lambda kv: (kv[0], self._compile_entry(kv[1])), todo)
            )
        for eid, compiled in compiled_list:
            if compiled is None:
                continue
            with self._lock:
                self._preloaded[eid] = compiled
            self._count("loaded")
            n += 1
        return n

    def _compile_entry(self, entry: dict) -> Optional[Callable]:
        from jax import export as jexport

        path = os.path.join(self.dir, entry.get("file", ""))
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
            if zlib.crc32(blob) != entry.get("crc"):
                raise ValueError("CRC mismatch")
            exported = jexport.deserialize(bytearray(blob))
            leaves = [
                jax.ShapeDtypeStruct(a.shape, a.dtype)
                for a in exported.in_avals
            ]
            args, kwargs = jax.tree_util.tree_unflatten(
                exported.in_tree, leaves
            )
            before = self._pcache_entries()
            # ktlint: ignore[aot-ledger-coverage] this IS the AotStore: the jit of a deserialized export is the wrapped route itself; the engine's outer _AotProgram proxy is already ledger-wrapped by _obs_wrap
            compiled = jax.jit(exported.call).lower(*args, **kwargs).compile()
            self._note_pcache(before)
        except Exception as e:
            log.warning(
                "AOT preload failed for %s (%s); will live-trace",
                entry.get("key", "?"), e,
            )
            self._count("rejected")
            return None
        return compiled

    # -- resolution --------------------------------------------------------
    def _resolve(self, key: str, sig: str, fn: Callable, args: tuple) -> Callable:
        """Pick the route for one (program, signature): a jitted
        deserialized export, an export-and-use (export mode), or the
        live jit function."""
        if self.live_trace_only:
            # Meshed topology: the deliberate live-trace record — one
            # honest ``traced`` count per (program, shape), no blobs.
            self._count("traced")
            return fn
        eid = _entry_id(key, sig)
        compiled = self._preloaded.get(eid)
        if compiled is not None:
            # Preloaded executable: already compiled and counted; the
            # guard only covers a pathological first-call failure.
            return self._precompiled_route(compiled, fn, key)
        with self._lock:
            entry = self._entries.get(eid)
        if entry is not None:
            loaded = self._load_entry(key, entry)
            if loaded is not None:
                return self._guarded(loaded, fn, key)
        if self.exporting:
            # Export is a SIDE EFFECT: the route stays the live jit
            # function, so cold-booted processes keep their donating
            # programs (export drops donation) — the export's extra
            # trace is a one-time cost inside the background prewarm
            # thread, never on a live tick.  Only warm boots (preload)
            # run the donation-free deserialized executables.
            self._export_entry(key, sig, eid, fn, args)
        self._count("traced")
        return fn

    def _load_entry(self, key: str, entry: dict) -> Optional[Callable]:
        from jax import export as jexport

        path = os.path.join(self.dir, entry.get("file", ""))
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError as e:
            log.warning("AOT blob %s unreadable (%s); live-tracing", path, e)
            self._count("rejected")
            return None
        if zlib.crc32(blob) != entry.get("crc"):
            log.warning(
                "AOT blob %s failed CRC (program %s); live-tracing", path, key
            )
            self._count("rejected")
            return None
        try:
            exported = jexport.deserialize(bytearray(blob))
        except Exception as e:
            log.warning("AOT deserialize failed for %s (%s); live-tracing", key, e)
            self._count("rejected")
            return None
        # ktlint: ignore[aot-ledger-coverage] this IS the AotStore: the jit of a deserialized export is the wrapped route itself; the engine's outer _AotProgram proxy is already ledger-wrapped by _obs_wrap
        return jax.jit(exported.call)

    def _precompiled_route(
        self, compiled: Callable, fallback: Callable, key: str
    ) -> Callable:
        state = {"dead": False}
        store = self

        def route(*args):
            if state["dead"]:
                return fallback(*args)
            try:
                return compiled(*args)
            except Exception as e:
                state["dead"] = True
                log.warning(
                    "preloaded AOT program %s failed (%s); live-tracing",
                    key, e,
                )
                store._count("rejected")
                return fallback(*args)

        return route

    def _guarded(self, loaded: Callable, fallback: Callable, key: str) -> Callable:
        """First-call guard around a loaded program: a call failure
        (platform refusing the artifact, aval mismatch) rejects the
        entry and permanently reroutes to the live trace."""
        state = {"ok": False, "dead": False}
        store = self

        def route(*args):
            if state["dead"]:
                return fallback(*args)
            if state["ok"]:
                return loaded(*args)
            before = store._pcache_entries()
            try:
                out = loaded(*args)
            except Exception as e:
                state["dead"] = True
                log.warning(
                    "AOT program %s failed on first call (%s); live-tracing",
                    key, e,
                )
                store._count("rejected")
                return fallback(*args)
            state["ok"] = True
            store._note_pcache(before)
            store._count("loaded")
            return out

        return route

    def _export_entry(
        self, key: str, sig: str, eid: str, fn: Callable, args: tuple
    ) -> bool:
        """Export ``fn`` at these avals and persist blob + manifest
        entry.  False on any failure — the program simply stays
        live-trace-only."""
        from jax import export as jexport

        try:
            with warnings.catch_warnings():
                # Donated buffers are dropped by export (a memory trade,
                # not a correctness one) — don't spam prewarm logs.
                warnings.simplefilter("ignore")
                exported = jexport.export(fn)(*args)
            blob = exported.serialize()
        except Exception as e:
            log.warning("AOT export failed for %s (%s); live-tracing", key, e)
            return False
        fname = f"{eid}.jaxexp"
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = os.path.join(self.dir, f".{fname}.tmp.{os.getpid()}")
            with open(tmp, "wb") as fh:
                fh.write(blob)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, os.path.join(self.dir, fname))
        except OSError as e:
            log.warning("AOT blob write failed for %s (%s)", key, e)
            return None
        with self._lock:
            self._entries[eid] = {
                "file": fname,
                "crc": zlib.crc32(bytes(blob)),
                "key": key,
                "sig_sha": hashlib.sha1(sig.encode()).hexdigest()[:12],
                "nbytes": len(blob),
            }
            self._dirty = True
        # ktlint: ignore[aot-ledger-coverage] this IS the AotStore: the jit of a deserialized export is the wrapped route itself; the engine's outer _AotProgram proxy is already ledger-wrapped by _obs_wrap
        return jax.jit(exported.call)


class _AotProgram:
    """Per-program router: one resolved route per argument signature."""

    __slots__ = ("_store", "_key", "_fn", "_routes")

    def __init__(self, store: AotStore, key: str, fn: Callable):
        self._store = store
        self._key = key
        self._fn = fn
        self._routes: dict[str, Callable] = {}

    def __call__(self, *args):
        sig = _sig_of(args)
        route = self._routes.get(sig)
        if route is None:
            route = self._store._resolve(self._key, sig, self._fn, args)
            self._routes[sig] = route
        return route(*args)
