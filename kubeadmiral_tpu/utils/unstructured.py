"""Dotted-path access into unstructured objects.

The FTC pathDefinition addresses replicas/status fields with dotted
paths like "spec.replicas" (reference: unstructured helpers in
pkg/controllers/util and types_federatedtypeconfig.go pathDefinition).
"""

from __future__ import annotations

import copy as _copy

from typing import Any, Optional


def get_path(obj: dict, path: str, default: Any = None) -> Any:
    if not path:
        return default
    cur: Any = obj
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return default
        cur = cur[part]
    return cur


def set_path(obj: dict, path: str, value: Any) -> None:
    parts = path.split(".")
    cur = obj
    for part in parts[:-1]:
        nxt = cur.get(part)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[part] = nxt
        cur = nxt
    cur[parts[-1]] = value


def delete_path(obj: dict, path: str) -> None:
    parts = path.split(".")
    cur: Optional[dict] = obj
    for part in parts[:-1]:
        if not isinstance(cur, dict):
            return
        cur = cur.get(part)  # type: ignore[assignment]
    if isinstance(cur, dict):
        cur.pop(parts[-1], None)


def _copy_json_fast(obj):
    t = type(obj)
    if t is dict:
        return {k: _copy_json_fast(v) for k, v in obj.items()}
    if t is list:
        return [_copy_json_fast(v) for v in obj]
    if t in (str, int, float, bool, type(None)):
        return obj
    if t is tuple:
        return tuple(_copy_json_fast(v) for v in obj)
    return _copy.deepcopy(obj)  # non-JSON node: memo-based fallback


def _copy_json_py(obj):
    try:
        return _copy_json_fast(obj)
    except RecursionError:
        return _copy.deepcopy(obj)


_native_copy = None
_native_checked = False


def copy_json(obj):
    """Deep copy for JSON-shaped objects (immutable leaves shared, dict
    keys shared).  Uses the C extension (native/fastcopy.cpp, ~8x the
    Python recursion) when a toolchain is available; non-JSON nodes and
    cyclic structures fall back to copy.deepcopy wholesale."""
    global _native_copy, _native_checked
    if not _native_checked:
        # Deferred import: utils must not import native at module load
        # (native imports nothing back, but keeps startup lazy).
        from kubeadmiral_tpu.native import load_fastcopy

        _native_copy = load_fastcopy()
        _native_checked = True
    if _native_copy is not None:
        try:
            return _native_copy(obj)
        except (TypeError, RecursionError):
            return _copy.deepcopy(obj)
    return _copy_json_py(obj)
