"""Hashing helpers compatible with the reference control plane's choices.

The reference uses Go's ``hash/fnv`` in two places that shape scheduling
semantics, so the exact bit patterns matter for parity:

* the replica planner tie-breaks equal-weight clusters by ``fnv.New32()``
  (FNV-1, 32-bit) over ``clusterName + replicaSetKey``
  (reference: pkg/controllers/util/planner/planner.go:184-198), and
* scheduling-trigger dedupe hashes a canonical JSON encoding
  (reference: pkg/controllers/scheduler/schedulingtriggers.go:106-148).

The byte loops run in the native C++ library when available
(kubeadmiral_tpu/native, built with g++ on demand) — at 100k objects per
tick the trigger hashing is the hottest host-side path — with these
pure-Python/numpy implementations as the fallback.
"""

from __future__ import annotations

import ctypes
import json
from typing import Any, Iterable

import numpy as np

from kubeadmiral_tpu import native

_FNV32_OFFSET = np.uint32(2166136261)
_FNV32_PRIME = np.uint32(16777619)

# Sentinel: resolved lazily on the first hash call so importing this
# module never blocks on the g++ on-demand build.
_UNRESOLVED = object()
_NATIVE: Any = _UNRESOLVED


def _native_lib():
    global _NATIVE
    if _NATIVE is _UNRESOLVED:
        _NATIVE = native.load()
    return _NATIVE


def fnv32(data: bytes) -> int:
    """FNV-1 32-bit (multiply, then xor) — matches Go's ``fnv.New32()``."""
    _NATIVE = _native_lib()
    if _NATIVE is not None:
        return _NATIVE.kadm_fnv32(data, len(data))
    h = 2166136261
    for b in data:
        h = ((h * 16777619) & 0xFFFFFFFF) ^ b
    return h


def fnv32a(data: bytes) -> int:
    """FNV-1a 32-bit (xor, then multiply) — matches Go's ``fnv.New32a()``."""
    _NATIVE = _native_lib()
    if _NATIVE is not None:
        return _NATIVE.kadm_fnv32a(data, len(data))
    h = 2166136261
    for b in data:
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


def fnv32_batch(prefixes: Iterable[str], suffix: str) -> np.ndarray:
    """FNV-1 of ``prefix + suffix`` for many prefixes, one suffix.

    Used by the planner featurizer: one object key (suffix) against every
    cluster name (prefix). Returns uint32[N].
    """
    prefs = list(prefixes)
    suffix_b = suffix.encode()
    _NATIVE = _native_lib()
    if _NATIVE is not None and prefs:
        encoded = [p.encode() for p in prefs]
        offsets = np.zeros(len(encoded) + 1, dtype=np.uint64)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        buf = b"".join(encoded)
        out = np.empty(len(encoded), dtype=np.uint32)
        _NATIVE.kadm_fnv32_batch(
            buf,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(encoded),
            suffix_b,
            len(suffix_b),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        )
        return out
    out = np.empty(len(prefs), dtype=np.uint32)
    for i, p in enumerate(prefs):
        out[i] = fnv32(p.encode() + suffix_b)
    return out


def fnv32_extend(state: int | np.ndarray, data: bytes) -> int | np.ndarray:
    """Continue an FNV-1 hash from a previous state over extra bytes.

    FNV is a streaming hash, so ``fnv32(a + b) == fnv32_extend(fnv32(a), b)``.
    This lets the featurizer hash every cluster name once and extend with
    each object key, turning O(B*C*len) work into O(C*len + B*C*len(key)).
    Accepts a scalar state or a uint32 ndarray of states (vectorized).
    """
    if isinstance(state, np.ndarray):
        h = state.astype(np.uint32).copy()
        _NATIVE = _native_lib()
        if _NATIVE is not None:
            _NATIVE.kadm_fnv32_extend_batch(
                h.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                len(h),
                data,
                len(data),
            )
            return h
        with np.errstate(over="ignore"):
            for b in data:
                h = (h * _FNV32_PRIME) ^ np.uint32(b)
        return h
    h = int(state)
    for b in data:
        h = ((h * 16777619) & 0xFFFFFFFF) ^ b
    return h


def uint32_to_sortable_int32(h: np.ndarray) -> np.ndarray:
    """Map uint32 to int32 preserving unsigned order (for device sorts).

    TPU-side sorts run on int32; shifting by 2**31 keeps ``a < b`` iff the
    unsigned values compare the same way.
    """
    return (h.astype(np.int64) - 2**31).astype(np.int32)


def stable_json_hash(value: Any) -> int:
    """FNV-1a over a canonical (sorted-key, compact) JSON encoding.

    The trigger-hash analogue: the reference marshals a sorted struct to
    JSON and hashes it so that reconciles with unchanged inputs can be
    skipped (schedulingtriggers.go:106-148). Python dicts are sorted and
    sets canonicalized so the encoding never depends on iteration order
    or PYTHONHASHSEED; other non-JSON types raise rather than hash
    unstably.
    """

    def canonical(v: Any) -> Any:
        # json.dumps only consults this hook for non-JSON types; nested
        # non-JSON elements inside the returned value are routed back here.
        if isinstance(v, (set, frozenset)):
            return sorted(v, key=lambda x: json.dumps(x, sort_keys=True, default=canonical))
        if isinstance(v, tuple):
            return list(v)
        raise TypeError(f"unhashable trigger value of type {type(v).__name__}")

    enc = json.dumps(value, sort_keys=True, separators=(",", ":"), default=canonical)
    return fnv32a(enc.encode())
