"""Kubernetes resource.Quantity parsing.

Implements the subset of quantity semantics the control plane relies on
(reference usage: k8s.io/apimachinery resource.Quantity via
pkg/controllers/scheduler/framework/util.go NewResource): decimal SI
suffixes (m, k, M, G, T, P, E), binary suffixes (Ki..Ei), plain and
scientific notation.  ``value()`` rounds **up** to an integer and
``milli_value()`` rounds up at milli precision, matching Go's
``Quantity.Value()`` / ``MilliValue()`` ceiling behavior that the
scheduler's resource math inherits.
"""

from __future__ import annotations

import functools
import math
import re
from fractions import Fraction

_SUFFIXES: dict[str, Fraction] = {
    "": Fraction(1),
    "n": Fraction(1, 1000**3),
    "u": Fraction(1, 1000**2),
    "m": Fraction(1, 1000),
    "k": Fraction(1000),
    "M": Fraction(1000**2),
    "G": Fraction(1000**3),
    "T": Fraction(1000**4),
    "P": Fraction(1000**5),
    "E": Fraction(1000**6),
    "Ki": Fraction(1024),
    "Mi": Fraction(1024**2),
    "Gi": Fraction(1024**3),
    "Ti": Fraction(1024**4),
    "Pi": Fraction(1024**5),
    "Ei": Fraction(1024**6),
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>[0-9]+(?:\.[0-9]*)?|\.[0-9]+)"
    r"(?:[eE](?P<exp>[+-]?[0-9]+))?"
    r"(?P<suffix>n|u|m|k|M|G|T|P|E|Ki|Mi|Gi|Ti|Pi|Ei)?$"
)


class Quantity:
    """An exact rational quantity with k8s-style string forms."""

    __slots__ = ("_value", "_text")

    def __init__(self, value: "Fraction | int | str | Quantity"):
        if isinstance(value, Quantity):
            self._value: Fraction = value._value
            self._text = value._text
            return
        if isinstance(value, str):
            self._value = _parse(value)
            self._text: str | None = value
        else:
            self._value = Fraction(value)
            self._text = None

    @property
    def raw(self) -> Fraction:
        return self._value

    def value(self) -> int:
        """Integer value, rounded away from zero (Go ``Quantity.Value()``)."""
        v = self._value
        return math.ceil(v) if v >= 0 else math.floor(v)

    def milli_value(self) -> int:
        """Milli-units, rounded away from zero (Go ``Quantity.MilliValue()``)."""
        v = self._value * 1000
        return math.ceil(v) if v >= 0 else math.floor(v)

    def __add__(self, other: "Quantity") -> "Quantity":
        return Quantity(self._value + Quantity(other)._value)

    def __sub__(self, other: "Quantity") -> "Quantity":
        return Quantity(self._value - Quantity(other)._value)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, (Quantity, int, str, Fraction)) and (
            self._value == Quantity(other)._value  # type: ignore[arg-type]
        )

    def __lt__(self, other: "Quantity") -> bool:
        return self._value < Quantity(other)._value

    def __le__(self, other: "Quantity") -> bool:
        return self._value <= Quantity(other)._value

    def __hash__(self) -> int:
        return hash(self._value)

    def __repr__(self) -> str:
        if self._text is not None:
            return f"Quantity({self._text!r})"
        return f"Quantity({str(self._value)})"

    def __str__(self) -> str:
        return self._text if self._text is not None else str(self._value)


def _parse(text: str) -> Fraction:
    m = _QUANTITY_RE.match(text.strip())
    if not m:
        raise ValueError(f"invalid quantity: {text!r}")
    num = Fraction(m.group("num"))
    if m.group("exp"):
        num *= Fraction(10) ** int(m.group("exp"))
    num *= _SUFFIXES[m.group("suffix") or ""]
    if m.group("sign") == "-":
        num = -num
    return num


def parse_quantity(text: "str | int | float") -> Quantity:
    if isinstance(text, float):
        return Quantity(Fraction(str(text)))
    return Quantity(text)


def cpu_to_millis(text: "str | int | float") -> int:
    """CPU quantity -> millicores (the scheduler's CPU unit)."""
    if isinstance(text, str):
        return _cpu_millis_cached(text)
    return parse_quantity(text).milli_value()


def to_int_value(text: "str | int | float") -> int:
    """Memory/storage/scalar quantity -> integer units (bytes for memory)."""
    if isinstance(text, str):
        return _int_value_cached(text)
    return parse_quantity(text).value()


# Quantity strings repeat massively across objects ("50m", "256Gi", node
# sizes): the Fraction parse dominated scheduling-unit construction at
# 10k-object batches, and the string -> int mappings are pure.
@functools.lru_cache(maxsize=16384)
def _cpu_millis_cached(text: str) -> int:
    return parse_quantity(text).milli_value()


@functools.lru_cache(maxsize=16384)
def _int_value_cached(text: str) -> int:
    return parse_quantity(text).value()
