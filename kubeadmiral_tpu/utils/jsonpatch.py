"""RFC 6902 JSON Patch application over unstructured objects.

The override machinery stores per-cluster mutations as JSON patches
(reference: pkg/controllers/util/overrides.go:57-232 applying
evanphx/json-patch); this is a self-contained implementation of the op
set with JSON-pointer escaping (~0 -> ~, ~1 -> /) and array index
semantics ("-" appends).
"""

from __future__ import annotations


from kubeadmiral_tpu.utils.unstructured import copy_json
from typing import Any


class PatchError(Exception):
    pass


def _unescape(token: str) -> str:
    return token.replace("~1", "/").replace("~0", "~")


def _tokens(pointer: str) -> list[str]:
    if pointer == "":
        return []
    if not pointer.startswith("/"):
        raise PatchError(f"invalid JSON pointer {pointer!r}")
    return [_unescape(t) for t in pointer[1:].split("/")]


def _walk(doc: Any, tokens: list[str]) -> Any:
    cur = doc
    for t in tokens:
        if isinstance(cur, dict):
            if t not in cur:
                raise PatchError(f"path segment {t!r} not found")
            cur = cur[t]
        elif isinstance(cur, list):
            cur = cur[_index(t, len(cur), append_ok=False)]
        else:
            raise PatchError(f"cannot traverse {type(cur).__name__} at {t!r}")
    return cur


def _index(token: str, length: int, append_ok: bool) -> int:
    if token == "-":
        if append_ok:
            return length
        raise PatchError("'-' not allowed here")
    try:
        i = int(token)
    except ValueError as e:
        raise PatchError(f"invalid array index {token!r}") from e
    if not (0 <= i <= (length if append_ok else length - 1)):
        raise PatchError(f"array index {i} out of range")
    return i


def _get(doc: Any, pointer: str) -> Any:
    return _walk(doc, _tokens(pointer))


def _add(doc: Any, pointer: str, value: Any) -> Any:
    tokens = _tokens(pointer)
    if not tokens:
        return value
    parent = _walk(doc, tokens[:-1])
    last = tokens[-1]
    if isinstance(parent, dict):
        parent[last] = value
    elif isinstance(parent, list):
        parent.insert(_index(last, len(parent), append_ok=True), value)
    else:
        raise PatchError(f"cannot add into {type(parent).__name__}")
    return doc


def _replace(doc: Any, pointer: str, value: Any) -> Any:
    """Overwrite in place (unlike add, which inserts into arrays)."""
    tokens = _tokens(pointer)
    if not tokens:
        return value
    parent = _walk(doc, tokens[:-1])
    last = tokens[-1]
    if isinstance(parent, dict):
        if last not in parent:
            raise PatchError(f"path {pointer!r} not found")
        parent[last] = value
    elif isinstance(parent, list):
        parent[_index(last, len(parent), append_ok=False)] = value
    else:
        raise PatchError(f"cannot replace in {type(parent).__name__}")
    return doc


def _remove(doc: Any, pointer: str) -> Any:
    tokens = _tokens(pointer)
    if not tokens:
        raise PatchError("cannot remove whole document")
    parent = _walk(doc, tokens[:-1])
    last = tokens[-1]
    if isinstance(parent, dict):
        if last not in parent:
            raise PatchError(f"path {pointer!r} not found")
        del parent[last]
    elif isinstance(parent, list):
        parent.pop(_index(last, len(parent), append_ok=False))
    else:
        raise PatchError(f"cannot remove from {type(parent).__name__}")
    return doc


def apply_patch(obj: dict, patches: list[dict]) -> dict:
    """Apply an RFC6902 patch list to a deep copy of ``obj``."""
    doc: Any = copy_json(obj)
    for p in patches:
        op = p.get("op")
        path = p.get("path", "")
        if op == "add":
            doc = _add(doc, path, copy_json(p.get("value")))
        elif op == "replace":
            doc = _replace(doc, path, copy_json(p.get("value")))
        elif op == "remove":
            doc = _remove(doc, path)
        elif op == "move":
            value = _get(doc, p["from"])
            doc = _remove(doc, p["from"])
            doc = _add(doc, path, value)
        elif op == "copy":
            value = copy_json(_get(doc, p["from"]))
            doc = _add(doc, path, value)
        elif op == "test":
            if _get(doc, path) != p.get("value"):
                raise PatchError(f"test failed at {path!r}")
        else:
            raise PatchError(f"unknown op {op!r}")
    return doc


def create_merge_patch(source: Any, target: Any) -> Any:
    """RFC 7386 JSON merge patch turning ``source`` into ``target``.

    The federate controller records this on the federated object so the
    template generator is reconstructible (reference:
    pkg/controllers/federate/util.go:330-349 CreateMergePatch).
    """
    if not isinstance(source, dict) or not isinstance(target, dict):
        return copy_json(target)
    patch: dict = {}
    for key, src_val in source.items():
        if key not in target:
            patch[key] = None
        elif src_val != target[key]:
            patch[key] = create_merge_patch(src_val, target[key])
    for key, tgt_val in target.items():
        if key not in source:
            patch[key] = copy_json(tgt_val)
    return patch


def apply_merge_patch(doc: Any, patch: Any) -> Any:
    """Apply an RFC 7386 merge patch (null deletes keys)."""
    if not isinstance(patch, dict):
        return copy_json(patch)
    result = copy_json(doc) if isinstance(doc, dict) else {}
    for key, val in patch.items():
        if val is None:
            result.pop(key, None)
        else:
            result[key] = apply_merge_patch(result.get(key), val)
    return result
