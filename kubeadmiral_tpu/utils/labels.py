"""Label/field selector matching with k8s semantics.

Mirrors the behavior the reference gets from k8s.io/apimachinery
labels.Requirement (reference usage: pkg/controllers/util/clusterselector/
util.go): NotIn and DoesNotExist match when the key is absent; Gt/Lt parse
the label value as an integer and require the key to exist.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

from kubeadmiral_tpu.models.types import (
    ClusterAffinity,
    PreferredSchedulingTerm,
    SelectorRequirement,
    SelectorTerm,
)


def match_requirement(labels: Mapping[str, str], req: SelectorRequirement) -> bool:
    has = req.key in labels
    value = labels.get(req.key)
    op = req.operator
    if op == "In":
        return has and value in req.values
    if op == "NotIn":
        return not has or value not in req.values
    if op == "Exists":
        return has
    if op == "DoesNotExist":
        return not has
    if op in ("Gt", "Lt"):
        if not has or len(req.values) != 1:
            return False
        try:
            lhs, rhs = int(value), int(req.values[0])
        except (TypeError, ValueError):
            return False
        return lhs > rhs if op == "Gt" else lhs < rhs
    raise ValueError(f"invalid selector operator {op!r}")


def match_field_requirement(fields: Mapping[str, str], req: SelectorRequirement) -> bool:
    """Field selectors support only In/NotIn with a single value
    (clusterselector/util.go:64-97)."""
    value = fields.get(req.key, "")
    if len(req.values) != 1:
        return False
    if req.operator == "In":
        return value == req.values[0]
    if req.operator == "NotIn":
        return value != req.values[0]
    return False


def match_term(
    labels: Mapping[str, str], fields: Mapping[str, str], term: SelectorTerm
) -> bool:
    """Empty term matches nothing; expressions and fields are ANDed
    (clusterselector/util.go:99-140)."""
    if not term.match_expressions and not term.match_fields:
        return False
    for req in term.match_expressions:
        if not match_requirement(labels, req):
            return False
    for req in term.match_fields:
        if not match_field_requirement(fields, req):
            return False
    return True


def match_terms(
    labels: Mapping[str, str],
    fields: Mapping[str, str],
    terms: Sequence[SelectorTerm],
) -> bool:
    """Terms are ORed."""
    return any(match_term(labels, fields, t) for t in terms)


def matches_selector_set(labels: Mapping[str, str], selector: Mapping[str, str]) -> bool:
    """labels.SelectorFromSet: every key/value must match exactly."""
    return all(labels.get(k) == v for k, v in selector.items())


def cluster_feasible(
    labels: Mapping[str, str],
    name: str,
    selector: Mapping[str, str],
    affinity: Optional[ClusterAffinity],
) -> bool:
    """The ClusterAffinity filter plugin's decision
    (cluster_affinity.go:50-93): selector-set AND required terms."""
    if selector and not matches_selector_set(labels, selector):
        return False
    if affinity is not None and affinity.required is not None:
        if not match_terms(labels, {"metadata.name": name}, affinity.required):
            return False
    return True


def preferred_score(
    labels: Mapping[str, str],
    name: str,
    affinity: Optional[ClusterAffinity],
) -> int:
    """Sum of weights of matching preferred terms (cluster_affinity.go:96-124).

    Only matchExpressions participate (the reference builds a label selector
    from the preference's expressions; a term with no expressions matches
    everything via labels.Nothing()? No — an empty requirement list yields
    labels.Nothing(), which matches nothing)."""
    if affinity is None:
        return 0
    score = 0
    for term in affinity.preferred:
        if term.weight == 0:
            continue
        exprs = term.preference.match_expressions
        if not exprs:
            continue  # labels.Nothing() matches no clusters
        if all(match_requirement(labels, r) for r in exprs):
            score += term.weight
    return score
