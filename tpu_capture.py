"""Opportunistic on-chip bench capture (VERDICT r4 #2).

The tunneled TPU relay has intermittent uptime windows (r2: up; r3:
wedged mid-round; r4: down all round).  A once-at-end-of-round bench
run wastes any window that opens earlier, so this watcher probes the
relay periodically through the build and, on the FIRST successful
claim, runs the bench configs back-to-back on the chip and writes one
``BENCH_DETAIL_c{N}_tpu.json`` artifact per config that succeeds
on-chip.

Single-tenancy discipline (BASELINE.md): the probe is one sacrificial
subprocess with a timeout, never concurrent with another claim; the
bench runs are sequential; nothing else may touch the chip while this
script is active.

Usage:  python tpu_capture.py            # defaults: configs 3,4,5
        TPU_CAPTURE_CONFIGS=3,4 TPU_CAPTURE_DEADLINE_S=14400 \
            TPU_CAPTURE_INTERVAL_S=600 python tpu_capture.py

The capture loop is dependency-injected (probe / runner / clock) so the
mechanism is testable without a chip: tests/test_tpu_capture.py drives
it with fakes.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


def default_probe() -> bool:
    """One bounded single-tenant chip probe in a throwaway subprocess."""
    from kubeadmiral_tpu.bench_support import probe_tpu

    return probe_tpu(attempts=1, probe_timeout=120.0) == ""


# One on-chip profiler grab per capture process (set after the first
# successful profiled capture; a failed window retries the grab).
_profiled = [False]


def default_runner(config: str, profile: bool | None = None) -> dict | None:
    """Run bench.py for one config on the chip; returns the parsed
    artifact on an on-chip success, None otherwise (a cpu-fallback
    artifact is NOT captured — the whole point is TPU evidence).

    ``profile=True`` additionally grabs ONE on-chip ``jax.profiler``
    trace around the first timed tick (bench.py's KT_PROFILE_TICKS
    hook): the narrow/megachunk/drift machinery has never been
    profiled on TPU, and a window that opens is the only chance to —
    the artifact directory lands under ``profiles/tpu_c<config>`` and
    the bench detail records it (detail.device_attr.profile_dir)."""
    env = dict(os.environ)
    env["BENCH_CONFIG"] = config
    # One probe attempt: the watcher already established the window;
    # if the chip vanished, fail fast and resume watching.
    env.setdefault("BENCH_TPU_ATTEMPTS", "1")
    if profile is None:
        profile = not _profiled[0]
    if profile and "KT_PROFILE_TICKS" not in env:
        env["KT_PROFILE_TICKS"] = "1"
        env.setdefault(
            "KT_PROFILE_DIR", os.path.join(REPO, "profiles", f"tpu_c{config}")
        )
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True,
            text=True,
            env=env,
            timeout=float(os.environ.get("TPU_CAPTURE_BENCH_TIMEOUT_S", 7200)),
        )
    except subprocess.TimeoutExpired:
        # Relay wedged mid-run (the r3 scenario): resume watching, do
        # not kill the watcher.
        return None
    if proc.returncode != 0:
        return None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                artifact = json.loads(line)
            except json.JSONDecodeError:
                continue
            if artifact.get("detail", {}).get("platform") == "tpu":
                if profile:
                    _profiled[0] = True
                return artifact
    return None


def capture_loop(
    configs,
    probe=default_probe,
    runner=default_runner,
    sleep=time.sleep,
    clock=time.monotonic,
    interval_s: float = 600.0,
    deadline_s: float = 6 * 3600.0,
    write_dir: str = REPO,
) -> dict[str, str]:
    """Watch for a relay window; on the first claim, capture every
    config sequentially.  Returns {config: artifact_path} for captures.
    A config that fails on-chip mid-window is retried in the next
    window; captured configs are never re-run."""
    captured: dict[str, str] = {}
    start = clock()
    while clock() - start < deadline_s:
        remaining = [c for c in configs if c not in captured]
        if not remaining:
            break
        if probe():
            for config in remaining:
                artifact = runner(config)
                if artifact is None:
                    # Chip lost mid-window: back to watching.
                    print(
                        f"# capture: config {config} lost the chip; rewatching",
                        file=sys.stderr,
                        flush=True,
                    )
                    break
                path = os.path.join(write_dir, f"BENCH_DETAIL_c{config}_tpu.json")
                with open(path, "w") as f:
                    json.dump(artifact, f)
                    f.write("\n")
                captured[config] = path
                print(f"# capture: config {config} -> {path}", file=sys.stderr)
            else:
                break  # every remaining config captured in this window
        sleep(interval_s)
    return captured


def main() -> int:
    configs = [
        c.strip()
        for c in os.environ.get("TPU_CAPTURE_CONFIGS", "3,4,5").split(",")
        if c.strip()
    ]
    captured = capture_loop(
        configs,
        interval_s=float(os.environ.get("TPU_CAPTURE_INTERVAL_S", 600)),
        deadline_s=float(os.environ.get("TPU_CAPTURE_DEADLINE_S", 6 * 3600)),
    )
    print(json.dumps({"captured": captured}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
