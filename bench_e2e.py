"""End-to-end control-plane benchmark with a per-stage budget.

Runs the FULL pipeline — source create -> federate -> batch-schedule ->
override -> sync (member writes) -> status collection + aggregation —
over an in-process fleet, and attributes wall time to each controller so
throughput regressions are assignable to a stage (VERDICT r1 #10).

Shapes via BENCH_E2E_OBJECTS / BENCH_E2E_CLUSTERS (default 1000x50, the
reference e2e suite's scale knob; config #2 of BASELINE.md).

Prints one JSON line:
  {"metric": "e2e_objects_per_sec_BxC", "value": ..., "unit": ...,
   "stages_s": {controller: seconds}, ...}
"""

from __future__ import annotations

import json
import os
import sys
import time

N_OBJECTS = int(os.environ.get("BENCH_E2E_OBJECTS", 1000))
N_CLUSTERS = int(os.environ.get("BENCH_E2E_CLUSTERS", 50))
# "inproc" (default): the in-memory ClusterFleet.  "http": every
# apiserver a real socket server (kwok-lite farm) — measures the
# transport path the bulk-write batching exists for.
TRANSPORT = os.environ.get("BENCH_E2E_TRANSPORT", "inproc")
# BENCH_E2E_CHAOS=1 appends a degraded-fleet phase after the main
# measurement: one member hard-down (connect-timeout partition), one
# flapping, while objects churn — reporting per-round settle-time
# p50/p99 ("tick stall") and shed-write counts under detail.chaos
# (`make chaos-e2e`).  Off by default so the gated numbers are
# untouched.
CHAOS = os.environ.get("BENCH_E2E_CHAOS", "") in ("1", "true", "yes")
CHAOS_ROUNDS = int(os.environ.get("BENCH_E2E_CHAOS_ROUNDS", 6))
# BENCH_E2E_SHARDS=N (N>1): the sharded control plane.  The invocation
# runs interleaved same-day A/B arms — [N=1, N=N] x BENCH_E2E_AB_PAIRS,
# medians per arm (the machine-drift noise rule: interleaved pairs, not
# single rounds) — and asserts the union of the N shards' placements
# AND flight-recorder reason counts is bit-identical to the unsharded
# oracle arm.  inproc: an in-process replica set (each stack built
# under its scoped ShardMap).  http: N real replica subprocesses
# (kubeadmiral_tpu.testing.shardreplica) over the farm, each holding
# its kt-shard-<i> lease.
N_SHARDS = int(os.environ.get("BENCH_E2E_SHARDS", 1))
# Cores this process may actually run on: the sharded A/B gate keys off
# this — a 1-core container cannot show parallel speedup no matter how
# good the sharding is, so bench_gate waives the speedup floor (and
# gates bounded overhead instead) when cpu_cores < shards.
try:
    CPU_CORES = len(os.sched_getaffinity(0))
except (AttributeError, OSError):
    CPU_CORES = os.cpu_count() or 1
AB_PAIRS = int(os.environ.get("BENCH_E2E_AB_PAIRS", 2))


def _coalesce_detail() -> dict:
    """The write-path knob state this round ran under (bench detail)."""
    from kubeadmiral_tpu.federation import dispatch as D

    return {
        "enabled": D.write_coalesce(),
        "member_batch": D.member_batch(),
        "member_inflight": D.member_inflight(),
    }


class StageTimer:
    """Wraps each controller's worker.step() with cumulative timing."""

    def __init__(self, named_controllers):
        self.stages = {name: 0.0 for name, _ in named_controllers}
        self.controllers = named_controllers

    def settle(self, max_rounds=10_000):
        if TRANSPORT == "http":
            # Watch events arrive asynchronously over sockets: quiesce
            # only after `grace` consecutive idle polls.
            deadline = time.monotonic() + 600.0
            idle = 0
            while time.monotonic() < deadline and idle < 12:
                progressed = False
                for name, ctl in self.controllers:
                    t0 = time.perf_counter()
                    stepped = True
                    while stepped:
                        stepped = ctl.worker.step()
                        progressed |= stepped
                    self.stages[name] += time.perf_counter() - t0
                if progressed:
                    idle = 0
                else:
                    idle += 1
                    time.sleep(0.05)
            return
        for _ in range(max_rounds):
            progressed = False
            for name, ctl in self.controllers:
                t0 = time.perf_counter()
                stepped = True
                # Drain this controller fully before moving on: batch
                # controllers amortize best over a full queue.
                while stepped:
                    stepped = ctl.worker.step()
                    progressed |= stepped
                self.stages[name] += time.perf_counter() - t0
            if not progressed:
                # Keys may be pending but not yet DUE (admission
                # backpressure defers enqueues under deep queues,
                # KT_ADMIT_DEPTH): wait those short fuses out instead of
                # quiescing early — but long-fuse requeues (heartbeats,
                # WAITING_FOR_REMOVAL revisits) still read as idle,
                # exactly as before.
                dues = [
                    d
                    for _, ctl in self.controllers
                    for d in (ctl.worker.queue.next_due_in(),)
                    if d is not None and d <= 0.25
                ]
                if not dues:
                    return
                time.sleep(min(dues) + 0.002)

    def settle_sharded(self, groups, max_rounds=10_000):
        """Inproc N-shard settle: each replica's controller stack drains
        in its OWN thread per round (replicas own disjoint keys; the COW
        store is lock-safe for concurrent writers) while the cluster
        singleton steps on the main thread.  On multi-core hosts this is
        where the sharded speedup comes from; on a single core the GIL
        serializes the threads and the A/B measures pure sharding
        overhead instead (bench_gate keys the speedup floor off
        detail.cpu_cores).  Stage seconds stay per-stage aggregates
        across replicas (the += merge), not wall time."""
        import threading

        lock = threading.Lock()
        cluster = [(n, c) for n, c in self.controllers if n == "cluster"]

        def drain(gi, group, flags):
            prog = False
            for name, ctl in group:
                t0 = time.perf_counter()
                stepped = True
                while stepped:
                    stepped = ctl.worker.step()
                    prog |= stepped
                dt = time.perf_counter() - t0
                with lock:
                    self.stages[name] += dt
            flags[gi] = prog

        for _ in range(max_rounds):
            progressed = False
            for name, ctl in cluster:
                t0 = time.perf_counter()
                while ctl.worker.step():
                    progressed = True
                self.stages[name] += time.perf_counter() - t0
            flags = [False] * len(groups)
            threads = [
                threading.Thread(
                    target=drain, args=(gi, group, flags), daemon=True
                )
                for gi, group in enumerate(groups)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            progressed |= any(flags)
            if not progressed:
                dues = [
                    d
                    for _, ctl in self.controllers
                    for d in (ctl.worker.queue.next_due_in(),)
                    if d is not None and d <= 0.25
                ]
                if not dues:
                    return
                time.sleep(min(dues) + 0.002)


def run_chaos(fleet, farm, timer, ftc, members) -> dict:
    """Degraded-fleet phase: partition one member, flap another, churn a
    slice of objects per round, and report how long each settle round
    ("tick") stalls plus the shed-write tally — the e2e measurement of
    ROADMAP item 5's "a member outage can't stall the tick loop".

    Also the SLO layer's fault-injection proof (ISSUE 13): the phase
    ASSERTS the freshness gauges actually move — oldest-pending rises
    while the hard-down member holds placements hostage and recovers
    after the fault clears — and reports the burn-rate transitions under
    ``detail.chaos.slo``."""
    from kubeadmiral_tpu.runtime import slo as SLO
    from kubeadmiral_tpu.transport import breaker as B
    from kubeadmiral_tpu.transport.faults import (
        FaultInjector,
        FaultPolicy,
        FaultyKube,
    )

    names = sorted(members)
    if len(names) < 3:
        return {"skipped": "needs >= 3 members"}
    # Partition the members actually HOLDING placements: the Divide
    # planner gives capacity-proportional shares, so the lowest-capacity
    # members (the first names) may legitimately hold zero objects — a
    # partition there stalls nothing and the freshness assertion would
    # measure an empty signal.
    by_load = sorted(
        names,
        key=lambda n: len(members[n].keys(ftc.source.resource)),
        reverse=True,
    )
    down, flappy = by_load[0], by_load[1]
    hard = FaultPolicy(partition=True)
    flap = FaultPolicy(partition=True, flap_period_s=0.5, flap_duty=0.4)
    injector = None
    if farm is not None:
        # Subprocess members are injectable too: farm.set_fault routes
        # through the member's fault-control endpoint (POST /faultz).
        # Degraded-mode rounds are bounded by the member-client timeout
        # (one probe/read pays it before the breaker opens): use a
        # chaos-appropriate budget instead of the default 10 s.
        fleet.factory.timeout = 2.0
        for client in fleet.members.values():
            client._timeout = 2.0
        farm.set_fault(down, hard)
        farm.set_fault(flappy, flap)
    else:
        # In-process fleet: wrap the two members in fault proxies (the
        # client-side half of the injection seam).
        injector = FaultInjector()
        for name, policy in ((down, hard), (flappy, flap)):
            fleet.members[name] = FaultyKube(
                fleet.members[name], name, injector, timeout=0.2
            )
            injector.set_fault(name, policy)

    rec = SLO.get_default()
    went_red: set = set()
    oldest_peak = 0.0
    durations = []
    from kubeadmiral_tpu.federation import dispatch as D
    from kubeadmiral_tpu.utils.unstructured import copy_json

    for r in range(CHAOS_ROUNDS):
        # One bulk round trip fetches the whole churn slice: the harness
        # must not serialize per-key on the store it is measuring.
        churn_keys = [
            f"default/web-{i:05d}" for i in range(r % 3, min(N_OBJECTS, 120), 3)
        ]
        got = D.bulk_get(fleet.host, ftc.source.resource, churn_keys) or {}
        for obj in got.values():
            if obj is None:
                continue
            try:
                obj = copy_json(obj)  # bulk results are read-only views
                obj["spec"]["replicas"] = (obj["spec"].get("replicas", 1) % 20) + 1
                fleet.host.update(ftc.source.resource, obj)
            except Exception:
                pass  # churn races are part of the scenario
        t0 = time.perf_counter()
        timer.settle()
        durations.append(time.perf_counter() - t0)
        if rec.enabled:
            status = rec.evaluate()
            oldest_peak = max(oldest_peak, rec.oldest_pending_seconds())
            went_red.update(n for n, e in status.items() if e.get("red"))

    # Clear faults and let the world converge before teardown.
    if farm is not None:
        farm.clear_fault(down)
        farm.clear_fault(flappy)
    else:
        injector.clear_all()
        for name in (down, flappy):
            proxy = fleet.members[name]
            fleet.members[name] = proxy._inner
            proxy.drain_stalled()
    # Recovery is paced by worker backoff requeues and the breaker's
    # half-open cool-down: keep settling until the shed writes land (the
    # freshness gauges must RECOVER, not just stop rising).
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        timer.settle()
        if not rec.enabled or rec.unwritten_placements() == 0:
            break
        time.sleep(0.25)

    slo_detail = None
    if rec.enabled:
        status = rec.evaluate()
        oldest_after = rec.oldest_pending_seconds()
        red_after = sorted(n for n, e in status.items() if e.get("red"))
        slo_detail = {
            "oldest_pending_peak_s": round(oldest_peak, 3),
            "oldest_pending_after_s": round(oldest_after, 3),
            "unwritten_after": rec.unwritten_placements(),
            "went_red": sorted(went_red),
            "red_after_recovery": red_after,
        }
        # The acceptance assertions: the freshness gauge moved during
        # the hard-down window and came back after recovery.
        assert oldest_peak > 0.2, (
            f"freshness never rose under a hard-down member "
            f"(peak {oldest_peak:.3f}s)"
        )
        assert rec.unwritten_placements() == 0, (
            "shed writes never converged after fault clearance: "
            f"{rec.unwritten_placements()} placements still unwritten"
        )
        assert oldest_after < max(0.5, oldest_peak / 2), (
            f"freshness never recovered (peak {oldest_peak:.3f}s, "
            f"after {oldest_after:.3f}s)"
        )

    registry = getattr(fleet, "_member_breakers", None)
    ranked = sorted(durations)
    snapshot = registry.snapshot() if registry is not None else {}
    return {
        "rounds": CHAOS_ROUNDS,
        "down_member": down,
        "flapping_member": flappy,
        "stall_p50_s": round(ranked[len(ranked) // 2], 3),
        "stall_p99_s": round(ranked[min(len(ranked) - 1, int(len(ranked) * 0.99))], 3),
        "stall_max_s": round(ranked[-1], 3),
        "shed_writes": registry.shed_total() if registry is not None else 0,
        "breaker_opens": sum(
            e.get("opens_total", 0) for e in snapshot.values()
        ),
        "breaker_states": {n: e["state"] for n, e in snapshot.items()
                           if e["state"] != B.CLOSED},
        **({"slo": slo_detail} if slo_detail is not None else {}),
    }


def _controller_set(fleet, ftc, shards):
    """The per-FTC controller stacks as replica GROUPS (one inner list
    per replica — settle_sharded drives each group in its own thread).
    shards>1: N in-process replicas, each constructed under its scoped
    ShardMap so every worker/intake boundary it owns filters to its
    shard; duplicate stage names merge in StageTimer, so per-stage time
    aggregates across replicas."""
    import contextlib

    from kubeadmiral_tpu.federation import shardmap
    from kubeadmiral_tpu.federation.federate import FederateController
    from kubeadmiral_tpu.federation.overridectl import OverrideController
    from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
    from kubeadmiral_tpu.federation.statusctl import StatusController
    from kubeadmiral_tpu.federation.sync import SyncController
    from kubeadmiral_tpu.runtime.flightrec import FlightRecorder
    from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

    groups = []
    for i in range(max(1, shards)):
        ctx = (
            shardmap.scoped(shardmap.ShardMap(shards, i))
            if shards > 1
            else contextlib.nullcontext()
        )
        with ctx:
            # A PRIVATE flight recorder per replica engine: reason-count
            # parity compares per-round recorders, so rounds (and
            # replicas) must not share the process-default ring.
            engine = SchedulerEngine(flight_recorder=FlightRecorder())
            groups.append([
                ("federate", FederateController(fleet.host, ftc)),
                ("schedule", SchedulerController(fleet.host, ftc, engine=engine)),
                ("override", OverrideController(fleet.host, ftc)),
                ("sync", SyncController(fleet, ftc)),
                ("status", StatusController(fleet, ftc)),
            ])
    return groups


def _placement_map(fed_objs) -> dict:
    """Bit-comparable placements (the soakharness fingerprint idiom):
    per fed key, the scheduler-written spec placements + overrides."""
    return {
        key: {
            "placements": (obj.get("spec") or {}).get("placements", []),
            "overrides": (obj.get("spec") or {}).get("overrides", []),
        }
        for key, obj in fed_objs.items()
        if obj is not None
    }


def _reason_map(named, keys) -> dict:
    """{key: reason_counts} unioned across the round's schedule-stage
    flight recorders (disjoint keys under sharding — first hit wins)."""
    out = {}
    for name, ctl in named:
        if name != "schedule":
            continue
        rec = getattr(ctl.engine, "flightrec", None)
        if rec is None or not rec.enabled:
            continue
        for key in keys:
            if key in out:
                continue
            r = rec.lookup(key)
            if r is not None:
                out[key] = [int(n) for n in r.reason_counts]
    return out


def _spawn_replicas(farm, shards):
    """N shardreplica subprocesses over the farm's host; returns
    [(proc, stderr_file)] once every replica reports ready + leased."""
    import subprocess
    import tempfile

    procs = []
    for i in range(shards):
        env = dict(os.environ)
        env["KT_SHARD_COUNT"] = str(shards)
        env["KT_SHARD_INDEX"] = str(i)
        env["KT_REPLICA_HOST_URL"] = farm.host_server.url
        token = getattr(farm.host, "_token", None)
        if token:
            env["KT_REPLICA_HOST_TOKEN"] = token
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        stderr = tempfile.TemporaryFile()
        procs.append(
            (
                subprocess.Popen(
                    [sys.executable, "-m", "kubeadmiral_tpu.testing.shardreplica"],
                    stdin=subprocess.PIPE,
                    stdout=subprocess.PIPE,
                    stderr=stderr,
                    text=True,
                    env=env,
                ),
                stderr,
            )
        )
    for proc, stderr in procs:
        hello = _replica_line(proc, stderr)
        assert hello.get("ok"), f"replica failed to start: {hello}"
    return procs


def _replica_line(proc, stderr, want_type=None) -> dict:
    for line in proc.stdout:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if want_type is None or doc.get("type") == want_type:
            return doc
    try:
        stderr.seek(0)
        tail = stderr.read()[-2000:]
    except Exception:
        tail = b""
    raise RuntimeError(
        f"shard replica died: stderr tail {tail.decode(errors='replace')!r}"
    )


def _replica_reports(procs) -> list:
    for proc, _ in procs:
        proc.stdin.write("report\n")
        proc.stdin.flush()
    return [_replica_line(proc, stderr, "report") for proc, stderr in procs]


def _close_replicas(procs) -> None:
    for proc, _ in procs:
        try:
            proc.stdin.close()
        except Exception:
            pass
    for proc, stderr in procs:
        try:
            proc.wait(timeout=15)
        except Exception:
            proc.kill()
            proc.wait()
        try:
            stderr.close()
        except Exception:
            pass


def _settle_replicated(timer, fleet, ftc, replicas) -> None:
    """Drive the parent-side cluster controller while the shard replica
    subprocesses reconcile over HTTP; done when every replica reports
    settled AND every fed object is fully propagated."""
    from kubeadmiral_tpu.federation import dispatch as D

    deadline = time.monotonic() + 3600.0
    while time.monotonic() < deadline:
        timer.settle()
        reports = _replica_reports(replicas)
        if not all(r.get("settled") for r in reports):
            continue
        fed_keys = fleet.host.keys(ftc.federated.resource)
        if len(fed_keys) < N_OBJECTS:
            continue
        objs = D.bulk_get(fleet.host, ftc.federated.resource, fed_keys) or {}
        done = all(
            o is not None
            and o.get("status", {}).get("clusters")
            and all(c["status"] == "OK" for c in o["status"]["clusters"])
            for o in objs.values()
        )
        if done:
            return
    raise RuntimeError("sharded HTTP settle timed out")


def run_round(shards: int = 1) -> dict:
    """One full pipeline round at ``shards`` control-plane replicas.
    Returns the artifact-shaped ``result`` plus the parity fingerprints
    (``placements``/``reasons``/``replica_reports``) the sharded A/B
    driver compares across arms."""
    import dataclasses

    from kubeadmiral_tpu.runtime import slo as SLO

    slo_rec = SLO.reset_default()

    from kubeadmiral_tpu.federation.clusterctl import (
        FEDERATED_CLUSTERS,
        FederatedClusterController,
        NODES,
    )
    from kubeadmiral_tpu.models.ftc import default_ftcs
    from kubeadmiral_tpu.federation.overridectl import OVERRIDE_POLICIES
    from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
    from kubeadmiral_tpu.testing.fakekube import ClusterFleet

    ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
    ftc = dataclasses.replace(
        ftc,
        controllers=(
            ("kubeadmiral.io/global-scheduler",),
            ("kubeadmiral.io/overridepolicy-controller",),
        ),
    )
    farm = None
    if TRANSPORT == "http":
        from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm

        # KT_FARM_SUBPROCESS=1: members as real separate processes (the
        # reference's kwokctl model) so HTTP numbers stop measuring the
        # single-interpreter GIL (VERDICT r4 #6).
        farm = KwokLiteFarm(
            member_subprocess=os.environ.get("KT_FARM_SUBPROCESS", "")
            in ("1", "true", "yes")
        )
        # Overlap child startup across all members before joining them.
        farm.spawn_members([f"m-{j:04d}" for j in range(N_CLUSTERS)])
        fleet = farm.fleet
    else:
        fleet = ClusterFleet()
    gvk = "apps/v1/Deployment"

    # The cluster controller is a SINGLETON outside any shard scope:
    # cluster pseudo-keys broadcast to every replica, and join/taint
    # bookkeeping must not be split by the hash ring.
    subproc_shards = TRANSPORT == "http" and shards > 1
    named = [
        ("cluster", FederatedClusterController(fleet, api_resource_probe=[gvk])),
    ]
    groups = None
    if not subproc_shards:
        groups = _controller_set(fleet, ftc, shards)
        for group in groups:
            named += group
    timer = StageTimer(named)
    inproc_sharded = not subproc_shards and shards > 1

    def settle():
        if inproc_sharded:
            timer.settle_sharded(groups)
        else:
            timer.settle()

    members = {}
    for j in range(N_CLUSTERS):
        name_j = f"m-{j:04d}"
        member = farm.add_member(name_j) if farm else fleet.add_member(name_j)
        members[name_j] = member
        member.create(
            NODES,
            {
                "apiVersion": "v1",
                "kind": "Node",
                "metadata": {"name": "n1"},
                "spec": {},
                "status": {
                    "allocatable": {"cpu": str(32 + j % 64), "memory": "256Gi"},
                    "conditions": [{"type": "Ready", "status": "True"}],
                },
            },
        )
        fleet.host.create(
            FEDERATED_CLUSTERS,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "FederatedCluster",
                "metadata": {"name": name_j, "labels": {"tier": str(j % 3)}},
                "spec": farm.cluster_spec(name_j) if farm else {},
            },
        )
    fleet.host.create(
        PROPAGATION_POLICIES,
        {
            "apiVersion": "core.kubeadmiral.io/v1alpha1",
            "kind": "PropagationPolicy",
            "metadata": {"name": "pp", "namespace": "default"},
            "spec": {"schedulingMode": "Divide"},
        },
    )
    fleet.host.create(
        OVERRIDE_POLICIES,
        {
            "apiVersion": "core.kubeadmiral.io/v1alpha1",
            "kind": "OverridePolicy",
            "metadata": {"name": "op", "namespace": "default"},
            "spec": {
                "overrideRules": [
                    {
                        "targetClusters": {"clusterSelector": {"tier": "1"}},
                        "overriders": {
                            "jsonpatch": [
                                {
                                    "operator": "add",
                                    "path": "/metadata/annotations/tier",
                                    "value": "one",
                                }
                            ]
                        },
                    }
                ]
            },
        },
    )
    settle()  # join clusters before the clock starts

    replicas = None
    if subproc_shards:
        # Spawned AFTER the join so every replica's replayed first list
        # already carries joined clusters + both policies; each acquires
        # its kt-shard-<i> lease before reporting ready.
        replicas = _spawn_replicas(farm, shards)

    def make_deployment(i):
        return {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {
                "name": f"web-{i:05d}",
                "namespace": "default",
                "labels": {
                    "kubeadmiral.io/propagation-policy-name": "pp",
                    "kubeadmiral.io/override-policy-name": "op",
                },
            },
            "spec": {
                "replicas": (i % 20) + 1,
                "selector": {"matchLabels": {"app": f"web-{i:05d}"}},
                "template": {
                    "metadata": {"labels": {"app": f"web-{i:05d}"}},
                    "spec": {
                        "containers": [
                            {
                                "name": "c",
                                "image": "nginx",
                                "resources": {"requests": {"cpu": "50m"}},
                            }
                        ]
                    },
                },
            },
        }

    t_create = time.perf_counter()
    for i in range(N_OBJECTS):
        fleet.host.create(ftc.source.resource, make_deployment(i))
    create_s = time.perf_counter() - t_create

    # Telemetry timeline riding the measured settle (ISSUE 16): the
    # sampler THREAD (not manual samples — this measures what a
    # production manager pays) scrapes the SLO evaluator + process RSS
    # into the downsampling ring; sample_seconds_total in the artifact
    # is the sampler's own cumulative cost, the "timeline overhead"
    # evidence.  KT_TIMELINE=0 removes the thread entirely.
    from kubeadmiral_tpu.runtime import timeline as TL

    tline = TL.Timeline()
    tline.attach_runtime(slo=slo_rec)
    TL.set_default(tline)
    tline_on = tline.start()

    stages_before = dict(timer.stages)
    t0 = time.perf_counter()
    if subproc_shards:
        _settle_replicated(timer, fleet, ftc, replicas)
    else:
        settle()
    total_s = time.perf_counter() - t0

    tline.stop()
    if tline_on:
        tline.sample_now()  # final scrape so short settles record >= 1

    # Verify full propagation: every placed (object, cluster) pair has a
    # member object and an OK propagation status.  (Divide mode drops
    # zero-replica clusters, so the expected count comes from the actual
    # placements, not N x C.)
    member_objects = sum(
        len(kube.keys(ftc.source.resource)) for kube in members.values()
    )
    expected = 0
    # Bulk point reads: the verification sweep over every fed object
    # must not serialize per-key on the store it just measured.
    from kubeadmiral_tpu.federation import dispatch as D

    fed_keys = fleet.host.keys(ftc.federated.resource)
    fed_objs = D.bulk_get(fleet.host, ftc.federated.resource, fed_keys) or {}
    for key in fed_keys:
        fed = fed_objs.get(key)
        assert fed is not None, key
        statuses = fed.get("status", {}).get("clusters", [])
        assert statuses and all(c["status"] == "OK" for c in statuses), key
        expected += len(statuses)
    propagated = {
        c["cluster"]
        for c in fed_objs["default/web-00000"]["status"]["clusters"]
    }
    # Parity fingerprints for the sharded A/B driver: scheduler-written
    # placements straight off the host, reason counts off this round's
    # private flight recorders (replica subprocesses report hashes of
    # their owned subset instead — collected below with the reports).
    placements = _placement_map(fed_objs)
    reasons = None if subproc_shards else _reason_map(named, fed_keys)

    stages = {
        name: round(timer.stages[name] - stages_before.get(name, 0.0), 3)
        for name in timer.stages
    }
    replica_reports = None
    if subproc_shards:
        replica_reports = _replica_reports(replicas)
        for rep in replica_reports:
            for name, secs in rep["stages_s"].items():
                stages[name] = round(stages.get(name, 0.0) + secs, 3)

    # Stage-decomposed event→placement-written latency (ISSUE 13): the
    # provenance tokens minted at source-event ingress closed on member
    # write acks during the settle above.  p50/p99 from the interpolated
    # histogram snapshot; the decomposition error is measured EXACTLY on
    # the exemplar ring (stage sums vs measured totals per event).
    slo_detail = None
    # Subprocess replicas host their own SLO recorders (tokens mint and
    # close inside the children), so the parent's recorder is empty and
    # the decomposition contract is theirs to keep, not ours.
    if slo_rec.enabled and not subproc_shards:
        summary = slo_rec.summary()
        decomp_err = 0.0
        for ex in summary["slowest"]:
            if ex["total_s"] > 1e-6:
                decomp_err = max(
                    decomp_err,
                    abs(sum(ex["stages_s"].values()) - ex["total_s"])
                    / ex["total_s"],
                )
        total = summary["stages"].get("total") or {}
        slo_detail = {
            "e2e_p50_ms": round((total.get("p50_s") or 0.0) * 1e3, 3),
            "e2e_p99_ms": round((total.get("p99_s") or 0.0) * 1e3, 3),
            "events_written": total.get("count", 0),
            "stages_ms": {
                stage: {
                    "p50": round((entry.get("p50_s") or 0.0) * 1e3, 3),
                    "p99": round((entry.get("p99_s") or 0.0) * 1e3, 3),
                }
                for stage, entry in summary["stages"].items()
                if stage != "total"
            },
            "decomposition_err_pct": round(decomp_err * 100.0, 3),
            "unwritten_placements": summary["unwritten_placements"],
            "objectives": {
                name: {"burn": entry["burn"], "red": entry["red"]}
                for name, entry in summary["objectives"].items()
            },
        }
        # The stage decomposition must sum to the measured end-to-end
        # latency (ISSUE 13 acceptance: within 10% per event).
        assert decomp_err <= 0.10, (
            f"stage decomposition error {decomp_err:.1%} exceeds 10%"
        )
        assert total.get("count", 0) > 0, "no SLO samples closed"

    from kubeadmiral_tpu.bench_support import bench_platform_detail

    result = {
        "metric": (
            f"e2e_objects_per_sec_{N_OBJECTS}x{N_CLUSTERS}"
            + ("_http" if TRANSPORT == "http" else "")
        ),
        "value": round(N_OBJECTS / total_s, 1),
        "unit": "objects/s",
        "detail": {
            "transport": TRANSPORT,
            # The bench-gate baseline key folds (transport, members) in,
            # the way device_count was folded in for engine rounds: a
            # 500-member HTTP round must never gate against (or seed)
            # an in-process 50-member baseline.
            "members": N_CLUSTERS,
            # ... and now (transport, members, shards): an N=4 sharded
            # round must never gate against an unsharded baseline.
            "shards": shards,
            "cpu_cores": CPU_CORES,
            "write_coalesce": _coalesce_detail(),
            "farm": (
                ("subprocess" if farm.member_subprocess else "inproc")
                if farm is not None
                else None
            ),
            **bench_platform_detail(),
            "total_s": round(total_s, 2),
            "create_s": round(create_s, 2),
            "stages_s": stages,
            "member_objects": member_objects,
            "member_objects_expected": expected,
            "member_writes_per_sec": round(member_objects / total_s, 1),
            **({"slo": slo_detail} if slo_detail is not None else {}),
            # Stats only (series filter matches nothing): the ring's
            # size/cost accounting without the multi-KB series payload.
            "timeline": {
                k: tline.to_doc(series="\x00")[k]
                for k in (
                    "enabled",
                    "samples_total",
                    "approx_bytes",
                    "sample_seconds_total",
                )
            },
        },
    }
    assert member_objects == expected, (member_objects, expected)
    assert propagated  # first object reached its placed members
    if farm is not None:
        # Fleet pane over the farm (ISSUE 17): one merged scrape of
        # every member's /metrics — the round's evidence that the whole
        # farm was observable, not just reachable.  Sample counts per
        # instance, not series payloads: a 500-member dump would
        # dominate the artifact.
        from kubeadmiral_tpu.runtime import fleetscrape

        pane = fleetscrape.FleetScraper(roster=farm.scrape_roster).scrape()
        samples = sorted(
            inst.get("samples", 0) for inst in pane["instances"].values()
        )
        result["detail"]["fleet"] = {
            "instances": len(pane["instances"]),
            "scrape_errors": pane["scrape_errors"],
            "scrape_seconds": pane["scrape_seconds"],
            "down": sorted(
                name
                for name, inst in pane["instances"].items()
                if not inst.get("up")
            ),
            "samples_min": samples[0] if samples else 0,
            "samples_max": samples[-1] if samples else 0,
            "samples_per_instance": {
                name: inst.get("samples", 0)
                for name, inst in sorted(pane["instances"].items())
            },
        }
    if CHAOS and shards == 1:
        result["detail"]["chaos"] = run_chaos(fleet, farm, timer, ftc, members)
    if replicas is not None:
        _close_replicas(replicas)
    if farm is not None:
        farm.close()
    print(f"# shards={shards} stages: {stages}", file=sys.stderr)
    return {
        "result": result,
        "placements": placements,
        "reasons": reasons,
        "replica_reports": replica_reports,
    }


def _median_idx(values) -> int:
    order = sorted(range(len(values)), key=lambda i: values[i])
    return order[len(order) // 2]


def main():
    from kubeadmiral_tpu.runtime.gctune import tune_gc_for_service

    tune_gc_for_service()

    # Chaos rounds are seconds-long, not minutes: tighten the SLO
    # freshness threshold and burn windows so the red→green transition
    # is observable inside the phase (set BEFORE the recorder's first
    # construction — thresholds are read once).
    if CHAOS:
        os.environ.setdefault("KT_SLO_FRESHNESS_S", "1.0")
        os.environ.setdefault("KT_SLO_WINDOWS_S", "3,10")

    if N_SHARDS <= 1:
        print(json.dumps(run_round(1)["result"]))
        return

    assert not CHAOS, "chaos is an unsharded mode: run it with BENCH_E2E_SHARDS=1"
    from kubeadmiral_tpu.federation import shardmap
    from kubeadmiral_tpu.utils.hashing import stable_json_hash

    # Interleaved same-day A/B arms (the ±12% noise rule): [1, N] per
    # pair so both arms see the same machine weather, medians per arm so
    # one noisy round decides nothing.
    arms = {1: [], N_SHARDS: []}
    for _pair in range(max(1, AB_PAIRS)):
        for n in (1, N_SHARDS):
            arms[n].append(run_round(n))

    # Placement parity: the union of N shards' scheduler output must be
    # bit-identical to the unsharded oracle.  The pipeline is
    # deterministic for a fixed world, so this is exact, not
    # statistical — every round is held to the first oracle round.
    oracle = arms[1][0]
    oracle_hash = stable_json_hash(oracle["placements"])
    for arm_n, rounds in arms.items():
        for r in rounds:
            got = stable_json_hash(r["placements"])
            assert got == oracle_hash, (
                f"placement parity broken: shards={arm_n} "
                f"hash {got} != oracle {oracle_hash}"
            )

    # Reason-count parity: inproc rounds carry the full {key: counts}
    # map; subprocess replicas report stable hashes of their owned
    # subset, which the oracle map is re-sliced against (so parity never
    # ships a 100k-key payload over the pipe).
    oracle_reasons = oracle["reasons"]
    reasons_parity = "not-recorded"
    if oracle_reasons:
        for r in arms[N_SHARDS]:
            if r["reasons"] is not None:
                assert r["reasons"] == oracle_reasons, (
                    "reason-count parity broken (inproc replica set)"
                )
                reasons_parity = "bit-identical"
            elif r["replica_reports"] is not None:
                for rep in r["replica_reports"]:
                    m = shardmap.ShardMap(N_SHARDS, rep["shard"])
                    subset = {
                        k: v for k, v in oracle_reasons.items() if m.owns(k)
                    }
                    assert rep["reasons_hash"] == stable_json_hash(subset), (
                        f"reason-count parity broken: shard {rep['shard']} "
                        f"({rep['reasons_keys']} keys vs oracle {len(subset)})"
                    )
                reasons_parity = "bit-identical"

    vals = {n: [r["result"]["value"] for r in rounds] for n, rounds in arms.items()}
    med1 = sorted(vals[1])[len(vals[1]) // 2]
    medN = sorted(vals[N_SHARDS])[len(vals[N_SHARDS]) // 2]
    head = arms[N_SHARDS][_median_idx(vals[N_SHARDS])]["result"]
    head["detail"]["sharded_ab"] = {
        "shards": N_SHARDS,
        "pairs": max(1, AB_PAIRS),
        "interleaved": True,
        "cpu_cores": CPU_CORES,
        "arm_objects_per_sec": {"s1": vals[1], f"s{N_SHARDS}": vals[N_SHARDS]},
        "arm_medians": {"s1": med1, f"s{N_SHARDS}": medN},
        "speedup": round(medN / med1, 3) if med1 else None,
        "parity": {"placements": "bit-identical", "reasons": reasons_parity},
    }
    print(json.dumps(head))


if __name__ == "__main__":
    from kubeadmiral_tpu.bench_support import run_resilient

    run_resilient(main, __file__)
