"""Telemetry spill durability (runtime/telespill.py) + cross-process
trace propagation (runtime/trace.py <-> transport) — the fleet
observatory's crash-and-correlate contracts:

* CRC-framed segments: torn tails, truncated frames, corrupt payloads
  and bad magic all quarantine (``*.quarantined``) while every
  fully-framed prefix record is salvaged;
* a SIGKILL mid-append loses at most the torn tail — a subprocess
  killed with a half-written frame yields every completed record;
* rotation keeps an instance's segments under the byte bound;
* KT_SPILL=0 leaves ZERO files;
* traceparent headers parent a server-side apiserver span under the
  client's span — across a real HTTP hop — and the Chrome export
  carries the wall-epoch anchor trace_assemble aligns lanes with.
"""

from __future__ import annotations

import json
import os
import signal
import struct
import subprocess
import sys
import time
import zlib

import pytest

from kubeadmiral_tpu.runtime import telespill, trace
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.telespill import (
    MAGIC,
    SpillWriter,
    TelemetrySpiller,
    load_dir,
    read_segment,
)


def _segments(directory):
    return sorted(
        de.name for de in os.scandir(directory)
        if de.name.endswith(".ktspill")
    )


def _one_segment_path(directory):
    names = _segments(directory)
    assert len(names) == 1, names
    return os.path.join(directory, names[0])


class TestSegmentDurability:
    def test_roundtrip(self, tmp_path):
        w = SpillWriter(str(tmp_path), instance="a")
        for i in range(5):
            assert w.append("spans", {"kind": "spans", "i": i})
        w.close()
        records = load_dir(str(tmp_path))
        assert [r["i"] for r in records] == list(range(5))

    def test_torn_tail_salvages_prefix_and_quarantines(self, tmp_path):
        w = SpillWriter(str(tmp_path), instance="a")
        for i in range(3):
            w.append("spans", {"i": i})
        w.close()
        path = _one_segment_path(tmp_path)
        with open(path, "ab") as fh:
            fh.write(struct.pack("<II", 100, 0))
            fh.write(b'{"torn": tr')  # tail cut mid-payload
        records, damaged = read_segment(path)
        assert damaged
        assert [r["i"] for r in records] == [0, 1, 2]
        assert not os.path.exists(path)
        assert os.path.exists(path + ".quarantined")

    def test_crc_corruption_quarantines(self, tmp_path):
        w = SpillWriter(str(tmp_path), instance="a")
        w.append("spans", {"i": 0})
        w.append("spans", {"i": 1})
        w.close()
        path = _one_segment_path(tmp_path)
        blob = bytearray(open(path, "rb").read())
        # Flip a byte inside the LAST record's payload: CRC must catch
        # it, the first record must still load.
        blob[-2] ^= 0xFF
        with open(path, "wb") as fh:
            fh.write(blob)
        records, damaged = read_segment(path)
        assert damaged
        assert [r["i"] for r in records] == [0]
        assert os.path.exists(path + ".quarantined")

    def test_bad_magic_quarantines_empty(self, tmp_path):
        path = tmp_path / "spill-x-1-000000.ktspill"
        path.write_bytes(b"NOTMAGIC" + b"x" * 64)
        records, damaged = read_segment(str(path))
        assert damaged and records == []
        assert os.path.exists(str(path) + ".quarantined")

    def test_quarantined_files_not_reloaded(self, tmp_path):
        w = SpillWriter(str(tmp_path), instance="a")
        w.append("spans", {"i": 0})
        w.close()
        path = _one_segment_path(tmp_path)
        with open(path, "ab") as fh:
            fh.write(b"\x01")  # short frame header: torn
        assert len(load_dir(str(tmp_path))) == 1  # salvaged + quarantined
        assert load_dir(str(tmp_path)) == []  # second pass: nothing left

    def test_sigkill_mid_append_recovers_framed_records(self, tmp_path):
        """A child writes 10 records, starts an 11th frame and SIGKILLs
        itself mid-payload: the parent must recover exactly the 10."""
        child = (
            "import os, signal, struct, sys\n"
            "from kubeadmiral_tpu.runtime.telespill import SpillWriter\n"
            "w = SpillWriter(sys.argv[1], instance='victim')\n"
            "for i in range(10):\n"
            "    w.append('spans', {'i': i})\n"
            "w._fh.write(struct.pack('<II', 999, 12345))\n"
            "w._fh.write(b'{\"half\": ')\n"
            "w._fh.flush()\n"
            "os.kill(os.getpid(), signal.SIGKILL)\n"
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.run(
            [sys.executable, "-c", child, str(tmp_path)],
            env=env, timeout=120, capture_output=True, text=True,
        )
        assert proc.returncode == -signal.SIGKILL, proc.stderr
        records = load_dir(str(tmp_path))
        assert [r["i"] for r in records] == list(range(10))
        assert any(
            n.endswith(".quarantined") for n in os.listdir(tmp_path)
        )

    def test_rotation_respects_byte_bound(self, tmp_path):
        w = SpillWriter(
            str(tmp_path), instance="a",
            max_bytes=64 << 10, segment_bytes=8 << 10,
        )
        payload = {"blob": "x" * 512}
        for i in range(400):  # ~200 KiB of records through an 8 KiB grain
            w.append("spans", dict(payload, i=i))
        w.close()
        total = sum(
            os.path.getsize(os.path.join(tmp_path, n))
            for n in _segments(tmp_path)
        )
        # Bound holds up to one segment of slack (the open segment
        # never deletes itself; pruning runs at rotation).
        assert total <= (64 << 10) + (8 << 10) + 1024
        assert len(_segments(tmp_path)) > 1
        # The NEWEST records survive pruning.
        records = load_dir(str(tmp_path))
        assert records and records[-1]["i"] == 399

    def test_kt_spill_off_leaves_zero_files(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KT_SPILL", "0")
        w = SpillWriter(str(tmp_path / "spill"), instance="a")
        assert not w.append("spans", {"i": 0})
        spiller = TelemetrySpiller(
            directory=str(tmp_path / "spill2"), instance="a"
        )
        assert not spiller.start()
        assert spiller.spill_now() == 0
        spiller.stop()
        assert not os.path.exists(tmp_path / "spill")
        assert not os.path.exists(tmp_path / "spill2")


class _NoRecorder:
    """Flight-recorder stub: spiller tests must not pick up whatever the
    process-default recorder accumulated in other tests."""

    enabled = False

    def decisions(self):
        return {}


class _NoTimeline:
    enabled = False


class TestTelemetrySpiller:
    def test_span_delta_spill(self, tmp_path):
        tracer = trace.Tracer()
        with tracer.span("tick", n=1):
            with tracer.span("inner"):
                pass
        spiller = TelemetrySpiller(
            directory=str(tmp_path), instance="mgr", tracer=tracer,
            timeline=_NoTimeline(), flightrec=_NoRecorder(),
        )
        assert spiller.spill_now() == 1
        # No new spans -> no new records (delta, not dump).
        assert spiller.spill_now() == 0
        with tracer.span("tick", n=2):
            pass
        assert spiller.spill_now() == 1
        records = [r for r in load_dir(str(tmp_path)) if r["kind"] == "spans"]
        names = [s["name"] for r in records for s in r["spans"]]
        assert names.count("tick") == 2 and "inner" in names
        env = records[0]
        assert {"instance", "pid", "wall", "mono", "wall_epoch"} <= set(env)
        inner = next(
            s for r in records for s in r["spans"] if s["name"] == "inner"
        )
        tick = next(
            s for r in records for s in r["spans"] if s["name"] == "tick"
        )
        assert inner["parent_id"] == tick["span_id"]
        assert inner["trace_id"] == tick["trace_id"]

    def test_timeline_raw_tier_delta(self, tmp_path):
        from kubeadmiral_tpu.runtime.timeline import Timeline

        m = Metrics()
        tl = Timeline(metrics=m, interval_s=0.05)
        tracer = trace.Tracer()
        spiller = TelemetrySpiller(
            directory=str(tmp_path), instance="mgr", tracer=tracer,
            timeline=tl, flightrec=_NoRecorder(),
        )
        m.counter("worker_retries_total", controller="sync")
        tl.sample_now(now=1.0)
        assert spiller.spill_now() == 1
        m.counter("worker_retries_total", controller="sync")
        tl.sample_now(now=2.0)
        assert spiller.spill_now() == 1
        records = [
            r for r in load_dir(str(tmp_path)) if r["kind"] == "timeline"
        ]
        assert len(records) == 2
        all_points = [
            p
            for r in records
            for s in r["series"].values()
            for p in s["points"]
        ]
        times = sorted(p[0] for p in all_points)
        # Delta semantics: the second record re-spills nothing from t=1.
        assert times[0] == 1.0 and times[-1] == 2.0
        t1_points = [p for p in all_points if p[0] == 1.0]
        assert len(t1_points) == len(
            [p for p in all_points if p[0] == 2.0]
        )


class TestTraceParent:
    def test_format_parse_roundtrip(self):
        tid = "a" * 32
        header = trace.format_traceparent(tid, 0x1234)
        assert header == f"00-{tid}-0000000000001234-01"
        assert trace.parse_traceparent(header) == (tid, 0x1234)

    @pytest.mark.parametrize(
        "bad",
        [
            None,
            "",
            "garbage",
            "01-" + "a" * 32 + "-" + "b" * 16 + "-01",  # unknown version
            "00-" + "a" * 31 + "-" + "b" * 16 + "-01",  # short trace id
            "00-" + "a" * 32 + "-" + "b" * 15 + "-01",  # short span id
            "00-" + "0" * 32 + "-" + "b" * 16 + "-01",  # zero trace id
            "00-" + "a" * 32 + "-" + "0" * 16 + "-01",  # zero span id
            "00-" + "z" * 32 + "-" + "b" * 16 + "-01",  # non-hex
        ],
    )
    def test_parse_rejects_malformed(self, bad):
        assert trace.parse_traceparent(bad) is None

    def test_children_inherit_trace_id(self):
        tracer = trace.Tracer()
        with tracer.span("root") as root:
            assert tracer.current_traceparent() == root.traceparent()
            with tracer.span("child") as child:
                assert child.trace_id == root.trace_id
                assert child.parent_id == root.span_id
        with tracer.span("other") as other:
            assert other.trace_id != root.trace_id

    def test_span_from_explicit_parent_across_threads(self):
        import threading

        tracer = trace.Tracer()
        seen = {}

        def work(parent):
            with tracer.span_from("pool-work", parent) as sp:
                seen["span"] = sp

        with tracer.span("flush") as flush:
            t = threading.Thread(target=work, args=(flush,))
            t.start()
            t.join()
        assert seen["span"].trace_id == flush.trace_id
        assert seen["span"].parent_id == flush.span_id

    def test_server_span_adopts_header(self):
        tracer = trace.Tracer()
        header = trace.format_traceparent("ab" * 16, 77)
        with tracer.server_span("apiserver.batch", header) as sp:
            assert sp.trace_id == "ab" * 16
            assert sp.parent_id == 77
            assert sp.args.get("remote_parent") is True
        with tracer.server_span("apiserver.batch", "garbage") as sp:
            assert sp.parent_id is None

    def test_chrome_trace_wall_epoch_anchor(self):
        tracer = trace.Tracer()
        with tracer.span("a"):
            pass
        doc = tracer.chrome_trace()
        other = doc["otherData"]
        assert other["pid"] == os.getpid()
        assert abs(other["wall_epoch"] - trace.wall_epoch()) < 1.0
        ev = next(e for e in doc["traceEvents"] if e["ph"] == "X")
        assert ev["args"]["trace_id"]
        # wall_epoch + ts lands at "now", not 1970 or 0.
        assert abs(
            (other["wall_epoch"] + ev["ts"] / 1e6) - time.time()
        ) < 60.0


class TestHttpPropagation:
    def test_server_side_child_span_over_http(self):
        from kubeadmiral_tpu.testing.fakekube import FakeKube
        from kubeadmiral_tpu.transport.apiserver import KubeApiServer
        from kubeadmiral_tpu.transport.client import HttpKube

        server = KubeApiServer(FakeKube("m0"), metrics=Metrics())
        client = HttpKube(server.url, name="m0")
        default_tracer = trace.get_default()
        before = {sp.span_id for sp in default_tracer.spans()}
        try:
            with trace.span("dispatch.member_write", cluster="m0") as mine:
                client.batch(
                    [
                        {
                            "verb": "create",
                            "resource": "v1/configmaps",
                            "object": {
                                "metadata": {
                                    "name": "c1", "namespace": "default"
                                }
                            },
                        }
                    ]
                )
            # The server span lands in the ring on the handler thread
            # AFTER the response bytes flush — poll briefly.
            deadline = time.monotonic() + 5.0
            server_spans: list = []
            while not server_spans and time.monotonic() < deadline:
                server_spans = [
                    sp for sp in default_tracer.spans()
                    if sp.span_id not in before
                    and sp.name == "apiserver.batch"
                ]
                if not server_spans:
                    time.sleep(0.01)
            assert server_spans, [
                sp.name for sp in default_tracer.spans()
                if sp.span_id not in before
            ]
            sp = server_spans[-1]
            assert sp.trace_id == mine.trace_id
            assert sp.parent_id == mine.span_id
            assert sp.args.get("remote_parent") is True
            assert sp.args.get("ops") == 1
            # The request verb was counted for the fleet pane.
            assert server.metrics.counters  # apiserver_requests_total
        finally:
            client.close()
            server.close()

    def test_no_open_span_sends_no_header(self):
        from kubeadmiral_tpu.transport.client import HttpKube

        client = HttpKube("http://127.0.0.1:1", name="x")
        assert "traceparent" not in client._headers()
        with trace.span("outer"):
            assert "traceparent" in client._headers()
