"""ktlint's own coverage (ISSUE 14 satellite): every rule trips on its
known-bad fixture, passes its known-good twin, and the full-tree run is
clean — the `make lint` contract, asserted from the suite so a rule
regression (or a repo regression) fails tests even when `make lint`
is skipped.

Fixtures live in tests/fixtures/ktlint/ and are PARSED, never imported
— a fixture full of deliberate violations must lint without executing.
"""

from pathlib import Path

import pytest

from kubeadmiral_tpu.runtime.knob_catalog import KNOBS, KnobSpec
from tools.ktlint import all_rules, run, run_rules, rule_by_id

FIXTURES = Path(__file__).parent / "fixtures" / "ktlint"


def _run_rule(rule_id: str, *fixtures: str):
    rule = rule_by_id(rule_id)
    violations, _ = run_rules([rule], paths=[FIXTURES / f for f in fixtures])
    return [v for v in violations if v.rule == rule_id], rule


# -- per-rule fixture pairs: bad must trip, good twin must pass ----------

CASES = [
    ("aot-ledger-coverage", "bad_unwrapped_jit.py", "good_wrapped_jit.py"),
    ("sharding-discipline", "bad_uncontracted_sort.py",
     "good_contracted_sort.py"),
    ("shard-intake-coverage", "bad_unsharded_watch.py",
     "good_shard_intake_watch.py"),
    ("donation-discipline", "bad_read_after_donate.py",
     "good_rebound_after_donate.py"),
    ("knob-catalog", "bad_undeclared_knob.py", "good_declared_knob.py"),
    ("lock-discipline", "bad_offlock_write.py", "good_locked_write.py"),
]


@pytest.mark.parametrize("rule_id,bad,good", CASES)
def test_bad_fixture_trips(rule_id, bad, good):
    violations, _ = _run_rule(rule_id, bad)
    assert violations, f"{bad} must trip {rule_id}"


@pytest.mark.parametrize("rule_id,bad,good", CASES)
def test_good_twin_passes(rule_id, bad, good):
    violations, _ = _run_rule(rule_id, good)
    assert violations == [], (
        f"{good} must pass {rule_id}: " + "\n".join(v.format() for v in violations)
    )


def test_bad_fixtures_trip_for_the_right_reason():
    """Spot-check messages so a rule that trips on the WRONG line
    doesn't vacuously satisfy the pair contract."""
    v, _ = _run_rule("aot-ledger-coverage", "bad_unwrapped_jit.py")
    assert any("@jax.jit" in x.message for x in v)
    assert any("AotStore.wrap" in x.message for x in v)
    v, _ = _run_rule("donation-discipline", "bad_read_after_donate.py")
    assert any("'prev'" in x.message for x in v)
    v, _ = _run_rule("knob-catalog", "bad_undeclared_knob.py")
    assert {"KT_TOTALLY_UNDECLARED_KNOB", "KT_ANOTHER_ROGUE_KNOB"} <= {
        x.message.split("'")[1] for x in v
    }
    v, _ = _run_rule("lock-discipline", "bad_offlock_write.py")
    assert any(".append()" in x.message for x in v)
    assert any("rebind" in x.message for x in v)
    v, _ = _run_rule("shard-intake-coverage", "bad_unsharded_watch.py")
    assert len(v) == 2  # the watch() and the watch_members() site
    assert all("ShardIntake" in x.message for x in v)


# -- suppressions --------------------------------------------------------

def test_suppression_with_reason_silences_the_rule():
    violations, _ = run(
        rule_ids=["aot-ledger-coverage"],
        paths=[FIXTURES / "good_suppressed.py"],
    )
    assert violations == []


def test_suppression_without_reason_is_itself_a_violation():
    violations, _ = run(
        rule_ids=["aot-ledger-coverage"],
        paths=[FIXTURES / "bad_suppression_no_reason.py"],
    )
    rules_hit = {v.rule for v in violations}
    # The malformed suppression reports AND does not silence the rule.
    assert "suppression-format" in rules_hit
    assert "aot-ledger-coverage" in rules_hit


# -- the make-lint contract: full tree clean, denominators real ----------

def test_full_tree_is_clean():
    violations, summary = run()
    assert violations == [], "\n".join(v.format() for v in violations)
    assert set(summary.values()) == {0}


def test_rules_actually_saw_the_tree():
    """Zero violations must come from inspection, not a walker that
    matched nothing.  The jit floor also replaces the old
    test_aot_coverage source enumeration: engine.py alone holds 40+
    sites, so a count below that means the rule lost the tree."""
    rules = all_rules()
    run_rules(rules)
    stats = {r.id: r.stats for r in rules}
    assert stats["aot-ledger-coverage"]["jit_sites"] >= 40
    assert stats["sharding-discipline"]["sort_sites"] >= 10
    assert stats["shard-intake-coverage"]["watch_sites"] >= 25
    assert stats["shard-intake-coverage"]["dropped_at_intake"] >= 4
    assert stats["shard-intake-coverage"]["worker_routed"] >= 15
    assert stats["donation-discipline"]["dispatch_sites"] >= 10
    assert stats["knob-catalog"]["knob_reads"] >= 60
    assert stats["lock-discipline"]["declared_classes"] >= 5
    assert stats["lock-discipline"]["mutation_sites"] >= 50


# -- knob catalog shape --------------------------------------------------

def test_knob_catalog_shape():
    assert len(KNOBS) >= 60
    for name, spec in KNOBS.items():
        assert name.startswith("KT_"), name
        assert isinstance(spec, KnobSpec)
        assert spec.type in ("bool", "int", "float", "str", "path"), name
        assert spec.anchor in ("operations.md", "observability.md"), name
        assert spec.help, name
