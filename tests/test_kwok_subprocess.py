"""Multi-process kwok-lite farm: member apiservers as real subprocesses
(VERDICT r4 #6 — the reference's kwokctl model, one process per fake
cluster, kwokprovider.go:70-260)."""

import json

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.statusctl import StatusController
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm


def settle(*controllers, rounds=400):
    import time

    idle = 0
    while idle < 10 and rounds:
        rounds -= 1
        progressed = False
        for ctl in controllers:
            while ctl.worker.step():
                progressed = True
        if progressed:
            idle = 0
        else:
            idle += 1
            time.sleep(0.05)


def test_subprocess_members_propagate_and_collect():
    farm = KwokLiteFarm(member_subprocess=True)
    try:
        fleet = farm.fleet
        admins = {}
        for name in ("p1", "p2"):
            admins[name] = farm.add_member(name)
            fleet.host.create(
                C.FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": farm.cluster_spec(name),
                    "status": {
                        "conditions": [
                            {"type": "Joined", "status": "True"},
                            {"type": "Ready", "status": "True"},
                        ]
                    },
                },
            )
        assert len(farm.member_procs) == 2
        pids = {p.pid for p in farm.member_procs.values()}
        assert len(pids) == 2  # really separate processes

        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        sync = SyncController(fleet, ftc)
        status = StatusController(fleet, ftc)

        fed = {
            "apiVersion": "types.kubeadmiral.io/v1alpha1",
            "kind": "FederatedDeployment",
            "metadata": {
                "name": "web",
                "namespace": "default",
                "annotations": {pending.PENDING_CONTROLLERS: json.dumps([])},
            },
            "spec": {
                "template": {
                    "apiVersion": "apps/v1",
                    "kind": "Deployment",
                    "metadata": {"name": "web", "namespace": "default"},
                    "spec": {
                        "replicas": 2,
                        "template": {
                            "metadata": {"labels": {"app": "web"}},
                            "spec": {"containers": [{"name": "c", "image": "nginx"}]},
                        },
                    },
                },
                "placements": [
                    {
                        "controller": C.SCHEDULER,
                        "placement": [{"cluster": "p1"}, {"cluster": "p2"}],
                    }
                ],
            },
        }
        fleet.host.create(ftc.federated.resource, fed)
        settle(sync, status)

        # Propagated into both member processes (read via admin clients).
        for name, admin in admins.items():
            obj = admin.try_get(ftc.source.resource, "default/web")
            assert obj is not None, name
            assert obj["metadata"]["labels"][C.MANAGED_LABEL] == "true"

        # Member status flows back into the status CR over the sockets.
        obj = admins["p1"].get(ftc.source.resource, "default/web")
        obj["status"] = {"replicas": 2, "readyReplicas": 2}
        admins["p1"].update_status(ftc.source.resource, obj)
        settle(sync, status)
        cr = fleet.host.get(ftc.status.resource, "default/web")
        by = {e["clusterName"]: e for e in cr["clusterStatus"]}
        assert by["p1"]["collectedFields"]["status"]["readyReplicas"] == 2
    finally:
        farm.close()
    for proc in farm.member_procs.values():
        assert proc.poll() is not None  # reaped on close
