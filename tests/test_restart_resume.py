"""Checkpoint/resume: a restarted control plane must not storm.

The reference's anti-restart-storm story (SURVEY.md §5.4) rests on two
mechanisms, both persisted in the apiserver rather than controller
memory: the scheduling-trigger-hash annotation prevents mass
rescheduling (reference: scheduler/schedulingtriggers.go:64-67), and
PropagatedVersion CRs let sync skip no-op member writes (reference:
sync/version/manager.go:49-487).  This test runs the e2e slice to
convergence, serializes every store to JSON (the etcd role), builds a
brand-new control plane over the restored state — fresh controllers,
empty in-memory caches — and asserts the resumed settle performs ZERO
member-cluster writes and ZERO host mutations.
"""

import json

# Aliased so pytest doesn't re-collect the slice tests here.
from test_e2e_slice import TestEndToEndSlice as _SliceBase, make_deployment, settle

from kubeadmiral_tpu.federation.clusterctl import FederatedClusterController
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.testing.fakekube import ClusterFleet


def converged_slice():
    """A fully converged e2e slice (composition, not inheritance, so the
    base tests aren't re-collected here)."""
    s = _SliceBase()
    s.setup_method()
    s.fleet.host.create(s.ftc.source.resource, make_deployment())
    s.settle(*s.everything())
    return s


def fresh_controllers(fleet, ftc):
    return (
        FederatedClusterController(fleet, api_resource_probe=["apps/v1/Deployment"]),
        FederateController(fleet.host, ftc),
        SchedulerController(fleet.host, ftc),
        SyncController(fleet, ftc),
    )


class WriteCounter:
    """Counts mutating calls on a kube store."""

    def __init__(self, kube):
        self.counts = {"create": 0, "update": 0, "update_status": 0, "delete": 0}
        for name in self.counts:
            original = getattr(kube, name)

            def wrapper(*args, _orig=original, _name=name, **kw):
                self.counts[_name] += 1
                return _orig(*args, **kw)

            setattr(kube, name, wrapper)

    @property
    def total(self) -> int:
        return sum(self.counts.values())


class TestRestartResume:
    def test_restart_performs_no_writes(self):
        # Phase 1: converge a live control plane.
        s = converged_slice()
        fed_before = s.fleet.host.get(s.ftc.federated.resource, "default/web")
        assert fed_before["status"]["clusters"]

        # Phase 2: "kill" the manager — serialize all state through JSON
        # (proving it is durable, like etcd), drop every controller and
        # in-memory cache, and bring up a brand-new control plane.
        snapshot = json.loads(json.dumps(s.fleet.dump()))
        restored = ClusterFleet.restore(snapshot)
        host_rv_before = restored.host.current_rv()

        host_counter = WriteCounter(restored.host)
        member_counters = {
            name: WriteCounter(kube) for name, kube in restored.members.items()
        }

        controllers = fresh_controllers(restored, s.ftc)
        settle(*controllers, rounds=40)

        # Phase 3: the resumed control plane observed everything via
        # LIST+WATCH and decided nothing needs doing.
        for name, counter in member_counters.items():
            assert counter.total == 0, (
                f"member {name} written on restart: {counter.counts} — "
                "PropagatedVersion skip failed"
            )
        assert host_counter.total == 0, (
            f"host written on restart: {host_counter.counts} — "
            "trigger-hash dedupe failed"
        )
        assert restored.host.current_rv() == host_rv_before

        fed_after = restored.host.get(s.ftc.federated.resource, "default/web")
        assert fed_after == fed_before

    def test_restart_still_reacts_to_new_work(self):
        """Resume must be quiet but not inert: a post-restart source
        update propagates normally."""
        s = converged_slice()
        restored = ClusterFleet.restore(json.loads(json.dumps(s.fleet.dump())))
        controllers = fresh_controllers(restored, s.ftc)
        settle(*controllers, rounds=40)

        src = restored.host.get(s.ftc.source.resource, "default/web")
        src["spec"]["replicas"] = 21
        restored.host.update(s.ftc.source.resource, src)
        settle(*controllers, rounds=40)

        total = sum(
            restored.member(n).get(s.ftc.source.resource, "default/web")[
                "spec"
            ]["replicas"]
            for n in ("c1", "c2", "c3")
        )
        assert total == 21
