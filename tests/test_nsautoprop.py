"""Namespace auto-propagation (reference: pkg/controllers/nsautoprop)."""

import dataclasses

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.clusterctl import (
    FEDERATED_CLUSTERS,
    FederatedClusterController,
    NODES,
)
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.nsautoprop import NamespaceAutoPropagationController
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.testing.fakekube import ClusterFleet

from test_e2e_slice import make_node, settle

NSAUTOPROP = "kubeadmiral.io/nsautoprop-controller"


def namespace_ftc(pipeline=((NSAUTOPROP,),)):
    ftc = next(f for f in default_ftcs() if f.name == "namespaces")
    return dataclasses.replace(ftc, controllers=pipeline)


def make_fed_namespace(name, annotations=None):
    obj = {
        "apiVersion": "types.kubeadmiral.io/v1alpha1",
        "kind": "FederatedNamespace",
        "metadata": {"name": name, "annotations": dict(annotations or {})},
        "spec": {"template": {"apiVersion": "v1", "kind": "Namespace",
                              "metadata": {"name": name}, "spec": {}}},
    }
    pending.set_pending(obj, ((NSAUTOPROP,),))
    return obj


class TestNSAutoProp:
    def setup_method(self):
        self.ftc = namespace_ftc()
        self.fleet = ClusterFleet()
        self.ctl = NamespaceAutoPropagationController(self.fleet.host, self.ftc)
        for name in ("c1", "c2"):
            self.fleet.host.create(
                FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": {},
                },
            )

    def fed(self, name):
        return self.fleet.host.get(self.ftc.federated.resource, name)

    def test_places_to_all_clusters_with_adoption_annotations(self):
        self.fleet.host.create(
            self.ftc.federated.resource, make_fed_namespace("team-a")
        )
        settle(self.ctl)
        fed = self.fed("team-a")
        assert C.get_placement(fed, NSAUTOPROP) == {"c1", "c2"}
        ann = fed["metadata"]["annotations"]
        assert ann[C.CONFLICT_RESOLUTION_INTERNAL] == "adopt"
        assert ann[C.ORPHAN_MODE_INTERNAL] == "adopted"
        assert pending.get_pending(fed) in ([], [[]])

    def test_new_cluster_extends_placement(self):
        self.fleet.host.create(
            self.ftc.federated.resource, make_fed_namespace("team-a")
        )
        settle(self.ctl)
        self.fleet.host.create(
            FEDERATED_CLUSTERS,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "FederatedCluster",
                "metadata": {"name": "c3"},
                "spec": {},
            },
        )
        settle(self.ctl)
        assert C.get_placement(self.fed("team-a"), NSAUTOPROP) == {"c1", "c2", "c3"}

    def test_skips_system_and_excluded_namespaces(self):
        ctl = NamespaceAutoPropagationController(
            self.fleet.host, self.ftc, exclude_regexp="^private-"
        )
        for name in ("kube-system", "kube-admiral-system", "private-x"):
            self.fleet.host.create(
                self.ftc.federated.resource, make_fed_namespace(name)
            )
        self.fleet.host.create(
            self.ftc.federated.resource,
            make_fed_namespace("opted-out", {C.NO_AUTO_PROPAGATION: "true"}),
        )
        settle(ctl)
        for name in ("kube-system", "kube-admiral-system", "private-x", "opted-out"):
            fed = self.fed(name)
            assert C.get_placement(fed, NSAUTOPROP) in (None, set()), name
            # Pipeline still advances so downstream controllers run.
            assert pending.get_pending(fed) in ([], [[]]), name


class TestNSAutoPropEndToEnd:
    """Namespace source -> federate -> nsautoprop -> sync, with member-side
    adoption and orphan-on-delete (controller.go:66-71 behavioral goals)."""

    def setup_method(self):
        self.ftc = namespace_ftc()
        self.fleet = ClusterFleet()
        self.clusterctl = FederatedClusterController(
            self.fleet, api_resource_probe=["v1/Namespace"]
        )
        self.federate = FederateController(self.fleet.host, self.ftc)
        self.nsautoprop = NamespaceAutoPropagationController(self.fleet.host, self.ftc)
        self.sync = SyncController(self.fleet, self.ftc)
        for name in ("c1", "c2"):
            member = self.fleet.add_member(name)
            member.create(NODES, make_node("n1", "8", "16Gi"))
            self.fleet.host.create(
                FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": {},
                },
            )

    def everything(self):
        return (self.clusterctl, self.federate, self.nsautoprop, self.sync)

    def test_namespace_propagates_and_adopts_preexisting(self):
        # c1 already has the namespace: it must be adopted, not conflicted.
        self.fleet.member("c1").create(
            self.ftc.source.resource,
            {"apiVersion": "v1", "kind": "Namespace",
             "metadata": {"name": "team-a"}, "spec": {}},
        )
        self.fleet.host.create(
            self.ftc.source.resource,
            {"apiVersion": "v1", "kind": "Namespace",
             "metadata": {"name": "team-a"}, "spec": {}},
        )
        settle(*self.everything(), rounds=40)

        for name in ("c1", "c2"):
            obj = self.fleet.member(name).get(self.ftc.source.resource, "team-a")
            assert obj["metadata"]["labels"][C.MANAGED_LABEL] == "true", name

        # Deleting the federated namespace orphans the adopted member copy
        # (c1) but removes the non-adopted one (c2).
        self.fleet.host.delete(self.ftc.source.resource, "team-a")
        settle(*self.everything(), rounds=40)
        assert self.fleet.member("c1").try_get(self.ftc.source.resource, "team-a")
        assert (
            self.fleet.member("c2").try_get(self.ftc.source.resource, "team-a")
            is None
        )
