"""Fit-flip survivor paths (ISSUE 10): selection-known replan,
score-only phase 1, and incremental delta featurization.

The drift gate classifies rows whose feasibility flipped at a changed
column (DRIFT_FITFLIP) as survivors the sort-free resolve cannot take —
their score normalization genuinely moves.  PR 10 routes them through
two cert-guarded kernels instead of full phase-1 slabs:

* ``drift_replan`` — kinf rows (maxClusters unlimited/negative): the
  new selection IS the new feasible set, no select sort at all;
* ``drift_scoreonly`` — finite-K rows: phase 1 reconstructed from the
  stored reason plane (+ dense fit recompute + full score recompute),
  then the unchanged narrow select/planner.

Contract (same as narrow/resolve): certified rows are bit-identical to
a dense stop-the-world re-solve — placements AND flight-recorder
records; cert failures drop to the slab path and are counted, never
silently wrong.  The delta-featurization leg is covered at the bottom:
dirty-row-hinted flushes equal full-walk scheduling, and full [B, C]
featurizes happen only on cold/topology transitions (counter-proven).
"""

import dataclasses

import numpy as np
import pytest

from kubeadmiral_tpu.models.types import (
    ClusterState,
    MODE_DIVIDE,
    SchedulingUnit,
    parse_resources,
)
from kubeadmiral_tpu.runtime.flightrec import FlightRecorder
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine
from kubeadmiral_tpu.scheduler.streaming import StreamingScheduler, is_placeholder

from test_engine_cache import results_equal
from test_engine_vs_sequential import random_cluster, random_unit

GVK = "apps/v1/Deployment"


def _clusters(c, cpu=64, avail_fn=None):
    out = []
    for j in range(c):
        avail = avail_fn(j) if avail_fn else {"cpu": f"{8 + j % 13}",
                                             "memory": f"{64 + 7 * j % 100}Gi"}
        out.append(
            ClusterState(
                name=f"m-{j:03d}",
                labels={},
                taints=(),
                allocatable=parse_resources(
                    {"cpu": str(cpu), "memory": "512Gi"}
                ),
                available=parse_resources(avail),
                api_resources=frozenset({GVK}),
            )
        )
    return out


def _fitflip_world(b=96, c=24):
    """Mixed kinf/finite-K, Duplicate/Divide rows whose cpu requests sit
    near the per-member availability — quartering one member's free cpu
    flips resources_fit for a band of rows (the replan/score-only home
    turf)."""
    clusters = _clusters(c)
    units = [
        SchedulingUnit(
            gvk=GVK,
            namespace="ns",
            name=f"w-{i:04d}",
            scheduling_mode=MODE_DIVIDE if i % 4 else "Duplicate",
            desired_replicas=(i % 30) + 2 if i % 4 else None,
            resource_request=parse_resources({"cpu": f"{1 + i % 6}"}),
            max_clusters=None if i % 3 else 2 + i % 5,
        )
        for i in range(b)
    ]
    return units, clusters


def _quarter_cpu(clusters, j):
    return [
        dataclasses.replace(
            cl,
            available={"cpu": cl.available["cpu"] // 4,
                       "memory": cl.available["memory"]},
        )
        if i == j
        else cl
        for i, cl in enumerate(clusters)
    ]


def _engine(**kw):
    kw.setdefault("chunk_size", 128)
    kw.setdefault("min_bucket", 32)
    kw.setdefault("min_cluster_bucket", 8)
    kw.setdefault("narrow_m", 16)
    # This module exercises the PR-10 THREE-STREAM survivor paths
    # (resolve / replan / score_only), kept alive behind
    # KT_SURVIVOR_UNIFIED=0 as the documented revert; the unified
    # kernel that replaced them as the default has its own suite
    # (tests/test_survivor_unified.py).
    unified = kw.pop("survivor_unified", False)
    eng = SchedulerEngine(**kw)
    eng.survivor_unified = unified
    return eng


class TestReplanScoreOnly:
    def test_fitflip_drift_engages_both_paths_exactly(self):
        units, clusters = _fitflip_world()
        rec = FlightRecorder(max_ticks=2, max_bytes=1 << 26)
        eng = _engine(flight_recorder=rec)
        eng.schedule(units, clusters)
        eng.schedule(list(units), clusters)
        drifted = _quarter_cpu(clusters, 3)
        got = eng.schedule(units, drifted)
        changed = eng.last_changed
        assert eng.drift_stats["gated"] >= 1, eng.drift_stats
        assert eng.drift_stats["replan"] > 0, eng.drift_stats
        assert eng.drift_stats["score_only"] > 0, eng.drift_stats

        oracle_rec = FlightRecorder(max_ticks=2, max_bytes=1 << 26)
        oracle = _engine(flight_recorder=oracle_rec)
        want = oracle.schedule(units, drifted)
        results_equal(got, want)
        # Flight-recorder parity for the re-decided rows: placements,
        # reason counts, feasible counts bit-identical everywhere;
        # top-k bit-identical on every path EXCEPT replan rows, whose
        # recorded top-k reflects the last solved score plane by design
        # (the selection-known replan skips the score recompute — the
        # staleness is provably decision-free for kinf rows).
        assert changed, "drift re-decided no rows"
        replan_rows = scored_rows = 0
        for row in changed:
            a = rec.lookup(units[row].key)
            b = oracle_rec.lookup(units[row].key)
            assert a is not None and b is not None, units[row].key
            assert a.placements == b.placements, units[row].key
            assert np.array_equal(a.reason_counts, b.reason_counts), (
                units[row].key
            )
            assert a.feasible_n == b.feasible_n, units[row].key
            if a.program.endswith(":replan"):
                replan_rows += 1
                continue
            scored_rows += 1
            assert np.array_equal(a.topk_idx, b.topk_idx), units[row].key
            assert np.array_equal(a.topk_scores, b.topk_scores), (
                units[row].key
            )
        assert replan_rows and scored_rows, (replan_rows, scored_rows)

    def test_chain_of_fitflip_drifts_stays_exact(self):
        """Replan repairs the prev planes in place (scores + reasons
        included); a chain of fit-flip drifts in both directions must
        not compound stale state."""
        units, clusters = _fitflip_world(b=64, c=20)
        eng = _engine(chunk_size=64)
        eng.schedule(units, clusters)
        eng.schedule(list(units), clusters)
        world = list(clusters)
        rng = np.random.default_rng(9)
        for step in range(6):
            j = int(rng.integers(0, len(world)))
            factor = 4 if step % 2 == 0 else 1  # shrink then restore
            world = [
                dataclasses.replace(
                    cl,
                    available={
                        "cpu": max(1, cl.available["cpu"] // 4)
                        if (i == j and factor == 4)
                        else (cl.available["cpu"] * 2 if i == j else cl.available["cpu"]),
                        "memory": cl.available["memory"],
                    },
                )
                for i, cl in enumerate(world)
            ]
            got = eng.schedule(units, world)
            want = _engine(chunk_size=64).schedule(units, world)
            results_equal(got, want)
        assert eng.drift_stats["replan"] > 0, eng.drift_stats

    def test_planner_spill_forces_replan_fallback(self):
        """Adversarial: kinf Divide rows whose weighted cascade touches
        more members than the narrow slot budget — plan_batch_narrow's
        phantom-tail cert fails, rows fall to the slab path (counted),
        outputs still exact."""
        c = 40
        clusters = _clusters(c, cpu=256, avail_fn=lambda j: {
            "cpu": "200", "memory": "400Gi",
        })
        units = [
            SchedulingUnit(
                gvk=GVK,
                namespace="ns",
                name=f"wide-{i:04d}",
                scheduling_mode=MODE_DIVIDE,
                # Far more replicas than slots: every feasible member
                # receives a share, so the cascade provably spills past
                # the M=16 narrow prefix.
                desired_replicas=400,
                resource_request=parse_resources({"cpu": f"{2 + i % 3}"}),
            )
            for i in range(48)
        ]
        eng = _engine(chunk_size=64)
        eng.schedule(units, clusters)
        eng.schedule(list(units), clusters)
        drifted = _quarter_cpu(clusters, 1)
        # Make the drifted member genuinely flip fit for some rows.
        drifted[1] = dataclasses.replace(
            drifted[1],
            available=parse_resources({"cpu": "1", "memory": "400Gi"}),
        )
        got = eng.schedule(units, drifted)
        assert eng.drift_stats["replan_fallback"] > 0, eng.drift_stats
        want = _engine(chunk_size=64).schedule(units, drifted)
        results_equal(got, want)

    def test_kt_replan_off_reverts_to_slabs(self):
        units, clusters = _fitflip_world(b=64, c=20)
        eng = _engine(chunk_size=64)
        eng.replan = False
        eng.schedule(units, clusters)
        eng.schedule(list(units), clusters)
        drifted = _quarter_cpu(clusters, 3)
        got = eng.schedule(units, drifted)
        assert eng.drift_stats["replan"] == 0
        assert eng.drift_stats["score_only"] == 0
        want = _engine(chunk_size=64).schedule(units, drifted)
        results_equal(got, want)

    def test_streaming_interleave_with_fitflips_bit_identical(self):
        """The PR-7 interleave differential, biased toward fit-flip
        drifts: streaming flushes (replan/score-only engaged) vs
        stop-the-world fresh engines — placements and recorder records
        bit-identical for every re-decided row."""
        rng = np.random.default_rng(17)
        units, clusters = _fitflip_world(b=64, c=20)
        rec = FlightRecorder(max_ticks=2, max_bytes=1 << 26)
        engine = _engine(chunk_size=64, flight_recorder=rec)
        stream = StreamingScheduler(engine, clusters, units,
                                    slab_rows=6, slab_age_ms=1e9)
        stream.flush()
        stream.flush()
        engaged = 0
        for step in range(8):
            if step % 2 == 0:
                j = int(rng.integers(0, len(stream.clusters)))
                base = stream.clusters[j]
                stream.update_cluster(dataclasses.replace(
                    base,
                    available={"cpu": max(1, base.available["cpu"] // 4),
                               "memory": base.available["memory"]},
                ))
            else:
                u = stream.units[int(rng.integers(0, 64))]
                if not is_placeholder(u):
                    stream.offer(dataclasses.replace(
                        u, desired_replicas=int(rng.integers(1, 60))))
            got = stream.flush()
            changed = engine.last_changed
            oracle_rec = FlightRecorder(max_ticks=2, max_bytes=1 << 26)
            oracle = _engine(chunk_size=64, flight_recorder=oracle_rec)
            want = oracle.schedule(stream.units, stream.clusters)
            results_equal(got, want)
            for row in (changed or []):
                u = stream.units[row]
                if is_placeholder(u):
                    continue
                a = rec.lookup(u.key)
                b = oracle_rec.lookup(u.key)
                assert a is not None and b is not None, u.key
                assert a.placements == b.placements, u.key
                assert np.array_equal(a.reason_counts, b.reason_counts), u.key
                if a.program.endswith(":replan"):
                    continue  # top-k is last-solved by design (see docs)
                assert np.array_equal(a.topk_idx, b.topk_idx), u.key
                assert np.array_equal(a.topk_scores, b.topk_scores), u.key
            engaged = max(engaged, engine.drift_stats["replan"]
                          + engine.drift_stats["score_only"])
        assert engaged > 0, engine.drift_stats


class TestPhase1I32:
    def test_i32_keys_match_i64_on_random_worlds(self):
        rng = np.random.default_rng(23)
        clusters = [random_cluster(rng, j) for j in range(14)]
        names = [c.name for c in clusters]
        units = [random_unit(rng, i, names) for i in range(64)]
        on = _engine(chunk_size=32, min_bucket=16)
        off = _engine(chunk_size=32, min_bucket=16)
        off.phase1_i32 = False
        results_equal(
            on.schedule(units, clusters), off.schedule(units, clusters)
        )
        churned = list(units)
        churned[3] = dataclasses.replace(units[3], desired_replicas=77)
        results_equal(
            on.schedule(churned, clusters), off.schedule(churned, clusters)
        )

    def test_webhook_score_overflow_falls_back_exactly(self):
        """Webhook scores can exceed the narrowed i32 key range — the
        per-row cert must route those rows to the dense fallback, never
        mis-rank them."""
        units, clusters = _fitflip_world(b=32, c=20)

        def webhook_eval(su, cls):
            ok = np.ones(len(cls), bool)
            scores = np.full(len(cls), (1 << 28), np.int64)
            scores[int(su.name[-2:], 10) % len(cls)] += 7
            return ok, scores

        on = _engine(chunk_size=32, min_bucket=16)
        off = _engine(chunk_size=32, min_bucket=16, narrow=False)
        got = on.schedule(units, clusters, webhook_eval=webhook_eval)
        want = off.schedule(units, clusters, webhook_eval=webhook_eval)
        results_equal(got, want)

    def test_wcheck_i32_matches_i64(self):
        """Dynamic-weight rows under a cpu-only drift: the i32 wcheck
        (host range guard holds at these cpu counts) must classify
        identically to i64."""
        units, clusters = _fitflip_world(b=64, c=20)
        # Dynamic weights: Divide + no static weights (the default
        # world); drift a member's cpu without flipping fit.
        drifted = [
            dataclasses.replace(
                cl,
                available={"cpu": cl.available["cpu"] + 3,
                           "memory": cl.available["memory"]},
            )
            if j == 5
            else cl
            for j, cl in enumerate(clusters)
        ]
        for i32 in (True, False):
            eng = _engine(chunk_size=64)
            eng.phase1_i32 = i32
            eng.schedule(units, clusters)
            eng.schedule(list(units), clusters)
            got = eng.schedule(units, drifted)
            want = _engine(chunk_size=64).schedule(units, drifted)
            results_equal(got, want)
            assert eng.drift_stats["wcheck"] > 0, (i32, eng.drift_stats)


class TestDeltaFeaturization:
    def test_dirty_hint_flushes_equal_full_walk(self):
        """Streaming with dirty-row hints (the O(changed) featurize
        walk) vs KT_DELTA_FEAT=0 (full featurize every changed chunk):
        identical placements across an interleaved event log."""
        rng = np.random.default_rng(31)
        units, clusters = _fitflip_world(b=64, c=16)

        def build(delta_feat):
            eng = _engine(chunk_size=64)
            eng.delta_feat = delta_feat
            stream = StreamingScheduler(eng, clusters, list(units),
                                        slab_rows=1 << 30, slab_age_ms=1e9)
            stream.flush()
            return eng, stream

        eng_a, stream_a = build(True)
        eng_b, stream_b = build(False)
        arrivals = 0
        for step in range(6):
            events = []
            kind = step % 3
            if kind == 0:
                for r in rng.integers(0, 64, 4):
                    u = stream_a.units[int(r)]
                    if is_placeholder(u):
                        continue
                    events.append(("offer", dataclasses.replace(
                        u, desired_replicas=int(rng.integers(1, 60)))))
            elif kind == 1:
                for _ in range(2):
                    events.append(("offer", random_unit(
                        rng, 2000 + arrivals,
                        [c.name for c in clusters])))
                    arrivals += 1
            else:
                live = [u for u in stream_a.units if not is_placeholder(u)]
                events.append(("remove", live[int(rng.integers(0, len(live)))].key))
            for verb, payload in events:
                getattr(stream_a, verb)(payload)
                getattr(stream_b, verb)(payload)
            results_equal(stream_a.flush(), stream_b.flush())
        # The hinted engine actually used delta featurization...
        assert eng_a.featurize_rows["delta"] > 0, eng_a.featurize_rows
        # ...while the opted-out engine rebuilt chunks in full.
        assert eng_b.featurize_rows["full"] > eng_a.featurize_rows["full"]

    def test_full_featurize_only_on_cold_and_topology_change(self):
        """The acceptance counter-proof: after the cold tick, steady /
        churn / drift ticks move DELTA rows only; a topology change
        (new member) is the only later full rebuild."""
        units, clusters = _fitflip_world(b=64, c=16)
        eng = _engine(chunk_size=64)
        eng.schedule(units, clusters)
        cold_full = eng.featurize_rows["full"]
        assert cold_full == len(units)
        # Steady + churn + capacity drift: delta rows only.
        eng.schedule(list(units), clusters)
        churned = list(units)
        churned[5] = dataclasses.replace(units[5], desired_replicas=61)
        eng.schedule(churned, clusters)
        eng.schedule(churned, _quarter_cpu(clusters, 2))
        assert eng.featurize_rows["full"] == cold_full, eng.featurize_rows
        assert eng.featurize_rows["delta"] >= 1
        # Topology change (a new member joins): full rebuild expected.
        grown = clusters + [_clusters(1)[0]]
        grown[-1] = dataclasses.replace(grown[-1], name="m-new")
        eng.schedule(churned, grown)
        assert eng.featurize_rows["full"] > cold_full

    def test_hint_ignored_when_another_caller_ticked(self):
        """The soundness guard: if a different caller ran the engine
        between flushes, the streaming hint must be dropped (full walk)
        — results stay exact."""
        units, clusters = _fitflip_world(b=48, c=16)
        eng = _engine(chunk_size=64)
        stream = StreamingScheduler(eng, clusters, list(units),
                                    slab_rows=1 << 30, slab_age_ms=1e9)
        stream.flush()
        # A foreign world ticks the engine in between.
        rng = np.random.default_rng(2)
        foreign = [random_unit(rng, 5000 + i, [c.name for c in clusters])
                   for i in range(16)]
        eng.schedule(foreign, clusters)
        u = stream.units[7]
        stream.offer(dataclasses.replace(u, desired_replicas=59))
        got = stream.flush()
        want = _engine(chunk_size=64).schedule(stream.units, clusters)
        results_equal(got, want)
