"""Packed placement export (ISSUE 3): the device-side top-k compaction
must be bit-exact against the sequential oracle's pack_one, the engine's
packed fetch format must produce placements identical to the dense
format on every path (including K-overflow fallbacks, score ties at the
select boundary and zero-replica rows), and flight-recorder records must
carry identical core fields in both formats."""

import dataclasses

import numpy as np
import pytest

from test_pipeline import R, random_problem, to_tick_inputs

from kubeadmiral_tpu.ops import pipeline as dev
from kubeadmiral_tpu.ops import reasons as RSN
from kubeadmiral_tpu.ops.pipeline_oracle import pack_one
from kubeadmiral_tpu.ops.planner import INT32_INF


def device_pack(problems, c, k):
    out = dev.schedule_tick(to_tick_inputs(problems, c))
    return dev.pack_rows(
        np.asarray(out.selected), np.asarray(out.replicas),
        np.asarray(out.counted), np.asarray(out.scores),
        np.asarray(out.reasons), k,
    )


class TestPackRowsVsOracle:
    @pytest.mark.parametrize("c,k", [(3, 8), (8, 4), (19, 8), (19, 32)])
    def test_pack_matches_oracle_bit_exactly(self, c, k):
        rng = np.random.default_rng(4000 + c * 100 + k)
        names = [f"member-{j}" for j in range(c)]
        # Cluster-axis tensors are shared across the batch in
        # TickInputs, so every problem must carry the same planes.
        shared_alloc = [[int(x) for x in rng.integers(5, 50, R)] for _ in range(c)]
        shared_used = [[int(x) for x in rng.integers(0, 40, R)] for _ in range(c)]
        shared_cpu_a = [int(x) for x in rng.integers(0, 30, c)]
        shared_cpu_v = [int(x) for x in rng.integers(-3, 25, c)]
        problems = []
        for i in range(60):
            p = random_problem(rng, c, f"ns-{i}/w-{i}", names)
            p.alloc, p.used = shared_alloc, shared_used
            p.cpu_alloc, p.cpu_avail = shared_cpu_a, shared_cpu_v
            problems.append(p)
        p = device_pack(problems, c, k)
        keff = min(k, c)
        for i, prob in enumerate(problems):
            want = pack_one(prob, keff)
            got = {
                "idx": np.asarray(p.idx)[i].tolist(),
                "rep": np.asarray(p.rep)[i].tolist(),
                "cnt": np.asarray(p.cnt)[i].tolist(),
                "sco": np.asarray(p.sco)[i].tolist(),
                "nsel": int(np.asarray(p.nsel)[i]),
                "nfeas": int(np.asarray(p.nfeas)[i]),
                "rsum": np.asarray(p.rsum)[i].tolist(),
            }
            assert got == want, (i, got, want, prob)

    def test_wire_roundtrip(self):
        c, k = 8, 4
        rng = np.random.default_rng(99)
        names = [f"member-{j}" for j in range(c)]
        problems = [
            random_problem(rng, c, f"ns/w-{i}", names) for i in range(20)
        ]
        out = dev.schedule_tick(to_tick_inputs(problems, c))
        planes = (
            np.asarray(out.selected), np.asarray(out.replicas),
            np.asarray(out.counted), np.asarray(out.scores),
            np.asarray(out.reasons),
        )
        wire = np.asarray(dev.pack_wire(*planes, k))
        assert wire.shape == (len(problems), dev.wire_width(k))
        p = dev.unpack_wire(wire, k)
        direct = dev.pack_rows(*planes, k)
        for field in p._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(p, field)), np.asarray(getattr(direct, field))
            )

    def test_overflow_flag_and_boundary_ties(self):
        """Score ties at the top-K select boundary resolve by cluster
        index in select_topk; the packed export must reproduce exactly
        that selected set, and rows selecting more than K clusters must
        flag overflow (nsel > K) without corrupting packable rows."""
        c = 12
        names = [f"m-{j}" for j in range(c)]
        rng = np.random.default_rng(0)

        def flat(maxc):
            # All clusters feasible with IDENTICAL scores: the top-K cut
            # is decided purely by the index tie-break.
            p = random_problem(rng, c, "ns/tie", names)
            p.filter_enabled = [True] * 5
            p.score_enabled = [False] * 5
            p.api_ok = [True] * c
            p.taint_ok_new = [True] * c
            p.taint_ok_cur = [True] * c
            p.selector_ok = [True] * c
            p.placement_ok = [True] * c
            p.placement_has = False
            p.request = [0] * R
            p.max_clusters = maxc
            p.mode_divide = False
            p.sticky = False
            p.current = {}
            return p

        k = 4
        problems = [flat(4), flat(7), flat(None), flat(0)]
        p = device_pack(problems, c, k)
        nsel = np.asarray(p.nsel).tolist()
        assert nsel == [4, 7, c, 0]
        # Row 0 fits exactly; ties broke by index: clusters 0..3.
        assert np.asarray(p.idx)[0].tolist() == [0, 1, 2, 3]
        # Rows 1 and 2 overflow (nsel > K); their first-K slots still
        # hold the lowest selected indices.
        assert np.asarray(p.idx)[1].tolist() == [0, 1, 2, 3]
        # Row 3 selects nothing: all slots padded.
        assert np.asarray(p.idx)[3].tolist() == [dev.PACK_FILL] * k
        assert np.asarray(p.rsum)[3][
            RSN.REASON_BITS.index(RSN.REASON_MAX_CLUSTERS)
        ] == c

    def test_zero_replica_rows_pack_empty(self):
        """Divide-mode rows whose planner assigns 0 everywhere are
        dropped from the selected set: packed rows must be empty with
        the zero_replicas summary accounting for every cut cluster."""
        c = 6
        names = [f"m-{j}" for j in range(c)]
        rng = np.random.default_rng(1)
        p = random_problem(rng, c, "ns/zero", names)
        p.filter_enabled = [True] * 5
        p.score_enabled = [False] * 5
        p.api_ok = [True] * c
        p.taint_ok_new = [True] * c
        p.taint_ok_cur = [True] * c
        p.selector_ok = [True] * c
        p.placement_ok = [True] * c
        p.placement_has = False
        p.request = [0] * R
        p.max_clusters = None
        p.mode_divide = True
        p.sticky = False
        p.current = {}
        p.total = 0
        p.weights = {j: 1 for j in range(c)}
        p.min_replicas = {}
        p.max_replicas = {}
        p.capacity = {}
        packed = device_pack([p], c, 4)
        assert int(np.asarray(packed.nsel)[0]) == 0
        assert np.asarray(packed.idx)[0].tolist() == [dev.PACK_FILL] * 4
        zr = RSN.REASON_BITS.index(RSN.REASON_ZERO_REPLICAS)
        assert int(np.asarray(packed.rsum)[0][zr]) == c


def make_engines(pack_k_min=16, **kw):
    from kubeadmiral_tpu.runtime.flightrec import FlightRecorder
    from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

    recs = {}
    engines = {}
    for fmt in ("packed", "dense"):
        recs[fmt] = FlightRecorder(max_ticks=8, max_bytes=64 << 20, topk=4)
        engines[fmt] = SchedulerEngine(
            chunk_size=16, min_bucket=8, min_cluster_bucket=8, mesh=None,
            fetch_format=fmt, flight_recorder=recs[fmt],
            pack_k_min=pack_k_min, **kw,
        )
    return engines, recs


def make_world(n_units=48, n_clusters=12, seed=11):
    from test_engine_vs_sequential import random_cluster, random_unit

    rng = np.random.default_rng(seed)
    clusters = [random_cluster(rng, j) for j in range(n_clusters)]
    names = [cl.name for cl in clusters]
    units = [random_unit(rng, i, names) for i in range(n_units)]
    return rng, units, clusters, names


def assert_results_equal(got, want):
    assert len(got) == len(want)
    for i, (a, b) in enumerate(zip(got, want)):
        assert dict(a.clusters) == dict(b.clusters), (
            i, dict(a.clusters), dict(b.clusters)
        )


class TestEnginePackedVsDense:
    """Packed-vs-dense A/B: identical placements on every fetch path,
    including engines whose tiny K forces routine overflow fallbacks."""

    @pytest.mark.parametrize("pack_k_min", [16, 2])
    def test_all_paths_identical(self, pack_k_min):
        from test_engine_vs_sequential import random_unit

        engines, recs = make_engines(pack_k_min=pack_k_min)
        rng, units, clusters, names = make_world()

        # Cold tick (full fetch path).
        cold = {f: e.schedule(units, clusters) for f, e in engines.items()}
        assert_results_equal(cold["packed"], cold["dense"])
        if pack_k_min == 2:
            # K=2 with unlimited-maxClusters rows: overflow MUST engage.
            assert engines["packed"].overflow_rows_total > 0

        # Churn tick (sub-batch or delta path).
        units2 = list(units)
        units2[3] = random_unit(rng, 300, names)
        units2[20] = random_unit(rng, 301, names)
        churn = {f: e.schedule(units2, clusters) for f, e in engines.items()}
        assert_results_equal(churn["packed"], churn["dense"])

        # Resource-drift tick (full dispatch + delta fetch path).
        drifted = list(clusters)
        drifted[0] = dataclasses.replace(
            drifted[0],
            available={k: max(0, v // 3) for k, v in drifted[0].available.items()},
        )
        drift = {f: e.schedule(units2, drifted) for f, e in engines.items()}
        assert_results_equal(drift["packed"], drift["dense"])

        # Both formats agree with a cache-less fresh engine too.
        from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

        fresh = SchedulerEngine(
            chunk_size=16, min_bucket=8, min_cluster_bucket=8, mesh=None,
            fetch_format="dense", flight_recorder=None,
        ).schedule(units2, drifted)
        assert_results_equal(drift["packed"], fresh)

    def test_want_scores_identical(self):
        engines, _ = make_engines()
        _, units, clusters, _ = make_world(seed=23)
        got = {
            f: e.schedule(units, clusters, want_scores=True)
            for f, e in engines.items()
        }
        assert_results_equal(got["packed"], got["dense"])
        for a, b in zip(got["packed"], got["dense"]):
            assert dict(a.scores) == dict(b.scores)

    def test_recorder_records_identical_core(self):
        """The flight recorder's format-independent core — placements,
        reason counts, feasible count, selected top-k — must be
        identical between packed and dense; only the dense format keeps
        the full per-cluster mask row."""
        engines, recs = make_engines()
        _, units, clusters, _ = make_world(seed=31)
        for e in engines.values():
            e.schedule(units, clusters)
        for su in units:
            a = recs["packed"].lookup(su.key)
            b = recs["dense"].lookup(su.key)
            assert a is not None and b is not None, su.key
            assert dict(a.placements) == dict(b.placements)
            assert a.reason_counts.tolist() == b.reason_counts.tolist()
            assert a.feasible_n == b.feasible_n
            assert a.topk_idx.tolist() == b.topk_idx.tolist()
            assert a.topk_scores.tolist() == b.topk_scores.tolist()
            assert a.reasons is None
            assert b.reasons is not None
            # The dense row's summary equals the packed wire summary.
            r = b.reasons.astype(np.int64)
            want_counts = [
                int(((r & bit) != 0).sum()) for bit in RSN.REASON_BITS
            ]
            assert a.reason_counts.tolist() == want_counts
            # summarize identically (the ScheduleFailed vocabulary).
            from kubeadmiral_tpu.runtime import flightrec as FR

            assert FR.summarize_reasons(a) == FR.summarize_reasons(b)

    def test_recorder_overflow_rows_record_identical_core(self):
        """K-overflow rows (dense re-fetch fallback) must still produce
        the same recorder core as the dense format."""
        engines, recs = make_engines(pack_k_min=2)
        _, units, clusters, _ = make_world(seed=37)
        for e in engines.values():
            e.schedule(units, clusters)
        assert engines["packed"].overflow_rows_total > 0
        for su in units:
            a = recs["packed"].lookup(su.key)
            b = recs["dense"].lookup(su.key)
            assert dict(a.placements) == dict(b.placements)
            assert a.reason_counts.tolist() == b.reason_counts.tolist()
            assert a.feasible_n == b.feasible_n
            assert a.topk_idx.tolist() == b.topk_idx.tolist()
            assert a.topk_scores.tolist() == b.topk_scores.tolist()

    def test_explain_covers_placements_and_rejected_summary(self):
        engines, recs = make_engines()
        _, units, clusters, names = make_world(seed=41)
        results = {f: e.schedule(units, clusters) for f, e in engines.items()}
        for i, su in enumerate(units):
            ex_p = recs["packed"].explain(su.key)
            ex_d = recs["dense"].explain(su.key)
            assert ex_p["placements"] == ex_d["placements"]
            assert ex_p["rejected"] == ex_d["rejected"]
            assert ex_p["feasible_clusters"] == ex_d["feasible_clusters"]
            # Packed explain covers exactly the selected clusters.
            assert set(ex_p["clusters"]) == set(results["packed"][i].clusters)
            for name, verdict in ex_p["clusters"].items():
                assert verdict["reasons"] == []
            # Dense explain still names every cluster's verdict.
            assert set(ex_d["clusters"]) == set(names)

    def test_fetch_bytes_accounting(self):
        engines, _ = make_engines()
        _, units, clusters, _ = make_world(seed=43)
        for e in engines.values():
            assert e.fetch_bytes_total == 0
            e.schedule(units, clusters)
            assert e.fetch_bytes_total > 0


class TestPackKPolicy:
    def test_k_tracks_finite_max_clusters(self):
        from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

        eng = SchedulerEngine(mesh=None, flight_recorder=None)

        class Inputs:
            max_clusters = np.asarray([3, 40, int(INT32_INF), -1], np.int32)

        # Largest finite bound is 40 -> pow2 64, capped by the cluster
        # bucket.
        assert eng._pack_k(Inputs(), 512) == 64
        assert eng._pack_k(Inputs(), 32) == 32

        class Unlimited:
            max_clusters = np.asarray([int(INT32_INF)], np.int32)

        assert eng._pack_k(Unlimited(), 512) == 16  # the KT_PACK_K floor
