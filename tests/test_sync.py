"""Sync-controller stack tests: retention, dispatch, propagation,
deletion — modeled on the reference's retain_test.go and the
resourcepropagation e2e flow."""

from __future__ import annotations

import json

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation import retain
from kubeadmiral_tpu.federation.resource import (
    FederatedResource,
    object_needs_update,
    object_version,
)
from kubeadmiral_tpu.federation.sync import FEDERATED_CLUSTERS, SyncController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.testing.fakekube import ClusterFleet


def deployment_ftc():
    return next(f for f in default_ftcs() if f.name == "deployments.apps")


def make_cluster(name: str, joined=True, ready=True, **meta):
    conditions = []
    if joined:
        conditions.append({"type": "Joined", "status": "True"})
    conditions.append({"type": "Ready", "status": "True" if ready else "False"})
    obj = {
        "apiVersion": "core.kubeadmiral.io/v1alpha1",
        "kind": "FederatedCluster",
        "metadata": {"name": name, **meta},
        "spec": {},
        "status": {"conditions": conditions},
    }
    return obj


def make_fed_deployment(name="web", namespace="default", clusters=("c1", "c2"), replicas=3):
    fed = {
        "apiVersion": "types.kubeadmiral.io/v1alpha1",
        "kind": "FederatedDeployment",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "annotations": {
                pending.PENDING_CONTROLLERS: json.dumps([]),
            },
        },
        "spec": {
            "template": {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {
                    "name": name,
                    "namespace": namespace,
                    "labels": {"app": name},
                },
                "spec": {
                    "replicas": replicas,
                    "selector": {"matchLabels": {"app": name}},
                    "template": {
                        "metadata": {"labels": {"app": name}},
                        "spec": {"containers": [{"name": "app", "image": "nginx"}]},
                    },
                },
            },
            "placements": [
                {
                    "controller": C.SCHEDULER,
                    "placement": [{"cluster": c} for c in clusters],
                }
            ],
        },
    }
    return fed


def fleet_with(n=2, names=None):
    fleet = ClusterFleet()
    names = names or [f"c{i + 1}" for i in range(n)]
    for name in names:
        fleet.add_member(name)
        fleet.host.create(FEDERATED_CLUSTERS, make_cluster(name))
    return fleet


def run_sync(ctl, rounds=5):
    for _ in range(rounds):
        if not ctl.worker.step():
            break


# -- retention ----------------------------------------------------------

class TestRetention:
    def test_merge_labels_with_tombstones(self):
        desired = {"metadata": {"labels": {"a": "1"}, "annotations": {}}}
        retain.record_propagated_keys(desired)
        # Simulate previous propagation of labels {a, gone}; cluster also
        # has its own label "hpa".
        cluster = {
            "metadata": {
                "labels": {"a": "0", "gone": "x", "hpa": "y"},
                "annotations": {
                    retain.PROPAGATED_LABEL_KEYS: "a,gone",
                    retain.PROPAGATED_ANNOTATION_KEYS: "",
                },
                "resourceVersion": "7",
            }
        }
        retain.retain_cluster_fields("Deployment", desired, cluster)
        labels = desired["metadata"]["labels"]
        assert labels["a"] == "1"  # template wins
        assert "gone" not in labels  # tombstoned: removed from template
        assert labels["hpa"] == "y"  # cluster-owned survives
        assert desired["metadata"]["resourceVersion"] == "7"

    def test_service_retains_cluster_ip_and_node_ports(self):
        desired = {
            "metadata": {},
            "spec": {"ports": [{"name": "http", "protocol": "TCP", "port": 80}]},
        }
        cluster = {
            "metadata": {"resourceVersion": "1"},
            "spec": {
                "clusterIP": "10.0.0.7",
                "ports": [
                    {"name": "http", "protocol": "TCP", "port": 80, "nodePort": 31234}
                ],
            },
        }
        retain.retain_cluster_fields("Service", desired, cluster)
        assert desired["spec"]["clusterIP"] == "10.0.0.7"
        assert desired["spec"]["ports"][0]["nodePort"] == 31234

    def test_argo_workflow_retains_member_status(self):
        # retain.go:624-636: Workflow status is NOT a subresource — an
        # update would wipe the workflow-controller's progress.
        desired = {
            "apiVersion": "argoproj.io/v1alpha1",
            "kind": "Workflow",
            "metadata": {},
            "spec": {"entrypoint": "main"},
        }
        cluster = {
            "metadata": {"resourceVersion": "9"},
            "status": {"phase": "Running", "nodes": {"n1": {"phase": "Pending"}}},
        }
        retain.retain_cluster_fields("Workflow", desired, cluster)
        assert desired["status"]["phase"] == "Running"
        assert desired["metadata"]["resourceVersion"] == "9"
        # No member status: a stale desired status must not be pushed.
        desired2 = {
            "apiVersion": "argoproj.io/v1alpha1",
            "kind": "Workflow",
            "metadata": {},
            "status": {"phase": "Stale"},
        }
        retain.retain_cluster_fields("Workflow", desired2, {"metadata": {}})
        assert "status" not in desired2

    def test_gvk_retainer_registry_extensible(self):
        calls = []
        retain.register_gvk_retainer(
            "example.io/v1/Widget", lambda d, c: calls.append((d, c))
        )
        try:
            desired = {"apiVersion": "example.io/v1", "kind": "Widget", "metadata": {}}
            cluster = {"metadata": {"resourceVersion": "2"}}
            retain.retain_cluster_fields("Widget", desired, cluster)
            assert calls == [(desired, cluster)]
            # Explicit gvk argument wins over apiVersion+kind inference.
            retain.retain_cluster_fields(
                "Other", {"metadata": {}}, cluster, gvk="example.io/v1/Widget"
            )
            assert len(calls) == 2
        finally:
            retain._GVK_RETAINERS.pop("example.io/v1/Widget", None)

    def test_serviceaccount_retains_generated_secrets(self):
        desired = {"metadata": {}}
        cluster = {
            "metadata": {"resourceVersion": "1"},
            "secrets": [{"name": "sa-token-xyz"}],
        }
        retain.retain_cluster_fields("ServiceAccount", desired, cluster)
        assert desired["secrets"] == [{"name": "sa-token-xyz"}]

    def test_job_retains_generated_selector(self):
        desired = {
            "metadata": {},
            "spec": {"template": {"metadata": {"labels": {"app": "x"}}}},
        }
        cluster = {
            "metadata": {"resourceVersion": "1"},
            "spec": {
                "selector": {"matchLabels": {"controller-uid": "u1"}},
                "template": {"metadata": {"labels": {"controller-uid": "u1"}}},
            },
        }
        retain.retain_cluster_fields("Job", desired, cluster)
        assert desired["spec"]["selector"]["matchLabels"]["controller-uid"] == "u1"
        assert (
            desired["spec"]["template"]["metadata"]["labels"]["controller-uid"] == "u1"
        )

    def test_job_manual_selector_not_retained(self):
        desired = {"metadata": {}, "spec": {"manualSelector": True, "selector": {"matchLabels": {"app": "x"}}}}
        cluster = {
            "metadata": {"resourceVersion": "1"},
            "spec": {"selector": {"matchLabels": {"controller-uid": "u1"}}},
        }
        retain.retain_cluster_fields("Job", desired, cluster)
        assert desired["spec"]["selector"] == {"matchLabels": {"app": "x"}}

    def test_pod_retains_sa_volume_and_defaults(self):
        desired = {
            "metadata": {},
            "spec": {
                "containers": [{"name": "app", "volumeMounts": []}],
                "volumes": [],
            },
        }
        cluster = {
            "metadata": {"resourceVersion": "1"},
            "spec": {
                "serviceAccountName": "default",
                "nodeName": "node-1",
                "volumes": [{"name": "kube-api-access-abcde", "projected": {}}],
                "containers": [
                    {
                        "name": "app",
                        "volumeMounts": [
                            {
                                "name": "kube-api-access-abcde",
                                "mountPath": "/var/run/secrets/kubernetes.io/serviceaccount",
                            }
                        ],
                    }
                ],
            },
        }
        retain.retain_cluster_fields("Pod", desired, cluster)
        assert desired["spec"]["serviceAccountName"] == "default"
        assert desired["spec"]["nodeName"] == "node-1"
        assert desired["spec"]["volumes"][0]["name"] == "kube-api-access-abcde"
        assert desired["spec"]["containers"][0]["volumeMounts"][0]["name"] == (
            "kube-api-access-abcde"
        )

    def test_retain_replicas_when_requested(self):
        desired = {"spec": {"replicas": 3}}
        cluster = {"spec": {"replicas": 7}}
        fed = {"spec": {"retainReplicas": True}}
        retain.retain_replicas(desired, cluster, fed, "spec.replicas")
        assert desired["spec"]["replicas"] == 7
        fed2 = {"spec": {}}
        desired2 = {"spec": {"replicas": 3}}
        retain.retain_replicas(desired2, cluster, fed2, "spec.replicas")
        assert desired2["spec"]["replicas"] == 3


# -- FederatedResource ---------------------------------------------------

class TestFederatedResource:
    def test_object_for_cluster_stamps_identity(self):
        fed = make_fed_deployment()
        res = FederatedResource(fed, deployment_ftc())
        obj = res.object_for_cluster("c1")
        assert obj["kind"] == "Deployment"
        assert obj["metadata"]["name"] == "web"
        assert obj["metadata"]["namespace"] == "default"
        assert C.SOURCE_GENERATION in obj["metadata"]["annotations"]

    def test_apply_overrides_orders_by_pipeline_and_adds_managed_label(self):
        fed = make_fed_deployment()
        # override entries listed sync-first but pipeline order is
        # scheduler -> override; scheduler's patch must land first.
        fed["spec"]["overrides"] = [
            {
                "controller": C.OVERRIDE_CONTROLLER,
                "clusters": [
                    {
                        "cluster": "c1",
                        "patches": [
                            {"op": "replace", "path": "/spec/replicas", "value": 9}
                        ],
                    }
                ],
            },
            {
                "controller": C.SCHEDULER,
                "clusters": [
                    {
                        "cluster": "c1",
                        "patches": [
                            {"op": "replace", "path": "/spec/replicas", "value": 5}
                        ],
                    }
                ],
            },
        ]
        res = FederatedResource(fed, deployment_ftc())
        obj = res.apply_overrides(res.object_for_cluster("c1"), "c1")
        assert obj["spec"]["replicas"] == 9  # later controller wins
        assert obj["metadata"]["labels"][C.MANAGED_LABEL] == "true"

    def test_object_version_and_needs_update(self):
        obj = {"metadata": {"generation": 4, "resourceVersion": "44"}}
        assert object_version(obj) == "gen:4"
        desired = {"spec": {"replicas": 2}}
        cluster = {"metadata": {"generation": 4}, "spec": {"replicas": 2}}
        assert not object_needs_update(desired, cluster, "gen:4", "spec.replicas")
        assert object_needs_update(desired, cluster, "gen:3", "spec.replicas")
        cluster2 = {"metadata": {"generation": 4}, "spec": {"replicas": 5}}
        assert object_needs_update(desired, cluster2, "gen:4", "spec.replicas")


# -- end-to-end propagation ----------------------------------------------

class TestSyncController:
    def test_propagates_to_placed_clusters(self):
        fleet = fleet_with(3)
        ctl = SyncController(fleet, deployment_ftc())
        fed = make_fed_deployment(clusters=("c1", "c2"))
        fleet.host.create(ctl._fed_resource, fed)
        run_sync(ctl)

        assert fleet.member("c1").try_get("apps/v1/deployments", "default/web")
        assert fleet.member("c2").try_get("apps/v1/deployments", "default/web")
        assert not fleet.member("c3").try_get("apps/v1/deployments", "default/web")
        obj = fleet.member("c1").get("apps/v1/deployments", "default/web")
        assert obj["metadata"]["labels"][C.MANAGED_LABEL] == "true"

        fed_after = fleet.host.get(ctl._fed_resource, "default/web")
        status = {
            c["cluster"]: c["status"] for c in fed_after["status"]["clusters"]
        }
        assert status == {"c1": "OK", "c2": "OK"}
        cond = {c["type"]: c for c in fed_after["status"]["conditions"]}
        assert cond["Propagation"]["status"] == "True"

    def test_version_skip_avoids_member_writes(self):
        fleet = fleet_with(1, names=["c1"])
        ctl = SyncController(fleet, deployment_ftc())
        fed = make_fed_deployment(clusters=("c1",))
        fleet.host.create(ctl._fed_resource, fed)
        run_sync(ctl)
        rv1 = fleet.member("c1").get("apps/v1/deployments", "default/web")[
            "metadata"
        ]["resourceVersion"]
        # Re-trigger with no template change: no member write.
        ctl.worker.enqueue("default/web")
        run_sync(ctl)
        rv2 = fleet.member("c1").get("apps/v1/deployments", "default/web")[
            "metadata"
        ]["resourceVersion"]
        assert rv1 == rv2

    def test_template_change_propagates(self):
        fleet = fleet_with(1, names=["c1"])
        ctl = SyncController(fleet, deployment_ftc())
        fed = make_fed_deployment(clusters=("c1",))
        fleet.host.create(ctl._fed_resource, fed)
        run_sync(ctl)
        cur = fleet.host.get(ctl._fed_resource, "default/web")
        cur["spec"]["template"]["spec"]["replicas"] = 11
        fleet.host.update(ctl._fed_resource, cur)
        run_sync(ctl)
        obj = fleet.member("c1").get("apps/v1/deployments", "default/web")
        assert obj["spec"]["replicas"] == 11

    def test_migration_deletes_from_removed_cluster(self):
        fleet = fleet_with(2)
        ctl = SyncController(fleet, deployment_ftc())
        fed = make_fed_deployment(clusters=("c1", "c2"))
        fleet.host.create(ctl._fed_resource, fed)
        run_sync(ctl)
        cur = fleet.host.get(ctl._fed_resource, "default/web")
        cur["spec"]["placements"] = [
            {"controller": C.SCHEDULER, "placement": [{"cluster": "c2"}]}
        ]
        fleet.host.update(ctl._fed_resource, cur)
        run_sync(ctl)
        assert fleet.member("c1").try_get("apps/v1/deployments", "default/web") is None
        assert fleet.member("c2").try_get("apps/v1/deployments", "default/web")

    def test_deletion_cascades_and_removes_finalizer(self):
        fleet = fleet_with(2)
        ctl = SyncController(fleet, deployment_ftc())
        fed = make_fed_deployment(clusters=("c1", "c2"))
        fleet.host.create(ctl._fed_resource, fed)
        run_sync(ctl)
        fleet.host.delete(ctl._fed_resource, "default/web")
        run_sync(ctl, rounds=10)
        assert fleet.member("c1").try_get("apps/v1/deployments", "default/web") is None
        assert fleet.host.try_get(ctl._fed_resource, "default/web") is None

    def test_orphan_all_keeps_member_objects(self):
        fleet = fleet_with(1, names=["c1"])
        ctl = SyncController(fleet, deployment_ftc())
        fed = make_fed_deployment(clusters=("c1",))
        fed["metadata"]["annotations"][C.ORPHAN_MODE] = "all"
        fleet.host.create(ctl._fed_resource, fed)
        run_sync(ctl)
        fleet.host.delete(ctl._fed_resource, "default/web")
        run_sync(ctl, rounds=10)
        obj = fleet.member("c1").try_get("apps/v1/deployments", "default/web")
        assert obj is not None
        assert C.MANAGED_LABEL not in obj["metadata"].get("labels", {})
        assert fleet.host.try_get(ctl._fed_resource, "default/web") is None

    def test_adoption_of_preexisting_resource(self):
        fleet = fleet_with(1, names=["c1"])
        # Pre-existing unmanaged member object.
        fleet.member("c1").create(
            "apps/v1/deployments",
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": "web", "namespace": "default"},
                "spec": {"replicas": 1},
            },
        )
        ctl = SyncController(fleet, deployment_ftc())
        fed = make_fed_deployment(clusters=("c1",))
        fed["metadata"]["annotations"][C.CONFLICT_RESOLUTION] = "adopt"
        fleet.host.create(ctl._fed_resource, fed)
        run_sync(ctl)
        obj = fleet.member("c1").get("apps/v1/deployments", "default/web")
        assert obj["metadata"]["labels"][C.MANAGED_LABEL] == "true"
        assert obj["metadata"]["annotations"]["kubeadmiral.io/adopted"] == "true"
        assert obj["spec"]["replicas"] == 3  # template took over

    def test_no_adoption_without_annotation(self):
        fleet = fleet_with(1, names=["c1"])
        fleet.member("c1").create(
            "apps/v1/deployments",
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": "web", "namespace": "default"},
                "spec": {"replicas": 1},
            },
        )
        ctl = SyncController(fleet, deployment_ftc())
        fed = make_fed_deployment(clusters=("c1",))
        fleet.host.create(ctl._fed_resource, fed)
        run_sync(ctl)
        obj = fleet.member("c1").get("apps/v1/deployments", "default/web")
        assert C.MANAGED_LABEL not in obj["metadata"].get("labels", {})
        fed_after = fleet.host.get(ctl._fed_resource, "default/web")
        status = {c["cluster"]: c["status"] for c in fed_after["status"]["clusters"]}
        assert status["c1"] == "AlreadyExists"

    def test_unready_cluster_reported_not_synced(self):
        fleet = ClusterFleet()
        fleet.add_member("c1")
        fleet.host.create(FEDERATED_CLUSTERS, make_cluster("c1", ready=False))
        ctl = SyncController(fleet, deployment_ftc())
        fed = make_fed_deployment(clusters=("c1",))
        fleet.host.create(ctl._fed_resource, fed)
        run_sync(ctl)
        assert fleet.member("c1").try_get("apps/v1/deployments", "default/web") is None
        fed_after = fleet.host.get(ctl._fed_resource, "default/web")
        status = {c["cluster"]: c["status"] for c in fed_after["status"]["clusters"]}
        assert status["c1"] == "ClusterNotReady"

    def test_pending_upstream_controllers_defer_sync(self):
        fleet = fleet_with(1, names=["c1"])
        ctl = SyncController(fleet, deployment_ftc())
        fed = make_fed_deployment(clusters=("c1",))
        fed["metadata"]["annotations"][pending.PENDING_CONTROLLERS] = json.dumps(
            [[C.SCHEDULER]]
        )
        fleet.host.create(ctl._fed_resource, fed)
        run_sync(ctl)
        assert fleet.member("c1").try_get("apps/v1/deployments", "default/web") is None

    def test_deletion_blocked_by_unready_cluster(self):
        # A joined-but-unready cluster that may hold the object must keep
        # the finalizer in place (no silent member-object leak).
        fleet = fleet_with(2)
        ctl = SyncController(fleet, deployment_ftc())
        fed = make_fed_deployment(clusters=("c1", "c2"))
        fleet.host.create(ctl._fed_resource, fed)
        run_sync(ctl)
        assert fleet.member("c2").try_get("apps/v1/deployments", "default/web")

        # c2 goes unready, then the federated object is deleted.
        c2 = fleet.host.get(FEDERATED_CLUSTERS, "c2")
        c2["status"]["conditions"] = [
            {"type": "Joined", "status": "True"},
            {"type": "Ready", "status": "False"},
        ]
        fleet.host.update_status(FEDERATED_CLUSTERS, c2)
        fleet.host.delete(ctl._fed_resource, "default/web")
        run_sync(ctl, rounds=10)

        # Finalizer still held; member object not leaked.
        assert fleet.host.try_get(ctl._fed_resource, "default/web") is not None
        assert fleet.member("c2").try_get("apps/v1/deployments", "default/web")

        # Cluster recovers -> deletion completes.
        c2 = fleet.host.get(FEDERATED_CLUSTERS, "c2")
        c2["status"]["conditions"] = [
            {"type": "Joined", "status": "True"},
            {"type": "Ready", "status": "True"},
        ]
        fleet.host.update_status(FEDERATED_CLUSTERS, c2)
        ctl.worker.enqueue("default/web")
        run_sync(ctl, rounds=10)
        assert fleet.member("c2").try_get("apps/v1/deployments", "default/web") is None
        assert fleet.host.try_get(ctl._fed_resource, "default/web") is None


class TestConfigMapDrift:
    def test_member_data_drift_is_repaired(self):
        # ConfigMaps carry no generation; drift detection must fall back
        # to resourceVersion so out-of-band member edits are reverted.
        ftc = next(f for f in default_ftcs() if f.name == "configmaps")
        fleet = fleet_with(1, names=["c1"])
        ctl = SyncController(fleet, ftc)
        fed = {
            "apiVersion": "types.kubeadmiral.io/v1alpha1",
            "kind": "FederatedConfigMap",
            "metadata": {
                "name": "cm",
                "namespace": "default",
                "annotations": {pending.PENDING_CONTROLLERS: json.dumps([])},
            },
            "spec": {
                "template": {
                    "apiVersion": "v1",
                    "kind": "ConfigMap",
                    "metadata": {"name": "cm", "namespace": "default"},
                    "data": {"k": "v"},
                },
                "placements": [
                    {"controller": C.SCHEDULER, "placement": [{"cluster": "c1"}]}
                ],
            },
        }
        fleet.host.create(ctl._fed_resource, fed)
        run_sync(ctl)
        obj = fleet.member("c1").get("v1/configmaps", "default/cm")
        assert obj["data"] == {"k": "v"}

        # Out-of-band member edit.
        obj["data"] = {"k": "tampered"}
        fleet.member("c1").update("v1/configmaps", obj)
        ctl.worker.enqueue("default/cm")
        run_sync(ctl)
        obj = fleet.member("c1").get("v1/configmaps", "default/cm")
        assert obj["data"] == {"k": "v"}
