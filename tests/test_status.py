"""Status controller + StatusAggregator (reference:
pkg/controllers/status, pkg/controllers/statusaggregator)."""

import json

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.statusctl import (
    StatusAggregator,
    StatusController,
    aggregate_workload_status,
)
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.testing.fakekube import ClusterFleet


def deployment_ftc():
    return next(f for f in default_ftcs() if f.name == "deployments.apps")


def make_cluster(name):
    return {
        "apiVersion": "core.kubeadmiral.io/v1alpha1",
        "kind": "FederatedCluster",
        "metadata": {"name": name},
        "spec": {},
        "status": {
            "conditions": [
                {"type": "Joined", "status": "True"},
                {"type": "Ready", "status": "True"},
            ]
        },
    }


def make_fed(name="web", clusters=("c1", "c2"), synced=True):
    fed = {
        "apiVersion": "types.kubeadmiral.io/v1alpha1",
        "kind": "FederatedDeployment",
        "metadata": {
            "name": name,
            "namespace": "default",
            "labels": {"app": name},
            "annotations": {pending.PENDING_CONTROLLERS: json.dumps([])},
        },
        "spec": {
            "template": {"apiVersion": "apps/v1", "kind": "Deployment"},
            "placements": [
                {
                    "controller": C.SCHEDULER,
                    "placement": [{"cluster": c} for c in clusters],
                }
            ],
        },
    }
    if synced:
        fed["status"] = {
            "clusters": [{"cluster": c, "status": "OK"} for c in clusters]
        }
    return fed


def member_deployment(name="web", replicas=3, ready=3):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": name,
            "namespace": "default",
            "labels": {C.MANAGED_LABEL: "true"},
        },
        "spec": {"replicas": replicas},
        "status": {
            "replicas": replicas,
            "readyReplicas": ready,
            "availableReplicas": ready,
            "updatedReplicas": replicas,
        },
    }


def fleet_with(names=("c1", "c2")):
    fleet = ClusterFleet()
    for n in names:
        fleet.add_member(n)
        fleet.host.create(C.FEDERATED_CLUSTERS, make_cluster(n))
    return fleet


class TestStatusController:
    def test_collects_fields_per_cluster(self):
        fleet = fleet_with()
        ftc = deployment_ftc()
        ctl = StatusController(fleet, ftc)
        fleet.member("c1").create(ftc.source.resource, member_deployment(replicas=2))
        fleet.member("c2").create(ftc.source.resource, member_deployment(replicas=5))
        fleet.host.create(ftc.federated.resource, make_fed())
        ctl.run_until_idle()

        status_cr = fleet.host.get(ftc.status.resource, "default/web")
        assert status_cr["kind"] == "FederatedDeploymentStatus"
        by_cluster = {
            e["clusterName"]: e for e in status_cr["clusterStatus"]
        }
        assert by_cluster["c1"]["collectedFields"]["status"]["replicas"] == 2
        assert by_cluster["c2"]["collectedFields"]["status"]["replicas"] == 5
        assert status_cr["metadata"]["labels"] == {"app": "web"}

    def test_member_status_change_updates_cr(self):
        fleet = fleet_with(("c1",))
        ftc = deployment_ftc()
        ctl = StatusController(fleet, ftc)
        fleet.member("c1").create(ftc.source.resource, member_deployment(replicas=1))
        fleet.host.create(ftc.federated.resource, make_fed(clusters=("c1",)))
        ctl.run_until_idle()

        obj = fleet.member("c1").get(ftc.source.resource, "default/web")
        obj["status"]["replicas"] = 7
        fleet.member("c1").update_status(ftc.source.resource, obj)
        ctl.run_until_idle()
        status_cr = fleet.host.get(ftc.status.resource, "default/web")
        assert (
            status_cr["clusterStatus"][0]["collectedFields"]["status"]["replicas"]
            == 7
        )

    def test_fed_deletion_removes_status_cr(self):
        fleet = fleet_with(("c1",))
        ftc = deployment_ftc()
        ctl = StatusController(fleet, ftc)
        fleet.host.create(ftc.federated.resource, make_fed(clusters=("c1",)))
        ctl.run_until_idle()
        assert fleet.host.try_get(ftc.status.resource, "default/web")
        fleet.host.delete(ftc.federated.resource, "default/web")
        ctl.run_until_idle()
        assert fleet.host.try_get(ftc.status.resource, "default/web") is None

    def test_removed_cluster_reported_unavailable(self):
        """A cluster leaving the federation must not keep serving its
        frozen last-known member status as live (MemberStore evict)."""
        fleet = fleet_with(("c1", "c2"))
        ftc = deployment_ftc()
        ctl = StatusController(fleet, ftc)
        fleet.member("c2").create(ftc.source.resource, member_deployment())
        fleet.host.create(ftc.federated.resource, make_fed())
        ctl.run_until_idle()
        by = {
            e["clusterName"]: e
            for e in fleet.host.get(ftc.status.resource, "default/web")[
                "clusterStatus"
            ]
        }
        assert "error" not in by["c2"]

        fleet.host.delete(C.FEDERATED_CLUSTERS, "c2")
        ctl.run_until_idle()
        by = {
            e["clusterName"]: e
            for e in fleet.host.get(ftc.status.resource, "default/web")[
                "clusterStatus"
            ]
        }
        assert by["c2"].get("error") == "cluster unavailable"

    def test_eviction_survives_later_reattach(self):
        """A deleted cluster must stay evicted across reattach() calls
        triggered by OTHER clusters' lifecycle events, and come back only
        when its FederatedCluster is re-created."""
        fleet = fleet_with(("c1", "c2"))
        ftc = deployment_ftc()
        ctl = StatusController(fleet, ftc)
        fleet.member("c2").create(ftc.source.resource, member_deployment())
        fleet.host.create(ftc.federated.resource, make_fed())
        ctl.run_until_idle()

        fleet.host.delete(C.FEDERATED_CLUSTERS, "c2")
        ctl.run_until_idle()
        # A third cluster joins: the reattach MUST NOT resurrect c2's
        # watch (its kube handle is still in fleet.members).
        fleet.add_member("c3")
        fleet.host.create(C.FEDERATED_CLUSTERS, make_cluster("c3"))
        ctl.run_until_idle()
        by = {
            e["clusterName"]: e
            for e in fleet.host.get(ftc.status.resource, "default/web")[
                "clusterStatus"
            ]
        }
        assert by["c2"].get("error") == "cluster unavailable"

        # Re-creating c2 lifts the eviction.
        fleet.host.create(C.FEDERATED_CLUSTERS, make_cluster("c2"))
        ctl.run_until_idle()
        by = {
            e["clusterName"]: e
            for e in fleet.host.get(ftc.status.resource, "default/web")[
                "clusterStatus"
            ]
        }
        assert "error" not in by["c2"]
        assert by["c2"]["collectedFields"]["status"]["replicas"] == 3

    def test_external_status_cr_deletion_recreated(self):
        """An out-of-band status-CR deletion invalidates the skip cache
        (level-triggered self-heal survives the fingerprint fast path)."""
        fleet = fleet_with(("c1",))
        ftc = deployment_ftc()
        ctl = StatusController(fleet, ftc)
        fleet.member("c1").create(ftc.source.resource, member_deployment())
        fleet.host.create(ftc.federated.resource, make_fed(clusters=("c1",)))
        ctl.run_until_idle()
        assert fleet.host.try_get(ftc.status.resource, "default/web") is not None
        fleet.host.delete(ftc.status.resource, "default/web")
        ctl.run_until_idle()
        assert fleet.host.try_get(ftc.status.resource, "default/web") is not None

    def test_unavailable_cluster_reported(self):
        fleet = fleet_with(("c1",))
        ftc = deployment_ftc()
        ctl = StatusController(fleet, ftc)
        fed = make_fed(clusters=("c1", "ghost"))
        fleet.host.create(ftc.federated.resource, fed)
        fleet.member("c1").create(ftc.source.resource, member_deployment())
        ctl.run_until_idle()
        status_cr = fleet.host.get(ftc.status.resource, "default/web")
        by_cluster = {e["clusterName"]: e for e in status_cr["clusterStatus"]}
        assert by_cluster["ghost"]["error"] == "cluster unavailable"


class TestWorkloadAggregation:
    def test_sums_counters(self):
        source = {"metadata": {"generation": 4}}
        objs = {
            "c1": member_deployment(replicas=2, ready=2),
            "c2": member_deployment(replicas=3, ready=1),
        }
        status = aggregate_workload_status(source, objs, True)
        assert status["replicas"] == 5
        assert status["readyReplicas"] == 3
        assert status["observedGeneration"] == 4

    def test_stale_clusters_hold_observed_generation(self):
        source = {"metadata": {"generation": 4}, "status": {"observedGeneration": 2}}
        status = aggregate_workload_status(source, {}, False)
        assert status["observedGeneration"] == 2


class TestStatusAggregator:
    def test_deployment_status_summed_onto_source(self):
        fleet = fleet_with()
        ftc = deployment_ftc()
        agg = StatusAggregator(fleet, ftc)
        fleet.host.create(
            ftc.source.resource,
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": "web", "namespace": "default"},
                "spec": {"replicas": 5},
            },
        )
        fleet.member("c1").create(ftc.source.resource, member_deployment(replicas=2))
        fleet.member("c2").create(ftc.source.resource, member_deployment(replicas=3))
        fleet.host.create(ftc.federated.resource, make_fed())
        agg.run_until_idle()

        src = fleet.host.get(ftc.source.resource, "default/web")
        assert src["status"]["replicas"] == 5
        assert src["status"]["readyReplicas"] == 6
        assert src["status"]["observedGeneration"] == src["metadata"]["generation"]

    def test_unsynced_cluster_blocks_observed_generation(self):
        fleet = fleet_with()
        ftc = deployment_ftc()
        agg = StatusAggregator(fleet, ftc)
        fleet.host.create(
            ftc.source.resource,
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": "web", "namespace": "default"},
                "spec": {"replicas": 5},
            },
        )
        fleet.member("c1").create(ftc.source.resource, member_deployment(replicas=2))
        # c2 has no object yet.
        fleet.host.create(ftc.federated.resource, make_fed())
        agg.run_until_idle()
        src = fleet.host.get(ftc.source.resource, "default/web")
        assert src["status"]["replicas"] == 2
        assert "observedGeneration" not in src["status"]

    def test_pluginless_kind_gets_feedback_annotation(self):
        fleet = fleet_with(("c1",))
        ftc = next(f for f in default_ftcs() if f.name == "configmaps")
        agg = StatusAggregator(fleet, ftc)
        fleet.host.create(
            ftc.source.resource,
            {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "metadata": {"name": "cm", "namespace": "default"},
                "data": {"k": "v"},
            },
        )
        fleet.member("c1").create(
            ftc.source.resource,
            {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "metadata": {"name": "cm", "namespace": "default"},
                "data": {"k": "v"},
                "status": {"phase": "Active"},
            },
        )
        fed = make_fed(name="cm", clusters=("c1",))
        fed["kind"] = "FederatedConfigMap"
        fleet.host.create(ftc.federated.resource, fed)
        agg.run_until_idle()
        src = fleet.host.get(ftc.source.resource, "default/cm")
        feedback = json.loads(
            src["metadata"]["annotations"][C.SOURCE_FEEDBACK_STATUS]
        )
        assert feedback["clusters"][0]["name"] == "c1"


class TestJobAggregation:
    def test_sums_and_completes(self):
        from kubeadmiral_tpu.federation.statusctl import aggregate_job_status

        objs = {
            "c1": {
                "status": {
                    "succeeded": 1,
                    "startTime": "2026-01-01T00:00:00Z",
                    "completionTime": "2026-01-01T01:00:00Z",
                }
            },
            "c2": {
                "status": {
                    "succeeded": 2,
                    "startTime": "2026-01-01T00:30:00Z",
                    "completionTime": "2026-01-01T02:00:00Z",
                }
            },
        }
        status = aggregate_job_status({}, objs, True)
        assert status["succeeded"] == 3
        assert status["startTime"] == "2026-01-01T00:00:00Z"
        assert status["completionTime"] == "2026-01-01T02:00:00Z"
        assert status["conditions"][0]["type"] == "Complete"

    def test_mixed_outcome_is_failed(self):
        from kubeadmiral_tpu.federation.statusctl import aggregate_job_status

        objs = {
            "c1": {"status": {"completionTime": "2026-01-01T01:00:00Z"}},
            "c2": {
                "status": {
                    "failed": 1,
                    "conditions": [{"type": "Failed", "status": "True"}],
                }
            },
        }
        status = aggregate_job_status({}, objs, True)
        cond = status["conditions"][0]
        assert cond["type"] == "Failed"
        assert cond["reason"] == "Mixed"

    def test_incomplete_jobs_have_no_condition(self):
        from kubeadmiral_tpu.federation.statusctl import aggregate_job_status

        objs = {
            "c1": {"status": {"active": 1}},
            "c2": {"status": {"completionTime": "2026-01-01T01:00:00Z"}},
        }
        status = aggregate_job_status({}, objs, True)
        assert "conditions" not in status
        assert status["active"] == 1


class TestPodAggregation:
    def test_phase_precedence(self):
        from kubeadmiral_tpu.federation.statusctl import aggregate_pod_status

        objs = {
            "c1": {"status": {"phase": "Running"}},
            "c2": {"status": {"phase": "Failed"}},
        }
        status = aggregate_pod_status({}, objs, True)
        assert status["phase"] == "Failed"

    def test_container_statuses_tagged_by_cluster(self):
        from kubeadmiral_tpu.federation.statusctl import aggregate_pod_status

        objs = {
            "c1": {
                "status": {
                    "phase": "Running",
                    "containerStatuses": [{"name": "app", "ready": True}],
                }
            },
        }
        status = aggregate_pod_status({}, objs, True)
        assert status["containerStatuses"][0]["name"] == "app (c1)"


class TestSingleClusterAggregation:
    def test_statefulset_adopts_lone_status(self):
        from kubeadmiral_tpu.federation.statusctl import (
            AGGREGATION_PLUGINS,
            aggregate_single_cluster,
        )

        assert AGGREGATION_PLUGINS["apps/v1/StatefulSet"] is aggregate_single_cluster
        objs = {"c1": {"status": {"readyReplicas": 3, "currentRevision": "r1"}}}
        assert aggregate_single_cluster({}, objs, True) == {
            "readyReplicas": 3,
            "currentRevision": "r1",
        }
        # Ambiguous with two clusters.
        objs["c2"] = {"status": {}}
        assert aggregate_single_cluster({}, objs, True) is None
