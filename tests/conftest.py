"""Test harness configuration.

Tests run on a virtual 8-device CPU platform so that multi-chip sharding
(jax.sharding.Mesh over objects x clusters) is exercised without TPU
hardware, mirroring how the driver dry-runs the multichip path.

The environment pre-imports jax at interpreter startup, so setting
JAX_PLATFORMS via os.environ here is too late — jax's config binds it at
import time.  Backends, however, initialize lazily (at the first
jax.devices()/dispatch), so `jax.config.update` plus an XLA_FLAGS env
update still take effect as long as they run before any test touches a
device.  Force, don't defer: the ambient environment pins JAX_PLATFORMS
to the real TPU backend, and concurrent test runs would serialize (and
block) on the single tunneled chip.
"""

import os
import re

os.environ["JAX_PLATFORMS"] = "cpu"

# Concurrency harness (runtime/lockcheck.py): instrumented locks +
# declared-shared-field write guard, ON for the whole suite (default
# off in production).  Must be set before any kubeadmiral_tpu import —
# lock construction and class decoration read it.  An explicit ambient
# setting (bisecting with it off) is respected.
os.environ.setdefault("KT_LOCKCHECK", "1")
flags = os.environ.get("XLA_FLAGS", "")
match = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
if match and int(match.group(1)) >= 8:
    pass  # respect a larger ambient mesh
elif match:
    os.environ["XLA_FLAGS"] = flags.replace(
        match.group(0), "--xla_force_host_platform_device_count=8"
    )
else:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) >= 8, (
    "virtual CPU mesh unavailable: jax backends were initialized before "
    f"conftest ran (devices={jax.devices()})"
)
