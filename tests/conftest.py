"""Test harness configuration.

Tests run on a virtual 8-device CPU platform so that multi-chip sharding
(jax.sharding.Mesh over objects x clusters) is exercised without TPU
hardware, mirroring how the driver dry-runs the multichip path.  The env
vars must be set before jax is first imported anywhere.
"""

import os

# Force, don't setdefault: the ambient environment pins JAX_PLATFORMS to
# the real TPU backend, and concurrent test runs would serialize (and
# block) on the single chip.  Tests always run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
